"""Filer unit tests: chunk overlay algebra (filechunks_test.go tables),
store contract (leveldb_store_test.go pattern), filer core semantics."""

import pytest

from seaweedfs_tpu.filer.entry import Attr, Entry, new_directory_entry
from seaweedfs_tpu.filer.filechunks import (
    FileChunk, minus_chunks, non_overlapping_visible_intervals, total_size,
    view_from_chunks)
from seaweedfs_tpu.filer.filer import Filer, FilerError
from seaweedfs_tpu.filer.filerstore import available_stores, create_store


def C(fid, off, size, mtime):
    return FileChunk(file_id=fid, offset=off, size=size, mtime=mtime)


class TestChunkAlgebra:
    def test_single_chunk(self):
        v = non_overlapping_visible_intervals([C("a", 0, 100, 1)])
        assert [(x.start, x.stop, x.file_id) for x in v] == [(0, 100, "a")]

    def test_full_overwrite(self):
        v = non_overlapping_visible_intervals(
            [C("a", 0, 100, 1), C("b", 0, 100, 2)])
        assert [(x.start, x.stop, x.file_id) for x in v] == [(0, 100, "b")]

    def test_partial_middle_overwrite(self):
        v = non_overlapping_visible_intervals(
            [C("a", 0, 100, 1), C("b", 30, 40, 2)])
        assert [(x.start, x.stop, x.file_id) for x in v] == [
            (0, 30, "a"), (30, 70, "b"), (70, 100, "a")]
        # tail interval must map to the right position INSIDE chunk a
        tail = v[2]
        assert tail.chunk_offset == 70

    def test_append_chunks(self):
        v = non_overlapping_visible_intervals(
            [C("a", 0, 50, 1), C("b", 50, 50, 2), C("c", 100, 7, 3)])
        assert [(x.start, x.stop) for x in v] == [(0, 50), (50, 100),
                                                 (100, 107)]
        assert total_size([C("a", 0, 50, 1), C("c", 100, 7, 3)]) == 107

    def test_mtime_order_not_list_order(self):
        v = non_overlapping_visible_intervals(
            [C("new", 0, 100, 5), C("old", 0, 100, 1)])
        assert v[0].file_id == "new"

    def test_views_clip(self):
        chunks = [C("a", 0, 100, 1), C("b", 30, 40, 2)]
        views = view_from_chunks(chunks, 25, 50)
        # 25-30 from a, 30-70 from b, 70-75 from a(offset 70)
        assert [(w.file_id, w.offset, w.size, w.logic_offset)
                for w in views] == [
            ("a", 25, 5, 25), ("b", 0, 40, 30), ("a", 70, 5, 70)]

    def test_views_beyond_eof(self):
        views = view_from_chunks([C("a", 0, 10, 1)], 8, 100)
        assert views == view_from_chunks([C("a", 0, 10, 1)], 8, 2)

    def test_minus_chunks(self):
        a = [C("x", 0, 1, 1), C("y", 1, 1, 1)]
        b = [C("y", 9, 9, 9)]
        assert [c.file_id for c in minus_chunks(a, b)] == ["x"]


@pytest.mark.parametrize("store_name", ["memory", "sqlite"])
class TestStoreContract:
    def _store(self, store_name, tmp_path):
        kwargs = {"path": str(tmp_path / "filer.db")} \
            if store_name == "sqlite" else {}
        return create_store(store_name, **kwargs)

    def test_crud(self, store_name, tmp_path):
        s = self._store(store_name, tmp_path)
        e = Entry("/a/b/file.txt", Attr(mtime=1.0, mime="text/plain"),
                  [C("3,01", 0, 10, 1)])
        s.insert_entry(e)
        got = s.find_entry("/a/b/file.txt")
        assert got.attr.mime == "text/plain"
        assert got.chunks[0].file_id == "3,01"
        e.attr.mime = "text/html"
        s.update_entry(e)
        assert s.find_entry("/a/b/file.txt").attr.mime == "text/html"
        s.delete_entry("/a/b/file.txt")
        assert s.find_entry("/a/b/file.txt") is None

    def test_listing_pagination(self, store_name, tmp_path):
        s = self._store(store_name, tmp_path)
        for name in ("a", "b", "c", "d", "e"):
            s.insert_entry(Entry(f"/dir/{name}", Attr(mtime=1.0)))
        page1 = s.list_directory_entries("/dir", "", False, 2)
        assert [e.name for e in page1] == ["a", "b"]
        page2 = s.list_directory_entries("/dir", "b", False, 10)
        assert [e.name for e in page2] == ["c", "d", "e"]
        page_inc = s.list_directory_entries("/dir", "b", True, 2)
        assert [e.name for e in page_inc] == ["b", "c"]

    def test_delete_folder_children(self, store_name, tmp_path):
        s = self._store(store_name, tmp_path)
        for p in ("/x/1", "/x/sub/2", "/x/sub/deep/3", "/y/other"):
            s.insert_entry(Entry(p, Attr(mtime=1.0)))
        s.delete_folder_children("/x")
        assert s.find_entry("/x/1") is None
        assert s.find_entry("/x/sub/2") is None
        assert s.find_entry("/x/sub/deep/3") is None
        assert s.find_entry("/y/other") is not None

    def test_root_listing(self, store_name, tmp_path):
        s = self._store(store_name, tmp_path)
        s.insert_entry(Entry("/top.txt", Attr(mtime=1.0)))
        got = s.list_directory_entries("/", "", False, 10)
        assert [e.name for e in got] == ["top.txt"]


def test_available_stores_includes_builtin():
    names = available_stores()
    assert "memory" in names and "sqlite" in names


class TestFilerCore:
    def test_create_makes_parents(self):
        f = Filer("memory")
        f.create_entry(Entry("/a/b/c/file", Attr(mtime=1.0)))
        assert f.find_entry("/a").is_directory
        assert f.find_entry("/a/b/c").is_directory
        kids = f.list_directory_entries("/a/b/c")
        assert [e.name for e in kids] == ["file"]

    def test_overwrite_deletes_old_chunks(self):
        f = Filer("memory")
        f.create_entry(Entry("/f", Attr(mtime=1.0), [C("1,aa", 0, 5, 1)]))
        f.create_entry(Entry("/f", Attr(mtime=2.0), [C("1,bb", 0, 9, 2)]))
        assert f.drain_pending_chunk_deletes() == ["1,aa"]

    def test_delete_recursive(self):
        f = Filer("memory")
        f.create_entry(Entry("/d/x", Attr(mtime=1.0), [C("1,aa", 0, 5, 1)]))
        f.create_entry(Entry("/d/sub/y", Attr(mtime=1.0),
                             [C("1,bb", 0, 5, 1)]))
        with pytest.raises(FilerError):
            f.delete_entry("/d")  # not empty, not recursive
        f.delete_entry("/d", recursive=True)
        assert f.find_entry("/d") is None
        assert sorted(f.drain_pending_chunk_deletes()) == ["1,aa", "1,bb"]

    def test_rename_tree(self):
        f = Filer("memory")
        f.create_entry(Entry("/old/a", Attr(mtime=1.0)))
        f.create_entry(Entry("/old/sub/b", Attr(mtime=1.0)))
        f.rename_entry("/old", "/new")
        assert f.find_entry("/old") is None
        assert f.find_entry("/new/a") is not None
        assert f.find_entry("/new/sub/b") is not None

    def test_file_over_directory_rejected(self):
        f = Filer("memory")
        f.create_entry(Entry("/d/x", Attr(mtime=1.0)))
        with pytest.raises(FilerError):
            f.create_entry(Entry("/d", Attr(mtime=1.0), [C("1,aa", 0, 1, 1)]))

    def test_notifications(self):
        f = Filer("memory")
        events = []
        f.listeners.append(lambda old, new: events.append(
            (old and old.full_path, new and new.full_path)))
        f.create_entry(Entry("/n", Attr(mtime=1.0)))
        f.delete_entry("/n")
        assert (None, "/n") in events and ("/n", None) in events
