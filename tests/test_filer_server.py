"""Filer server e2e over the in-proc cluster: auto-chunked uploads,
streaming range reads, listings, rename, recursive delete + chunk GC."""

import asyncio
import random

from cluster_util import Cluster, run


def _cluster(tmp_path, **kw):
    c = Cluster(str(tmp_path), **kw)
    c.with_filer = True
    return c


def test_upload_download_multi_chunk(tmp_path):
    async def body():
        async with _cluster(tmp_path) as c:
            f = c.filer
            rng = random.Random(9)
            # 3.5 chunks worth of data (chunk_size = 256KB)
            data = bytes(rng.getrandbits(8)
                         for _ in range(int(3.5 * 256 * 1024)))
            async with c.http.post(
                    f"http://{f.url}/docs/big.bin", data=data) as resp:
                assert resp.status == 201, await resp.text()
            # entry has 4 chunks
            async with c.http.get(f"http://{f.url}/__api__/lookup",
                                  params={"path": "/docs/big.bin"}) as resp:
                meta = await resp.json()
            assert len(meta["chunks"]) == 4
            assert meta["FileSize"] == len(data)
            # full read
            async with c.http.get(f"http://{f.url}/docs/big.bin") as resp:
                assert resp.status == 200
                got = await resp.read()
            assert got == data
            # range read across a chunk boundary
            start, ln = 256 * 1024 - 100, 300
            async with c.http.get(
                    f"http://{f.url}/docs/big.bin",
                    headers={"Range": f"bytes={start}-{start+ln-1}"}) as resp:
                assert resp.status == 206
                assert await resp.read() == data[start:start + ln]
            # suffix range
            async with c.http.get(
                    f"http://{f.url}/docs/big.bin",
                    headers={"Range": "bytes=-100"}) as resp:
                assert await resp.read() == data[-100:]
    run(body())


def test_listing_and_rename_and_delete(tmp_path):
    async def body():
        async with _cluster(tmp_path) as c:
            f = c.filer
            for name in ("a.txt", "b.txt"):
                async with c.http.post(f"http://{f.url}/dir/{name}",
                                       data=b"data-" + name.encode()) as r:
                    assert r.status == 201
            # directory listing
            async with c.http.get(f"http://{f.url}/dir") as resp:
                listing = await resp.json()
            assert [e["FullPath"] for e in listing["Entries"]] == \
                ["/dir/a.txt", "/dir/b.txt"]
            # rename directory
            async with c.http.post(f"http://{f.url}/moved",
                                   params={"mv.from": "/dir"}) as resp:
                assert resp.status == 200
            async with c.http.get(f"http://{f.url}/moved/a.txt") as resp:
                assert await resp.read() == b"data-a.txt"
            # recursive delete queues chunk GC
            async with c.http.delete(f"http://{f.url}/moved",
                                     params={"recursive": "true"}) as resp:
                assert resp.status == 204
            async with c.http.get(f"http://{f.url}/moved/a.txt") as resp:
                assert resp.status == 404
            # chunk GC drains: blobs eventually deleted from volume servers
            for _ in range(30):
                await asyncio.sleep(0.2)
                if not f._pending:
                    break
            assert not f._pending
    run(body())


def test_overwrite_gc_and_mkdir(tmp_path):
    async def body():
        async with _cluster(tmp_path) as c:
            f = c.filer
            async with c.http.post(f"http://{f.url}/f.bin",
                                   data=b"version-1") as r:
                assert r.status == 201
            async with c.http.get(f"http://{f.url}/__api__/lookup",
                                  params={"path": "/f.bin"}) as r:
                old_fid = (await r.json())["chunks"][0]["file_id"]
            async with c.http.post(f"http://{f.url}/f.bin",
                                   data=b"version-2!") as r:
                assert r.status == 201
            async with c.http.get(f"http://{f.url}/f.bin") as r:
                assert await r.read() == b"version-2!"
            assert old_fid in f._pending  # queued for GC
            # mkdir
            async with c.http.post(f"http://{f.url}/newdir",
                                   params={"mkdir": "true"}) as r:
                assert r.status == 201
            async with c.http.get(f"http://{f.url}/__api__/lookup",
                                  params={"path": "/newdir"}) as r:
                assert (await r.json())["IsDirectory"] is True
            # multipart upload form
            import aiohttp
            form = aiohttp.FormData()
            form.add_field("file", b"formdata", filename="form.txt",
                           content_type="text/plain")
            async with c.http.post(f"http://{f.url}/up.txt",
                                   data=form) as r:
                assert r.status == 201
            async with c.http.get(f"http://{f.url}/up.txt") as r:
                assert await r.read() == b"formdata"
                assert r.headers["Content-Type"].startswith("text/plain")
    run(body())


def test_sparse_file_streams_zero_filled_holes(tmp_path):
    """A hole between chunks must read back as zeros with a full-length
    body (filer2/stream.go semantics; the view clip jumps holes)."""
    async def body():
        async with _cluster(tmp_path) as c:
            f = c.filer
            from seaweedfs_tpu.filer.filechunks import FileChunk
            from seaweedfs_tpu.filer.entry import Attr, Entry
            import time as _t

            # store two real chunks, then register an entry whose chunk
            # list leaves a hole [10, 20)
            async with c.http.post(f"http://{f.url}/tmp/a", data=b"A" * 10) as r:
                assert r.status == 201
            async with c.http.post(f"http://{f.url}/tmp/b", data=b"B" * 10) as r:
                assert r.status == 201
            ea = f.filer.find_entry("/tmp/a")
            eb = f.filer.find_entry("/tmp/b")
            sparse = Entry("/sparse.bin", Attr(mtime=_t.time()), chunks=[
                FileChunk(ea.chunks[0].file_id, 0, 10, 1),
                FileChunk(eb.chunks[0].file_id, 20, 10, 2),
            ])
            f.filer.create_entry(sparse)
            async with c.http.get(f"http://{f.url}/sparse.bin") as resp:
                assert resp.status == 200
                got = await resp.read()
            assert got == b"A" * 10 + b"\x00" * 10 + b"B" * 10
            # range read starting inside the hole
            async with c.http.get(
                    f"http://{f.url}/sparse.bin",
                    headers={"Range": "bytes=15-24"}) as resp:
                assert resp.status == 206
                assert await resp.read() == b"\x00" * 5 + b"B" * 5
    run(body())


def test_filer_knobs_redirect_listing(tmp_path):
    """-redirectOnRead / -disableDirListing / -dirListLimit
    (command/filer.go:50-53)."""
    async def body():
        c = Cluster(str(tmp_path))
        c.with_filer = True
        async with c:
            f = c.filer
            async with c.http.post(f"http://{f.url}/d/one.bin",
                                   data=b"single-chunk") as r:
                assert r.status == 201
            # single-chunk GET redirects straight to the volume server
            f.redirect_on_read = True
            async with c.http.get(f"http://{f.url}/d/one.bin",
                                  allow_redirects=False) as resp:
                assert resp.status == 302
                loc = resp.headers["Location"]
            async with c.http.get(loc) as resp:
                assert await resp.read() == b"single-chunk"
            # ...but range reads still proxy (the redirect would lose
            # the filer's chunk-overlay semantics)
            async with c.http.get(
                    f"http://{f.url}/d/one.bin", allow_redirects=False,
                    headers={"Range": "bytes=0-5"}) as resp:
                assert resp.status == 206
            f.redirect_on_read = False

            # listing cap + kill switch
            f.dir_list_limit = 1
            async with c.http.get(f"http://{f.url}/d/",
                                  params={"limit": "1000"}) as resp:
                body_ = await resp.json()
                assert len(body_["Entries"]) == 1
            f.dir_list_limit = 100_000
            f.disable_dir_listing = True
            async with c.http.get(f"http://{f.url}/d/") as resp:
                assert resp.status == 405
            f.disable_dir_listing = False
    run(body())
