"""Sharded filer metadata plane (ISSUE 18): shard-map algebra, the
raft CAS apply, redirect hints, merged cross-shard pagination, the
journaled two-phase move's crash replay, limit clamps and the
singleflight listing fence."""

import asyncio
import contextlib
import json

import aiohttp
import pytest

from cluster_util import run
from seaweedfs_tpu.filer.entry import Attr, Entry
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.shard import (ShardMap, apply_map_op, covers,
                                       merge_entry_lists)
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.util import failpoints


# -- pure map algebra --------------------------------------------------

def test_covers_is_boundary_aware():
    assert covers("/", "/anything")
    assert covers("/a", "/a")
    assert covers("/a", "/a/b/c")
    assert not covers("/a", "/ab")       # sibling, not child
    assert not covers("/a/b", "/a")


def test_route_longest_prefix_wins():
    m = ShardMap(rules=[["/", 0], ["/a", 1], ["/a/b", 2]],
                 owners={0: "h:0", 1: "h:1", 2: "h:2"})
    assert m.route("/a/b/c") == 2
    assert m.route("/a/x") == 1
    assert m.route("/ax") == 0           # /a must not cover /ax
    assert m.route("/z") == 0
    assert m.matched_prefix("/a/b/c") == "/a/b"
    assert m.shards_under("/a") == {2}   # rules STRICTLY below /a
    assert m.shards_under("/") == {1, 2}


def test_apply_map_op_split_lifecycle():
    m = ShardMap(rules=[["/", 0]], owners={0: "h:0", 1: "h:1"})
    m = apply_map_op(m, {"op": "split_intent", "prefix": "/hot",
                         "to": 1})
    mv = m.move_by_id("split:/hot")
    assert mv is not None and mv["state"] == "copy"
    assert m.route("/hot/x") == 0        # routing unchanged pre-flip
    # idempotent re-submit: a deposed leader's replayed proposal
    assert apply_map_op(m, {"op": "split_intent", "prefix": "/hot",
                            "to": 1}).moves == m.moves
    m = apply_map_op(m, {"op": "commit_move", "id": "split:/hot"})
    assert m.route("/hot/x") == 1        # the one-apply flip
    assert m.move_by_id("split:/hot")["state"] == "cleanup"
    with pytest.raises(ValueError):      # past the flip: no abort
        apply_map_op(m, {"op": "abort_move", "id": "split:/hot"})
    m = apply_map_op(m, {"op": "move_done", "id": "split:/hot"})
    assert m.moves == []
    assert m.route("/hot/x") == 1
    # move_done twice: idempotent completion, not an error
    assert apply_map_op(m, {"op": "move_done",
                            "id": "split:/hot"}).moves == []


def test_apply_map_op_rejects_invalid_transitions():
    m = ShardMap(rules=[["/", 0]], owners={0: "h:0", 1: "h:1"})
    with pytest.raises(ValueError):      # self-split
        apply_map_op(m, {"op": "split_intent", "prefix": "/x",
                         "to": 0})
    with pytest.raises(ValueError):      # the root rule is load-bearing
        apply_map_op(m, {"op": "set", "rules": [["/a", 1]]})
    with pytest.raises(ValueError):
        apply_map_op(m, {"op": "commit_move", "id": "split:/nope"})
    with pytest.raises(ValueError):
        apply_map_op(m, {"op": "frobnicate"})
    m = apply_map_op(m, {"op": "split_intent", "prefix": "/x",
                         "to": 1})
    with pytest.raises(ValueError):      # overlapping concurrent move
        apply_map_op(m, {"op": "rename_intent", "src": "/x/f",
                         "dst": "/y/f"})


# -- k-way merged pagination ------------------------------------------

def _e(path: str, mtime: float = 1.0) -> Entry:
    return Entry(full_path=path, attr=Attr(mtime=mtime))


def test_merge_exactly_once_in_order_across_boundary():
    s0 = [_e("/d/a"), _e("/d/m"), _e("/d/z")]
    s1 = [_e("/d/b"), _e("/d/sub")]
    got = merge_entry_lists([s0, s1], "", False, 10)
    assert [e.name for e in got] == ["a", "b", "m", "sub", "z"]
    # pagination: resume exclusive after 'b', limit 2
    got = merge_entry_lists([s0, s1], "b", False, 2)
    assert [e.name for e in got] == ["m", "sub"]
    # inclusive resume re-serves the boundary name exactly once
    got = merge_entry_lists([s0, s1], "m", True, 10)
    assert [e.name for e in got] == ["m", "sub", "z"]


def test_merge_dedups_preferring_route_owner():
    # dual-write window of an in-flight move: both shards hold /d/x —
    # the copy from the shard the map routes the path to must win
    m = ShardMap(rules=[["/", 0], ["/d/x", 1]],
                 owners={0: "h:0", 1: "h:1"})
    stale = _e("/d/x", mtime=1.0)      # left behind on shard 0
    fresh = _e("/d/x", mtime=9.0)      # the routed owner's copy
    got = merge_entry_lists([[stale], [fresh]], "", False, 10,
                            sources=[0, 1], prefer=m)
    assert len(got) == 1 and got[0].attr.mtime == 9.0
    # order independence: the routed-owner page wins either way
    got = merge_entry_lists([[fresh], [stale]], "", False, 10,
                            sources=[1, 0], prefer=m)
    assert len(got) == 1 and got[0].attr.mtime == 9.0


# -- raft-committed apply: the epoch CAS ------------------------------

def test_election_shard_map_cas(tmp_path):
    async def body():
        m = MasterServer(port=0, meta_dir=str(tmp_path))
        await m.start()
        try:
            el = m.election
            base = el.applied_shard_epoch
            # a deposed leader's proposal carries a stale base: no-op
            el._apply_shard_map({"base": base + 7, "map": {
                "rules": [["/", 0], ["/evil", 1]], "owners": {},
                "moves": []}})
            assert el.applied_shard_epoch == base
            assert m.shard_map is None or not any(
                p == "/evil" for p, _ in m.shard_map["rules"])
            # the current base applies, bumps the epoch, mirrors into
            # the server's adopt hook
            el._apply_shard_map({"base": base, "map": {
                "rules": [["/", 0], ["/good", 1]],
                "owners": {"1": "h:1"}, "moves": []}})
            assert el.applied_shard_epoch == base + 1
            assert ["/good", 1] in m.shard_map["rules"]
            assert m.shard_map["epoch"] == base + 1
        finally:
            await m.stop()
    run(body())


def test_master_shards_endpoint_cas_and_400(tmp_path):
    async def body():
        m = MasterServer(port=0, meta_dir=str(tmp_path))
        await m.start()
        try:
            async with aiohttp.ClientSession() as http:
                async def post(op, status=200):
                    async with http.post(
                            f"http://{m.url}/cluster/shards",
                            json=op) as r:
                        assert r.status == status, await r.text()
                        return await r.json()

                body1 = await post({"op": "register", "shard": 1,
                                    "url": "h:1"})
                e1 = body1["map"]["epoch"]
                await post({"op": "set", "rules": [["/a", 1]]},
                           status=400)       # no root rule
                await post({"op": "split_intent", "prefix": "/a",
                            "to": 0}, status=400)  # self-split
                body2 = await post({"op": "split_intent",
                                    "prefix": "/a", "to": 1})
                assert body2["map"]["moves"]
                # idempotent re-submit answers ok without a new move
                body3 = await post({"op": "split_intent",
                                    "prefix": "/a", "to": 1})
                assert len(body3["map"]["moves"]) == 1
                assert body3["map"]["epoch"] > e1
                async with http.get(
                        f"http://{m.url}/cluster/shards") as r:
                    got = await r.json()
                assert got["moves"] and "leader" in got
        finally:
            await m.stop()
    run(body())


# -- live sharded cluster ---------------------------------------------

class ShardCluster:
    """Master + N in-proc sharded FilerServers (memory store)."""

    def __init__(self, tmpdir: str, n: int = 2):
        self.tmpdir = tmpdir
        self.n = n
        self.master: MasterServer | None = None
        self.filers: list[FilerServer] = []
        self.http: aiohttp.ClientSession | None = None

    async def __aenter__(self) -> "ShardCluster":
        self.master = MasterServer(port=0, meta_dir=self.tmpdir)
        await self.master.start()
        for sid in range(self.n):
            f = FilerServer(Filer("memory"), self.master.url, port=0,
                            shard_id=sid, shard_of=self.n,
                            shard_split_mbps=64.0)
            await f.start()
            self.filers.append(f)
        self.http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=20))
        for _ in range(200):
            async with self.http.get(
                    f"http://{self.master.url}/cluster/shards") as r:
                body = await r.json()
            if len(body.get("owners", {})) == self.n:
                return self
            await asyncio.sleep(0.05)
        raise AssertionError("shards never registered")

    async def __aexit__(self, *exc) -> None:
        if self.http:
            await self.http.close()
        for f in self.filers:
            with contextlib.suppress(Exception):
                await f.stop()
        with contextlib.suppress(Exception):
            await self.master.stop()

    async def set_rules(self, rules: list) -> None:
        async with self.http.post(
                f"http://{self.master.url}/cluster/shards",
                json={"op": "set", "rules": rules}) as r:
            assert r.status == 200, await r.text()
        await self.refresh()

    async def refresh(self) -> None:
        for f in self.filers:
            await f.shard.routes.refresh(f.shard._http, force=True)

    async def create(self, filer: FilerServer, path: str,
                     mtime: float = 1.0) -> int:
        async with self.http.post(
                f"http://{filer.url}/__api__/entry",
                json={"FullPath": path, "Mtime": mtime},
                allow_redirects=False) as r:
            return r.status

    async def wait_moves_drained(self, tries: int = 300) -> None:
        for _ in range(tries):
            for f in self.filers:
                f.shard._executor_wake.set()
            async with self.http.get(
                    f"http://{self.master.url}/cluster/shards") as r:
                body = await r.json()
            if not body.get("moves"):
                await self.refresh()
                return
            await asyncio.sleep(0.1)
        raise AssertionError(f"moves never drained: {body['moves']}")


def test_redirect_hints_and_routed_create(tmp_path):
    async def body():
        async with ShardCluster(str(tmp_path)) as c:
            await c.set_rules([["/", 0], ["/s1", 1]])
            f0, f1 = c.filers
            # foreign create answers 307 + the learnable hint trio
            async with c.http.post(
                    f"http://{f0.url}/__api__/entry",
                    json={"FullPath": "/s1/a", "Mtime": 5.0},
                    allow_redirects=False) as r:
                assert r.status == 307
                assert r.headers["X-Shard-Owner"] == f1.url
                assert r.headers["X-Shard-Prefix"] == "/s1"
                assert int(r.headers["X-Shard-Epoch"]) >= 1
            assert await c.create(f1, "/s1/a", 5.0) == 200
            # entry lives on shard 1 only
            assert f1.filer.find_entry("/s1/a") is not None
            assert f0.filer.find_entry("/s1/a") is None
            # a routed GET through the WRONG shard follows the hint
            async with c.http.get(
                    f"http://{f0.url}/__api__/lookup",
                    params={"path": "/s1/a"}) as r:
                assert r.status == 200
                assert (await r.json())["Mtime"] == 5.0
    run(body())


def test_merged_listing_exactly_once_across_boundary(tmp_path):
    async def body():
        async with ShardCluster(str(tmp_path)) as c:
            await c.set_rules([["/", 0], ["/d/sub", 1]])
            f0, f1 = c.filers
            for p in ("/d/a", "/d/m", "/d/z"):
                assert await c.create(f0, p) == 200
            assert await c.create(f1, "/d/sub/x") == 200
            # the /d/sub DIRECTORY row lives on shard 1; a listing of
            # /d through shard 0 must merge it in, exactly once, in
            # name order — paged at limit=1 across the shard boundary
            seen = []
            start = ""
            while True:
                async with c.http.get(
                        f"http://{f0.url}/__api__/list",
                        params={"path": "/d", "startFile": start,
                                "limit": "1"}) as r:
                    assert r.status == 200
                    page = (await r.json())["entries"]
                if not page:
                    break
                seen.extend(e["FullPath"] for e in page)
                start = page[-1]["FullPath"].rsplit("/", 1)[1]
            assert seen == ["/d/a", "/d/m", "/d/sub", "/d/z"]
            # and via the foreign shard: redirected, same answer
            async with c.http.get(
                    f"http://{f1.url}/__api__/list",
                    params={"path": "/d", "limit": "10"}) as r:
                assert r.status == 200
                names = [e["FullPath"]
                         for e in (await r.json())["entries"]]
            assert names == ["/d/a", "/d/m", "/d/sub", "/d/z"]
    run(body())


def test_online_split_moves_and_tombstones(tmp_path):
    async def body():
        async with ShardCluster(str(tmp_path)) as c:
            f0, f1 = c.filers
            paths = [f"/hot/d/f{i:02d}" for i in range(20)]
            for i, p in enumerate(paths):
                assert await c.create(f0, p, mtime=100.0 + i) == 200
            async with c.http.post(
                    f"http://{c.master.url}/cluster/shards",
                    json={"op": "split_intent", "prefix": "/hot",
                          "to": 1}) as r:
                assert r.status == 200, await r.text()
            await c.refresh()
            await c.wait_moves_drained()
            # routing flipped, data landed, source tombstoned
            assert f0.shard.map.route("/hot/d/f00") == 1
            for i, p in enumerate(paths):
                e = f1.filer.find_entry(p)
                assert e is not None and e.attr.mtime == 100.0 + i
                assert f0.filer.find_entry(p) is None
            assert f0.filer.find_entry("/hot") is None
            assert f0.shard.counters["moved"] >= len(paths)
            assert f1.shard.counters["ingest"] >= len(paths)
            # the moved prefix still answers through EITHER shard
            async with c.http.get(
                    f"http://{f0.url}/__api__/lookup",
                    params={"path": paths[0]}) as r:
                assert r.status == 200
    run(body())


def test_cross_shard_rename_replays_from_journal(tmp_path):
    async def body():
        async with ShardCluster(str(tmp_path)) as c:
            await c.set_rules([["/", 0], ["/s1", 1]])
            f0, f1 = c.filers
            assert await c.create(f0, "/src/f", mtime=123.0) == 200
            # commit the intent WITHOUT a foreground requester: this
            # is the crash-replay path — a journaled move with no one
            # driving it must be picked up by the source's executor
            async with c.http.post(
                    f"http://{c.master.url}/cluster/shards",
                    json={"op": "rename_intent", "src": "/src/f",
                          "dst": "/s1/f"}) as r:
                assert r.status == 200, await r.text()
            await c.refresh()
            await c.wait_moves_drained()
            e = f1.filer.find_entry("/s1/f")
            assert e is not None and e.attr.mtime == 123.0
            assert f0.filer.find_entry("/src/f") is None
            assert f0.shard.counters["replayed"] >= 1
    run(body())


def test_rename_replay_resumes_from_cleanup_state(tmp_path):
    async def body():
        async with ShardCluster(str(tmp_path)) as c:
            await c.set_rules([["/", 0], ["/s1", 1]])
            f0, f1 = c.filers
            assert await c.create(f0, "/src2/g", mtime=7.0) == 200
            # block every executor commit hop: the copy lands but the
            # intent cannot advance (a SIGKILL between copy and commit
            # leaves exactly this state in the committed map)
            failpoints.arm("filer.shard.move", "error")
            try:
                async with c.http.post(
                        f"http://{c.master.url}/cluster/shards",
                        json={"op": "rename_intent", "src": "/src2/g",
                              "dst": "/s1/g"}) as r:
                    assert r.status == 200
                await c.refresh()
                f0.shard._executor_wake.set()
                await asyncio.sleep(0.5)
                # advance the journal to cleanup OURSELVES (the crashed
                # executor's commit, replayed by the operator/master)
                async with c.http.post(
                        f"http://{c.master.url}/cluster/shards",
                        json={"op": "commit_move",
                              "id": "rename:/src2/g:/s1/g"}) as r:
                    assert r.status == 200
            finally:
                failpoints.disarm("filer.shard.move")
            await c.refresh()
            await c.wait_moves_drained()
            # resumed from cleanup: catch-up copy + tombstone + done
            e = f1.filer.find_entry("/s1/g")
            assert e is not None and e.attr.mtime == 7.0
            assert f0.filer.find_entry("/src2/g") is None
            assert f1.filer.find_entry("/s1/g").attr.mtime == 7.0
    run(body())


# -- limit clamps and the singleflight fence (unsharded filer) ---------

class OneFiler:
    """Master + a single UNSHARDED in-proc filer."""

    def __init__(self, tmpdir: str, **kw):
        self.tmpdir = tmpdir
        self.kw = kw
        self.master: MasterServer | None = None
        self.filer: FilerServer | None = None
        self.http: aiohttp.ClientSession | None = None

    async def __aenter__(self) -> "OneFiler":
        self.master = MasterServer(port=0, meta_dir=self.tmpdir)
        await self.master.start()
        self.filer = FilerServer(Filer("memory"), self.master.url,
                                 port=0, **self.kw)
        await self.filer.start()
        self.http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=20))
        return self

    async def __aexit__(self, *exc) -> None:
        if self.http:
            await self.http.close()
        with contextlib.suppress(Exception):
            await self.filer.stop()
        with contextlib.suppress(Exception):
            await self.master.stop()


def test_negative_limit_clamps_not_unlimited(tmp_path):
    async def body():
        async with OneFiler(str(tmp_path), dir_list_limit=3) as c:
            f = c.filer
            for i in range(5):
                async with c.http.post(
                        f"http://{f.url}/__api__/entry",
                        json={"FullPath": f"/dir/f{i}"}) as r:
                    assert r.status == 200
            # SQLite reads LIMIT -1 as unlimited: a negative client
            # value must clamp to the cap, on BOTH listing surfaces
            for limit in ("-1", "0", "-999"):
                async with c.http.get(
                        f"http://{f.url}/dir",
                        params={"limit": limit},
                        headers={"Accept": "application/json"}) as r:
                    assert r.status == 200
                    assert len((await r.json())["Entries"]) == 3
                async with c.http.get(
                        f"http://{f.url}/__api__/list",
                        params={"path": "/dir", "limit": limit}) as r:
                    assert r.status == 200
                    assert len((await r.json())["entries"]) == 3
            # pagination edge: resume AT the cap boundary, no repeat
            async with c.http.get(
                    f"http://{f.url}/__api__/list",
                    params={"path": "/dir", "limit": "2",
                            "startFile": "f1"}) as r:
                names = [e["FullPath"]
                         for e in (await r.json())["entries"]]
            assert names == ["/dir/f2", "/dir/f3"]
    run(body())


def test_singleflight_listing_collapses_and_fences(tmp_path):
    async def body():
        async with OneFiler(str(tmp_path)) as c:
            f = c.filer
            for i in range(3):
                async with c.http.post(
                        f"http://{f.url}/__api__/entry",
                        json={"FullPath": f"/sf/f{i}"}) as r:
                    assert r.status == 200
            calls = {"n": 0}
            gate = asyncio.Event()
            real = f.filer.list_directory_entries

            def slow_list(*a, **kw):
                calls["n"] += 1
                # block the fill in its executor thread until every
                # concurrent caller has had time to pile onto the key
                import time as _t
                while not gate.is_set():
                    _t.sleep(0.01)
                return real(*a, **kw)

            f.filer.list_directory_entries = slow_list
            try:
                tasks = [asyncio.create_task(
                    f._list_entries("/sf", "", False, 100))
                    for _ in range(6)]
                await asyncio.sleep(0.3)
                gate.set()
                pages = await asyncio.gather(*tasks)
                # one underlying store query served all six callers
                assert calls["n"] == 1
                assert all(len(p) == 3 for p in pages)
                assert f._list_sf.collapsed >= 5
                # write-invalidation fence: a mutation bumps the dir
                # generation, so the next listing cannot reuse the
                # collapsed round's key
                f.bump_gen_fence("/sf")
                gate.set()
                again = await f._list_entries("/sf", "", False, 100)
                assert calls["n"] == 2
                assert len(again) == 3
            finally:
                f.filer.list_directory_entries = real
    run(body())
