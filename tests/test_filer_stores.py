"""FilerStore contract matrix over every live store implementation.

Reference test model: weed/filer2/leveldb/leveldb_store_test.go +
leveldb2/… run the same CRUD/listing assertions against throwaway store
dirs; here one parametrized matrix covers memory, sqlite, the embedded
log-structured leveldb-class store, its 8-way sharded variant, and the
abstract_sql (sqlite dialect) store. Driver-gated stores (redis, mysql,
postgres, etcd, cassandra) register only when their client libraries
import.
"""

import time

import pytest

from seaweedfs_tpu.filer.entry import Attr, Entry, new_directory_entry
from seaweedfs_tpu.filer.filechunks import FileChunk
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filerstore import available_stores, create_store

LIVE_STORES = ["memory", "sqlite", "leveldb", "leveldb2", "sql"]


def _mk(name, tmp_path):
    kwargs = {
        "memory": {},
        "sqlite": {"path": str(tmp_path / "s.db")},
        "leveldb": {"dir": str(tmp_path / "ldb")},
        "leveldb2": {"dir": str(tmp_path / "ldb2")},
        "sql": {"path": str(tmp_path / "sql.db")},
    }[name]
    return create_store(name, **kwargs)


def _file_entry(path, n=1):
    return Entry(path, Attr(mtime=time.time(), mode=0o660),
                 chunks=[FileChunk(f"1,{n:08x}", 0, 10 * n, n)])


def test_registry_lists_live_stores():
    avail = available_stores()
    for name in LIVE_STORES:
        assert name in avail, f"{name} not registered ({avail})"


@pytest.mark.parametrize("store_name", LIVE_STORES)
def test_store_contract(store_name, tmp_path):
    s = _mk(store_name, tmp_path)
    try:
        # insert + find
        s.insert_entry(new_directory_entry("/d"))
        s.insert_entry(_file_entry("/d/b.txt", 1))
        s.insert_entry(_file_entry("/d/a.txt", 2))
        s.insert_entry(_file_entry("/d/c.txt", 3))
        got = s.find_entry("/d/a.txt")
        assert got is not None and got.chunks[0].file_id == "1,00000002"
        assert s.find_entry("/d/zzz") is None

        # update overwrites
        e = _file_entry("/d/a.txt", 9)
        s.update_entry(e)
        assert s.find_entry("/d/a.txt").chunks[0].file_id == "1,00000009"

        # sorted listing + pagination (start_file exclusive/inclusive)
        names = [x.name for x in s.list_directory_entries("/d", "", False, 10)]
        assert names == ["a.txt", "b.txt", "c.txt"]
        names = [x.name for x in s.list_directory_entries(
            "/d", "a.txt", False, 10)]
        assert names == ["b.txt", "c.txt"]
        names = [x.name for x in s.list_directory_entries(
            "/d", "b.txt", True, 1)]
        assert names == ["b.txt"]

        # delete one
        s.delete_entry("/d/b.txt")
        assert s.find_entry("/d/b.txt") is None
        assert len(s.list_directory_entries("/d", "", False, 10)) == 2

        # delete_folder_children clears the subtree
        s.insert_entry(new_directory_entry("/d/sub"))
        s.insert_entry(_file_entry("/d/sub/x", 4))
        s.delete_folder_children("/d")
        assert s.list_directory_entries("/d", "", False, 10) == []
        assert s.find_entry("/d/sub/x") is None
    finally:
        s.close()


@pytest.mark.parametrize("store_name", ["leveldb", "leveldb2", "sql"])
def test_store_durability_across_reopen(store_name, tmp_path):
    s = _mk(store_name, tmp_path)
    s.insert_entry(new_directory_entry("/p"))
    for i in range(20):
        s.insert_entry(_file_entry(f"/p/f{i:02d}", i + 1))
    s.delete_entry("/p/f00")
    s.close()

    s2 = _mk(store_name, tmp_path)
    try:
        assert s2.find_entry("/p/f00") is None
        assert s2.find_entry("/p/f07").chunks[0].file_id == "1,00000008"
        assert len(s2.list_directory_entries("/p", "", False, 100)) == 19
    finally:
        s2.close()


def test_leveldb_wal_replay_without_compaction(tmp_path):
    """Kill-without-close: state must rebuild from the WAL alone."""
    s = _mk("leveldb", tmp_path)
    s.insert_entry(new_directory_entry("/w"))
    s.insert_entry(_file_entry("/w/a", 1))
    s.insert_entry(_file_entry("/w/b", 2))
    s.delete_entry("/w/a")
    s._log.flush()  # simulate crash: no close(), no snapshot

    s2 = _mk("leveldb", tmp_path)
    try:
        assert s2.find_entry("/w/a") is None
        assert s2.find_entry("/w/b") is not None
    finally:
        s2.close()


@pytest.mark.parametrize("store_name", ["leveldb2", "sql"])
def test_filer_over_store(store_name, tmp_path):
    """The Filer core drives the store through mkdir -p + recursive
    delete paths."""
    f = Filer(_mk(store_name, tmp_path))
    f.create_entry(_file_entry("/a/b/c/file.bin", 5))
    assert f.find_entry("/a/b/c").is_directory
    assert f.find_entry("/a/b/c/file.bin").chunks[0].size == 50
    f.delete_entry("/a", recursive=True)
    assert f.find_entry("/a/b/c/file.bin") is None
    assert f.drain_pending_chunk_deletes() == ["1,00000005"]
    f.close()
