"""FilerStore contract matrix over every live store implementation.

Reference test model: weed/filer2/leveldb/leveldb_store_test.go +
leveldb2/… run the same CRUD/listing assertions against throwaway store
dirs; here one parametrized matrix covers memory, sqlite, the embedded
log-structured leveldb-class store, its 8-way sharded variant, and the
abstract_sql (sqlite dialect) store. Driver-gated stores (redis, mysql,
postgres, etcd, cassandra) register only when their client libraries
import.
"""

import time

import pytest

from seaweedfs_tpu.filer.entry import Attr, Entry, new_directory_entry
from seaweedfs_tpu.filer.filechunks import FileChunk
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filerstore import available_stores, create_store

LIVE_STORES = ["memory", "sqlite", "leveldb", "leveldb2", "sql"]


def _mk(name, tmp_path):
    kwargs = {
        "memory": {},
        "sqlite": {"path": str(tmp_path / "s.db")},
        "leveldb": {"dir": str(tmp_path / "ldb")},
        "leveldb2": {"dir": str(tmp_path / "ldb2")},
        "sql": {"path": str(tmp_path / "sql.db")},
    }[name]
    return create_store(name, **kwargs)


def _file_entry(path, n=1):
    return Entry(path, Attr(mtime=time.time(), mode=0o660),
                 chunks=[FileChunk(f"1,{n:08x}", 0, 10 * n, n)])


def test_registry_lists_live_stores():
    avail = available_stores()
    for name in LIVE_STORES:
        assert name in avail, f"{name} not registered ({avail})"


def _run_contract(s):
    """The shared CRUD+listing+subtree contract every store must satisfy
    (leveldb_store_test.go pattern)."""
    try:
        # insert + find
        s.insert_entry(new_directory_entry("/d"))
        s.insert_entry(_file_entry("/d/b.txt", 1))
        s.insert_entry(_file_entry("/d/a.txt", 2))
        s.insert_entry(_file_entry("/d/c.txt", 3))
        got = s.find_entry("/d/a.txt")
        assert got is not None and got.chunks[0].file_id == "1,00000002"
        assert s.find_entry("/d/zzz") is None

        # update overwrites
        e = _file_entry("/d/a.txt", 9)
        s.update_entry(e)
        assert s.find_entry("/d/a.txt").chunks[0].file_id == "1,00000009"

        # sorted listing + pagination (start_file exclusive/inclusive)
        names = [x.name for x in s.list_directory_entries("/d", "", False, 10)]
        assert names == ["a.txt", "b.txt", "c.txt"]
        names = [x.name for x in s.list_directory_entries(
            "/d", "a.txt", False, 10)]
        assert names == ["b.txt", "c.txt"]
        names = [x.name for x in s.list_directory_entries(
            "/d", "b.txt", True, 1)]
        assert names == ["b.txt"]

        # delete one
        s.delete_entry("/d/b.txt")
        assert s.find_entry("/d/b.txt") is None
        assert len(s.list_directory_entries("/d", "", False, 10)) == 2

        # delete_folder_children clears the subtree
        s.insert_entry(new_directory_entry("/d/sub"))
        s.insert_entry(_file_entry("/d/sub/x", 4))
        s.delete_folder_children("/d")
        assert s.list_directory_entries("/d", "", False, 10) == []
        assert s.find_entry("/d/sub/x") is None
    finally:
        s.close()


@pytest.mark.parametrize("store_name", LIVE_STORES)
def test_store_contract(store_name, tmp_path):
    _run_contract(_mk(store_name, tmp_path))


@pytest.mark.parametrize("store_name", ["leveldb", "leveldb2", "sql"])
def test_store_durability_across_reopen(store_name, tmp_path):
    s = _mk(store_name, tmp_path)
    s.insert_entry(new_directory_entry("/p"))
    for i in range(20):
        s.insert_entry(_file_entry(f"/p/f{i:02d}", i + 1))
    s.delete_entry("/p/f00")
    s.close()

    s2 = _mk(store_name, tmp_path)
    try:
        assert s2.find_entry("/p/f00") is None
        assert s2.find_entry("/p/f07").chunks[0].file_id == "1,00000008"
        assert len(s2.list_directory_entries("/p", "", False, 100)) == 19
    finally:
        s2.close()


def test_leveldb_wal_replay_without_compaction(tmp_path):
    """Kill-without-close: state must rebuild from the WAL alone."""
    s = _mk("leveldb", tmp_path)
    s.insert_entry(new_directory_entry("/w"))
    s.insert_entry(_file_entry("/w/a", 1))
    s.insert_entry(_file_entry("/w/b", 2))
    s.delete_entry("/w/a")
    s._log.flush()  # simulate crash: no close(), no snapshot

    s2 = _mk("leveldb", tmp_path)
    try:
        assert s2.find_entry("/w/a") is None
        assert s2.find_entry("/w/b") is not None
    finally:
        s2.close()


@pytest.mark.parametrize("store_name", ["leveldb2", "sql"])
def test_filer_over_store(store_name, tmp_path):
    """The Filer core drives the store through mkdir -p + recursive
    delete paths."""
    f = Filer(_mk(store_name, tmp_path))
    f.create_entry(_file_entry("/a/b/c/file.bin", 5))
    assert f.find_entry("/a/b/c").is_directory
    assert f.find_entry("/a/b/c/file.bin").chunks[0].size == 50
    f.delete_entry("/a", recursive=True)
    assert f.find_entry("/a/b/c/file.bin") is None
    assert f.drain_pending_chunk_deletes() == ["1,00000005"]
    f.close()


# ---------------------------------------------------------------------------
# Driver-gated stores through in-memory fake drivers: the SAME contract
# executes in CI without redis/etcd/cassandra/tikv servers. The fake
# modules are injected into sys.modules before the store module imports,
# so the real adapter code (key layout, CQL, scans) runs end-to-end.
# ---------------------------------------------------------------------------

import importlib  # noqa: E402
import json  # noqa: E402
import re  # noqa: E402
import sys  # noqa: E402
import types  # noqa: E402


class FakeRedis:
    def __init__(self, **_):
        self.kv = {}
        self.zsets = {}

    def set(self, k, v):
        self.kv[k] = v.encode() if isinstance(v, str) else v

    def get(self, k):
        return self.kv.get(k)

    def delete(self, *keys):
        for k in keys:
            self.kv.pop(k, None)
            self.zsets.pop(k, None)

    def zadd(self, key, mapping):
        self.zsets.setdefault(key, set()).update(mapping)

    def zrem(self, key, member):
        self.zsets.get(key, set()).discard(member)

    def zrange(self, key, lo, hi):
        names = sorted(self.zsets.get(key, set()))
        if hi == -1:
            hi = len(names) - 1
        return [n.encode() for n in names[lo:hi + 1]]

    def zrangebylex(self, key, lo, hi):
        names = sorted(self.zsets.get(key, set()))
        if lo != "-":
            start = lo[1:]  # "[name" inclusive
            names = [n for n in names if n >= start]
        return [n.encode() for n in names]

    def close(self):
        pass


class FakeEtcd3Client:
    def __init__(self, **_):
        self.kv = {}

    def put(self, k, v):
        self.kv[k] = v.encode() if isinstance(v, str) else v

    def get(self, k):
        return self.kv.get(k), None

    def delete(self, k):
        self.kv.pop(k, None)

    def delete_prefix(self, p):
        for k in [k for k in self.kv if k.startswith(p)]:
            del self.kv[k]

    def get_prefix(self, p, sort_order=None):
        for k in sorted(k for k in self.kv if k.startswith(p)):
            yield self.kv[k], None


class _CassRow:
    def __init__(self, meta):
        self.meta = meta


class _CassResult(list):
    def one(self):
        return self[0] if self else None


class FakeCassSession:
    """Understands exactly the CQL statements cassandra_store issues."""

    def __init__(self):
        self.table = {}  # (directory, name) -> meta

    def set_keyspace(self, ks):
        pass

    def execute(self, stmt, params=None):
        s = " ".join(stmt.split())
        if s.startswith("CREATE"):
            return _CassResult()
        if s.startswith("INSERT"):
            d, n, meta = params
            self.table[(d, n)] = meta
            return _CassResult()
        if s.startswith("DELETE"):
            self.table.pop(tuple(params), None)
            return _CassResult()
        if "name >" in s or "name >=" in s:
            cmp_inclusive = "name >=" in s
            limit = int(re.search(r"LIMIT (\d+)", s).group(1))
            d, start = params
            rows = sorted((n, m) for (dd, n), m in self.table.items()
                          if dd == d and (n >= start if cmp_inclusive
                                          else n > start))
            return _CassResult(_CassRow(m) for _, m in rows[:limit])
        if s.startswith("SELECT"):
            meta = self.table.get(tuple(params))
            return _CassResult([] if meta is None else [_CassRow(meta)])
        raise AssertionError(f"unexpected CQL: {s}")


class FakeCassCluster:
    def __init__(self, hosts):
        self._session = FakeCassSession()

    def connect(self):
        return self._session

    def shutdown(self):
        pass


class FakeTikvClient:
    def __init__(self):
        self.kv = {}

    def put(self, k, v):
        self.kv[bytes(k)] = bytes(v)

    def get(self, k):
        return self.kv.get(bytes(k))

    def delete(self, k):
        self.kv.pop(bytes(k), None)

    def delete_range(self, start, end):
        for k in [k for k in self.kv if start <= k < end]:
            del self.kv[k]

    def scan(self, start, end, limit):
        out = [(k, v) for k, v in sorted(self.kv.items())
               if start <= k < end]
        return out[:limit]


def _fake_module(name, **attrs):
    mod = types.ModuleType(name)
    for k, v in attrs.items():
        setattr(mod, k, v)
    return mod


def _import_with_fake(monkeypatch, driver_mods, store_mod):
    for name, mod in driver_mods.items():
        monkeypatch.setitem(sys.modules, name, mod)
    modname = f"seaweedfs_tpu.filer.stores.{store_mod}"
    sys.modules.pop(modname, None)
    return importlib.import_module(modname)


def test_redis_store_contract_with_fake_driver(monkeypatch):
    mod = _import_with_fake(
        monkeypatch, {"redis": _fake_module("redis", Redis=FakeRedis)},
        "redis_store")
    _run_contract(mod.RedisStore())


def test_etcd_store_contract_with_fake_driver(monkeypatch):
    fake = FakeEtcd3Client()
    mod = _import_with_fake(
        monkeypatch,
        {"etcd3": _fake_module("etcd3", client=lambda **kw: fake)},
        "etcd_store")
    _run_contract(mod.EtcdStore())


def test_cassandra_store_contract_with_fake_driver(monkeypatch):
    cassandra = _fake_module("cassandra")
    cluster_mod = _fake_module("cassandra.cluster", Cluster=FakeCassCluster)
    cassandra.cluster = cluster_mod
    mod = _import_with_fake(
        monkeypatch,
        {"cassandra": cassandra, "cassandra.cluster": cluster_mod},
        "cassandra_store")
    _run_contract(mod.CassandraStore())


def test_tikv_store_contract_with_fake_driver(monkeypatch):
    fake = FakeTikvClient()
    tikv = _fake_module(
        "tikv_client",
        RawClient=types.SimpleNamespace(connect=lambda addr: fake))
    mod = _import_with_fake(monkeypatch, {"tikv_client": tikv},
                            "tikv_store")
    _run_contract(mod.TikvStore())


def test_tikv_store_with_injected_client():
    """tikv registers via _load_builtin once importable, and accepts an
    injected client (the fake-driver pattern the other adapters use)."""
    from seaweedfs_tpu.filer.stores.tikv_store import TikvStore

    s = TikvStore(client=FakeTikvClient())
    s.insert_entry(new_directory_entry("/t"))
    s.insert_entry(_file_entry("/t/q", 1))
    assert s.find_entry("/t/q") is not None
    s.delete_folder_children("/t")
    assert s.find_entry("/t/q") is None
