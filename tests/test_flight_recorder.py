"""Flight-recorder debug surfaces over live in-proc servers: the
volume server's /debug/timeline//events//health (forced snapshots,
journal from real store transitions, health schema) and the filer's
reserved-path twins."""

from __future__ import annotations

import pytest

from cluster_util import Cluster, run
from seaweedfs_tpu.stats import timeline
from seaweedfs_tpu.util import events


@pytest.fixture(autouse=True)
def _fresh():
    timeline.init(interval_s=10.0, ring=64)
    timeline.reset()
    events.reset()
    yield
    timeline.reset()
    events.reset()


def test_volume_debug_surfaces(tmp_path):
    async def main():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            a = await c.assign()
            await c.put(a["fid"], a["url"], b"x" * 4096)
            base = f"http://{vs.url}"
            # forced snapshot -> at least one window with request hists
            async with c.http.post(
                    f"{base}/debug/timeline?snap=1") as r:
                assert r.status == 200
                await r.json()
            await c.get(a["fid"], a["url"])
            async with c.http.post(
                    f"{base}/debug/timeline?snap=1") as r:
                tl = await r.json()
            assert tl["interval_s"] > 0 and tl["ring"] >= 4
            assert tl["windows"], "forced snapshots must yield windows"
            win = tl["windows"][-1]
            for k in ("wall_ms", "dt_s", "rates", "gauges", "hist",
                      "quantiles"):
                assert k in win
            assert any("build_info" in k for k in win["gauges"])
            # POST without ?snap=1 is a client error
            async with c.http.post(f"{base}/debug/timeline") as r:
                assert r.status == 400
            # clamped query params never 500
            async with c.http.get(
                    f"{base}/debug/timeline?n=-5") as r:
                assert r.status == 200
                assert (await r.json())["windows"] == []
            async with c.http.get(
                    f"{base}/debug/timeline?n=999999999") as r:
                assert r.status == 200
            async with c.http.get(f"{base}/debug/timeline?n=zz") as r:
                assert r.status == 400

            # journal: the allocate above recorded a volume_mount
            async with c.http.get(f"{base}/debug/events") as r:
                assert r.status == 200
                ev = await r.json()
            types = [e["type"] for e in ev["events"]]
            assert "volume_mount" in types
            async with c.http.get(
                    f"{base}/debug/events?type=volume_mount&n=1") as r:
                only = await r.json()
            assert len(only["events"]) == 1
            assert only["events"][0]["type"] == "volume_mount"

            # health: stable schema with no -slo configured
            async with c.http.get(f"{base}/debug/health") as r:
                assert r.status == 200
                h = await r.json()
            assert h["status"] == "ok" and h["objectives"] == []

            # traces clamp regression on the live route
            async with c.http.get(
                    f"{base}/debug/traces?n=-1&slowest=999999999") as r:
                assert r.status == 200

    run(main())


def test_filer_recorder_twins(tmp_path):
    async def main():
        c = Cluster(str(tmp_path), n_servers=1)
        c.with_filer = True
        async with c:
            base = f"http://{c.filer.url}"
            async with c.http.post(
                    f"{base}/__debug__/timeline?snap=1") as r:
                assert r.status == 200
            async with c.http.get(f"{base}/__debug__/timeline") as r:
                assert r.status == 200
                assert "windows" in await r.json()
            async with c.http.get(f"{base}/__debug__/events") as r:
                assert r.status == 200
                assert "events" in await r.json()
            async with c.http.get(f"{base}/__debug__/health") as r:
                assert r.status == 200
                h = await r.json()
            assert h["status"] in ("ok", "warn", "page")

    run(main())


def test_master_recorder_routes(tmp_path):
    async def main():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            base = f"http://{c.master.url}"
            async with c.http.post(
                    f"{base}/debug/timeline?snap=1") as r:
                assert r.status == 200
            async with c.http.get(f"{base}/debug/events") as r:
                assert r.status == 200
            async with c.http.get(f"{base}/debug/health") as r:
                assert (await r.json())["status"] == "ok"

    run(main())
