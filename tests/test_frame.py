"""Binary frame wire (util/frame.py + server/frameserver.py).

Codec hardening (partial reassembly, torn/oversized/garbage corpus,
request-id reuse), the multiplexed channel (out-of-order completion,
FLAG_FALLBACK, fail-fast reconnect backoff), the sync pools'
max-idle/stale-retry discipline (the satellite connpool fix), and —
the acceptance bar — frame-vs-HTTP semantic parity against a REAL
in-proc volume server: byte-equal bodies through both transports,
Range/conditional/sendfile included, manifests downgrading to HTTP.
"""

from __future__ import annotations

import asyncio
import json
import random
import socket
import threading
import time

import pytest

from cluster_util import Cluster, run
from seaweedfs_tpu.util import failpoints as fp
from seaweedfs_tpu.util.frame import (
    FLAG_FALLBACK, Frame, FrameChannel, FrameChannelError, FrameDecoder,
    FrameError, FrameFallback, FrameHub, HEADER_SIZE, HELLO, HELLO_OK,
    MAGIC, MAX_FRAME, MAX_META, REQ, RESP, encode_frame, overhead_model)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


# ---------------------------------------------------------------- codec

def test_codec_roundtrip_meta_payload_flags():
    raw = encode_frame(REQ, 7, {"m": "GET", "p": "/3,01ab",
                                "q": {"x": "1"}}, b"payload", flags=1)
    dec = FrameDecoder()
    frames = dec.feed(raw)
    assert len(frames) == 1
    f = frames[0]
    assert (f.type, f.flags, f.req_id) == (REQ, 1, 7)
    assert f.meta == {"m": "GET", "p": "/3,01ab", "q": {"x": "1"}}
    assert f.payload == b"payload"
    assert not dec.pending


def test_codec_empty_meta_and_payload():
    frames = FrameDecoder().feed(encode_frame(HELLO_OK, 0))
    assert frames[0].meta == {} and frames[0].payload == b""


def test_partial_reassembly_byte_by_byte():
    raw = encode_frame(RESP, 3, {"s": 200}, b"x" * 100)
    dec = FrameDecoder()
    out = []
    for i in range(len(raw)):
        out += dec.feed(raw[i:i + 1])
        if i < len(raw) - 1:
            assert not out, f"frame completed early at byte {i}"
    assert len(out) == 1 and out[0].payload == b"x" * 100
    assert not dec.pending


def test_many_frames_single_feed_and_split_boundary():
    frames_raw = b"".join(encode_frame(REQ, i, {"m": "GET"}, b"b%d" % i)
                          for i in range(5))
    dec = FrameDecoder()
    # split at a deliberately frame-misaligned point
    cut = HEADER_SIZE + 3
    got = dec.feed(frames_raw[:cut]) + dec.feed(frames_raw[cut:])
    assert [f.req_id for f in got] == list(range(5))
    assert [f.payload for f in got] == [b"b%d" % i for i in range(5)]


def test_torn_oversized_garbage_frames_raise():
    import struct
    # declared length below the 12-byte fixed section
    torn = struct.pack(">IBBHQ", 4, REQ, 0, 0, 1)
    with pytest.raises(FrameError):
        FrameDecoder().feed(torn)
    # oversized declared length
    huge = struct.pack(">IBBHQ", MAX_FRAME + 1, REQ, 0, 0, 1)
    with pytest.raises(FrameError):
        FrameDecoder().feed(huge)
    # meta length exceeding the frame
    lying = struct.pack(">IBBHQ", 20, REQ, 0, 4000, 1) + b"\0" * 16
    with pytest.raises(FrameError):
        FrameDecoder().feed(lying)
    # meta that is not JSON
    bad = struct.pack(">IBBHQ", 12 + 4, REQ, 0, 4, 1) + b"!!!!"
    with pytest.raises(FrameError):
        FrameDecoder().feed(bad)
    # meta that is JSON but not an object
    arr = b"[1]"
    bad2 = struct.pack(">IBBHQ", 12 + len(arr), REQ, 0, len(arr), 1) + arr
    with pytest.raises(FrameError):
        FrameDecoder().feed(bad2)
    # oversized meta blob refused at encode time too
    with pytest.raises(FrameError):
        encode_frame(REQ, 1, {"k": "v" * (MAX_META + 1)})


def test_garbage_corpus_never_hangs_or_leaks_exceptions():
    """Fuzz-ish corpus: random byte streams fed in random-sized chunks
    either decode (improbable) or raise FrameError — never any other
    exception, never an infinite loop. Seeded => deterministic."""
    rng = random.Random(0xF7A3E)
    for case in range(200):
        blob = bytes(rng.getrandbits(8)
                     for _ in range(rng.randrange(1, 400)))
        dec = FrameDecoder()
        pos = 0
        try:
            while pos < len(blob):
                step = rng.randrange(1, 64)
                dec.feed(blob[pos:pos + step])
                pos += step
        except FrameError:
            continue                  # the expected refusal
        # stream happened to parse as incomplete/valid frames: fine


def test_valid_frames_then_garbage_tear():
    raw = encode_frame(RESP, 1, {"s": 200}, b"ok") + b"\xffGARBAGE" * 4
    dec = FrameDecoder()
    with pytest.raises(FrameError):
        dec.feed(raw)


def test_decoder_counts_overhead_not_payload():
    dec = FrameDecoder()
    dec.feed(encode_frame(RESP, 1, {"s": 200}, b"z" * 500))
    meta_len = len(json.dumps({"s": 200}, separators=(",", ":")))
    assert dec.overhead_bytes == HEADER_SIZE + meta_len
    assert dec.frames == 1


def test_overhead_model_is_deterministic_and_small():
    a = overhead_model("GET", "/3,01637037d6",
                       resp_headers={"Etag": '"5f328b31"'})
    b = overhead_model("GET", "/3,01637037d6",
                       resp_headers={"Etag": '"5f328b31"'})
    assert a == b
    # the point of the wire: per-needle protocol overhead far below
    # a typical HTTP request+response header pair (~350+ bytes)
    assert a < 200


# ------------------------------------------------- channel (loopback)

class _EchoFrameServer:
    """Minimal in-test frame peer with scriptable behaviors."""

    def __init__(self):
        self.server = None
        self.port = 0
        self.delay_ids: dict[int, float] = {}
        self.drop_ids: set[int] = set()
        self.fallback_ids: set[int] = set()
        self.reverse_batch = 0       # answer every N reqs in reverse
        self._batch: list = []
        self.seen_req_ids: list[int] = []
        self._writers: set = set()

    async def __aenter__(self):
        self.server = await asyncio.start_server(
            self._conn, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def __aexit__(self, *exc):
        self.server.close()
        await self.server.wait_closed()
        for w in list(self._writers):  # sever live connections too
            w.close()

    async def _conn(self, reader, writer):
        self._writers.add(writer)
        dec = FrameDecoder()
        try:
            while True:
                chunk = await reader.read(65536)
                if not chunk:
                    return
                if dec.frames == 0 and bytes(dec._buf) == b"" and \
                        chunk.startswith(MAGIC):
                    chunk = chunk[len(MAGIC):]
                for fr in dec.feed(chunk):
                    await self._handle(fr, writer)
        except (FrameError, OSError, asyncio.CancelledError):
            pass
        finally:
            self._writers.discard(writer)
            writer.close()

    async def _handle(self, fr: Frame, writer):
        if fr.type == HELLO:
            writer.write(encode_frame(HELLO_OK, fr.req_id, {"v": 1}))
            await writer.drain()
            return
        if fr.type != REQ:
            return
        self.seen_req_ids.append(fr.req_id)
        if fr.req_id in self.drop_ids:
            return                    # never answer: client times out
        if fr.req_id in self.fallback_ids:
            writer.write(encode_frame(RESP, fr.req_id, {"s": 421},
                                      flags=FLAG_FALLBACK))
            await writer.drain()
            return
        resp = encode_frame(
            RESP, fr.req_id,
            {"s": 200, "h": {"x-echo": fr.meta.get("p", "")}},
            fr.payload or fr.meta.get("p", "").encode())
        if self.reverse_batch > 1:
            self._batch.append((fr.req_id, resp))
            if len(self._batch) >= self.reverse_batch:
                for _, r in reversed(self._batch):
                    writer.write(r)
                self._batch.clear()
                await writer.drain()
            return
        delay = self.delay_ids.get(fr.req_id, 0)
        if delay:
            await asyncio.sleep(delay)
        writer.write(resp)
        await writer.drain()


def test_channel_pipelines_and_completes_out_of_order():
    async def body():
        async with _EchoFrameServer() as srv:
            srv.reverse_batch = 4     # hold 4, answer newest-first
            ch = FrameChannel(target=f"127.0.0.1:{srv.port}")
            try:
                results = await asyncio.gather(*(
                    ch.request("GET", f"/path-{i}") for i in range(4)))
                for i, (st, hdrs, body_) in enumerate(results):
                    assert st == 200
                    assert body_ == f"/path-{i}".encode()
                    assert hdrs["x-echo"] == f"/path-{i}"
                # all four multiplexed over ONE connection
                assert ch.stats.connects == 1
                assert ch.stats.requests == 4
            finally:
                await ch.close()
    run(body())


def test_channel_flag_fallback_raises_framefallback():
    async def body():
        async with _EchoFrameServer() as srv:
            srv.fallback_ids = {1}
            ch = FrameChannel(target=f"127.0.0.1:{srv.port}")
            try:
                with pytest.raises(FrameFallback):
                    await ch.request("GET", "/x")
                # FrameFallback IS a FrameChannelError (single except
                # arm downgrades to HTTP in every caller)
                assert issubclass(FrameFallback, FrameChannelError)
                st, _, _ = await ch.request("GET", "/y")  # channel fine
                assert st == 200
            finally:
                await ch.close()
    run(body())


def test_request_id_reuse_after_timeout_and_wraparound():
    """A timed-out id must not poison its successor: the late response
    for the dead id is discarded, and the 32-bit id counter wraps
    through (skipping 0) without colliding."""
    async def body():
        async with _EchoFrameServer() as srv:
            srv.drop_ids = {1}
            ch = FrameChannel(target=f"127.0.0.1:{srv.port}")
            try:
                with pytest.raises(FrameChannelError):
                    await ch.request("GET", "/dead", timeout=0.2)
                # id 1 timed out; later reuse of the SLOT is clean
                st, _, got = await ch.request("GET", "/alive")
                assert st == 200 and got == b"/alive"
                # wraparound: next id after 0xFFFFFFFF is 1, never 0
                # (and the reused id 1 must answer normally now)
                srv.drop_ids = set()
                ch._next_id = 0xFFFFFFFF
                st, _, _ = await ch.request("GET", "/wrap")
                assert st == 200
                assert ch._next_id == 1
                st, _, _ = await ch.request("GET", "/wrapped")
                assert st == 200
                assert srv.seen_req_ids[-2:] == [0xFFFFFFFF, 1]
            finally:
                await ch.close()
    run(body())


def test_channel_fail_fast_backoff_then_reconnect():
    async def body():
        async with _EchoFrameServer() as srv:
            port = srv.port
            ch = FrameChannel(target=f"127.0.0.1:{port}")
            st, _, _ = await ch.request("GET", "/up")
            assert st == 200
            await srv.__aexit__()
            # sever: in-flight-free channel notices on next use
            with pytest.raises(FrameChannelError):
                await ch.request("GET", "/down", timeout=1.0)
            # backoff window open: fails in microseconds, no connect
            t0 = time.monotonic()
            with pytest.raises(FrameChannelError):
                await ch.request("GET", "/fast-fail")
            assert time.monotonic() - t0 < 0.05
            # peer returns on the same port; after the window, the
            # channel transparently reconnects
            srv2 = _EchoFrameServer()
            srv2.server = await asyncio.start_server(
                srv2._conn, "127.0.0.1", port)
            try:
                deadline = time.monotonic() + 5
                while True:
                    try:
                        st, _, _ = await ch.request("GET", "/back")
                        break
                    except FrameChannelError:
                        assert time.monotonic() < deadline
                        await asyncio.sleep(0.05)
                assert st == 200 and ch.stats.connects == 2
            finally:
                await ch.close()
                srv2.server.close()
                await srv2.server.wait_closed()
    run(body())


def test_worker_frame_failpoint_fires_on_request():
    async def body():
        async with _EchoFrameServer() as srv:
            ch = FrameChannel(target=f"127.0.0.1:{srv.port}")
            try:
                fp.arm("worker.frame", "error:1")
                with pytest.raises(OSError):
                    await ch.request("GET", "/x")
                st, _, _ = await ch.request("GET", "/x")
                assert st == 200
            finally:
                await ch.close()
    run(body())


def test_hub_caches_and_bounds_channels():
    async def body():
        hub = FrameHub()
        try:
            a = hub.get(target="127.0.0.1:1")
            assert hub.get(target="127.0.0.1:1") is a
            for i in range(2, FrameHub.MAX_CHANNELS + 2):
                hub.get(target=f"127.0.0.1:{i}")
            assert len(hub._channels) <= FrameHub.MAX_CHANNELS
        finally:
            await hub.close()
    run(body())


# ------------------------------------------------- sync pools

def test_idle_pool_max_idle_eviction():
    from seaweedfs_tpu.util.connpool import _IdlePool

    class _C:
        closed = False

        def close(self):
            self.closed = True

    pool = _IdlePool(per_target=4, max_idle_s=0.05)
    c1 = _C()
    pool.give("t", c1)
    assert pool.take("t") is c1       # fresh: reused
    pool.give("t", c1)
    time.sleep(0.08)
    assert pool.take("t") is None     # parked too long: evicted...
    assert c1.closed                  # ...and closed, not leaked


def test_idle_pool_drop_target_closes_all():
    from seaweedfs_tpu.util.connpool import _IdlePool

    class _C:
        closed = False

        def close(self):
            self.closed = True

    pool = _IdlePool(per_target=4, max_idle_s=60)
    conns = [_C() for _ in range(3)]
    for c in conns:
        pool.give("t", c)
    pool.give("other", _C())
    pool.drop_target("t")
    assert all(c.closed for c in conns)
    assert pool.take("t") is None
    assert pool.take("other") is not None   # other targets untouched


def _http_server_that_closes_after_each_response():
    """Keep-alive-claiming HTTP server that actually closes every
    connection after one response — the respawned-sibling shape that
    poisons a pooled socket."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    port = srv.getsockname()[1]
    served = []

    def loop():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            try:
                conn.recv(65536)
                conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n"
                             b"\r\nok")
                served.append(1)
            finally:
                conn.close()

    th = threading.Thread(target=loop, daemon=True)
    th.start()
    return srv, port, served


def test_sync_http_pool_retries_stale_and_drains_target():
    from seaweedfs_tpu.util.connpool import SyncHttpPool
    srv, port, served = _http_server_that_closes_after_each_response()
    try:
        pool = SyncHttpPool(timeout=5)
        target = f"127.0.0.1:{port}"
        st, body = pool.request(target, "/a")
        assert (st, body) == (200, b"ok")
        # server closed the socket; the pool may have parked it (the
        # response did not declare close) — next request must retry
        # fresh instead of surfacing the stale-socket error
        st, body = pool.request(target, "/b")
        assert (st, body) == (200, b"ok")
        pool.close()
    finally:
        srv.close()


def test_sync_frame_pool_refuses_http_peer_as_unsupported():
    from seaweedfs_tpu.util.connpool import (FrameUnsupported,
                                             SyncFramePool)
    srv, port, _ = _http_server_that_closes_after_each_response()
    try:
        pool = SyncFramePool(timeout=5)
        with pytest.raises(FrameUnsupported):
            pool.request(f"127.0.0.1:{port}", "/admin/ec/shard_read",
                         query={"volume": "1", "reads": "0:0:10"})
        pool.close()
    finally:
        srv.close()


def test_sync_frame_pool_roundtrip_and_stale_retry(tmp_path):
    """SyncFramePool against the REAL frame listener: a pooled
    connection severed between uses is retried fresh (the respawn
    shape), and the reads come back byte-equal."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()
            vs = c.servers[0]
            payload = b"sync-frame-pool" * 10
            st, _ = await c.put(a["fid"], a["url"], payload)
            assert st == 201
            from seaweedfs_tpu.util.connpool import SyncFramePool
            pool = SyncFramePool(timeout=10)
            target = f"127.0.0.1:{vs.port}"

            def fetch():
                return pool.request(target, "/" + a["fid"])

            st, body_ = await asyncio.to_thread(fetch)
            assert (st, body_) == (200, payload)
            # sever the parked connection under the pool
            for _, conn in pool._pool._idle.get(target, []):
                conn.sock.close()
            st, body_ = await asyncio.to_thread(fetch)
            assert (st, body_) == (200, payload)
            pool.close()
    run(body())


# --------------------------------------- frame vs HTTP semantic parity

async def _frame_get(ch, path, headers=None):
    return await ch.request("GET", path, headers=headers)


def test_frame_parity_with_http_listener(tmp_path):
    """The acceptance bar: the SAME needles served over the frame
    adapter and the HTTP listeners are byte-equal — plain, ranged
    (suffix/open-ended), conditional 304, HEAD, 404 and the sendfile
    cold path included."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()
            vs = c.servers[0]
            fid = a["fid"]
            payload = bytes(range(256)) * 4
            st, _ = await c.put(fid, a["url"], payload)
            assert st == 201
            big = await c.assign()
            bigbody = bytes((i * 131 + 17) % 256 for i in range(300_000))
            st, _ = await c.put(big["fid"], big["url"], bigbody)
            assert st == 201

            ch = FrameChannel(target=f"127.0.0.1:{vs.port}")
            try:
                # plain read
                fst, fh, fbody = await _frame_get(ch, "/" + fid)
                async with c.http.get(
                        f"http://{a['url']}/{fid}") as r:
                    hbody = await r.read()
                    assert (fst, fbody) == (r.status, hbody)
                    assert fh["Etag"] == r.headers["Etag"]
                # ranges: suffix, open-ended, bounded
                for rng, want in (("bytes=5-9", payload[5:10]),
                                  ("bytes=1000-", payload[1000:]),
                                  ("bytes=-24", payload[-24:])):
                    fst, fh, fbody = await _frame_get(
                        ch, "/" + fid, headers={"range": rng})
                    async with c.http.get(
                            f"http://{a['url']}/{fid}",
                            headers={"Range": rng}) as r:
                        assert fst == r.status == 206
                        assert fbody == await r.read() == want
                        assert fh["Content-Range"] == \
                            r.headers["Content-Range"]
                # conditional 304
                fst, fh, fbody = await _frame_get(ch, "/" + fid)
                etag = fh["Etag"]
                fst, _, fbody = await _frame_get(
                    ch, "/" + fid, headers={"if-none-match": etag})
                assert (fst, fbody) == (304, b"")
                # HEAD: headers, no body — parity with the HTTP
                # listener's HEAD answer (same status, same Etag)
                hst, hh, hb = await ch.request("HEAD", "/" + fid)
                assert hst == 200 and hb == b""
                async with c.http.head(
                        f"http://{a['url']}/{fid}") as r:
                    assert r.status == 200
                    assert hh["Etag"] == r.headers["Etag"]
                # 404
                missing = fid.split(",")[0] + ",ffffffffdeadbeef"
                fst, _, _ = await _frame_get(ch, "/" + missing)
                assert fst == 404
                # sendfile cold path: large body, frame-declared
                # length, byte-equal with the HTTP listener
                fst, fh, fbody = await _frame_get(ch, "/" + big["fid"])
                assert fst == 200 and fbody == bigbody
                assert ch.stats.payload_in >= len(bigbody)
                # ranged sendfile slice
                fst, _, fbody = await _frame_get(
                    ch, "/" + big["fid"],
                    headers={"range": "bytes=250000-"})
                assert fst == 206 and fbody == bigbody[250000:]
                # pipelined-after-sendfile: the frame stream stays in
                # sync after a sendfile payload
                results = await asyncio.gather(
                    _frame_get(ch, "/" + big["fid"]),
                    _frame_get(ch, "/" + fid),
                    _frame_get(ch, "/" + fid))
                assert results[0][2] == bigbody
                assert results[1][2] == results[2][2] == payload
                assert ch.stats.connects == 1
            finally:
                await ch.close()
    run(body())


def test_frame_write_delete_parity(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            ch = FrameChannel(target=f"127.0.0.1:{vs.port}")
            try:
                a = await c.assign()
                st, _, body_ = await ch.request(
                    "POST", "/" + a["fid"], body=b"frame-written")
                assert st == 201, body_
                fsize = json.loads(body_)["size"]
                # same-length HTTP write reports the same stored size
                b2 = await c.assign()
                hst, hbody = await c.put(b2["fid"], b2["url"],
                                         b"http--written")
                assert hst == 201 and hbody["size"] == fsize
                # readback over BOTH transports
                fst, _, fbody = await ch.request("GET", "/" + a["fid"])
                async with c.http.get(
                        f"http://{a['url']}/{a['fid']}") as r:
                    assert fbody == await r.read() == b"frame-written"
                    assert fst == r.status == 200
                st, _, _ = await ch.request("DELETE", "/" + a["fid"])
                assert st == 200
                fst, _, _ = await ch.request("GET", "/" + a["fid"])
                assert fst == 404
            finally:
                await ch.close()
    run(body())


def test_frame_manifest_read_downgrades_to_http(tmp_path):
    """A chunked-manifest GET cannot stream over one frame: the server
    answers FLAG_FALLBACK and the client retries over HTTP — the
    exact degradation an old peer produces."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            from seaweedfs_tpu.util.chunked import upload_in_chunks
            from seaweedfs_tpu.util.client import WeedClient
            data = bytes((i * 13 + 5) % 256 for i in range(300_000))
            async with WeedClient(c.master.url) as wc:
                mfid, _ = await upload_in_chunks(wc, data, 1)
            ch = FrameChannel(target=f"127.0.0.1:{vs.port}")
            try:
                with pytest.raises(FrameFallback):
                    await ch.request("GET", "/" + mfid)
                assert ch.stats.fallbacks == 1
                # HTTP serves the assembled file
                async with c.http.get(
                        f"http://127.0.0.1:{vs.port}/{mfid}") as r:
                    assert r.status == 200
                    assert await r.read() == data
            finally:
                await ch.close()
    run(body())


def test_frame_batch_parity(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            fids = {}
            for i in range(5):
                a = await c.assign()
                body_ = f"batch-{i}-".encode() * 20
                st, _ = await c.put(a["fid"], a["url"], body_)
                assert st == 201
                fids[a["fid"]] = body_
            ask = list(fids)
            from seaweedfs_tpu.util.batchframe import parse_all
            ch = FrameChannel(target=f"127.0.0.1:{vs.port}")
            try:
                fst, _, fraw = await ch.request(
                    "GET", "/batch", query={"fids": ",".join(ask)})
                async with c.http.get(
                        f"http://127.0.0.1:{vs.port}/batch",
                        params={"fids": ",".join(ask)}) as r:
                    hraw = await r.read()
                    assert fst == r.status == 200
                # byte-equal framing through both transports
                assert fraw == hraw
                rows = parse_all(fraw)
                assert [m["fid"] for m, _ in rows] == ask
                assert all(fids[m["fid"]] == b for m, b in rows)
            finally:
                await ch.close()
    run(body())


# --------------------------------------- client pipelined multi-read

def test_weedclient_pipelined_read(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            from seaweedfs_tpu.util.client import WeedClient
            async with WeedClient(c.master.url) as wc:
                fids = {}
                for i in range(12):
                    a = await c.assign()
                    body_ = f"pipe-{i}-".encode() * 10
                    st, _ = await c.put(a["fid"], a["url"], body_)
                    assert st == 201
                    fids[a["fid"]] = body_
                missing = next(iter(fids)).split(",")[0] + \
                    ",ffffffffdeadbeef"
                ask = list(fids) + [missing]
                got = await wc.pipelined_read(ask, depth=4)
                assert got[missing] is None
                for fid, body_ in fids.items():
                    assert got[fid] == body_
                # all needles rode ONE multiplexed connection (the
                # hub also holds a master channel now that lookups
                # ride frames — pick the busy data channel)
                stats = max(wc.frame_hub.stats_dict().values(),
                            key=lambda s: s["requests"])
                assert stats["connects"] == 1
                assert stats["requests"] == len(ask)
                assert stats["fallbacks"] == 0
    run(body())


def test_weedclient_pipelined_read_falls_back_on_channel_fault(tmp_path):
    """client.pipeline failpoint severs every frame request: the
    results must still be correct, served via the HTTP path."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            from seaweedfs_tpu.util.client import WeedClient
            async with WeedClient(c.master.url) as wc:
                fids = {}
                for i in range(4):
                    a = await c.assign()
                    body_ = f"fb-{i}-".encode() * 10
                    st, _ = await c.put(a["fid"], a["url"], body_)
                    assert st == 201
                    fids[a["fid"]] = body_
                fp.arm("client.pipeline", "error")
                got = await wc.pipelined_read(list(fids), depth=2)
                for fid, body_ in fids.items():
                    assert got[fid] == body_
    run(body())


# ------------------------------------------------- review hardening

def test_sibling_forward_gates_external_mutations(tmp_path):
    """On a jwt-secured cluster an identity-less frame HELLO is
    refused outright (GOAWAY before any payload is served): an
    untokened client never reaches the token-marked sibling forward
    at all, and a properly-identified channel confirms the needle was
    genuinely never written."""
    async def body():
        from seaweedfs_tpu.server.volume_server import VolumeServer
        from seaweedfs_tpu.server.workers import WorkerContext
        from seaweedfs_tpu.storage.store import Store
        async with Cluster(str(tmp_path), n_servers=0) as c:
            import os as _os
            state_dir = str(tmp_path / "wstate")
            d = str(tmp_path / "wdata")
            workers = []
            for i in range(2):
                ctx = WorkerContext(i, 2, 0, state_dir, token="tok")
                store = Store([d], max_volume_counts=[16],
                              partition=(i, 2))
                vs = VolumeServer(store, c.master.url, port=0,
                                  pulse_seconds=0.2, worker_ctx=ctx,
                                  jwt_key="secret")
                await vs.start()
                workers.append(vs)
            for vs in workers:
                vs.store.public_url = workers[0].url
                await vs.heartbeat_once()
            try:
                # a fid on an ODD vid => worker 0 must forward it
                fid = None
                for _ in range(16):
                    a = await c.assign()
                    if int(a["fid"].split(",")[0]) % 2 == 1:
                        fid = a["fid"]
                        break
                assert fid is not None
                ch = FrameChannel(
                    target=f"127.0.0.1:{workers[0].port}")
                try:
                    # write AND delete for the sibling-owned vid: the
                    # identity-less HELLO is refused before any
                    # payload is served
                    with pytest.raises(FrameChannelError,
                                       match="handshake refused"):
                        await ch.request("POST", "/" + fid,
                                         body=b"laundered?")
                    with pytest.raises(FrameChannelError):
                        await ch.request("DELETE", "/" + fid)
                finally:
                    await ch.close()
                # the needle was genuinely never written: ask over a
                # channel carrying a verifiable jwt identity
                ch2 = FrameChannel(
                    target=f"127.0.0.1:{workers[0].port}",
                    jwt_key="secret")
                try:
                    st, _, _ = await ch2.request("GET", "/" + fid)
                    assert st == 404
                finally:
                    await ch2.close()
            finally:
                for vs in workers:
                    await vs.stop()
    run(body())


def test_oversized_response_downgrades_not_tears(tmp_path, monkeypatch):
    """A body that would exceed the peer decoder's MAX_FRAME answers
    FLAG_FALLBACK (one request rides HTTP) instead of emitting a
    frame that kills the whole multiplexed channel."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            from seaweedfs_tpu.server import frameserver
            monkeypatch.setattr(frameserver, "MAX_FRAME", (1 << 20) + 4096)
            vs = c.servers[0]
            a = await c.assign()
            small = await c.assign()
            big = b"x" * 8192            # > (MAX_FRAME - 1MB) = 4096
            st, _ = await c.put(a["fid"], a["url"], big)
            assert st == 201
            st, _ = await c.put(small["fid"], small["url"], b"tiny")
            assert st == 201
            ch = FrameChannel(target=f"127.0.0.1:{vs.port}")
            try:
                with pytest.raises(FrameFallback):
                    await ch.request("GET", "/" + a["fid"])
                # the channel survived: other requests still answer
                st, _, got = await ch.request("GET", "/" + small["fid"])
                assert (st, got) == (200, b"tiny")
                assert ch.stats.connects == 1
            finally:
                await ch.close()
    run(body())


def test_oversize_meta_does_not_leak_pending():
    """encode_frame rejecting an oversized meta must leave _pending
    empty — a leaked entry would flip the reader loop onto the 30s
    response timeout and tear healthy channels."""
    async def body():
        async with _EchoFrameServer() as srv:
            ch = FrameChannel(target=f"127.0.0.1:{srv.port}")
            try:
                st, _, _ = await ch.request("GET", "/warm")
                assert st == 200
                from seaweedfs_tpu.util.frame import MAX_META
                with pytest.raises(FrameError):
                    await ch.request(
                        "GET", "/x",
                        headers={"h": "v" * (MAX_META + 1)})
                assert not ch._pending
                st, _, _ = await ch.request("GET", "/still-fine")
                assert st == 200
            finally:
                await ch.close()
    run(body())


def test_teardown_fails_pending_even_without_error():
    """The idle-close race: a future registered as the reader loop
    idles out must be failed by _teardown, not left to its 30s
    request timeout."""
    async def body():
        async with _EchoFrameServer() as srv:
            ch = FrameChannel(target=f"127.0.0.1:{srv.port}")
            try:
                st, _, _ = await ch.request("GET", "/warm")
                assert st == 200
                loop = asyncio.get_running_loop()
                fut = loop.create_future()
                ch._pending[99] = fut
                ch._teardown(ch._writer, None)       # idle path: no err
                assert fut.done()
                with pytest.raises(FrameChannelError):
                    fut.result()
            finally:
                await ch.close()
    run(body())


def test_cancelled_request_leaves_no_pending_entry():
    """The PR-20 cancel-leak fix: a requester cancelled while awaiting
    its response must pop its _pending registration on the way out
    (finally), not leak it until response arrival or teardown — a
    leaked entry pins the reader loop's timeout accounting."""
    async def body():
        async with _EchoFrameServer() as srv:
            ch = FrameChannel(target=f"127.0.0.1:{srv.port}")
            try:
                st, _, _ = await ch.request("GET", "/warm")
                assert st == 200
                next_id = srv.seen_req_ids[-1] + 1
                srv.drop_ids = {next_id}     # never answered
                t = asyncio.create_task(ch.request("GET", "/hang"))
                await asyncio.sleep(0.05)    # parked awaiting the resp
                assert next_id in ch._pending
                t.cancel()
                with pytest.raises(asyncio.CancelledError):
                    await t
                assert not ch._pending       # registration dropped
                assert ch._inflight == 0     # slot released too
                st, _, _ = await ch.request("GET", "/still-fine")
                assert st == 200
            finally:
                await ch.close()
    run(body())


def test_cancelled_window_waiter_does_not_shrink_window():
    """The PR-20 _acquire_slot fix: a waiter cancelled while parked on
    the congestion window must leave the queue AND give back any slot
    reserved for it in the same tick — the old shape permanently
    shrank the window by one per cancelled waiter."""
    async def body():
        async with _EchoFrameServer() as srv:
            ch = FrameChannel(target=f"127.0.0.1:{srv.port}")
            try:
                st, _, _ = await ch.request("GET", "/warm")
                assert st == 200
                ch._cwnd = 1.0               # one slot total
                slow_id = srv.seen_req_ids[-1] + 1
                srv.delay_ids = {slow_id: 0.2}
                t1 = asyncio.create_task(ch.request("GET", "/slow"))
                await asyncio.sleep(0.05)    # t1 owns the only slot
                t2 = asyncio.create_task(ch.request("GET", "/parked"))
                await asyncio.sleep(0.05)    # t2 queued on the window
                assert len(ch._win_waiters) == 1
                t2.cancel()
                with pytest.raises(asyncio.CancelledError):
                    await t2
                assert not ch._win_waiters   # queue entry dropped
                st, _, _ = await t1
                assert st == 200
                assert ch._inflight == 0     # window fully restored
                st, _, _ = await ch.request("GET", "/after")
                assert st == 200
            finally:
                await ch.close()
    run(body())
