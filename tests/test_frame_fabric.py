"""The inter-host frame fabric: replication fan-out, raft RPCs, EC
shard gather, heartbeat/lookup and client data hops all ride the
multiplexed binary wire by default, fall back to HTTP byte-identically
when the frame leg is severed, refuse unauthenticated HELLOs on a
jwt-secured cluster before any payload, and fail pending requests
immediately when a channel dies mid-pipeline."""

from __future__ import annotations

import asyncio
import json
import random
import socket
import time

import pytest

from cluster_util import Cluster, run

from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.util import events
from seaweedfs_tpu.util import failpoints as fp
from seaweedfs_tpu.util.client import WeedClient
from seaweedfs_tpu.util.connpool import FrameProbeGate
from seaweedfs_tpu.util.frame import (FrameChannel, FrameChannelError,
                                      FrameDecoder, HELLO, HELLO_OK,
                                      MAGIC, REQ, encode_frame)


@pytest.fixture(autouse=True)
def _clean():
    fp.reset()
    events.reset()
    yield
    fp.reset()
    events.reset()


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _fid_parts(fid: str) -> tuple[int, int]:
    vid, rest = fid.split(",")
    return int(vid), int(rest[:-8], 16)


async def _put_replicated(c: Cluster, data: bytes) -> str:
    a = await c.assign(replication="001")
    assert "fid" in a, a
    st, _ = await c.put(a["fid"], a["url"], data)
    assert st == 201
    return a["fid"]


def _holders(c: Cluster, vid: int):
    return [vs for vs in c.servers if vid in vs.store.volumes]


# ---------------------------------------------------------------------------
# replication fan-out
# ---------------------------------------------------------------------------

def test_replication_fanout_rides_frames_byte_identical(tmp_path):
    """A replicated write fans out over a frame channel; both holders
    end with byte-identical needles. With the frame leg severed
    (replication.frame armed) the HTTP fallback produces the SAME
    bytes — the two transports are provably interchangeable."""
    async def go():
        async with Cluster(str(tmp_path), n_servers=3) as c:
            data_a = b"frame-fabric-fanout" * 911
            fid_a = await _put_replicated(c, data_a)
            vid, key = _fid_parts(fid_a)
            holders = _holders(c, vid)
            assert len(holders) == 2, [vs.url for vs in c.servers]
            got = [vs.store.read_needle(vid, key).data for vs in holders]
            assert got[0] == got[1] == data_a
            # the fan-out hop really rode a frame channel: the primary
            # holder's hub has an inter-host channel to its replica
            fanout_reqs = sum(
                s["requests"]
                for vs in holders
                for tgt, s in vs.frame_hub.stats_dict().items()
                if any(tgt == peer.url for peer in c.servers))
            assert fanout_reqs >= 1, \
                [vs.frame_hub.stats_dict() for vs in holders]

            # sever the frame leg: every fan-out attempt errors and
            # the write must ride HTTP, still byte-identical
            fp.arm("replication.frame", "error:*")
            data_b = b"http-fallback-fanout" * 907
            fid_b = await _put_replicated(c, data_b)
            vid_b, key_b = _fid_parts(fid_b)
            holders_b = _holders(c, vid_b)
            assert len(holders_b) == 2
            got_b = [vs.store.read_needle(vid_b, key_b).data
                     for vs in holders_b]
            assert got_b[0] == got_b[1] == data_b

            # delete propagates to both holders (frames again)
            fp.reset()
            assert await c.delete(fid_a, holders[0].url) == 200
            await asyncio.sleep(0.05)
            for vs in holders:
                st, _ = await c.get(fid_a, vs.url)
                assert st == 404, vs.url
    run(go())


# ---------------------------------------------------------------------------
# heartbeat / lookup / client hops
# ---------------------------------------------------------------------------

def test_control_plane_and_client_hops_ride_frames(tmp_path):
    """Volume->master heartbeats and client->master lookups plus
    client->volume reads all travel frame channels by default."""
    async def go():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            data = b"hop-check" * 512
            fid = await _put_replicated(c, data)
            await c.heartbeat_all()
            # every volume server holds a frame channel to the master
            # with at least one completed request (the heartbeat)
            for vs in c.servers:
                stats = vs.frame_hub.stats_dict()
                master_stats = [s for tgt, s in stats.items()
                                if tgt == c.master.url]
                assert master_stats and master_stats[0]["requests"] >= 1, \
                    stats
            async with WeedClient(c.master.url) as wc:
                assert await wc.read(fid) == data
                stats = wc.frame_hub.stats_dict()
                # lookup rode a frame to the master AND the data GET
                # rode a frame to a volume server
                assert any(tgt == c.master.url and s["requests"] >= 1
                           for tgt, s in stats.items()), stats
                assert any(tgt != c.master.url and s["requests"] >= 1
                           for tgt, s in stats.items()), stats

                # upload + delete over frames round-trip too
                blob = b"client-frame-write" * 64
                fid2 = await wc.upload_data(blob)
                assert await wc.read(fid2) == blob
                await wc.delete_fids([fid2])
                with pytest.raises(Exception):
                    await wc.read(fid2)
    run(go())


# ---------------------------------------------------------------------------
# raft over frames
# ---------------------------------------------------------------------------

async def _make_masters(n: int = 3) -> list[MasterServer]:
    ports = _free_ports(n)
    urls = [f"127.0.0.1:{p}" for p in ports]
    masters = []
    for p in ports:
        m = MasterServer(port=p, pulse_seconds=0.1, peers=urls,
                         election_timeout=(0.4, 0.8),
                         election_pulse=0.1)
        await m.start()
        masters.append(m)
    return masters


async def _wait_single_leader(masters, timeout: float = 10.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        live = [m for m in masters if m.election is not None]
        leaders = [m for m in live if m.is_leader]
        agreed = {m.leader_url for m in live}
        if len(leaders) == 1 and agreed == {leaders[0].url}:
            return leaders[0]
        await asyncio.sleep(0.05)
    raise AssertionError(
        f"no stable leader: roles={[m.election.role for m in masters]}")


def test_raft_rpcs_ride_frames_and_survive_frame_loss(tmp_path):
    """Vote/append RPCs default to frame channels (hub traffic is
    observable on the leader); with master.raft.frame armed the
    quorum still re-elects after leader death via the HTTP fallback."""
    async def go():
        masters = await _make_masters(3)
        stopped: set = set()
        try:
            leader = await _wait_single_leader(masters)
            # let a few heartbeat rounds run, then check the fabric
            await asyncio.sleep(0.4)
            hub = leader.election.frame_hub
            assert hub is not None
            reqs = sum(s["requests"] for s in hub.stats_dict().values())
            assert reqs >= 2, hub.stats_dict()   # appends to 2 peers

            # sever EVERY raft frame leg and kill the leader: the
            # remaining pair must still elect over HTTP
            fp.arm("master.raft.frame", "error:*")
            survivors = [m for m in masters if m is not leader]
            await leader.stop()
            stopped.add(leader)
            new_leader = await _wait_single_leader(survivors)
            assert new_leader is not leader
        finally:
            for m in masters:
                if m not in stopped:
                    await m.stop()
    run(go())


# ---------------------------------------------------------------------------
# EC inter-host gather
# ---------------------------------------------------------------------------

def test_ec_gather_falls_back_to_http_when_frames_sever(tmp_path):
    """Cross-host EC shard gather rides the sync frame pool; with
    ec.fetch.frame armed every gather rides HTTP instead and reads
    stay byte-exact."""
    async def go():
        from seaweedfs_tpu.shell.env import CommandEnv
        from seaweedfs_tpu.shell import ec_commands as ec
        async with Cluster(str(tmp_path), n_servers=3) as c:
            rng = random.Random(7)
            files = []
            for _ in range(12):
                a = await c.assign(collection="ecfab")
                data = bytes(rng.getrandbits(8)
                             for _ in range(rng.randint(800, 6000)))
                st, _ = await c.put(a["fid"], a["url"], data)
                assert st == 201
                files.append((a["fid"], data))
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                vids = sorted({int(f.split(",")[0]) for f, _ in files})
                res = await ec.ec_encode(env, collection="ecfab",
                                         vids=vids)
                assert res, "ec.encode produced no results"

            # reads from any server now require cross-host gather;
            # default path (frames) first...
            for fid, data in files[:4]:
                for vs in c.servers:
                    st, got = await c.get(fid, vs.url)
                    assert st == 200 and got == data, (fid, vs.url)
            # ...then with the frame leg severed: HTTP fallback only
            fp.arm("ec.fetch.frame", "error:*")
            for fid, data in files[4:8]:
                for vs in c.servers:
                    st, got = await c.get(fid, vs.url)
                    assert st == 200 and got == data, \
                        ("http-fallback", fid, vs.url)
    run(go())


# ---------------------------------------------------------------------------
# HELLO authentication
# ---------------------------------------------------------------------------

def test_master_refuses_unauthenticated_hello_on_jwt_cluster(tmp_path):
    """On a jwt-secured cluster the master's frame listener refuses an
    identity-less or wrong-key HELLO at the handshake — before any
    request payload crosses the wire — while the correct key works."""
    async def go():
        port = _free_ports(1)[0]
        m = MasterServer(port=port, pulse_seconds=0.1,
                         jwt_key="fabric-secret")
        await m.start()
        try:
            # no identity at all
            chan = FrameChannel(target=m.url)
            with pytest.raises(FrameChannelError,
                               match="handshake refused"):
                await chan.request("GET", "/dir/assign", timeout=5.0)
            await chan.close()
            # wrong key: signature check fails, same refusal
            chan = FrameChannel(target=m.url, jwt_key="wrong-secret")
            with pytest.raises(FrameChannelError,
                               match="handshake refused"):
                await chan.request("GET", "/dir/assign", timeout=5.0)
            await chan.close()
            # right key: handshake accepted, request served (404 —
            # the bare master holds no volumes — but it ANSWERED,
            # which an unauthenticated channel never got to)
            chan = FrameChannel(target=m.url, jwt_key="fabric-secret")
            status, _, body = await chan.request(
                "GET", "/dir/lookup", query={"volumeId": "1"},
                timeout=5.0)
            assert status in (200, 404), (status, body)
            assert isinstance(json.loads(body), dict)
            await chan.close()
        finally:
            await m.stop()
    run(go())


# ---------------------------------------------------------------------------
# probe gate (sticky-downgrade fix)
# ---------------------------------------------------------------------------

class _FixedRng:
    def __init__(self, v: float):
        self.v = v

    def random(self) -> float:
        return self.v


def test_probe_gate_backoff_doubles_caps_and_journals():
    now = [0.0]
    gate = FrameProbeGate(base_s=1.0, cap_s=8.0, rng=_FixedRng(0.5),
                          clock=lambda: now[0])
    t = "10.0.0.9:8080"
    assert gate.allow(t)                      # never refused: probe
    # rng 0.5 -> jitter multiplier exactly 1.0: delays are the pure
    # doubling sequence 1, 2, 4, 8, then capped at 8
    assert gate.refused(t, "no frame listener") == pytest.approx(1.0)
    assert not gate.allow(t)                  # inside the backoff
    now[0] = 1.01
    assert gate.allow(t)                      # window elapsed: reprobe
    assert gate.refused(t) == pytest.approx(2.0)
    assert gate.refused(t) == pytest.approx(4.0)
    assert gate.refused(t) == pytest.approx(8.0)
    assert gate.refused(t) == pytest.approx(8.0)   # capped, not sticky
    # success clears the strikes entirely
    gate.ok(t)
    assert gate.allow(t)
    assert gate.refused(t) == pytest.approx(1.0)
    # every refusal journaled a frame_downgrade with the evidence
    rows = events.events_dict(types={"frame_downgrade"})["events"]
    assert len(rows) == 6
    assert rows[-1]["target"] == t
    assert rows[-1]["strikes"] == 1
    assert rows[-1]["reason"] == "no frame listener"
    assert rows[-1]["retry_in_s"] == pytest.approx(1.0)


def test_probe_gate_jitter_spans_half_to_one_and_a_half():
    lo = FrameProbeGate(base_s=2.0, rng=_FixedRng(0.0),
                        clock=lambda: 0.0)
    hi = FrameProbeGate(base_s=2.0, rng=_FixedRng(0.999),
                        clock=lambda: 0.0)
    assert lo.refused("a") == pytest.approx(1.0)       # 2.0 * 0.5
    assert hi.refused("a") == pytest.approx(2.998)     # 2.0 * 1.499


# ---------------------------------------------------------------------------
# congestion window (AIMD)
# ---------------------------------------------------------------------------

def test_congestion_window_aimd_shrink_grow_clamps():
    ch = FrameChannel(target="127.0.0.1:1")
    assert ch.window == FrameChannel.CWND_INIT
    ch._rtt_best = float("inf")
    ch._observe_rtt(0.010)                 # sets the floor; grows
    assert ch.stats.window_grows == 1
    cwnd_before = ch._cwnd
    ch._observe_rtt(0.050)                 # > 2x floor: shrink x0.7
    assert ch.stats.window_shrinks == 1
    assert ch._cwnd == pytest.approx(cwnd_before * 0.7)
    # sustained queueing shrinks to CWND_MIN and clamps there
    for _ in range(50):
        ch._observe_rtt(1.0)
    assert ch.window == FrameChannel.CWND_MIN
    shrinks = ch.stats.window_shrinks
    ch._observe_rtt(1.0)                   # at the floor: no shrink
    assert ch.stats.window_shrinks == shrinks
    # clean RTTs grow additively back up to CWND_MAX and clamp
    for _ in range(5000):
        ch._observe_rtt(0.010)
    assert ch.window == FrameChannel.CWND_MAX
    assert ch.stats.window_grows > 1


# ---------------------------------------------------------------------------
# severed channel fast-fail
# ---------------------------------------------------------------------------

def test_severed_channel_fails_pending_requests_immediately():
    """Requests pipelined on a channel whose peer dies mid-flight must
    fail with FrameChannelError as soon as the socket closes — not
    after the 30s request timeout."""
    async def go():
        conns = []

        async def handle(reader, writer):
            conns.append(writer)
            dec = FrameDecoder()
            reqs = 0
            first = True
            while reqs < 4:                # swallow the pipeline...
                data = await reader.read(64 * 1024)
                if not data:
                    return
                if first and data.startswith(MAGIC):
                    data = data[len(MAGIC):]
                first = False
                for f in dec.feed(data):
                    if f.type == HELLO:
                        writer.write(encode_frame(HELLO_OK, f.req_id,
                                                  {"v": 1}))
                        await writer.drain()
                    elif f.type == REQ:
                        reqs += 1
            writer.close()                 # ...then sever, answering 0

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        chan = FrameChannel(target=f"127.0.0.1:{port}",
                            request_timeout=30.0)
        try:
            t0 = time.monotonic()
            results = await asyncio.gather(
                *(chan.request("GET", f"/p{i}") for i in range(4)),
                return_exceptions=True)
            elapsed = time.monotonic() - t0
            assert all(isinstance(r, (FrameChannelError, OSError))
                       for r in results), results
            # far below both the 30s request timeout and the 60s idle
            # reap — the sever itself failed the pending requests
            assert elapsed < 5.0, elapsed
        finally:
            await chan.close()
            server.close()
            await server.wait_closed()
    run(go())
