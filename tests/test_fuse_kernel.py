"""Kernel FUSE end-to-end: VFS -> fusekernel -> WFS -> filer -> volume.

Reference: weed/command/mount_std.go:26-139 (the reference mounts via
the bazil fuse fork and is exercised against a kernel in its e2e suite).
Here the built-in /dev/fuse binding (mount/fusekernel.py) serves the
same node layer the in-proc tests cover, through a REAL kernel mount:
cp a tree in, read it back byte-identical through the page cache,
rename/unlink/xattr via syscalls, unmount.

Skipped when the environment cannot mount (no /dev/fuse, no
CAP_SYS_ADMIN and no usable fusermount).
"""

from __future__ import annotations

import asyncio
import os
import threading

import pytest

from cluster_util import Cluster, run


def _can_mount(tmp_path) -> str | None:
    """Return a skip reason, or None when kernel mounts work here."""
    if not os.path.exists("/dev/fuse"):
        return "/dev/fuse absent"
    try:
        fd = os.open("/dev/fuse", os.O_RDWR)
        os.close(fd)
    except OSError as e:
        return f"/dev/fuse not openable: {e}"
    # probe an actual mount: sandboxes often strip CAP_SYS_ADMIN
    from seaweedfs_tpu.mount import fusekernel
    probe = tmp_path / "probe"
    probe.mkdir()
    try:
        fd = fusekernel._mount_dev_fuse(str(probe), False)
    except Exception as e:
        return f"mount not permitted: {e}"
    os.close(fd)
    fusekernel.unmount(str(probe))
    # the exercise drives user.* xattr syscalls through the mount; on a
    # filesystem without xattr support (tmpfs /tmp in this container)
    # the VFS rejects them with ENOTSUP before FUSE ever sees the op
    xprobe = tmp_path / "xattr_probe"
    xprobe.write_bytes(b"")
    try:
        os.setxattr(str(xprobe), "user.probe", b"1")
        os.removexattr(str(xprobe), "user.probe")
    except OSError as e:
        return ("filesystem lacks xattr support "
                f"(tmpfs? setxattr: {e})")
    return None


def _exercise(mp: str) -> None:
    """Blocking VFS syscalls against the mounted tree (runs in a worker
    thread so the cluster's event loop keeps serving HTTP)."""
    # create a small tree through the kernel
    os.mkdir(f"{mp}/docs")
    payloads = {
        f"{mp}/hello.txt": b"hello, kernel world\n",
        f"{mp}/docs/big.bin": os.urandom(300_000),   # > max_write page runs
        f"{mp}/docs/empty": b"",
    }
    for p, data in payloads.items():
        with open(p, "wb") as f:
            f.write(data)
    # read back byte-identical (fresh fds, through the page cache)
    for p, data in payloads.items():
        with open(p, "rb") as f:
            assert f.read() == data, p
        assert os.path.getsize(p) == len(data)
    # listing
    assert sorted(os.listdir(mp)) == ["docs", "hello.txt"]
    assert sorted(os.listdir(f"{mp}/docs")) == ["big.bin", "empty"]
    # append + partial read via seek
    with open(f"{mp}/hello.txt", "ab") as f:
        f.write(b"line2\n")
    with open(f"{mp}/hello.txt", "rb") as f:
        f.seek(7)
        assert f.read(6) == b"kernel"
    # truncate through the kernel
    os.truncate(f"{mp}/docs/big.bin", 1000)
    assert os.path.getsize(f"{mp}/docs/big.bin") == 1000
    with open(f"{mp}/docs/big.bin", "rb") as fh:
        assert fh.read() == payloads[f"{mp}/docs/big.bin"][:1000]
    # rename across directories, then unlink
    os.rename(f"{mp}/docs/big.bin", f"{mp}/moved.bin")
    assert os.path.getsize(f"{mp}/moved.bin") == 1000
    # chmod visible through getattr
    os.chmod(f"{mp}/moved.bin", 0o600)
    assert (os.stat(f"{mp}/moved.bin").st_mode & 0o777) == 0o600
    # xattr syscalls hit Entry.extended
    os.setxattr(f"{mp}/hello.txt", "user.tag", b"tpu")
    assert os.getxattr(f"{mp}/hello.txt", "user.tag") == b"tpu"
    assert "user.tag" in os.listxattr(f"{mp}/hello.txt")
    os.removexattr(f"{mp}/hello.txt", "user.tag")
    assert "user.tag" not in os.listxattr(f"{mp}/hello.txt")
    os.unlink(f"{mp}/moved.bin")
    os.unlink(f"{mp}/docs/empty")
    os.rmdir(f"{mp}/docs")
    assert os.listdir(mp) == ["hello.txt"]


def test_kernel_mount_roundtrip(tmp_path):
    reason = _can_mount(tmp_path)
    if reason:
        pytest.skip(reason)

    from seaweedfs_tpu.filer.filer import Filer
    from seaweedfs_tpu.mount import fusekernel
    from seaweedfs_tpu.mount.fuse_adapter import SeaweedFuseOps
    from seaweedfs_tpu.mount.wfs import WFS, MountOptions

    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            wfs = WFS(Filer("memory"),
                      c.master.url.replace("http://", ""),
                      MountOptions(chunk_size_limit=64 * 1024))
            ops = SeaweedFuseOps(wfs)   # runs WFS on its own loop thread
            mp = tmp_path / "mnt"
            mp.mkdir()
            ready = threading.Event()
            t = threading.Thread(
                target=lambda: fusekernel.FUSE(ops, str(mp),
                                               ready_event=ready),
                daemon=True)
            t.start()
            assert ready.wait(10), "kernel mount did not come up"
            try:
                await asyncio.to_thread(_exercise, str(mp))
            finally:
                await asyncio.to_thread(fusekernel.unmount, str(mp))
                # join via a thread: destroy() drains deletes over HTTP
                # served by THIS event loop — a sync join would deadlock
                await asyncio.to_thread(t.join, 10)
            assert not t.is_alive(), "serve loop did not exit on unmount"

    run(body())
