"""GF(256) field + RS matrix unit tests (phase-0 oracles)."""

import itertools

import numpy as np
import pytest

from seaweedfs_tpu.ec import gf
from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder


def test_field_basics():
    # alpha=2 generates the multiplicative group; known values for poly 0x11D.
    assert gf.gf_mul(0, 5) == 0
    assert gf.gf_mul(1, 77) == 77
    assert gf.gf_mul(2, 0x80) == 0x1D  # overflow reduces by the polynomial
    for a in (1, 2, 3, 97, 255):
        assert gf.gf_mul(a, gf.gf_inv(a)) == 1
        assert gf.gf_div(gf.gf_mul(a, 7), 7) == a


def test_field_is_a_field():
    # spot-check associativity/distributivity on a sample grid
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(0, 256, 3))
        assert gf.gf_mul(a, gf.gf_mul(b, c)) == gf.gf_mul(gf.gf_mul(a, b), c)
        assert gf.gf_mul(a, b ^ c) == gf.gf_mul(a, b) ^ gf.gf_mul(a, c)


def test_mul_table_matches_scalar():
    for c in (0, 1, 2, 29, 142, 255):
        t = gf.mul_table(c)
        for x in (0, 1, 7, 128, 255):
            assert t[x] == gf.gf_mul(c, x)


def test_matrix_inversion_roundtrip():
    rng = np.random.default_rng(1)
    for n in (1, 3, 10):
        while True:
            m = rng.integers(0, 256, (n, n)).astype(np.uint8)
            try:
                inv = gf.mat_invert(m)
                break
            except ValueError:
                continue
        assert np.array_equal(gf.mat_mul(m, inv), gf.mat_identity(n))


def test_rs_matrix_systematic_and_mds():
    m = gf.rs_matrix()
    assert m.shape == (14, 10)
    assert np.array_equal(m[:10], gf.mat_identity(10))
    # MDS property: every 10-of-14 row subset must be invertible.
    for rows in itertools.combinations(range(14), 10):
        gf.mat_invert(m[list(rows)])  # raises if singular


def test_cpu_encoder_roundtrip_all_subsets():
    rng = np.random.default_rng(2)
    enc = CpuEncoder()
    data = [rng.integers(0, 256, 512).astype(np.uint8) for _ in range(10)]
    shards = enc.encode(list(data))
    assert len(shards) == 14
    assert enc.verify(shards)

    # Reconstruct the full shard set from every 10-of-14 subset.
    for keep in itertools.combinations(range(14), 10):
        partial = [shards[i] if i in keep else None for i in range(14)]
        rebuilt = enc.reconstruct(partial)
        for a, b in zip(rebuilt, shards):
            assert np.array_equal(a, b)


def test_cpu_encoder_reconstruct_data_only():
    rng = np.random.default_rng(3)
    enc = CpuEncoder()
    shards = enc.encode([rng.integers(0, 256, 64).astype(np.uint8)
                         for _ in range(10)])
    partial = list(shards)
    partial[3] = None
    partial[12] = None
    out = enc.reconstruct_data(partial)
    assert np.array_equal(out[3], shards[3])
    assert out[12] is None  # parity not rebuilt on the data-only path


def test_reconstruct_needs_k_shards():
    enc = CpuEncoder()
    shards = enc.encode([np.zeros(8, np.uint8) for _ in range(10)])
    partial = [None] * 5 + list(shards[5:14])
    assert len([s for s in partial if s is not None]) == 9
    with pytest.raises(ValueError):
        enc.reconstruct(partial)


def test_bitplane_constants_reproduce_mul():
    coeff = gf.parity_matrix()
    bp = gf.bitplane_constants(coeff)
    rng = np.random.default_rng(4)
    for _ in range(50):
        p = int(rng.integers(0, 4))
        i = int(rng.integers(0, 10))
        x = int(rng.integers(0, 256))
        want = gf.gf_mul(int(coeff[p, i]), x)
        got = 0
        for j in range(8):
            if (x >> j) & 1:
                got ^= int(bp[p, i, j])
        assert got == want


def test_gf2_matrix_reproduces_parity():
    coeff = gf.parity_matrix()
    b = gf.gf2_matrix(coeff)  # (32, 80)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, 10).astype(np.uint8)
    # expand to 80 input bits (byte i, bit j -> index i*8+j)
    in_bits = np.array([(int(data[i]) >> j) & 1
                        for i in range(10) for j in range(8)], dtype=np.int64)
    out_bits = (b.astype(np.int64) @ in_bits) % 2
    parity_bytes = [
        sum(int(out_bits[p * 8 + bit]) << bit for bit in range(8))
        for p in range(4)
    ]
    enc = CpuEncoder()
    shards = enc.encode([np.array([v], np.uint8) for v in data])
    for p in range(4):
        assert parity_bytes[p] == int(shards[10 + p][0])
