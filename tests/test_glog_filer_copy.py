"""glog leveled logging (weed/glog analog) + filer.copy CLI tests."""

from __future__ import annotations

import os

from cluster_util import Cluster, run

from seaweedfs_tpu.util import glog


def test_glog_verbosity_gate(capsys):
    glog.init(verbosity=2, logtostderr=True)
    glog.V(2).infof("visible %d", 42)
    glog.V(3).infof("invisible")
    assert not glog.V(3)
    assert glog.V(2)
    err = capsys.readouterr().err
    assert "visible 42" in err
    assert "invisible" not in err
    glog.init(verbosity=0)


def test_glog_severity_files(tmp_path):
    d = str(tmp_path / "logs")
    glog.init(verbosity=0, log_dir=d, logtostderr=False)
    glog.info("hello-info")
    glog.warning("hello-warn")
    glog.error("hello-err")
    files = os.listdir(d)
    assert any("INFO" in f for f in files)
    assert any("WARNING" in f for f in files)
    assert any("ERROR" in f for f in files)
    joined = ""
    for f in files:
        with open(os.path.join(d, f)) as fh:
            joined += fh.read()
    assert "hello-info" in joined and "hello-err" in joined
    glog.init(verbosity=0)  # reset global state for other tests


def test_filer_copy_tree(tmp_path):
    src = tmp_path / "tree"
    (src / "sub").mkdir(parents=True)
    (src / "a.txt").write_bytes(b"alpha")
    (src / "sub" / "b.txt").write_bytes(b"beta" * 1000)
    (src / "sub" / "c.bin").write_bytes(os.urandom(10))

    async def body():
        from seaweedfs_tpu.cli import _run_filer_copy

        class Args:
            paths = [str(src), None]
            concurrency = 4
            include = "*.txt"
            collection = ""
            replication = ""
            ttl = ""

        c = Cluster(str(tmp_path / "cluster"))
        c.with_filer = True
        async with c:
            Args.paths[1] = f"http://{c.filer.url}/dst/"
            await _run_filer_copy(Args)

            async def fget(path):
                async with c.http.get(
                        f"http://{c.filer.url}{path}") as resp:
                    return resp.status, await resp.read()

            st, data = await fget("/dst/tree/a.txt")
            assert st == 200 and data == b"alpha"
            st, data = await fget("/dst/tree/sub/b.txt")
            assert st == 200 and data == b"beta" * 1000
            # .bin filtered out by -include
            st, _ = await fget("/dst/tree/sub/c.bin")
            assert st == 404

    run(body())
