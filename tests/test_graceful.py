"""Graceful-shutdown contract of the CLI server commands.

Reference: weed/util/signal_handling.go:19-44 (OnInterrupt cleanups) +
weed/util/pprof.go:18-31 (profile dump on interrupt). SIGTERM must stop
the server loop, run the servers' stop() path (store close / needle-map
commit), exit rc=0, and fire atexit hooks so -cpuprofile produces output.
"""

import os
import signal
import socket
import subprocess
import sys
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_sigterm_stops_volume_server_and_dumps_profile(tmp_path):
    port = _free_port()
    prof = tmp_path / "vol.prof"
    log = tmp_path / "out.log"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    with open(log, "w") as lf:
        proc = subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", "volume",
             "-port", str(port), "-dir", str(tmp_path / "v"), "-max", "2",
             "-master", "127.0.0.1:1", "-cpuprofile", str(prof)],
            stdout=lf, stderr=subprocess.STDOUT, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            if "listening" in log.read_text():
                break
            if proc.poll() is not None:
                raise AssertionError(
                    f"server died on startup: {log.read_text()}")
            time.sleep(0.2)
        else:
            raise AssertionError(f"server never came up: {log.read_text()}")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        assert rc == 0, f"non-graceful exit rc={rc}: {log.read_text()}"
        assert prof.exists() and prof.stat().st_size > 0, \
            "-cpuprofile produced no output on SIGTERM"
    finally:
        if proc.poll() is None:
            proc.kill()
