"""IP white-list guard (security/guard.go:43-137 semantics)."""

from __future__ import annotations

from cluster_util import Cluster, run

from seaweedfs_tpu.security.guard import Guard, parse_white_list


def test_guard_matching():
    assert Guard(()).allows("10.0.0.1")  # empty list admits everyone
    g = Guard(["127.0.0.1", "10.1.0.0/16"])
    assert g.allows("127.0.0.1")
    assert g.allows("10.1.255.3")
    assert not g.allows("10.2.0.1")
    assert not g.allows("192.168.0.9")
    assert not g.allows(None)
    assert not g.allows("not-an-ip")
    assert parse_white_list(" 1.2.3.4 , 10.0.0.0/8 ,") == \
        ["1.2.3.4", "10.0.0.0/8"]
    # a typo'd entry fails fast instead of silently never matching
    import pytest
    with pytest.raises(ValueError):
        Guard(["10.0.0.256"])
    # the CLI flag parser turns that into a clean exit, not a traceback
    with pytest.raises(SystemExit, match="10.0.0.256"):
        parse_white_list("127.0.0.1,10.0.0.256")


def test_path_guarded_prefix_semantics():
    from seaweedfs_tpu.security.guard import path_guarded
    prefixes = ("/submit", "/vol/status", "/stats/")
    assert path_guarded("/submit", prefixes)
    assert path_guarded("/submit/extra", prefixes)
    # unrelated siblings must NOT be guarded (plain startswith would)
    assert not path_guarded("/submitfoo", prefixes)
    assert not path_guarded("/vol/statusx", prefixes)
    # entries ending in '/' guard the whole subtree
    assert path_guarded("/stats/health", prefixes)
    assert not path_guarded("/stats", prefixes)


def test_white_list_enforced_over_http(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            # flip on a whitelist that excludes the loopback client
            c.master.guard = Guard(["10.9.9.9"])
            c.servers[0].guard = Guard(["10.9.9.9"])
            async with c.http.get(
                    f"http://{c.master.url}/dir/assign") as resp:
                assert resp.status == 401
            # the mesh stays open: cluster status, raft/heartbeat.
            # /dir/lookup is guarded (master_server.go:111) but
            # heartbeating volume-server IPs are auto-admitted — the
            # loopback client shares the VS's IP here, so it passes
            async with c.http.get(
                    f"http://{c.master.url}/cluster/status") as resp:
                assert resp.status == 200
            async with c.http.get(
                    f"http://{c.master.url}/dir/lookup",
                    params={"volumeId": "1"}) as resp:
                assert resp.status != 401
            # ...but a non-peer, non-whitelisted IP is rejected: clear
            # the learned peer set to simulate a foreign client
            saved_peers = set(c.master._peer_ips)
            c.master._peer_ips.clear()
            async with c.http.get(
                    f"http://{c.master.url}/dir/lookup",
                    params={"volumeId": "1"}) as resp:
                assert resp.status == 401
            c.master._peer_ips.update(saved_peers)
            # volume: client writes guarded; without mTLS the /admin
            # mutation surface is guarded too (else a 401'd client could
            # still tombstone needles via /admin/batch_delete); reads
            # and replica forwards (JWT-covered when enforced) stay open
            vs = c.servers[0].url
            async with c.http.post(f"http://{vs}/1,01deadbeef",
                                   data=b"x") as resp:
                assert resp.status == 401
            async with c.http.post(
                    f"http://{vs}/admin/vacuum/check",
                    params={"volume": "1"}) as resp:
                assert resp.status == 401
            async with c.http.get(
                    f"http://{vs}/admin/volume/status",
                    params={"volume": "1"}) as resp:
                assert resp.status != 401  # GETs aren't mutations
            # without write JWTs a ?type=replicate spoof must NOT bypass
            # the IP guard (peers have to be whitelisted instead)
            async with c.http.post(f"http://{vs}/9,01deadbeef",
                                   data=b"x",
                                   params={"type": "replicate"}) as resp:
                assert (await resp.json())["error"] == \
                    "ip not in whitelist"
            # with JWTs enforced, replica forwards skip the IP guard and
            # are authenticated by their forwarded token instead
            c.servers[0].jwt_key = "k"
            async with c.http.post(f"http://{vs}/9,01deadbeef",
                                   data=b"x",
                                   params={"type": "replicate"}) as resp:
                assert (await resp.json())["error"] == "missing jwt"
            c.servers[0].jwt_key = ""
            async with c.http.get(f"http://{vs}/status") as resp:
                assert resp.status == 200
            # widen the list to include loopback: everything works again
            c.master.guard = Guard(["127.0.0.0/8"])
            c.servers[0].guard = Guard(["127.0.0.0/8"])
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"guarded-ok")
            assert st == 201
    run(body())
