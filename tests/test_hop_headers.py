"""Hop-by-hop header discipline on the sibling proxy (satellite of the
binary-wire PR): RFC 7230 §6.1 connection-scoped headers and the
hop-specific entity headers (Content-Length, Content-Encoding, Date,
Server) must be stripped in BOTH directions by `proxy_request` (the
HTTP hop) and `proxy_request_frame` (the frame hop) — and the
mid-body-death path must abort the client transport instead of
splicing a 502 into needle bytes."""

from __future__ import annotations

import asyncio
import json

import pytest
from aiohttp import web

from cluster_util import run
from seaweedfs_tpu.server import workers as wk
from seaweedfs_tpu.util import tracing
from seaweedfs_tpu.util.frame import (HELLO, HELLO_OK, MAGIC, REQ, RESP,
                                      FrameChannel, FrameDecoder,
                                      encode_frame)

# hop headers a peer might emit; each must never cross the proxy
_REQ_HOP = {
    "Connection": "keep-alive",
    "Keep-Alive": "timeout=7",
    "Proxy-Authorization": "Basic c3B5",
    "TE": "trailers",
    "Trailer": "X-T",
    "Upgrade": "h2c",
}
_RESP_HOP = {
    "Keep-Alive": "timeout=9",
    "Proxy-Authenticate": "Basic realm=x",
    "X-Entity": "survives",           # a normal header DOES cross
}


@pytest.fixture(autouse=True)
def _tracing_on():
    # the real worker middleware always has the proxy span open when
    # it forwards, which is what makes tracing.inject stamp the hop
    tracing.init(sample=1.0, slow_ms=0.0)
    tracing.reset()
    yield
    tracing.init(sample=1.0, slow_ms=0.0)
    tracing.reset()


async def _echo_sibling() -> tuple[web.AppRunner, str]:
    """Fake sibling: echoes the received request headers in its JSON
    body and emits hop-by-hop RESPONSE headers that must be eaten."""

    async def h(req: web.Request) -> web.Response:
        body = json.dumps({"seen": dict(req.headers),
                           "path": req.path}).encode()
        resp = web.Response(body=body, content_type="application/json")
        for k, v in _RESP_HOP.items():
            resp.headers[k] = v
        return resp

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", h)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"127.0.0.1:{port}"


async def _front(handler) -> tuple[web.AppRunner, int]:
    """Minimal aiohttp front whose handler proxies to the sibling —
    gives the proxy functions a REAL web.Request/transport."""
    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1]


def _assert_request_headers_clean(seen: dict) -> None:
    lower = {k.lower() for k in seen}
    for k in _REQ_HOP:
        assert k.lower() not in lower, f"request hop header {k} crossed"
    assert "x-custom" in lower           # ordinary headers DO cross
    assert "traceparent" in lower        # trace propagation rides along


def test_proxy_request_strips_hop_headers_both_directions(tmp_path):
    async def body():
        import aiohttp
        sib_runner, sib = await _echo_sibling()

        async def handler(req: web.Request):
            async with aiohttp.ClientSession() as session:
                with tracing.start_root("volume", "read"), \
                        tracing.start("proxy", "sibling"):
                    return await wk.proxy_request(req, session, sib,
                                                  "tok")

        front_runner, port = await _front(handler)
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{port}/3,01deadbeef",
                        headers={**_REQ_HOP, "X-Custom": "yes"},
                        skip_auto_headers=("User-Agent",)) as r:
                    assert r.status == 200
                    got = json.loads(await r.read())
                    # direction 1: request hop headers never reached
                    # the sibling; the worker token DID
                    _assert_request_headers_clean(got["seen"])
                    assert got["seen"].get(wk.WORKER_HEADER) == "tok"
                    # direction 2: sibling's hop response headers were
                    # eaten, its entity header survived
                    assert "Proxy-Authenticate" not in r.headers
                    assert r.headers.get("Keep-Alive") != "timeout=9"
                    assert r.headers["X-Entity"] == "survives"
        finally:
            await front_runner.cleanup()
            await sib_runner.cleanup()
    run(body())


def test_proxy_request_frame_strips_hop_headers_both_directions():
    async def body():
        # frame echo sibling: replies with the received meta headers
        # in the body and hop headers in its response meta
        writers = set()

        async def conn(reader, writer):
            writers.add(writer)
            dec = FrameDecoder()
            try:
                while True:
                    chunk = await reader.read(65536)
                    if not chunk:
                        return
                    if chunk.startswith(MAGIC):
                        chunk = chunk[len(MAGIC):]
                    for fr in dec.feed(chunk):
                        if fr.type == HELLO:
                            writer.write(encode_frame(
                                HELLO_OK, fr.req_id, {"v": 1}))
                        elif fr.type == REQ:
                            writer.write(encode_frame(
                                RESP, fr.req_id,
                                {"s": 200, "h": dict(_RESP_HOP),
                                 "ct": "application/json"},
                                json.dumps(
                                    {"seen": fr.meta.get("h", {}),
                                     "path": fr.meta.get("p")}
                                ).encode()))
                    await writer.drain()
            except (OSError, asyncio.CancelledError):
                pass
            finally:
                writers.discard(writer)
                writer.close()

        srv = await asyncio.start_server(conn, "127.0.0.1", 0)
        sport = srv.sockets[0].getsockname()[1]
        ch = FrameChannel(target=f"127.0.0.1:{sport}")

        async def handler(req: web.Request):
            with tracing.start_root("volume", "read"), \
                    tracing.start("proxy", "sibling"):
                return await wk.proxy_request_frame(req, ch)

        front_runner, port = await _front(handler)
        try:
            import aiohttp
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{port}/3,01deadbeef",
                        headers={**_REQ_HOP, "X-Custom": "yes"},
                        skip_auto_headers=("User-Agent",)) as r:
                    assert r.status == 200
                    got = json.loads(await r.read())
                    _assert_request_headers_clean(got["seen"])
                    # the frame hop carries the client address exactly
                    # like the HTTP hop
                    assert wk.FORWARDED_HEADER.lower() in got["seen"]
                    assert "Proxy-Authenticate" not in r.headers
                    assert r.headers.get("Keep-Alive") != "timeout=9"
                    assert r.headers["X-Entity"] == "survives"
        finally:
            await ch.close()
            await front_runner.cleanup()
            srv.close()
            await srv.wait_closed()
            for w in list(writers):
                w.close()
    run(body())


def test_proxy_mid_body_death_aborts_never_splices_502():
    """Sibling dies after the headers and part of the body: the proxy
    must sever the client transport (torn read), never emit a 502
    JSON — and the pre-body-death 502 must carry no hop headers."""
    async def body():
        import aiohttp

        async def conn(reader, writer):
            # raw sibling: declare 100 bytes, send 10, die
            await reader.read(65536)
            writer.write(b"HTTP/1.1 200 OK\r\nContent-Length: 100\r\n"
                         b"Keep-Alive: timeout=9\r\n\r\n0123456789")
            await writer.drain()
            writer.close()

        srv = await asyncio.start_server(conn, "127.0.0.1", 0)
        sport = srv.sockets[0].getsockname()[1]

        async def handler(req: web.Request):
            async with aiohttp.ClientSession() as session:
                return await wk.proxy_request(
                    req, session, f"127.0.0.1:{sport}", "tok")

        front_runner, port = await _front(handler)
        try:
            async with aiohttp.ClientSession() as http:
                with pytest.raises((aiohttp.ClientError,
                                    asyncio.TimeoutError)):
                    async with http.get(
                            f"http://127.0.0.1:{port}/3,01beef",
                            timeout=aiohttp.ClientTimeout(total=10)
                            ) as r:
                        # headers may arrive (200, CL=100) but the
                        # body MUST tear — reading it raises
                        body_ = await r.read()
                        # a spliced 502 would surface as a short but
                        # "complete" read; reject that explicitly
                        assert len(body_) == 100, "spliced body"
        finally:
            await front_runner.cleanup()
            srv.close()
            await srv.wait_closed()

        # pre-body death (sibling unreachable): a clean 502 JSON with
        # no hop headers
        async def handler2(req: web.Request):
            async with aiohttp.ClientSession() as session:
                return await wk.proxy_request(
                    req, session, "127.0.0.1:9", "tok")

        front2, port2 = await _front(handler2)
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{port2}/3,01beef") as r:
                    assert r.status == 502
                    assert "error" in await r.json()
                    for k in ("Keep-Alive", "Proxy-Authenticate",
                              "Transfer-Encoding"):
                        assert k not in r.headers
        finally:
            await front2.cleanup()
    run(body())
