"""Image resize on read + EXIF fix on upload + JSON query pushdown.

Reference behaviors: weed/images/resizing.go + orientation.go (hooked at
volume_server_handlers_read.go:211-227 / needle.go ParseUpload) and
weed/query/json/query_json.go + server/volume_grpc_query.go.
"""

import io
import json

import pytest

from cluster_util import Cluster, run

from seaweedfs_tpu.images import fix_jpeg_orientation, resizing
from seaweedfs_tpu.query import Filter, query_json

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402


def _png(w, h, color=(255, 0, 0)):
    img = Image.new("RGB", (w, h), color)
    out = io.BytesIO()
    img.save(out, format="PNG")
    return out.getvalue()


# ---- pure-function tests ----

def test_resized_default_stretches_fit_proportional():
    data = _png(100, 50)
    # default mode stretches to the exact box (resizing.go imaging.Resize)
    img = Image.open(io.BytesIO(resizing.resized("image/png", data, 50, 50)))
    assert img.size == (50, 50)
    # fit is proportional within the box
    img = Image.open(io.BytesIO(
        resizing.resized("image/png", data, 50, 50, mode="fit")))
    assert img.size == (50, 25)


def test_resized_fill_crops():
    data = _png(100, 50)
    out = resizing.resized("image/png", data, 40, 40, mode="fill")
    img = Image.open(io.BytesIO(out))
    assert img.size == (40, 40)


def test_resized_single_dimension_and_noop():
    data = _png(100, 50)
    img = Image.open(io.BytesIO(resizing.resized("image/png", data, 50, 0)))
    assert img.size == (50, 25)
    # already small enough -> unchanged bytes
    assert resizing.resized("image/png", data, 200, 200) == data
    # non-image mime -> unchanged
    assert resizing.resized("text/plain", b"hello", 10, 10) == b"hello"


def test_fix_jpeg_orientation():
    img = Image.new("RGB", (40, 20), (0, 128, 255))
    out = io.BytesIO()
    exif = Image.Exif()
    exif[0x0112] = 6  # rotate 90 CW to display upright
    img.save(out, format="JPEG", exif=exif)
    fixed = fix_jpeg_orientation(out.getvalue())
    fimg = Image.open(io.BytesIO(fixed))
    assert fimg.size == (20, 40)  # rotated
    assert fimg.getexif().get(0x0112, 1) == 1
    # non-jpeg passes through
    png = _png(4, 4)
    assert fix_jpeg_orientation(png) == png


def test_query_json_filter_and_projection():
    data = b"\n".join(json.dumps(r).encode() for r in [
        {"name": "a", "age": 30, "addr": {"city": "sf"}},
        {"name": "b", "age": 10, "addr": {"city": "nyc"}},
        {"name": "c", "age": 25, "addr": {"city": "sf"}},
    ])
    got = query_json(data, Filter("age", ">", "20"), ["name", "addr.city"])
    assert got == [{"name": "a", "addr.city": "sf"},
                   {"name": "c", "addr.city": "sf"}]
    # string equality + like
    got = query_json(data, Filter("addr.city", "=", "nyc"), ["name"])
    assert got == [{"name": "b"}]
    got = query_json(data, Filter("name", "like", "a"), None)
    assert got[0]["age"] == 30
    # whole-body JSON array form
    arr = json.dumps([{"x": 1}, {"x": 2}]).encode()
    assert query_json(arr, Filter("x", ">=", "2"), ["x"]) == [{"x": 2}]


# ---- server integration ----

def test_volume_server_resize_and_query(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            # image upload + resized read
            a = await c.assign()
            png = _png(64, 32)
            async with c.http.post(
                    f"http://{a['url']}/{a['fid']}", data=png,
                    headers={"Content-Type": "image/png"}) as resp:
                assert resp.status == 201
            async with c.http.get(
                    f"http://{a['publicUrl']}/{a['fid']}",
                    params={"width": "32", "height": "32",
                            "mode": "fit"}) as resp:
                assert resp.status == 200
                img = Image.open(io.BytesIO(await resp.read()))
                assert img.size == (32, 16)
            # bad width param: serve original, not 500
            async with c.http.get(
                    f"http://{a['publicUrl']}/{a['fid']}",
                    params={"width": "abc"}) as resp:
                assert resp.status == 200
                assert await resp.read() == png
            # unknown query operand: clean 400
            async with c.http.post(
                    f"http://{a['url']}/admin/query",
                    json={"fromFileIds": [a["fid"]],
                          "filter": {"field": "x", "operand": "~",
                                     "value": "1"}}) as resp:
                assert resp.status == 400

            # JSON records + query pushdown
            recs = [{"user": "u1", "n": i} for i in range(5)]
            a2 = await c.assign()
            body_json = "\n".join(json.dumps(r) for r in recs).encode()
            async with c.http.post(
                    f"http://{a2['url']}/{a2['fid']}", data=body_json,
                    headers={"Content-Type": "application/json"}) as resp:
                assert resp.status == 201
            q = {"fromFileIds": [a2["fid"]],
                 "filter": {"field": "n", "operand": ">=", "value": "3"},
                 "selections": ["n"]}
            async with c.http.post(
                    f"http://{a2['url']}/admin/query", json=q) as resp:
                assert resp.status == 200
                lines = [json.loads(x) for x in
                         (await resp.text()).strip().splitlines()]
            assert lines == [{"n": 3}, {"n": 4}]
    run(body())
