"""On-disk format interop proof against the reference's committed fixture.

The reference ships a Go-produced volume (weed/storage/erasure_coding/1.dat
+ 1.idx, 298 needles) and its ec_test.go:20-177 proves every needle reads
back identically through stripe math, directly and reconstructed from a
shard subset. Here the SAME Go-written bytes flow through this package's
needle/idx/EC readers — if any format constant (header layout, offset
units, CRC, padding, superblock, stripe math) drifts from the reference,
these tests fail.
"""

import os
import shutil

import pytest

from seaweedfs_tpu.ec import pipeline as pl
from seaweedfs_tpu.ec.ec_volume import EcVolume
from seaweedfs_tpu.ec.locate import shard_file_size
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.needle_map import walk_index_blob

FIXTURE_DIR = "/root/reference/weed/storage/erasure_coding"
# ec_test.go:16-18 block geometry for the fixture-sized volume
LB = 10000
SB = 100

pytestmark = pytest.mark.skipif(
    not os.path.exists(os.path.join(FIXTURE_DIR, "1.dat")),
    reason="reference fixture not present")


@pytest.fixture(scope="module")
def fixture(tmp_path_factory):
    """Copy the Go fixture, stripe it into 14 shards with our pipeline."""
    d = str(tmp_path_factory.mktemp("interop"))
    for ext in (".dat", ".idx"):
        shutil.copy(os.path.join(FIXTURE_DIR, "1" + ext),
                    os.path.join(d, "1" + ext))
    base = os.path.join(d, "1")
    pl.write_sorted_file_from_idx(base)
    pl.write_ec_files(base, encoder=pl.get_encoder("cpu"),
                      large_block=LB, small_block=SB, buffer_size=100)
    with open(base + ".idx", "rb") as f:
        entries = [e for e in walk_index_blob(f.read())
                   if e[2] != t.TOMBSTONE_FILE_SIZE]
    with open(base + ".dat", "rb") as f:
        dat = f.read()
    return d, base, entries, dat


def test_go_idx_walks(fixture):
    _, _, entries, dat = fixture
    assert len(entries) == 298  # known fixture content
    for key, off, size in entries:
        assert off % t.NEEDLE_PADDING_SIZE == 0
        assert 0 < off < len(dat)


def test_go_superblock_version(fixture):
    _, _, _, dat = fixture
    # Go fixture superblock: version 3, no replication/ttl
    assert dat[0] == t.VERSION3
    assert dat[1] == 0


def test_go_needles_parse_with_crc(fixture):
    """Every Go-written needle record parses with our reader and its
    CRC32-Castagnoli verifies (needle_read_write.go layout)."""
    _, _, entries, dat = fixture
    version = dat[0]
    for key, off, size in entries:
        rec = dat[off:off + t.actual_size(size, version)]
        n = Needle.from_bytes(rec, version=version)  # raises on CRC drift
        assert n.id == key
        assert n.size == size
        assert len(n.data) > 0


def test_dual_read_direct(fixture):
    """validateFiles/assertSame: each needle's raw .dat bytes must equal
    the bytes gathered through shard stripe math (ec_test.go:43-91)."""
    d, base, entries, dat = fixture
    version = dat[0]
    ev = EcVolume(d, "", 1, large_block=LB, small_block=SB,
                  encoder=pl.get_encoder("cpu"))
    try:
        for key, off, size in entries:
            want = Needle.from_bytes(
                dat[off:off + t.actual_size(size, version)], version=version)
            got = ev.read_needle(key)
            assert got.data == want.data, key
            assert got.cookie == want.cookie, key
    finally:
        ev.close()


def test_dual_read_reconstructed(fixture):
    """readFromOtherEcFiles: reads still match with 4 shards destroyed,
    served through on-the-fly reconstruction (ec_test.go:93-141)."""
    d, base, entries, dat = fixture
    version = dat[0]
    sample = entries[::13]  # ~23 spread across the volume
    for missing in [(0, 1, 2, 3), (10, 11, 12, 13), (3, 6, 9, 12)]:
        ev = EcVolume(d, "", 1, large_block=LB, small_block=SB,
                      encoder=pl.get_encoder("cpu"))
        try:
            for sid in missing:
                ev.shards.pop(sid).close()
            for key, off, size in sample:
                want = Needle.from_bytes(
                    dat[off:off + t.actual_size(size, version)],
                    version=version)
                got = ev.read_needle(key)
                assert got.data == want.data, (missing, key)
        finally:
            ev.close()


def test_shard_sizes_and_dat_size_recovery(fixture):
    d, base, entries, dat = fixture
    want = shard_file_size(len(dat), LB, SB)
    for i in range(14):
        assert os.path.getsize(base + pl.to_ext(i)) == want, i
    # FindDatFileSize recovers the live extent from .ecx (ec_decoder.go:47)
    found = pl.find_dat_file_size(base)
    assert found == len(dat)


def test_decode_back_matches_go_bytes(fixture, tmp_path):
    """ec.decode round trip: shards -> .dat must reproduce the Go-written
    volume byte-for-byte (ec_decoder.go:150-191)."""
    d, base, entries, dat = fixture
    nb = str(tmp_path / "1")
    for i in range(10):
        shutil.copy(base + pl.to_ext(i), nb + pl.to_ext(i))
    shutil.copy(base + ".ecx", nb + ".ecx")
    pl.write_dat_file(nb, pl.find_dat_file_size(nb),
                      large_block=LB, small_block=SB, buffer_size=1000)
    with open(nb + ".dat", "rb") as f:
        assert f.read() == dat
