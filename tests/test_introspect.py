"""Cluster-scope introspection (stats/introspect.py + the master's
/debug/cluster/* surface):

- assemble_trace is pure and deterministic: span dedupe across nodes
  (finished beats in-flight), self-time rollups per tier AND host, one
  nested tree, byte-identical output regardless of node arrival order;
- cluster_nodes enumerates self + quorum peers + topology volume
  servers + shard-map filers + ?extra= nodes, deduped, in a stable
  order;
- fanout degrades, never hangs: an armed introspect.fanout failpoint
  or an unreachable member becomes an explicit missing_nodes row
  inside the per-node deadline;
- end-to-end on the in-proc cluster: one traced read assembles into a
  single tree through /debug/cluster/trace/<id>, the timeline/health
  views merge every member, and a degraded pull retries to the
  byte-identical complete body once the fault clears.
"""

from __future__ import annotations

import asyncio
import json
import time

import pytest

from seaweedfs_tpu.stats import introspect
from seaweedfs_tpu.util import failpoints as fp
from seaweedfs_tpu.util import tracing

from cluster_util import Cluster, run


@pytest.fixture(autouse=True)
def _clean():
    tracing.init(sample=1.0, slow_ms=0.0)
    tracing.reset()
    fp.reset()
    introspect.init()
    yield
    tracing.init(sample=1.0, slow_ms=0.0)
    tracing.reset()
    fp.reset()
    introspect.init()


# ---------------------------------------------------------------------------
# assemble_trace (pure)


def _span(sid: str, parent: str, tier: str, start: float, dur: float,
          inflight: bool = False) -> dict:
    d = {"span": sid, "parent": parent, "tier": tier, "op": "x",
         "start_ms": start, "dur_ms": dur}
    if inflight:
        d["inflight"] = True
    return d


def test_assemble_dedupes_and_attributes_self_time():
    # host h2 saw span "b" only in-flight; h1 has the finished record
    payloads = [
        ("h1:1", {"spans": [_span("a", "", "s3", 0.0, 10.0),
                            _span("b", "a", "volume", 1.0, 6.0)]}),
        ("h2:1", {"spans": [_span("b", "a", "volume", 1.0, 2.0,
                                  inflight=True),
                            _span("c", "b", "store", 2.0, 4.0)]}),
    ]
    out = introspect.assemble_trace("t" * 32, payloads)
    assert out["spans"] == 3 and out["inflight"] == 0
    by_id = {}
    stack = list(out["tree"])
    while stack:
        n = stack.pop()
        by_id[n["span"]] = n
        stack.extend(n.get("children", ()))
    # the finished record won the dedupe (dur 6, not 2) and kept h1
    assert by_id["b"]["dur_ms"] == 6.0 and by_id["b"]["host"] == "h1:1"
    # one chain a -> b -> c
    assert out["tree"][0]["span"] == "a"
    assert out["tree"][0]["children"][0]["span"] == "b"
    assert out["tree"][0]["children"][0]["children"][0]["span"] == "c"
    # self-time: a=10-6, b=6-4, c=4
    assert by_id["a"]["self_ms"] == 4.0
    assert by_id["b"]["self_ms"] == 2.0
    assert out["tiers"] == {"s3": 4.0, "store": 4.0, "volume": 2.0}
    # host attribution follows the deduped winner
    assert out["hosts"] == {"h1:1": 6.0, "h2:1": 4.0}
    assert out["complete"] and out["missing_nodes"] == []


def test_assemble_byte_identical_across_arrival_order():
    payloads = [
        ("h1:1", {"spans": [_span("a", "", "s3", 0.0, 9.0)]}),
        ("h2:1", {"spans": [_span("b", "a", "volume", 1.0, 5.0)]}),
        ("h3:1", {"spans": [_span("c", "a", "filer", 2.0, 2.0)]}),
    ]
    missing = [{"node": "h9:1", "kind": "volume", "error": "timeout"}]
    first = json.dumps(
        introspect.assemble_trace("ab" * 16, payloads, list(missing)),
        sort_keys=True)
    again = json.dumps(
        introspect.assemble_trace("ab" * 16, list(reversed(payloads)),
                                  list(missing)),
        sort_keys=True)
    assert first == again
    body = json.loads(first)
    assert not body["complete"]
    assert body["missing_nodes"] == missing


def test_assemble_orphan_parent_becomes_root_and_cycles_terminate():
    # b's parent never reported (lost host) -> b roots; a cycle between
    # d and e must not recurse forever
    payloads = [("h1:1", {"spans": [
        _span("b", "ghost", "volume", 1.0, 3.0),
        _span("d", "e", "s3", 2.0, 1.0),
        _span("e", "d", "s3", 3.0, 1.0)]})]
    out = introspect.assemble_trace("cd" * 16, payloads)
    roots = {n["span"] for n in out["tree"]}
    assert "b" in roots
    assert out["spans"] == 3


# ---------------------------------------------------------------------------
# cluster_nodes


class _FakeNode:
    def __init__(self, url):
        self.url = url


class _FakeMaster:
    url = "m0:9333"
    _peers = ["m1:9333", "m0:9333"]

    class topo:
        @staticmethod
        def all_nodes():
            return [_FakeNode("v0:8080"), _FakeNode("v1:8080")]

    def _shard_map_dict(self):
        return {"owners": {"1": "f1:8888", "0": "f0:8888",
                           "2": "f0:8888"}}


def test_cluster_nodes_enumeration_dedupe_and_extra():
    nodes = introspect.cluster_nodes(
        _FakeMaster(), extra="s3:gw:8333,v0:8080,wd:9999")
    addrs = [(n["node"], n["kind"]) for n in nodes]
    assert addrs == [("m0:9333", "master"), ("m1:9333", "master"),
                     ("v0:8080", "volume"), ("v1:8080", "volume"),
                     ("f0:8888", "filer"), ("f1:8888", "filer"),
                     ("gw:8333", "s3"), ("wd:9999", "volume")]
    assert nodes[0]["local"]
    # the path-shadowing tiers get the reserved prefix
    prefixes = {n["node"]: n["prefix"] for n in nodes}
    assert prefixes["f0:8888"] == "/__debug__"
    assert prefixes["gw:8333"] == "/__debug__"
    assert prefixes["v0:8080"] == "/debug"


# ---------------------------------------------------------------------------
# fanout degradation (no servers needed: every node is unreachable)


def test_fanout_failpoint_degrades_to_missing_rows():
    import aiohttp

    async def go():
        fp.arm("introspect.fanout", "error")
        nodes = [{"node": "a:1", "kind": "volume", "prefix": "/debug"},
                 {"node": "b:2", "kind": "filer",
                  "prefix": "/__debug__"},
                 {"node": "self:0", "kind": "master",
                  "prefix": "/debug", "local": True}]
        async with aiohttp.ClientSession() as http:
            results, missing = await introspect.fanout(
                nodes, "/traces", http, deadline=2.0,
                local=lambda: {"spans": []})
        # the local node never rides the network: it still answered
        assert [nd["node"] for nd, _ in results] == ["self:0"]
        assert [m["node"] for m in missing] == ["a:1", "b:2"]
        assert all(m["error"] for m in missing)
    run(go())


def test_fanout_unreachable_member_is_bounded_by_deadline():
    import aiohttp

    async def go():
        # RFC 5737 TEST-NET: never routable -> connect hangs, the
        # per-node deadline must cut it
        nodes = [{"node": "192.0.2.1:9", "kind": "volume",
                  "prefix": "/debug"}]
        async with aiohttp.ClientSession() as http:
            t0 = time.monotonic()
            results, missing = await introspect.fanout(
                nodes, "/timeline", http, deadline=0.5)
            elapsed = time.monotonic() - t0
        assert results == [] and len(missing) == 1
        assert missing[0]["error"]
        assert elapsed < 3.0, elapsed
    run(go())


# ---------------------------------------------------------------------------
# end-to-end: the master's /debug/cluster/* surface


def test_cluster_trace_assembles_one_tree(tmp_path):
    async def go():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"cluster-scope")
            assert st == 201
            tracing.reset()
            tid = "5a" * 16
            tp = f"00-{tid}-{'1b' * 8}-01"
            async with c.http.get(f"http://{a['url']}/{a['fid']}",
                                  headers={"traceparent": tp}) as r:
                assert r.status == 200

            m = c.master.url
            async with c.http.get(
                    f"http://{m}/debug/cluster/trace/{tid}") as r:
                assert r.status == 200
                body = await r.json()
            assert body["trace_id"] == tid
            assert body["spans"] >= 1
            assert "volume" in body["tiers"], body["tiers"]
            assert body["complete"] and body["missing_nodes"] == []
            # every span in the tree carries its reporting host
            assert all(n.get("host") for n in body["tree"])

            # the index names the views and the member enumeration
            async with c.http.get(f"http://{m}/debug/cluster") as r:
                idx = await r.json()
            assert "/debug/cluster/health" in idx["views"]
            assert len(idx["nodes"]) == 3       # master + 2 volumes
            assert idx["deadline_s"] == introspect.deadline_s()

            # empty trace id -> 400, not a fan-out
            async with c.http.get(
                    f"http://{m}/debug/cluster/trace/%20") as r:
                assert r.status == 400
    run(go())


def test_cluster_timeline_health_and_events_merge_members(tmp_path):
    async def go():
        from seaweedfs_tpu.stats import timeline
        timeline.init(interval_s=1.0, ring=64)
        timeline.reset()
        async with Cluster(str(tmp_path), n_servers=2) as c:
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"merge-me")
            assert st == 201
            timeline.snap()
            await c.get(a["fid"], a["url"])
            timeline.snap()
            m = c.master.url
            async with c.http.get(
                    f"http://{m}/debug/cluster/timeline",
                    params={"n": "10"}) as r:
                assert r.status == 200
                tl = await r.json()
            assert tl["nodes"] == 3 and tl["missing_nodes"] == []
            # in-proc the members share one registry: same-wall windows
            # fold instead of double-counting
            assert "windows" in tl
            async with c.http.get(
                    f"http://{m}/debug/cluster/health") as r:
                assert r.status == 200
                h = await r.json()
            assert h["nodes"] == 3 and h["missing_nodes"] == []
            assert "status" in h and "objectives" in h
            async with c.http.get(
                    f"http://{m}/debug/cluster/events",
                    params={"n": "50"}) as r:
                assert r.status == 200
                ev = await r.json()
            assert ev["nodes"] == 3
            # every merged row carries its origin node
            assert all("node" in row for row in ev.get("events", []))
    run(go())


def test_cluster_trace_degraded_then_byte_identical_retry(tmp_path):
    """A member failing mid-fanout degrades to a missing_nodes row
    inside the deadline; once the fault clears, two consecutive pulls
    of the completed trace return byte-identical bodies."""
    async def go():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"degrade-me")
            assert st == 201
            tracing.reset()
            tid = "7c" * 16
            tp = f"00-{tid}-{'2d' * 8}-01"
            async with c.http.get(f"http://{a['url']}/{a['fid']}",
                                  headers={"traceparent": tp}) as r:
                assert r.status == 200

            m = c.master.url
            # every non-local pull fails while armed (the local master
            # node still answers) -> partial tree, explicit rows, 200
            fp.arm("introspect.fanout", "error:100")
            t0 = time.monotonic()
            async with c.http.get(
                    f"http://{m}/debug/cluster/trace/{tid}") as r:
                assert r.status == 200
                degraded = await r.json()
            elapsed = time.monotonic() - t0
            assert elapsed < introspect.deadline_s() + 2.0, elapsed
            assert not degraded["complete"]
            assert len(degraded["missing_nodes"]) == 2
            assert {mr["error"] for mr in degraded["missing_nodes"]}

            fp.reset()
            async with c.http.get(
                    f"http://{m}/debug/cluster/trace/{tid}") as r:
                first = await r.read()
            async with c.http.get(
                    f"http://{m}/debug/cluster/trace/{tid}") as r:
                second = await r.read()
            assert first == second
            body = json.loads(first)
            assert body["complete"] and body["missing_nodes"] == []
            assert body["spans"] >= degraded["spans"]
    run(go())
