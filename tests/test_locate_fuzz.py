"""Seeded randomized check of the EC stripe interval math against a
byte-wise oracle built from the layout definition.

ec/locate.py maps a logical (offset, size) to shard-local intervals
(ec_locate.go:11-83); an off-by-one here reads the wrong shard bytes on
every degraded read. The oracle lays the logical stream out row-major
into 10 columns of large then small blocks by brute force and compares
every mapped byte."""

from __future__ import annotations

import random

from seaweedfs_tpu.ec import locate
from seaweedfs_tpu.ec.gf import DATA_SHARDS


def _oracle_map(dat_size: int, large: int, small: int):
    """logical offset -> (shard, shard_offset) via brute-force layout."""
    out = {}
    large_rows = 0
    pos = 0
    # rows of 10 large blocks — STRICTLY greater, matching the encoder
    # loop (ec_encoder.go:208 / pipeline.py): an exact multiple is laid
    # out entirely as small rows
    while dat_size - pos > DATA_SHARDS * large:
        for col in range(DATA_SHARDS):
            for b in range(large):
                out[pos] = (col, large_rows * large + b)
                pos += 1
        large_rows += 1
    # tail: rows of 10 small blocks (last row may be partial)
    small_row = 0
    while pos < dat_size:
        for col in range(DATA_SHARDS):
            for b in range(small):
                if pos >= dat_size:
                    return out
                out[pos] = (col, large_rows * large
                            + small_row * small + b)
                pos += 1
        small_row += 1
    return out


def test_locate_matches_bytewise_oracle():
    rng = random.Random(77)
    for large, small in ((40, 8), (64, 16), (100, 10)):
        # boundary sizes first: exact large-row multiples and the
        # within-10*small window where the reference's own read formulas
        # disagree with its encoder (see locate.n_large_block_rows)
        fixed = [DATA_SHARDS * large, 2 * DATA_SHARDS * large,
                 DATA_SHARDS * large - 1,
                 DATA_SHARDS * large - DATA_SHARDS * small + 1,
                 DATA_SHARDS * large + 1]
        sizes = fixed + [rng.randint(1, DATA_SHARDS * large * 2 + 137)
                         for _ in range(12)]
        for dat_size in sizes:
            oracle = _oracle_map(dat_size, large, small)
            for _ in range(40):
                off = rng.randint(0, dat_size - 1)
                size = rng.randint(1, dat_size - off)
                ivs = locate.locate_data(large, small, dat_size, off, size)
                assert sum(iv.size for iv in ivs) == size
                pos = off
                for iv in ivs:
                    sid, soff = iv.to_shard_and_offset(large, small)
                    for j in range(iv.size):
                        assert oracle[pos + j] == (sid, soff + j), (
                            dat_size, off, size, iv, pos + j)
                    pos += iv.size
