"""Master automatic maintenance: leader-only auto-vacuum + admin scripts.

Reference: weed/server/master_server.go:186-250 (startAdminScripts),
weed/topology/topology_event_handling.go:22-28 (auto-vacuum timer),
weed/command/scaffold.go:337-361 (master.toml [master.maintenance]).
"""

import asyncio
import os

from cluster_util import Cluster, run


async def _fill_and_delete(c: Cluster) -> tuple[str, int, int]:
    """Write 10 needles to one volume, delete 8 — returns (vid, surviving
    fid, dirty size)."""
    a = await c.assign()
    vid = a["fid"].split(",")[0]
    fids = [a["fid"]]
    st, _ = await c.put(a["fid"], a["url"], b"k" * 10_000)
    assert st == 201
    for i in range(2, 11):
        fid = f"{vid},{i:02x}cafebabe"
        st, _ = await c.put(fid, a["url"], b"g" * 10_000)
        assert st == 201
        fids.append(fid)
    for fid in fids[2:]:
        assert await c.delete(fid, a["url"]) == 200
    v = c.servers[0].store.volumes[int(vid)]
    return a, fids, v


def test_master_auto_vacuum(tmp_path):
    """A cluster left alone reclaims space once garbage crosses the
    threshold — no shell interaction."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1, master_kwargs={
                "maintenance_interval_s": 0.3,
                "garbage_threshold": 0.2}) as c:
            a, fids, v = await _fill_and_delete(c)
            dirty = v.data_size()
            assert v.garbage_level() > 0.2
            for _ in range(60):  # up to ~6s for the loop to fire
                await asyncio.sleep(0.1)
                if v.data_size() < dirty and v.garbage_level() == 0.0:
                    break
            assert v.data_size() < dirty, "auto-vacuum never ran"
            assert v.garbage_level() == 0.0
            # survivors still readable after compaction
            for fid in fids[:2]:
                st, data = await c.get(fid, a["publicUrl"])
                assert st == 200 and len(data) == 10_000
    run(body())


def test_master_admin_scripts(tmp_path):
    """Configured admin script lines run on their own cadence through the
    shell dispatcher (reference-style -k=v flags included)."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1, master_kwargs={
                "maintenance_interval_s": 0,      # isolate the scripts path
                "admin_scripts": [
                    "volume.vacuum -garbageThreshold=0.2"],
                "admin_scripts_interval_s": 0.3}) as c:
            a, fids, v = await _fill_and_delete(c)
            dirty = v.data_size()
            for _ in range(60):
                await asyncio.sleep(0.1)
                if v.data_size() < dirty:
                    break
            assert v.data_size() < dirty, "admin script never ran"
    run(body())


def test_master_toml_parsing(tmp_path, monkeypatch):
    """master.toml discovery feeds [master.maintenance] into the server
    config (scaffold.go:337-361)."""
    from seaweedfs_tpu.cli import _load_master_toml

    (tmp_path / "master.toml").write_text(
        '[master.maintenance]\n'
        'scripts = """\n'
        '  volume.fix.replication\n'
        '  ec.rebuild -force\n'
        '"""\n'
        'sleep_minutes = 3\n'
        '[master.sequencer]\n'
        'type = "memory"\n')
    monkeypatch.chdir(tmp_path)
    cfg = _load_master_toml()
    assert cfg["admin_scripts"] == ["volume.fix.replication",
                                    "ec.rebuild -force"]
    assert cfg["admin_scripts_interval_s"] == 180.0
    assert "sequencer" not in cfg  # memory = default, not forwarded


def test_only_leader_runs_maintenance(tmp_path):
    """In a multi-master cluster the maintenance loops are leader-gated:
    followers wake up, see they are not leader, and do nothing — so a
    vacuum never runs twice concurrently (topology_event_handling.go's
    loop runs only on the elected master)."""
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    import asyncio

    from test_election import _make_cluster, _wait_single_leader
    from seaweedfs_tpu.shell import volume_commands as vc

    async def body():
        masters = await _make_cluster(2)
        try:
            leader = await _wait_single_leader(masters)
            follower = next(m for m in masters if m is not leader)
            # both loops configured hot; count volume_vacuum invocations
            calls = []
            orig = vc.volume_vacuum

            async def counting(env, *a, **kw):
                calls.append(env.master_url)
                return []
            vc.volume_vacuum = counting
            try:
                for m in masters:
                    m.maintenance_interval_s = 0.2
                    m._tasks.append(asyncio.create_task(
                        m._auto_vacuum_loop()))
                await asyncio.sleep(1.2)
            finally:
                vc.volume_vacuum = orig
            assert calls, "leader never ran maintenance"
            assert set(calls) == {leader.url}, (calls, leader.url,
                                                follower.url)
        finally:
            for m in masters:
                await m.stop()
    run(body())
