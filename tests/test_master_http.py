"""Master HTTP surface parity: /submit, /{fid} redirect, /vol/status,
/vol/vacuum (master_server.go:108-121 route table)."""

from __future__ import annotations

from cluster_util import Cluster, run


def test_submit_and_fid_redirect(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            murl = f"http://{c.master.url}"
            # raw-body submit
            async with c.http.post(f"{murl}/submit",
                                   data=b"submitted-bytes") as resp:
                assert resp.status == 200, await resp.text()
                sub = await resp.json()
            assert sub["size"] == 15 and "," in sub["fid"]

            # GET master/<fid> redirects to a volume server that serves it
            async with c.http.get(f"{murl}/{sub['fid']}",
                                  allow_redirects=False) as resp:
                assert resp.status == 301
                loc = resp.headers["Location"]
            async with c.http.get(loc) as resp:
                assert resp.status == 200
                assert await resp.read() == b"submitted-bytes"

            # multipart submit keeps the client file name in the reply
            import aiohttp
            form = aiohttp.FormData()
            form.add_field("file", b"mp-bytes", filename="hello.bin",
                           content_type="application/x-thing")
            async with c.http.post(f"{murl}/submit", data=form) as resp:
                assert resp.status == 200, await resp.text()
                sub2 = await resp.json()
            assert sub2["fileName"] == "hello.bin" and sub2["size"] == 8

            # unknown volume 404s instead of redirecting
            async with c.http.get(f"{murl}/999,deadbeef",
                                  allow_redirects=False) as resp:
                assert resp.status == 404

            # /vol/status mirrors the topology dump
            async with c.http.get(f"{murl}/vol/status") as resp:
                assert resp.status == 200
                assert (await resp.json())["nodes"]

    run(body())


def test_heartbeat_does_not_self_admit_whitelist(tmp_path):
    """ADVICE round 5: an empty POST to /cluster/heartbeat used to
    record req.remote into _peer_ips BEFORE any validation, letting any
    client self-admit past -whiteList on /dir/lookup. Now only a
    parseable volume-server registration admits the sender."""
    import aiohttp

    from seaweedfs_tpu.master.server import MasterServer

    async def body():
        m = MasterServer(port=0, white_list=["10.9.9.9"])
        await m.start()
        try:
            async with aiohttp.ClientSession() as http:
                murl = f"http://{m.url}"
                # garbage heartbeats: rejected, nothing admitted
                for payload in (b"", b"{}", b"[1,2]", b"{\"ip\": \"\"}"):
                    async with http.post(f"{murl}/cluster/heartbeat",
                                         data=payload) as resp:
                        assert resp.status == 400, await resp.text()
                assert not m._peer_ips
                async with http.get(
                        f"{murl}/dir/lookup",
                        params={"volumeId": "1"}) as resp:
                    assert resp.status == 401   # still whitelisted out
                # a real registration DOES admit the volume server
                from seaweedfs_tpu.pb import messages as pb
                hb = pb.Heartbeat(ip="127.0.0.1", port=12345,
                                  public_url="127.0.0.1:12345")
                async with http.post(f"{murl}/cluster/heartbeat",
                                     json=hb.to_dict()) as resp:
                    assert resp.status == 200
                assert "127.0.0.1" in m._peer_ips
                async with http.get(
                        f"{murl}/dir/lookup",
                        params={"volumeId": "1"}) as resp:
                    assert resp.status == 404   # past the guard now
        finally:
            await m.stop()

    run(body())


def test_http_vacuum_trigger(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            murl = f"http://{c.master.url}"
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"x" * 2000)
            assert st == 201
            # a second needle in the same volume, then delete the first:
            # the volume now holds reclaimable garbage
            a2 = await c.assign()
            fid2 = f"{a['fid'].split(',')[0]},{a2['fid'].split(',')[1]}"
            await c.put(fid2, a["url"], b"y" * 100)
            assert await c.delete(a["fid"], a["url"]) in (200, 202)

            async with c.http.post(
                    f"{murl}/vol/vacuum",
                    params={"garbageThreshold": "0.01"}) as resp:
                assert resp.status == 200, await resp.text()
                out = await resp.json()
            vacuumed = {v["volume"] for v in out["vacuumed"]
                        if v.get("vacuumed")}
            assert int(a["fid"].split(",")[0]) in vacuumed
            # survivor still readable, deleted needle gone
            st, data = await c.get(fid2, a["url"])
            assert (st, data) == (200, b"y" * 100)
            st, _ = await c.get(a["fid"], a["url"])
            assert st == 404

    run(body())
