"""Master HTTP surface parity: /submit, /{fid} redirect, /vol/status,
/vol/vacuum (master_server.go:108-121 route table)."""

from __future__ import annotations

from cluster_util import Cluster, run


def test_submit_and_fid_redirect(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            murl = f"http://{c.master.url}"
            # raw-body submit
            async with c.http.post(f"{murl}/submit",
                                   data=b"submitted-bytes") as resp:
                assert resp.status == 200, await resp.text()
                sub = await resp.json()
            assert sub["size"] == 15 and "," in sub["fid"]

            # GET master/<fid> redirects to a volume server that serves it
            async with c.http.get(f"{murl}/{sub['fid']}",
                                  allow_redirects=False) as resp:
                assert resp.status == 301
                loc = resp.headers["Location"]
            async with c.http.get(loc) as resp:
                assert resp.status == 200
                assert await resp.read() == b"submitted-bytes"

            # multipart submit keeps the client file name in the reply
            import aiohttp
            form = aiohttp.FormData()
            form.add_field("file", b"mp-bytes", filename="hello.bin",
                           content_type="application/x-thing")
            async with c.http.post(f"{murl}/submit", data=form) as resp:
                assert resp.status == 200, await resp.text()
                sub2 = await resp.json()
            assert sub2["fileName"] == "hello.bin" and sub2["size"] == 8

            # unknown volume 404s instead of redirecting
            async with c.http.get(f"{murl}/999,deadbeef",
                                  allow_redirects=False) as resp:
                assert resp.status == 404

            # /vol/status mirrors the topology dump
            async with c.http.get(f"{murl}/vol/status") as resp:
                assert resp.status == 200
                assert (await resp.json())["nodes"]

    run(body())


def test_http_vacuum_trigger(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            murl = f"http://{c.master.url}"
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"x" * 2000)
            assert st == 201
            # a second needle in the same volume, then delete the first:
            # the volume now holds reclaimable garbage
            a2 = await c.assign()
            fid2 = f"{a['fid'].split(',')[0]},{a2['fid'].split(',')[1]}"
            await c.put(fid2, a["url"], b"y" * 100)
            assert await c.delete(a["fid"], a["url"]) in (200, 202)

            async with c.http.post(
                    f"{murl}/vol/vacuum",
                    params={"garbageThreshold": "0.01"}) as resp:
                assert resp.status == 200, await resp.text()
                out = await resp.json()
            vacuumed = {v["volume"] for v in out["vacuumed"]
                        if v.get("vacuumed")}
            assert int(a["fid"].split(",")[0]) in vacuumed
            # survivor still readable, deleted needle gone
            st, data = await c.get(fid2, a["url"])
            assert (st, data) == (200, b"y" * 100)
            st, _ = await c.get(a["fid"], a["url"])
            assert st == 404

    run(body())
