"""wdclient MasterClient: watch-stream-fed vid→location map.

Reference: weed/wdclient/masterclient.go (KeepConnected consumer w/
failover) + vid_map.go (round-robin lookup).
"""

import asyncio

from cluster_util import Cluster, run

from seaweedfs_tpu.util.masterclient import MasterClient


def test_masterclient_sync_and_deltas(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            # create a volume before the client connects (snapshot path)
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"watch me")
            assert st == 201
            vid = int(a["fid"].split(",")[0])

            mc = MasterClient(c.master.url, name="test")
            await mc.start()
            try:
                await mc.wait_synced()
                locs = mc.lookup(vid)
                assert any(loc.url == a["url"] for loc in locs)

                # lookup_file_id returns a URL that serves the blob
                url = mc.lookup_file_id(a["fid"])
                assert url is not None
                async with c.http.get(url) as resp:
                    assert resp.status == 200
                    assert await resp.read() == b"watch me"

                # delta path: grow a new volume after connect
                a2 = await c.assign(collection="wc")
                vid2 = int(a2["fid"].split(",")[0])
                for _ in range(50):
                    if mc.lookup(vid2):
                        break
                    await asyncio.sleep(0.1)
                assert mc.lookup(vid2), "new volume never reached watcher"

                # unknown vid
                assert mc.lookup(99999) == []
                assert mc.lookup_file_id("99999,deadbeef01") is None
            finally:
                await mc.stop()
    run(body())


def test_masterclient_round_robin(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            a = await c.assign(replication="001")
            st, _ = await c.put(a["fid"], a["url"], b"rr")
            assert st == 201
            await c.heartbeat_all()
            vid = int(a["fid"].split(",")[0])
            mc = MasterClient(c.master.url)
            await mc.start()
            try:
                await mc.wait_synced()
                for _ in range(50):
                    if len(mc.lookup(vid)) == 2:
                        break
                    await asyncio.sleep(0.1)
                locs = mc.lookup(vid)
                assert len(locs) == 2
                # round-robin alternates replicas
                urls = {mc.lookup_file_id(a["fid"]) for _ in range(4)}
                assert len(urls) == 2
            finally:
                await mc.stop()
    run(body())


def test_filer_uses_watch_map(tmp_path):
    """Filer reads flow through the attached MasterClient map."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            from seaweedfs_tpu.filer.filer import Filer
            from seaweedfs_tpu.server.filer_server import FilerServer
            fs = FilerServer(Filer("memory"), c.master.url, port=0,
                             chunk_size=1024)
            await fs.start()
            try:
                await fs.master_client.wait_synced()
                payload = b"z" * 3000  # 3 chunks
                async with c.http.post(
                        f"http://{fs.url}/d/file.bin",
                        data=payload) as resp:
                    assert resp.status in (200, 201)
                async with c.http.get(
                        f"http://{fs.url}/d/file.bin") as resp:
                    assert resp.status == 200
                    assert await resp.read() == payload
                # the freshly-grown volume reaches the watch map once the
                # volume server's next heartbeat reports it
                for _ in range(50):
                    if fs.master_client.vid_count > 0:
                        break
                    await asyncio.sleep(0.1)
                assert fs.master_client.vid_count > 0
            finally:
                await fs.stop()
    run(body())
