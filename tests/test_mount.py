"""Mount-layer (FUSE node) semantics against an in-proc cluster.

Mirrors the reference's weed/filesys behaviors: write-back dirty pages
(contiguous coalescing, non-contiguous flush, oversized split), chunk
overlay on overwrite, rename/remove with data GC, truncate clipping,
xattr on Entry.extended.
"""

import asyncio

import pytest

from seaweedfs_tpu.filer.filechunks import total_size
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.mount.dir import Dir, MountError
from seaweedfs_tpu.mount.wfs import WFS, MountOptions

from cluster_util import Cluster, run


async def _with_wfs(tmpdir, fn, chunk_limit=1024):
    async with Cluster(str(tmpdir), n_servers=2) as c:
        wfs = WFS(Filer("memory"), c.master.url.replace("http://", ""),
                  MountOptions(chunk_size_limit=chunk_limit))
        await wfs.start()
        try:
            return await fn(c, wfs)
        finally:
            await wfs.close()


def test_write_read_roundtrip(tmp_path):
    async def body(c, wfs):
        root = wfs.root
        f, fh = await root.create("hello.txt")
        data = b"hello, tpu-native world"
        assert await fh.write(0, data) == len(data)
        await fh.flush()
        await fh.release()

        # fresh node: read through views
        node = await root.lookup("hello.txt")
        fh2 = node.open()
        assert await fh2.read(0, 4096) == data
        assert await fh2.read(7, 3) == data[7:10]
        a = await node.attr()
        assert a["size"] == len(data)
        await fh2.release()

    run(_with_wfs(tmp_path, body))


def test_contiguous_writes_coalesce_one_chunk(tmp_path):
    async def body(c, wfs):
        f, fh = await wfs.root.create("seq.bin")
        for i in range(8):
            await fh.write(i * 100, bytes([i]) * 100)
        await fh.flush()
        assert len(f.entry.chunks) == 1  # coalesced in the dirty buffer
        fh2 = (await wfs.root.lookup("seq.bin")).open()
        got = await fh2.read(0, 800)
        assert got == b"".join(bytes([i]) * 100 for i in range(8))

    run(_with_wfs(tmp_path, body))


def test_noncontiguous_write_forces_flush(tmp_path):
    async def body(c, wfs):
        f, fh = await wfs.root.create("gap.bin")
        await fh.write(0, b"a" * 100)
        await fh.write(500, b"b" * 100)   # gap -> flush first range
        await fh.write(100, b"c" * 100)   # backwards -> flush again
        await fh.flush()
        assert len(f.entry.chunks) == 3
        fh2 = (await wfs.root.lookup("gap.bin")).open()
        got = await fh2.read(0, 600)
        assert got[:100] == b"a" * 100
        assert got[100:200] == b"c" * 100
        assert got[500:600] == b"b" * 100

    run(_with_wfs(tmp_path, body))


def test_oversized_write_splits_chunks(tmp_path):
    async def body(c, wfs):
        f, fh = await wfs.root.create("big.bin")
        blob = bytes(range(256)) * 16  # 4096 bytes, chunk limit 1024
        await fh.write(0, blob)
        await fh.flush()
        assert len(f.entry.chunks) == 4
        fh2 = (await wfs.root.lookup("big.bin")).open()
        assert await fh2.read(0, len(blob)) == blob

    run(_with_wfs(tmp_path, body))


def test_overwrite_overlay_and_gc(tmp_path):
    async def body(c, wfs):
        f, fh = await wfs.root.create("ow.bin")
        await fh.write(0, b"x" * 300)
        await fh.flush()
        await fh.write(100, b"y" * 100)
        await fh.flush()
        fh2 = (await wfs.root.lookup("ow.bin")).open()
        got = await fh2.read(0, 300)
        assert got == b"x" * 100 + b"y" * 100 + b"x" * 100

    run(_with_wfs(tmp_path, body))


def test_mkdir_readdir_rename_remove(tmp_path):
    async def body(c, wfs):
        d = await wfs.root.mkdir("docs")
        f, fh = await d.create("a.txt")
        await fh.write(0, b"A")
        await fh.flush()
        await fh.release()
        names = [e.name for e in await d.read_dir_all()]
        assert names == ["a.txt"]

        # rename into a sibling dir
        d2 = await wfs.root.mkdir("archive")
        await d.rename("a.txt", d2, "b.txt")
        assert [e.name for e in await d2.read_dir_all()] == ["b.txt"]
        with pytest.raises(MountError):
            await d.lookup("a.txt")
        node = await d2.lookup("b.txt")
        assert await node.open().read(0, 10) == b"A"

        # rmdir non-empty fails; file remove drops chunks
        with pytest.raises(MountError):
            await wfs.root.remove("archive", is_dir=True)
        await d2.remove("b.txt")
        deleted = await wfs.drain_deletes()
        assert deleted >= 1
        await wfs.root.remove("archive", is_dir=True)

    run(_with_wfs(tmp_path, body))


def test_truncate_clips_chunks(tmp_path):
    async def body(c, wfs):
        f, fh = await wfs.root.create("t.bin")
        await fh.write(0, b"q" * 1000)
        await fh.write(1000, b"r" * 1000)  # second chunk after flush
        await fh.flush()
        node = await wfs.root.lookup("t.bin")
        await node.setattr(size=1500)
        entry = wfs.filer.find_entry("/t.bin")
        assert total_size(entry.chunks) == 1500
        await node.setattr(size=0)
        entry = wfs.filer.find_entry("/t.bin")
        assert entry.chunks == []

    run(_with_wfs(tmp_path, body, chunk_limit=1000))


def test_xattr(tmp_path):
    async def body(c, wfs):
        f, fh = await wfs.root.create("x.txt")
        await fh.flush()
        node = await wfs.root.lookup("x.txt")
        await node.set_xattr("user.tag", b"\x01\x02")
        assert await node.get_xattr("user.tag") == b"\x01\x02"
        assert await node.list_xattr() == ["user.tag"]
        await node.remove_xattr("user.tag")
        with pytest.raises(MountError):
            await node.get_xattr("user.tag")

        d = await wfs.root.mkdir("xd")
        await d.set_xattr("user.k", b"v")
        assert await d.get_xattr("user.k") == b"v"

    run(_with_wfs(tmp_path, body))


def test_sparse_read_zero_fills_and_eof(tmp_path):
    async def body(c, wfs):
        f, fh = await wfs.root.create("sp.bin")
        await fh.write(0, b"a" * 10)
        await fh.write(20, b"b" * 10)  # hole [10,20)
        await fh.flush()
        node = await wfs.root.lookup("sp.bin")
        fh2 = node.open()
        assert await fh2.read(0, 30) == b"a" * 10 + b"\0" * 10 + b"b" * 10
        assert await fh2.read(12, 5) == b"\0" * 5   # inside the hole
        assert await fh2.read(0, 15) == b"a" * 10 + b"\0" * 5
        assert await fh2.read(30, 10) == b""        # EOF
    run(_with_wfs(tmp_path, body))


def test_fsync_then_sequential_writes_still_coalesce(tmp_path):
    async def body(c, wfs):
        f, fh = await wfs.root.create("fs.bin")
        await fh.write(0, b"x" * 500)
        await fh.flush()                 # periodic fsync
        for i in range(5):
            await fh.write(500 + i * 100, bytes([i]) * 100)
        await fh.flush()
        # post-fsync sequential writes coalesce into ONE more chunk
        assert len(f.entry.chunks) == 2
    run(_with_wfs(tmp_path, body))
