"""MXU GF(2) bit-matmul path (ops/gf256_mxu.py) vs the CPU oracle.

bench.py races this formulation against the VPU Pallas kernel on the real
chip; these tests pin its correctness off-chip (plain XLA, runs on the CPU
backend) so a fast-but-wrong path can never win the race. Contract under
test: klauspost Encode/Reconstruct semantics
(/root/reference/weed/storage/erasure_coding/ec_encoder.go:192,264).
"""

import os

import numpy as np
import pytest

from seaweedfs_tpu.ec import gf
from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
from seaweedfs_tpu.ops.gf256_mxu import mxu_words_transform
from seaweedfs_tpu.ops.gf256_pallas import bytes_to_words, words_to_bytes


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(21)


def _run(coeff, byte_rows, n, block_bm=8):
    words = [bytes_to_words(b, block_bm=block_bm) for b in byte_rows]
    outs = mxu_words_transform(np.asarray(coeff, np.uint8), words)
    return [words_to_bytes(np.asarray(o), n) for o in outs]


def test_mxu_encode_matches_cpu(rng):
    cpu = CpuEncoder(use_native=False)
    for n in (512, 4096, 128 * 1024):  # one and many (wm,128) word rows
        data = [rng.integers(0, 256, n).astype(np.uint8) for _ in range(10)]
        want = cpu.encode(list(data))[10:]
        got = _run(gf.parity_matrix(), data, n)
        for p in range(4):
            assert np.array_equal(got[p], want[p]), (n, p)


def test_mxu_encode_unaligned_padding(rng):
    """n not a multiple of the word-block quantum: zero padding must not
    perturb the live prefix (GF transform is elementwise over bytes)."""
    cpu = CpuEncoder(use_native=False)
    n = 1000
    data = [rng.integers(0, 256, n).astype(np.uint8) for _ in range(10)]
    want = cpu.encode(list(data))[10:]
    got = _run(gf.parity_matrix(), data, n)
    for p in range(4):
        assert np.array_equal(got[p], want[p]), p


def test_mxu_rebuild_coeffs(rng):
    """Rebuild matrices: worst-case 4 data shards lost, mixed losses, and
    a single-row reconstruct — the shapes store_ec.go:322 generates."""
    cpu = CpuEncoder(use_native=False)
    n = 2048
    shards = cpu.encode([rng.integers(0, 256, n).astype(np.uint8)
                         for _ in range(10)])
    cases = [
        ([0, 1, 2, 3], list(range(4, 14))),
        ([0, 5, 11, 13], [1, 2, 3, 4, 6, 7, 8, 9, 10, 12]),
        ([7], [0, 1, 2, 3, 4, 5, 6, 8, 9, 10]),
    ]
    for want_rows, present in cases:
        coeff = gf.shard_rows(want_rows, present)
        got = _run(coeff, [shards[i] for i in present], n)
        for j, sid in enumerate(want_rows):
            assert np.array_equal(got[j], shards[sid]), (want_rows, sid)


def test_mxu_multiple_wm_blocks(rng):
    """Several grid blocks with the default block quantum (the shape the
    bench times)."""
    cpu = CpuEncoder(use_native=False)
    n = 384 * 1024  # wm=768 -> 3 blocks at block_bm=256
    data = [rng.integers(0, 256, n).astype(np.uint8) for _ in range(10)]
    want = cpu.encode(list(data))[10:]
    got = _run(gf.parity_matrix(), data, n, block_bm=256)
    for p in range(4):
        assert np.array_equal(got[p], want[p]), p


def test_mxu_chunked_streaming(rng):
    """wm > chunk_wm streams through lax.map chunks (the blocking that
    keeps the 32x bitplane expansion off HBM at 64MB+ shard sizes),
    including a ragged final chunk."""
    cpu = CpuEncoder(use_native=False)
    n = 5 * 8 * 512  # wm=40: chunk_wm=16 -> 2 full chunks + ragged 8
    data = [rng.integers(0, 256, n).astype(np.uint8) for _ in range(10)]
    want = cpu.encode(list(data))[10:]
    words = [bytes_to_words(b, block_bm=8) for b in data]
    outs = mxu_words_transform(np.asarray(gf.parity_matrix(), np.uint8),
                               words, chunk_wm=16)
    got = [words_to_bytes(np.asarray(o), n) for o in outs]
    for p in range(4):
        assert np.array_equal(got[p], want[p]), p


def test_pipeline_with_mxu_method(rng, tmp_path, monkeypatch):
    """SWTPU_EC_METHOD=mxu drives the whole file pipeline through the MXU
    formulation (pipeline.py branch) and must produce identical shards."""
    from seaweedfs_tpu.ec import pipeline as pl
    from seaweedfs_tpu.ec.encoder_jax import JaxEncoder

    n = 40960
    base_cpu = str(tmp_path / "c")
    base_mxu = str(tmp_path / "m")
    payload = rng.integers(0, 256, n).astype(np.uint8).tobytes()
    for b in (base_cpu, base_mxu):
        with open(b + ".dat", "wb") as f:
            f.write(payload)
    pl.write_ec_files(base_cpu, encoder=pl.get_encoder("cpu"),
                      large_block=4096, small_block=512, buffer_size=512)
    monkeypatch.setenv("SWTPU_EC_METHOD", "mxu")
    pl.write_ec_files(base_mxu, encoder=JaxEncoder(use_pallas=False),
                      large_block=4096, small_block=512, buffer_size=512)
    for i in range(14):
        with open(base_cpu + pl.to_ext(i), "rb") as a, \
                open(base_mxu + pl.to_ext(i), "rb") as b:
            assert a.read() == b.read(), i
