"""Native GF(256) kernel (native/gf256.c) vs the numpy oracle.

Mirrors the reference's dual-oracle pattern (ec_test.go:20-177): the same
bytes must come back whether produced by the assembly-speed path or the
table-lookup oracle.
"""

from __future__ import annotations

import numpy as np
import pytest

from seaweedfs_tpu.ec import gf
from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
from seaweedfs_tpu.native import gf256 as native_gf
from seaweedfs_tpu.util.crc32c import crc32c

pytestmark = pytest.mark.skipif(
    not native_gf.available(), reason="native toolchain unavailable")


def _rand(n: int, seed: int = 0) -> list[np.ndarray]:
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, n).astype(np.uint8)
            for _ in range(gf.DATA_SHARDS)]


def test_native_encode_matches_numpy_oracle():
    data = _rand(100_003)  # odd length exercises the AVX2 tail loop
    a = CpuEncoder(use_native=False).encode(list(data))
    b = CpuEncoder(use_native=True).encode(list(data))
    assert all(np.array_equal(x, y) for x, y in zip(a, b))


def test_native_avx2_matches_scalar():
    data = _rand(65_537, seed=3)
    consts = gf.parity_matrix()
    s = native_gf.transform(consts, data, scalar=True)
    v = native_gf.transform(consts, data, scalar=False)
    assert all(np.array_equal(x, y) for x, y in zip(s, v))


def test_native_reconstruct_all_loss_patterns():
    data = _rand(4_096, seed=5)
    enc = CpuEncoder(use_native=True)
    full = enc.encode(list(data))
    # worst case: all four lost are data shards; also mixed + parity-only
    for lost in [(0, 1, 2, 3), (0, 5, 10, 13), (10, 11, 12, 13), (7,)]:
        part = [None if i in lost else full[i] for i in range(gf.TOTAL_SHARDS)]
        out = enc.reconstruct(part)
        for i in range(gf.TOTAL_SHARDS):
            assert np.array_equal(out[i], full[i]), (lost, i)


def test_native_random_matrix_agrees_with_gf_math():
    rng = np.random.default_rng(11)
    consts = rng.integers(0, 256, (3, 5)).astype(np.uint8)
    inputs = [rng.integers(0, 256, 1_000).astype(np.uint8) for _ in range(5)]
    got = native_gf.transform(consts, inputs)
    for r in range(3):
        want = np.zeros(1_000, np.uint8)
        for j in range(5):
            want ^= gf.mul_table(int(consts[r, j]))[inputs[j]]
        assert np.array_equal(got[r], want)


def test_native_crc32c_vector():
    # RFC 3720 test vector for CRC32-Castagnoli
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0
