"""Native compact needle map vs the dict-based oracle.

Mirrors the reference's compact_map_test.go (correctness incl. overwrite
and tombstone replay) and a scaled-down compact_map_perf_test.go
(bulk-insert throughput + lookups over a million keys).
"""

from __future__ import annotations

import random
import time

import pytest

from seaweedfs_tpu.native import needle_map as native_nm
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle_map import (CompactNeedleMap,
                                              MemoryNeedleMap,
                                              best_needle_map)

pytestmark = pytest.mark.skipif(
    not native_nm.available(), reason="native toolchain unavailable")


def test_native_map_basics():
    m = native_nm.NativeMap()
    try:
        assert m.get(1) is None
        assert m.set(1, 100, 10) is None
        assert m.set(2, 200, 20) is None
        assert m.get(1) == (100, 10)
        old = m.set(1, 300, 30)  # overwrite returns previous
        assert old == (100, 10)
        assert m.get(1) == (300, 30)
        assert len(m) == 2
        assert sorted(m.items()) == [(1, 300, 30), (2, 200, 20)]
    finally:
        m.close()


def test_native_map_survives_growth_and_random_ops():
    m = native_nm.NativeMap()
    oracle: dict[int, tuple[int, int]] = {}
    rng = random.Random(7)
    try:
        for _ in range(50_000):
            k = rng.randrange(1, 20_000)
            v = (rng.randrange(2**32), rng.randrange(2**32))
            m.set(k, *v)
            oracle[k] = v
        assert len(m) == len(oracle)
        for k, v in oracle.items():
            assert m.get(k) == v
        assert m.get(999_999_999) is None
    finally:
        m.close()


def test_compact_needle_map_matches_dict_map(tmp_path):
    """Same .idx replay (puts, overwrites, tombstones) must produce
    identical state through both map kinds."""
    ops = []
    rng = random.Random(3)
    for i in range(1, 500):
        ops.append(("put", i, i * 8, 100 + i))
    for i in range(1, 500, 7):
        ops.append(("del", i, 50_000 + i))
    for i in range(1, 500, 13):
        ops.append(("put", i, 100_000 + i * 8, 300))

    def replay(map_cls, path):
        nm = map_cls(path)
        for op in ops:
            if op[0] == "put":
                nm.put(op[1], op[2], op[3])
            else:
                nm.delete(op[1], op[2])
        return nm

    a = replay(MemoryNeedleMap, str(tmp_path / "a.idx"))
    b = replay(CompactNeedleMap, str(tmp_path / "b.idx"))
    try:
        assert len(a) == len(b)
        assert a.file_count == b.file_count
        assert a.deleted_count == b.deleted_count
        assert a.deleted_bytes == b.deleted_bytes
        assert a.max_file_key == b.max_file_key
        for k in range(1, 500):
            va, vb = a.get(k), b.get(k)
            assert (va is None) == (vb is None), k
            if va is not None:
                assert (va.offset, va.size) == (vb.offset, vb.size), k
        # reload from the .idx files written by each
        a2 = MemoryNeedleMap(str(tmp_path / "b.idx"))  # cross-read
        for k in range(1, 500):
            va, vb = a2.get(k), b.get(k)
            assert (va is None) == (vb is None), k
    finally:
        a.close()
        b.close()


def test_native_map_bulk_million():
    """Scaled compact_map_perf_test: 1M ascending keys, then lookups."""
    m = native_nm.NativeMap()
    try:
        n = 1_000_000
        t0 = time.perf_counter()
        for k in range(1, n + 1):
            m.set(k, k & 0xFFFFFFFF, 128)
        insert_s = time.perf_counter() - t0
        assert len(m) == n
        t0 = time.perf_counter()
        for k in range(1, n + 1, 97):
            assert m.get(k) is not None
        lookup_s = time.perf_counter() - t0
        # loose sanity bound: a million ctypes inserts should be seconds,
        # not minutes (the C side itself is ~10ns/op)
        assert insert_s < 30 and lookup_s < 5, (insert_s, lookup_s)
    finally:
        m.close()


def test_offsets_past_4gib_survive(tmp_path):
    """Raw byte offsets past 4 GiB must round-trip (stored /8 like .idx;
    a raw uint32 store would wrap silently)."""
    nm = CompactNeedleMap(str(tmp_path / "big.idx"))
    try:
        big = (1 << 32) + 8 * 123  # > 4 GiB, 8-byte aligned
        nm.put(42, big, 512)
        got = nm.get(42)
        assert got is not None and got.offset == big and got.size == 512
        # tombstone with offset at the high end too
        nm.delete(42, big + 1024)
        assert nm.get(42).size == t.TOMBSTONE_FILE_SIZE
    finally:
        nm.close()


def test_best_needle_map_selects_native(tmp_path):
    nm = best_needle_map(str(tmp_path / "x.idx"))
    assert isinstance(nm, CompactNeedleMap)
    nm.put(5, 80, 64)
    assert nm.get(5).offset == 80
    nm.close()
