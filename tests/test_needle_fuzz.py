"""Seeded randomized round-trip of the needle wire format.

needle.py's v2/v3 serialization (v1 carries only cookie/id/data and
gets its own round trip below) (needle_read_write.go:128-200) packs
variable-length name/mime/pairs/TTL behind flag bits with 8-byte
alignment; a mis-sized field silently shifts every later one. 400
random needles round-trip byte-exactly through to_bytes/from_bytes, and
the on-disk record parses back through the volume scan path too."""

from __future__ import annotations

import random

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import (FLAG_HAS_LAST_MODIFIED, Needle)


def _rand_needle(rng: random.Random, live: bool = False) -> Needle:
    n = Needle(
        cookie=rng.randrange(1 << 32),
        id=rng.randrange(1, 1 << 63),
        data=rng.randbytes(rng.randint(0, 2000)),
        name=rng.randbytes(rng.randint(0, 80)) if rng.random() < 0.5
        else b"",
        mime=(b"application/x-" + rng.randbytes(5).hex().encode())
        if rng.random() < 0.4 else b"",
        pairs=(b'{"k":"' + rng.randbytes(4).hex().encode() + b'"}')
        if rng.random() < 0.3 else b"",
        ttl=t.TTL(rng.randint(1, 255), rng.choice((1, 2, 3, 4)))
        if rng.random() < 0.3 else t.TTL(),
    )
    if live:
        # volume reads enforce TTL expiry against last_modified; keep
        # these needles alive
        import time
        n.ttl = t.TTL()
        n.last_modified = int(time.time())
        n.set_flag(FLAG_HAS_LAST_MODIFIED)
    elif rng.random() < 0.5:
        n.last_modified = rng.randrange(1, 1 << 38)
        n.set_flag(FLAG_HAS_LAST_MODIFIED)
    if rng.random() < 0.5:
        n.append_at_ns = rng.randrange(1, 1 << 62)
    return n


def test_needle_roundtrip_fuzz():
    rng = random.Random(99)
    for case in range(400):
        version = rng.choice((2, 3))
        n = _rand_needle(rng)
        blob = n.to_bytes(version)
        assert len(blob) % 8 == 0, "record not 8-byte aligned"
        m = Needle.from_bytes(blob, version)
        assert m.cookie == n.cookie and m.id == n.id, case
        assert m.data == n.data, case
        assert m.name == n.name, case
        assert m.mime == n.mime, case
        assert m.pairs == n.pairs, case
        assert (m.ttl.count, m.ttl.unit) == (n.ttl.count, n.ttl.unit), case
        if n.has(FLAG_HAS_LAST_MODIFIED):
            assert m.last_modified == n.last_modified, case
        if version == 3:
            assert m.append_at_ns == n.append_at_ns, case
        # re-serialization is byte-stable
        assert m.to_bytes(version) == blob, case
        # v1 keeps only cookie/id/data
        n1 = Needle(cookie=n.cookie, id=n.id, data=n.data)
        b1 = n1.to_bytes(1)
        m1 = Needle.from_bytes(b1, 1)
        assert (m1.cookie, m1.id, m1.data) == (n.cookie, n.id, n.data)


def test_needle_volume_roundtrip_fuzz(tmp_path):
    from seaweedfs_tpu.storage.volume import Volume

    rng = random.Random(7)
    v = Volume(str(tmp_path), "", 77)
    wrote = []
    for i in range(60):
        n = _rand_needle(rng, live=True)
        n.id = i + 1
        v.write_needle(n)
        wrote.append(n)
    for n in wrote:
        got = v.read_needle(n.id, n.cookie)
        assert got.data == n.data
        assert got.name == n.name
        assert got.mime == n.mime
        assert got.pairs == n.pairs
    v.close()
    # reload from disk: integrity check + reads still agree
    v2 = Volume(str(tmp_path), "", 77, create_if_missing=False)
    for n in wrote:
        assert v2.read_needle(n.id, n.cookie).data == n.data
    v2.close()
