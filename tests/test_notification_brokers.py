"""Fake-driver contract tests for the kafka/SQS/GCP-pubsub publishers.

The driver libraries aren't in this image; these fakes expose the exact
client surface the adapters call (kafka_queue.go / aws_sqs_pub.go /
google_pub_sub.go analogs), so the publish logic executes in CI and a
drift in the adapter <-> driver contract fails here, not in production.
"""

import json

import pytest

from seaweedfs_tpu.notification.brokers import (GooglePubSubQueue,
                                                KafkaQueue, SqsQueue)
from seaweedfs_tpu.notification.queues import (MESSAGE_QUEUES, event_of,
                                               load_configuration)


class FakeKafkaProducer:
    def __init__(self):
        self.sent = []
        self.flushed = self.closed = False

    def send(self, topic, key=None, value=None):
        self.sent.append((topic, key, value))

    def flush(self):
        self.flushed = True

    def close(self):
        self.closed = True


class FakeSqsClient:
    def __init__(self, existing=()):
        self.queues = {n: f"https://sqs.fake/{n}" for n in existing}
        self.messages = []

    def get_queue_url(self, QueueName):
        if QueueName not in self.queues:
            raise KeyError(QueueName)
        return {"QueueUrl": self.queues[QueueName]}

    def create_queue(self, QueueName):
        self.queues[QueueName] = f"https://sqs.fake/{QueueName}"
        return {"QueueUrl": self.queues[QueueName]}

    def send_message(self, QueueUrl, MessageBody, MessageAttributes):
        self.messages.append((QueueUrl, MessageBody, MessageAttributes))


class FakePublisherClient:
    def __init__(self, existing=()):
        self.topics = set(existing)
        self.published = []

    def topic_path(self, project, topic):
        return f"projects/{project}/topics/{topic}"

    def get_topic(self, topic):
        if topic not in self.topics:
            raise KeyError(topic)

    def create_topic(self, name):
        self.topics.add(name)

    def publish(self, topic, data, **attrs):
        self.published.append((topic, data, attrs))


def test_kafka_publish_and_close():
    q = KafkaQueue()
    fake = FakeKafkaProducer()
    q.initialize({"hosts": ["h:9092"], "topic": "events"}, client=fake)
    q.send_message("/a/b.txt", {"x": 1})
    q.send_message("/c", {"y": 2})
    assert fake.sent[0] == ("events", b"/a/b.txt", b'{"x": 1}')
    assert fake.sent[1][1] == b"/c"
    q.close()
    assert fake.flushed and fake.closed


def test_sqs_existing_and_created_queue():
    q = SqsQueue()
    fake = FakeSqsClient(existing=["weedq"])
    q.initialize({"region": "us-east-1", "sqs_queue_name": "weedq"},
                 client=fake)
    q.send_message("/k", {"n": 3})
    url, body, attrs = fake.messages[0]
    assert url.endswith("/weedq")
    assert json.loads(body) == {"n": 3}
    assert attrs["key"]["StringValue"] == "/k"

    q2 = SqsQueue()
    fake2 = FakeSqsClient()  # queue absent -> created
    q2.initialize({"sqs_queue_name": "newq"}, client=fake2)
    assert "newq" in fake2.queues


def test_pubsub_topic_ensure_and_publish():
    q = GooglePubSubQueue()
    fake = FakePublisherClient()
    q.initialize({"project_id": "p1", "topic": "t1"}, client=fake)
    assert "projects/p1/topics/t1" in fake.topics  # created on demand
    q.send_message("/z", {"m": 4})
    topic, data, attrs = fake.published[0]
    assert topic == "projects/p1/topics/t1"
    assert json.loads(data) == {"m": 4}
    assert attrs == {"key": "/z"}


def test_uninitialized_brokers_raise_clear_errors():
    for q in (KafkaQueue(), SqsQueue(), GooglePubSubQueue()):
        with pytest.raises(RuntimeError, match="not initialized"):
            q.send_message("/x", {})
    # driver import is gated with an actionable message
    with pytest.raises(RuntimeError, match="kafka-python"):
        KafkaQueue().initialize({"hosts": ["h:9092"]})
    with pytest.raises(RuntimeError, match="boto3"):
        SqsQueue().initialize({"sqs_queue_name": "q"})
    with pytest.raises(RuntimeError, match="google-cloud-pubsub"):
        GooglePubSubQueue().initialize({"project_id": "p"})


def test_registry_contains_brokers():
    names = {q.name for q in MESSAGE_QUEUES}
    assert {"kafka", "aws_sqs", "google_pub_sub"} <= names
    # exactly-one-enabled rule still applies across broker entries
    with pytest.raises(ValueError):
        load_configuration({"kafka": {"enabled": True},
                            "aws_sqs": {"enabled": True}})


def test_event_roundtrip_through_fake_broker():
    """attach-style event payloads survive the broker wire format."""
    class E:
        def to_dict(self):
            return {"FullPath": "/a", "chunks": []}
        dir_path = "/"
    q = KafkaQueue()
    fake = FakeKafkaProducer()
    q.initialize({"hosts": []}, client=fake)
    q.send_message("/a", event_of(None, E()))
    _, key, value = fake.sent[0]
    ev = json.loads(value)
    assert ev["new_entry"]["FullPath"] == "/a"
    assert ev["old_entry"] is None
