"""4/5-byte offset widths (reference: storage/types/offset_5bytes.go,
Makefile:16 `5BytesOffset` build tag) and the silent-wrap guards around
the 32 GiB boundary."""

import numpy as np
import pytest

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle_map import (MemoryNeedleMap, NeedleValue,
                                              pack_entry, unpack_entry,
                                              walk_index_blob,
                                              write_sorted_index,
                                              SortedFileNeedleMap)


@pytest.fixture
def five_byte():
    t.set_offset_size(5)
    yield
    t.set_offset_size(4)


def test_defaults_match_reference_4byte_layout():
    assert t.OFFSET_SIZE == 4
    assert t.NEEDLE_MAP_ENTRY_SIZE == 16
    assert t.max_volume_size() == 32 * 1024 ** 3


def test_4byte_offset_overflow_raises():
    """Past 32 GiB the 4-byte width must refuse, not wrap (wrapping maps
    reads to the wrong needle — silent corruption)."""
    with pytest.raises(OverflowError, match="set_offset_size"):
        t.offset_to_bytes(32 * 1024 ** 3)
    # largest representable offset still round-trips
    top = 32 * 1024 ** 3 - 8
    assert t.offset_from_bytes(t.offset_to_bytes(top)) == top


def test_5byte_wire_layout_matches_reference(five_byte):
    """offset_5bytes.go:18-24 stores the LOW 32 bits big-endian in
    bytes[0..3] and the HIGH byte LAST — pin the exact wire bytes so a
    reference-written 5-byte index parses identically."""
    units = (0x07 << 32) | 0x0A0B0C0D
    blob = pack_entry(0x11, units * 8, 5)
    # key(8) + low32-BE + high byte + size(4)
    assert blob[8:13] == bytes([0x0A, 0x0B, 0x0C, 0x0D, 0x07])
    assert t.offset_to_bytes(units * 8) == bytes(
        [0x0A, 0x0B, 0x0C, 0x0D, 0x07])
    assert t.offset_from_bytes(blob[8:13]) == units * 8


def test_5byte_entry_roundtrip_past_32gb(five_byte):
    """Synthetic >32 GiB offsets round-trip through the 17-byte entry
    (offset_5bytes.go:14-16: 8 TB volumes)."""
    assert t.NEEDLE_MAP_ENTRY_SIZE == 17
    assert t.max_volume_size() == 8 * 1024 ** 4
    for off in (0, 8, 32 * 1024 ** 3, 5 * 1024 ** 4, 8 * 1024 ** 4 - 8):
        blob = pack_entry(0x1234, off, 777)
        assert len(blob) == 17
        key, got_off, size = unpack_entry(blob)
        assert (key, got_off, size) == (0x1234, off, 777)
    with pytest.raises(OverflowError):
        t.offset_to_bytes(8 * 1024 ** 4)


def test_5byte_idx_log_and_walk(five_byte, tmp_path):
    """MemoryNeedleMap .idx append log + reload with 5-byte entries,
    including an offset far past the 4-byte range."""
    path = str(tmp_path / "v.idx")
    nm = MemoryNeedleMap(path)
    big = 40 * 1024 ** 3  # > 32 GiB
    nm.put(1, 8, 100)
    nm.put(2, big, 200)
    nm.delete(1, big + 1024)
    nm.close()

    nm2 = MemoryNeedleMap(path)
    assert nm2.get(2).offset == big
    assert nm2.get(1).size == t.TOMBSTONE_FILE_SIZE
    with open(path, "rb") as fh:
        entries = list(walk_index_blob(fh.read()))
    assert entries[1] == (2, big, 200)
    nm2.close()


def test_5byte_sorted_index(five_byte, tmp_path):
    path = str(tmp_path / "v.ecx")
    big = 100 * 1024 ** 3
    write_sorted_index([(7, big, 50), (3, 16, 20)], path)
    sm = SortedFileNeedleMap(path)
    assert sm.get(7).offset == big
    assert sm.get(3).offset == 16
    assert sm.get(99) is None
    sm.close()


def test_native_compact_map_refuses_past_32gb():
    """ADVICE: the native uint32 store must raise instead of letting
    ctypes silently truncate offsets past 32 GiB."""
    from seaweedfs_tpu.native import needle_map as native_nm

    if not native_nm.available():
        pytest.skip("native map not built")
    from seaweedfs_tpu.storage.needle_map import _NativeMapAdapter

    ad = _NativeMapAdapter()
    ad[1] = NeedleValue(1, 8, 10)
    assert ad.get(1).offset == 8
    with pytest.raises(OverflowError, match="32 GiB"):
        ad[2] = NeedleValue(2, 33 * 1024 ** 3, 10)
    ad.close()


def test_best_needle_map_5byte_avoids_native(five_byte):
    from seaweedfs_tpu.storage.needle_map import (CompactNeedleMap,
                                                  best_needle_map)

    nm = best_needle_map(kind="auto")
    assert not isinstance(nm, CompactNeedleMap)
    nm.close()
    with pytest.raises(ValueError, match="5-byte"):
        best_needle_map(kind="compact")


def test_volume_roundtrip_with_5byte_offsets(five_byte, tmp_path):
    """A whole volume written/read under the 5-byte width (same data
    path, wider index entries)."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    d = str(tmp_path)
    v = Volume(d, "", 9)
    rng = np.random.default_rng(5)
    blobs = {i: rng.integers(0, 256, 100 + i).astype(np.uint8).tobytes()
             for i in range(1, 20)}
    for i, data in blobs.items():
        v.write_needle(Needle(cookie=i, id=i, data=data))
    v.close()

    v2 = Volume(d, "", 9, create_if_missing=False)
    for i, data in blobs.items():
        assert v2.read_needle(i, cookie=i).data == data
    v2.close()
