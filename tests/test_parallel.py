"""Distributed EC over the 8-device virtual CPU mesh."""

import jax
import numpy as np
import pytest

if not hasattr(jax, "shard_map"):
    pytest.skip("jax.shard_map missing in installed jax "
                f"({jax.__version__}); parallel/mesh.py needs it",
                allow_module_level=True)

from seaweedfs_tpu.ec import gf
from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
from seaweedfs_tpu.parallel import mesh as pmesh


def test_mesh_shape(eight_devices):
    m = pmesh.make_mesh(eight_devices)
    assert m.devices.size == 8
    assert m.axis_names == ("vol", "shard")


def test_batched_encode_matches_oracle(eight_devices):
    m = pmesh.make_mesh(eight_devices)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (8, 10, 1024)).astype(np.uint8)
    out = np.asarray(pmesh.batched_encode(m, data))
    assert out.shape == (8, 14, 1024)
    oracle = CpuEncoder()
    for v in (0, 3, 7):
        want = oracle.encode([r for r in data[v]])
        for sid in range(14):
            assert np.array_equal(out[v, sid], want[sid]), (v, sid)


def test_batched_encode_odd_word_count(eight_devices):
    """Per-device word counts that aren't multiples of the preferred
    Pallas block (wm=264 on a 1-wide shard axis) must still tile — the
    kernel falls back to a gcd block size."""
    import jax
    m = pmesh.make_mesh(jax.devices()[:1])
    rng = np.random.default_rng(7)
    n = 264 * 512  # wm=264: not a multiple of bm=256
    data = rng.integers(0, 256, (1, 10, n)).astype(np.uint8)
    out = np.asarray(pmesh.batched_encode(m, data))
    want = CpuEncoder().encode([r for r in data[0]])
    for sid in range(14):
        assert np.array_equal(out[0, sid], want[sid]), sid


def test_full_cycle_rebuild(eight_devices):
    m = pmesh.make_mesh(eight_devices)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (4, 10, 512)).astype(np.uint8)
    lost = (2, 5, 10, 13)
    encoded, rebuilt = pmesh.full_cycle_step(m, data, lost_rows=lost)
    encoded, rebuilt = np.asarray(encoded), np.asarray(rebuilt)
    for v in range(4):
        for j, sid in enumerate(lost):
            assert np.array_equal(rebuilt[v, j], encoded[v, sid]), (v, sid)


def test_batched_verify_scrub(eight_devices):
    m = pmesh.make_mesh(eight_devices)
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (5, 10, 1024)).astype(np.uint8)
    encoded = np.asarray(pmesh.batched_encode(m, data))
    clean = np.asarray(pmesh.batched_verify(m, encoded))
    assert clean.tolist() == [0] * 5
    # flip one byte in a data shard of volume 3: exactly the parity
    # bytes of that column go inconsistent (4 parity rows -> count 4)
    corrupt = encoded.copy()
    corrupt[3, 2, 100] ^= 0x5A
    bad = np.asarray(pmesh.batched_verify(m, corrupt))
    assert bad[3] > 0 and all(bad[v] == 0 for v in range(5) if v != 3)
    # a flipped PARITY byte is also caught, on the right volume
    corrupt2 = encoded.copy()
    corrupt2[1, 12, 7] ^= 1
    bad2 = np.asarray(pmesh.batched_verify(m, corrupt2))
    assert bad2[1] == 1 and bad2[3] == 0
