"""Dump-on-demand profiling (util/pprof.py): dump_now() snapshots the
armed cProfile/tracemalloc profiles mid-flight and keeps sampling,
SIGUSR2 triggers the same dump, and /debug/pprof serves the armed
state (?dump=1 writes the files) on the live servers.
"""

from __future__ import annotations

import os
import signal
import time

import pytest

from seaweedfs_tpu.util import pprof

from cluster_util import Cluster, run


@pytest.fixture(autouse=True)
def _clean_module_state():
    """setup_profiling mutates module globals and starts tracemalloc;
    restore so other tests see an unarmed process."""
    yield
    import tracemalloc
    with pprof._lock:
        prof = pprof._cpu[0] if pprof._cpu else None
    if prof is not None:
        prof.disable()
    pprof._cpu = None
    pprof._mem_path = ""
    if tracemalloc.is_tracing():
        tracemalloc.stop()


def _burn():
    return sum(i * i for i in range(20_000))


def test_dump_now_snapshots_mid_flight_and_keeps_profiling(tmp_path):
    cpu = str(tmp_path / "cpu.prof")
    mem = str(tmp_path / "mem.txt")
    pprof.setup_profiling(cpu_profile=cpu, mem_profile=mem)
    assert pprof.pprof_dict() == {"cpu": True, "mem": True}
    _burn()
    out = pprof.dump_now()
    assert out == {"cpu": cpu, "mem": mem}
    assert os.path.getsize(cpu) > 0
    assert os.path.getsize(mem) > 0
    # profiling continued after the dump: a later snapshot has MORE
    # accumulated call data than the first
    first = os.path.getsize(cpu)
    for _ in range(5):
        _burn()
    pprof.dump_now()
    assert os.path.getsize(cpu) >= first


def test_worker_index_suffixes_dump_paths(tmp_path):
    assert pprof.profile_path("/x/p.out", 3) == "/x/p.out.w3"
    assert pprof.profile_path("/x/p.out", -1) == "/x/p.out"
    cpu = str(tmp_path / "w.prof")
    pprof.setup_profiling(cpu_profile=cpu, worker_index=1)
    assert pprof.dump_now() == {"cpu": cpu + ".w1"}
    assert os.path.exists(cpu + ".w1")


def test_sigusr2_dumps_on_demand(tmp_path):
    cpu = str(tmp_path / "sig.prof")
    pprof.setup_profiling(cpu_profile=cpu)
    assert not os.path.exists(cpu)
    os.kill(os.getpid(), signal.SIGUSR2)
    # the handler runs synchronously on the main thread's next bytecode
    for _ in range(100):
        if os.path.exists(cpu):
            break
        time.sleep(0.01)
    assert os.path.exists(cpu) and os.path.getsize(cpu) > 0


def test_dump_now_unarmed_is_empty():
    assert pprof.dump_now() == {}
    assert pprof.pprof_dict(dump=True) == {"cpu": False, "mem": False,
                                           "dumped": {}}


def test_debug_pprof_route_reports_and_dumps(tmp_path):
    async def go():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            # nothing armed: the route reports so without writing
            async with c.http.get(
                    f"http://{vs.url}/debug/pprof") as r:
                assert r.status == 200
                body = await r.json()
            assert body == {"cpu": False, "mem": False}
            # arm mid-run, then dump through the route
            cpu = str(tmp_path / "route.prof")
            pprof.setup_profiling(cpu_profile=cpu)
            async with c.http.get(
                    f"http://{vs.url}/debug/pprof",
                    params={"dump": "1"}) as r:
                assert r.status == 200
                body = await r.json()
            assert body["cpu"] and body["dumped"]["cpu"] == cpu
            assert os.path.exists(cpu)
            # the master serves the same handler
            async with c.http.get(
                    f"http://{c.master.url}/debug/pprof") as r:
                assert r.status == 200
                assert (await r.json())["cpu"] is True
    run(go())
