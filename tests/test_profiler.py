"""Continuous sampling profiler (stats/profiler.py):

- deterministic accounting: the absolute-deadline tick loop delivers
  samples = hz x window within jitter (the overhead gate's contract);
- trace-tier attribution: a thread burning inside an s3-tier span
  folds under "s3;..." stacks;
- bounded memory: past MAX_FOLDED distinct stacks new ones fold into
  "(other)";
- whole-host merge sums folded counts; folded_text renders stable
  flamegraph lines; the shared query parser rejects junk.
"""

from __future__ import annotations

import threading
import time

import pytest

from seaweedfs_tpu.stats import profiler
from seaweedfs_tpu.util import tracing

from cluster_util import run


@pytest.fixture(autouse=True)
def _clean():
    profiler.stop()
    profiler.init(0.0)
    profiler.reset()
    yield
    profiler.stop()
    profiler.init(0.0)
    profiler.reset()


class _Burner:
    """A busy thread with a recognizable frame for the sampler."""

    def __init__(self, tier: str = ""):
        self.tier = tier
        self._stop = threading.Event()
        self.t = threading.Thread(target=self._spin, daemon=True)

    def _spin(self):
        if self.tier:
            with tracing.start_root(self.tier, "burn"):
                self._burn_loop()
        else:
            self._burn_loop()

    def _burn_loop(self):
        x = 0
        while not self._stop.is_set():
            x = (x + 1) % 1000003

    def __enter__(self):
        self.t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self.t.join(timeout=2.0)


def test_window_sample_accounting_tracks_hz_times_elapsed():
    async def go():
        with _Burner():
            p = await profiler.profile_window(0.8, hz=250)
        expect = 250 * 0.8
        assert abs(p["samples"] - expect) <= expect * 0.10 + 2, p["samples"]
        assert p["window_s"] == 0.8 and p["hz"] == 250
        assert p["folded"] and not p["running"]
        assert sum(p["folded"].values()) > 0
    run(go())


def test_window_attributes_active_trace_tier():
    tracing.init(sample=1.0)

    async def go():
        # arm before the span is entered (profiler.start() does this
        # at boot in production; the thread records its tier on entry)
        tracing.track_thread_tiers(True)
        with _Burner(tier="s3"):
            p = await profiler.profile_window(0.4, hz=200)
        tracing.track_thread_tiers(False)
        s3_keys = [k for k in p["folded"] if k.startswith("s3;")]
        assert s3_keys, list(p["folded"])[:5]
        # the burner's own frame shows up under the attributed tier
        assert any("_burn_loop" in k for k in s3_keys), s3_keys
    run(go())


def test_always_on_sampler_and_window_piggyback():
    async def go():
        profiler.init(200.0)
        assert profiler.enabled()
        profiler.start()
        assert profiler.running()
        with _Burner():
            p = await profiler.profile_window(0.3)
        # the piggybacked sink rode the always-on cadence
        assert p["hz"] == 200.0 and p["running"]
        expect = 200 * 0.3
        assert abs(p["samples"] - expect) <= expect * 0.25 + 2
        agg = profiler.profile_dict()
        assert agg["samples"] >= p["samples"]
        assert agg["running"] and agg["hz"] == 200.0
        profiler.stop()
        assert not profiler.running()
    run(go())


def test_init_zero_disables_start():
    profiler.init(0.0)
    assert profiler.start() is None
    assert not profiler.running()


def test_overflow_folds_into_other_bucket():
    sink = {"folded": {f"stub;{i}": 1 for i in range(profiler.MAX_FOLDED)},
            "samples": 0}
    with _Burner():
        time.sleep(0.02)
        profiler._sample_once([sink])
    assert sink["samples"] == 1
    assert len(sink["folded"]) == profiler.MAX_FOLDED + 1
    assert sink["folded"]["(other)"] >= 1


def test_merge_sums_and_folded_text_is_stable():
    p1 = {"hz": 99.0, "running": True, "window_s": 2.0, "samples": 10,
          "folded": {"-;a.py:f": 6, "-;b.py:g": 1}}
    p2 = {"hz": 50.0, "running": False, "window_s": 1.0, "samples": 4,
          "folded": {"-;a.py:f": 2, "-;c.py:h": 2}}
    m = profiler.merge_payloads([p1, p2])
    assert m["samples"] == 14 and m["hz"] == 99.0 and m["running"]
    assert m["folded"] == {"-;a.py:f": 8, "-;b.py:g": 1, "-;c.py:h": 2}
    txt = profiler.folded_text(m)
    assert txt == "-;a.py:f 8\n-;c.py:h 2\n-;b.py:g 1\n"
    assert profiler.folded_text({"folded": {}}) == ""


def test_profile_query_parses_and_rejects():
    async def go():
        out = await profiler.profile_query({})
        assert out["window_s"] == 0.0       # the always-on aggregate
        with pytest.raises(ValueError):
            await profiler.profile_query({"seconds": "junk"})
        # seconds clamp: a huge window is cut to MAX_WINDOW_S
        p = await profiler.profile_query({"seconds": "0.1", "hz": "50"})
        assert p["window_s"] == 0.1 and p["hz"] == 50.0
    run(go())
