"""Multi-tenant QoS tests: WFQ fairness properties, rate-bucket burst
clamp, the priority shed ladder, the bandwidth arbiter's floor and
deterministic ledger, bounded tenant metric labels, keyed retry
budgets, per-tenant SLO specs, and the S3 SlowDown shed shape."""

from __future__ import annotations

import asyncio
import random

import pytest

from seaweedfs_tpu import qos
from seaweedfs_tpu.ec.scrub import TokenBucket
from seaweedfs_tpu.qos.admission import (AdmissionController, RateBucket,
                                         TenantClass, WFQ,
                                         parse_tenant_flag,
                                         parse_tenant_flags)
from seaweedfs_tpu.qos.arbiter import BandwidthArbiter, MiB
from seaweedfs_tpu.stats import metrics
from seaweedfs_tpu.util.resilience import RetryBudget


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def run(coro):
    return asyncio.run(coro)


# ---- tenant flag parsing ----

def test_parse_tenant_flag_roundtrip():
    t = parse_tenant_flag("paying:8:100:200")
    assert (t.name, t.weight, t.rps, t.burst) == ("paying", 8.0, 100.0,
                                                  200.0)
    # burst defaults to max(rps, 1)
    assert parse_tenant_flag("x:1:50").burst == 50.0
    assert parse_tenant_flag("x:1:0").burst == 1.0


@pytest.mark.parametrize("bad", [
    "", "justakey", "k:1", "k:1:2:3:4", "k:zero:1", ":1:1",
    "k:0:1", "k:-1:1", "k:1:-5", "k:1:1:0",
])
def test_parse_tenant_flag_refuses_malformed(bad):
    with pytest.raises(ValueError):
        parse_tenant_flag(bad)


def test_parse_tenant_flags_ensures_default_and_refuses_dupes():
    out = parse_tenant_flags(["a:2:10"])
    assert "default" in out and out["default"].rps == 0.0
    with pytest.raises(ValueError):
        parse_tenant_flags(["a:2:10", "a:3:20"])


# ---- RateBucket ----

def test_rate_bucket_burst_clamp_and_honest_retry_after():
    clock = FakeClock()
    b = RateBucket(10.0, burst=5.0, now=clock)
    # a long idle period must never bank more than burst
    clock.t += 100.0
    assert b.tokens == 5.0
    for _ in range(5):
        assert b.try_take() == 0.0
    ra = b.try_take()
    assert ra == pytest.approx(0.1)        # 1 token at 10/s
    # advancing the advertised Retry-After (plus float dust) admits
    clock.t += ra + 1e-6
    assert b.try_take() == 0.0
    # rate <= 0 disables the limit entirely
    free = RateBucket(0.0, now=clock)
    assert all(free.try_take() == 0.0 for _ in range(1000))


# ---- WFQ properties ----

def test_wfq_work_conservation():
    q = WFQ({"a": 3.0, "b": 1.0})
    rng = random.Random(7)
    n = 500
    for i in range(n):
        q.push(rng.choice("ab"), i)
    seen = []
    while len(q):
        seen.append(q.pop())
    assert len(seen) == n                  # nothing lost, nothing extra
    assert q.pop() is None


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_wfq_weight_proportional_service(seed):
    # both tenants continuously backlogged: service over any prefix
    # must track the 4:1 weight ratio
    q = WFQ({"fat": 4.0, "thin": 1.0})
    rng = random.Random(seed)
    items = ["fat"] * 400 + ["thin"] * 400
    rng.shuffle(items)
    for i, t in enumerate(items):
        q.push(t, i)
    first = [q.pop()[0] for _ in range(100)]
    fat = first.count("fat")
    assert 70 <= fat <= 90, f"fat got {fat}/100, want ~80"


def test_wfq_identical_seeds_are_deterministic():
    def drain(seed):
        q = WFQ({"a": 2.0, "b": 1.0, "c": 5.0})
        rng = random.Random(seed)
        for i in range(300):
            q.push(rng.choice("abc"), i)
        return [q.pop() for _ in range(300)]

    assert drain(42) == drain(42)
    assert drain(42) != drain(43)


def test_wfq_idle_tenant_banks_no_credit():
    q = WFQ({"a": 1.0, "b": 1.0})
    for i in range(100):
        q.push("a", i)
    for _ in range(100):
        q.pop()
    # b arrives after a long a-only backlog: it enters at the current
    # virtual clock, not at 0 — equal weights alternate from here on
    for i in range(10):
        q.push("a", f"a{i}")
        q.push("b", f"b{i}")
    order = [q.pop()[0] for _ in range(20)]
    assert order.count("a") == order.count("b") == 10


# ---- AdmissionController: throttle + shed ladder ----

def _ctrl(clock, probe, **kw):
    tenants = parse_tenant_flags(
        ["paying:8:1000:2000", "abuser:1:2:2"])
    kw.setdefault("lag_shed_ms", 100.0)
    return AdmissionController(tenants, now=clock, probe=probe, **kw)


def test_throttle_429_with_bucket_refill_retry_after():
    clock = FakeClock()
    ctrl = _ctrl(clock, probe=lambda: (0.0, 0.0))

    async def go():
        # burst 2 admits two, the third throttles
        for _ in range(2):
            dec = await ctrl.acquire("s3", "get", "abuser")
            assert dec.admitted
            ctrl.release(dec)
        dec = await ctrl.acquire("s3", "get", "abuser")
        assert not dec.admitted and dec.status == 429
        assert dec.reason == "throttle"
        assert dec.retry_after_s == pytest.approx(0.5)  # 1 token @ 2/s
        # the paying tenant is untouched by the abuser's drained bucket
        dec2 = await ctrl.acquire("s3", "get", "paying")
        assert dec2.admitted
        ctrl.release(dec2)

    run(go())


def test_shed_ladder_lowest_class_first_highest_never():
    clock = FakeClock()
    lag = {"ms": 0.0}
    ctrl = _ctrl(clock, probe=lambda: (lag["ms"], 0.0))

    async def go():
        # saturate: one rung per LEVEL_STEP_S, never faster
        lag["ms"] = 500.0
        clock.t += 1.0
        dec = await ctrl.acquire("s3", "get", "abuser")
        assert not dec.admitted and dec.status == 503
        assert dec.reason == "overload"
        # same instant: the paying (highest-weight) class still admits
        dec = await ctrl.acquire("s3", "get", "paying")
        assert dec.admitted
        ctrl.release(dec)
        # the ladder excludes the top class: however long the overload
        # lasts, paying is never overload-shed
        for _ in range(10):
            clock.t += 1.0
            dec = await ctrl.acquire("s3", "get", "paying")
            assert dec.admitted
            ctrl.release(dec)
        # recovery: lag drops below the hysteresis fraction
        lag["ms"] = 0.0
        clock.t += 1.0
        dec = await ctrl.acquire("s3", "get", "abuser")
        assert dec.admitted, "abuser not readmitted after recovery"
        ctrl.release(dec)

    run(go())


def test_shed_hysteresis_holds_level_between_steps():
    clock = FakeClock()
    lag = {"ms": 500.0}
    ctrl = _ctrl(clock, probe=lambda: (lag["ms"], 0.0))

    async def go():
        clock.t += 1.0
        await ctrl.acquire("s3", "get", "abuser")   # raises level to 1
        # lag recovers but NOT below RECOVER_FRAC * threshold
        lag["ms"] = 90.0                            # 0.9 of 100ms
        clock.t += 1.0
        dec = await ctrl.acquire("s3", "get", "abuser")
        assert not dec.admitted, "level dropped inside hysteresis band"

    run(go())


def test_queue_deadline_sheds_instead_of_silent_wait():
    clock = FakeClock()
    ctrl = _ctrl(clock, probe=lambda: (0.0, 0.0), inflight_limit=1,
                 queue_deadline_s=0.05)

    async def go():
        d1 = await ctrl.acquire("s3", "get", "paying")
        assert d1.admitted
        # the slot is taken: the next acquire parks in the WFQ and the
        # deadline sheds it with an honest 503 (never a silent queue)
        d2 = await ctrl.acquire("s3", "get", "paying")
        assert not d2.admitted and d2.status == 503
        assert d2.reason == "queue_deadline"
        assert d2.retry_after_s > 0
        ctrl.release(d1)

    run(go())


def test_release_wakes_queued_waiter():
    clock = FakeClock()
    ctrl = _ctrl(clock, probe=lambda: (0.0, 0.0), inflight_limit=1,
                 queue_deadline_s=5.0)

    async def go():
        d1 = await ctrl.acquire("s3", "get", "paying")
        waiter = asyncio.create_task(
            ctrl.acquire("s3", "get", "paying"))
        await asyncio.sleep(0.01)
        assert not waiter.done()
        ctrl.release(d1)
        d2 = await asyncio.wait_for(waiter, 1.0)
        assert d2.admitted and d2.queued_s >= 0.0
        ctrl.release(d2)

    run(go())


def test_to_dict_surfaces_counters_and_ladder():
    clock = FakeClock()
    ctrl = _ctrl(clock, probe=lambda: (12.0, 0.0))

    async def go():
        dec = await ctrl.acquire("s3", "get", "paying")
        ctrl.release(dec)

    run(go())
    d = ctrl.to_dict()
    assert d["tenants"]["paying"]["admitted"] == 1
    assert d["tenants"]["abuser"]["admitted"] == 0
    assert d["shed_level"] == 0
    assert d["ladder"] == [1.0]            # abuser+default; paying (8) excluded
    assert d["probes"]["lag_ms"] == pytest.approx(12.0)


# ---- bandwidth arbiter ----

def _paced(clock, sleeps):
    async def fake_sleep(d):
        sleeps.append(d)
        clock.t += d
    return fake_sleep


def test_arbiter_idle_cluster_grants_full_base_rate():
    clock, sleeps = FakeClock(), []
    arb = BandwidthArbiter(budget_mbps=10.0, now=clock)
    inner = TokenBucket(4 * MiB, now=clock, sleep=_paced(clock, sleeps))
    gb = arb.adopt("scrub", inner)

    async def go():
        for _ in range(4):
            await gb.consume(1 * MiB)

    run(go())
    assert arb.rate_for("scrub") == pytest.approx(4 * MiB)
    assert arb.to_dict()["consumers"]["scrub"]["yields"] == 0


def test_arbiter_floor_never_starved_and_grants_bounded():
    clock, sleeps = FakeClock(), []
    arb = BandwidthArbiter(budget_mbps=10.0, floor=0.25, now=clock)
    inner = TokenBucket(4 * MiB, now=clock, sleep=_paced(clock, sleeps))
    gb = arb.adopt("autopilot", inner)

    async def go():
        # sustained foreground pressure way past the budget
        for _ in range(50):
            arb.note_foreground(2 * MiB)
            clock.t += 0.01
        assert arb.foreground_bps() > 10 * MiB
        for _ in range(8):
            arb.note_foreground(2 * MiB)     # keep the window pressurised
            await gb.consume(1 * MiB)

    run(go())
    rows = list(arb.grants)
    assert len(rows) == 8
    base = 4 * MiB
    for r in rows:
        # the starvation-proof floor: never below floor * base even at
        # full squeeze, and never above the base entitlement
        assert r["rate_bps"] >= int(0.25 * base) - 1
        assert r["rate_bps"] <= base
        assert r["yielded"]
    c = arb.to_dict()["consumers"]["autopilot"]
    assert c["granted_bytes"] == 8 * MiB     # ledger accounts every byte
    assert c["yields"] == 8
    # pacing really happened: squeezed rate => the bucket slept
    assert sum(sleeps) > 0


def test_arbiter_ledger_is_deterministic_over_identical_runs():
    def one_run():
        clock, sleeps = FakeClock(), []
        arb = BandwidthArbiter(budget_mbps=8.0, floor=0.25, now=clock)
        gb = arb.adopt("scrub", TokenBucket(
            2 * MiB, now=clock, sleep=_paced(clock, sleeps)))

        async def go():
            for i in range(12):
                arb.note_foreground((i % 5) * MiB)
                clock.t += 0.05
                await gb.consume(MiB // 2)

        run(go())
        # wall_ms is a display stamp (time.time); everything the
        # pacing-floor asserts rely on must be clock-deterministic
        return [{k: v for k, v in r.items() if k != "wall_ms"}
                for r in arb.grants], sleeps

    rows_a, sleeps_a = one_run()
    rows_b, sleeps_b = one_run()
    assert rows_a == rows_b
    assert sleeps_a == sleeps_b


def test_arbiter_node_reports_age_out():
    clock = FakeClock()
    arb = BandwidthArbiter(budget_mbps=10.0, now=clock)
    arb.note_node_foreground("127.0.0.1:8080", 5 * MiB)
    assert arb.foreground_bps() == pytest.approx(5 * MiB)
    clock.t += 20.0                          # past NODE_REPORT_TTL_S
    assert arb.foreground_bps() == 0.0


def test_arbiter_disabled_budget_passes_base_through():
    clock, sleeps = FakeClock(), []
    arb = BandwidthArbiter(budget_mbps=0.0, now=clock)
    gb = arb.adopt("scrub", TokenBucket(
        MiB, now=clock, sleep=_paced(clock, sleeps)))

    async def go():
        arb.note_foreground(100 * MiB)
        await gb.consume(1024)

    run(go())
    assert arb.rate_for("scrub") == pytest.approx(MiB)
    assert arb.to_dict()["consumers"]["scrub"]["yields"] == 0


# ---- bounded tenant labels ----

def test_bounded_label_set_caps_cardinality_at_10k_keys():
    s = metrics.BoundedLabelSet(seed=["paying", "abuser"], cap=32)
    out = {s.get(f"key{i}") for i in range(10_000)}
    out |= {s.get("paying"), s.get("abuser")}
    assert len(out) <= 32 + 1               # cap plus the "other" bucket
    assert "other" in out
    assert s.get("paying") == "paying"      # seeds always pass through
    # a key admitted before the cap stays stable afterwards
    assert s.get("key0") == "key0"
    assert s.get("key9999") == "other"


# ---- keyed retry budget ----

def test_retry_budget_pools_are_isolated_by_key():
    b = RetryBudget(ratio=0.1, burst=2.0)
    assert b.allow_retry("master|abuser")
    assert b.allow_retry("master|abuser")
    assert not b.allow_retry("master|abuser")   # abuser pool exhausted
    # the paying tenant's pool is untouched by the abuser's storm
    assert b.allow_retry("master|paying")
    # and the process-global pool ("") keeps its legacy behavior
    assert b.allow_retry()
    assert b.allow_retry()
    assert not b.allow_retry()


def test_retry_budget_overflow_folds_past_max_pools():
    b = RetryBudget(ratio=0.1, burst=1.0)
    for i in range(RetryBudget.MAX_POOLS + 10):
        b.record_attempt(f"up{i}")
    # pools stopped growing at the cap; the overflow key still works
    assert len(b._pools) <= RetryBudget.MAX_POOLS + 1
    assert b.allow_retry(f"up{RetryBudget.MAX_POOLS + 5}") in (True,
                                                               False)


# ---- per-tenant SLO specs ----

def test_slo_spec_parses_tenant_qualifier():
    from seaweedfs_tpu.stats.slo import SloSpec
    s = SloSpec("s3.get/paying:p99<200ms@99")
    assert (s.tier, s.op, s.tenant) == ("s3", "get", "paying")
    assert s.to_dict()["tenant"] == "paying"
    # tenant-less specs keep their exact legacy shape
    s2 = SloSpec("volume.read:p99<50ms@99.9")
    assert s2.tenant == ""
    assert "tenant" not in s2.to_dict()


def test_slo_tenant_spec_matches_tenant_histogram_rows():
    from seaweedfs_tpu.stats.slo import SloSpec, _TENANT_HIST, _matches
    s = SloSpec("s3.get/paying:p99<200ms@99")
    paying = (_TENANT_HIST
              + '{tier="s3",op="get",tenant="paying"}')
    abuser = (_TENANT_HIST
              + '{tier="s3",op="get",tenant="abuser"}')
    assert _matches(s, paying)
    assert not _matches(s, abuser)
    # a tenant spec never matches the tenant-less tier histogram
    assert not _matches(
        s, 'SeaweedFS_request_seconds{tier="s3",op="get"}')
    # and a tenant-less spec never matches the tenant histogram
    s2 = SloSpec("s3.get:p99<200ms@99")
    assert not _matches(s2, paying)


# ---- S3 shed response shape ----

def test_s3_shed_is_aws_shaped_slowdown_with_retry_after():
    from seaweedfs_tpu.filer.filer import Filer
    from seaweedfs_tpu.s3.gateway import S3Gateway

    clock = FakeClock()
    tenants = {"default": TenantClass("default", 1.0, 1.0, 1.0)}
    ctrl = AdmissionController(tenants, now=clock,
                               probe=lambda: (0.0, 0.0))

    async def go():
        import aiohttp
        gw = S3Gateway(Filer("memory"), "127.0.0.1:1", port=0,
                       admission=ctrl)
        await gw.start()
        try:
            async with aiohttp.ClientSession() as http:
                base = f"http://{gw.url}"
                async with http.put(f"{base}/b1") as r:
                    assert r.status == 200, await r.text()
                # burst 1 drained: the next request is throttled with
                # the AWS SlowDown shape + an honest Retry-After
                async with http.get(f"{base}/b1") as r:
                    assert r.status == 429
                    assert r.headers["Retry-After"] == "1"
                    body = await r.read()
                    assert b"<Code>SlowDown</Code>" in body
                    assert b"reduce your request rate" in body
                # the bucket refills exactly as advertised
                clock.t += 1.0
                async with http.get(f"{base}/b1") as r:
                    assert r.status == 200
        finally:
            await gw.stop()

    run(go())


# ---- debug surface merge ----

def test_qos_merge_payloads_sums_counters_and_takes_worst_level():
    p1 = {"qos": {"tenants": {"a": {"admitted": 3, "throttled": 1,
                                    "shed": 0, "queued": 0,
                                    "queue_depth": 1, "tokens": 2.0,
                                    "cls": "a", "weight": 2.0,
                                    "rps": 10.0, "burst": 10.0}},
                  "inflight": 2, "inflight_limit": 256, "queued": 1,
                  "shed_level": 0, "ladder": [1.0],
                  "probes": {"lag_ms": 5.0, "wait_ms": 0.0},
                  "arbiter": {"budget_mbps": 10.0, "floor": 0.25,
                              "foreground_bps": 100.0,
                              "consumers": {"scrub": {
                                  "base_bps": 100, "rate_bps": 50,
                                  "granted_bytes": 10, "yields": 1,
                                  "slept_s": 0.5}},
                              "grants": [{"wall_ms": 1}]}}}
    p2 = {"qos": {"tenants": {"a": {"admitted": 2, "throttled": 0,
                                    "shed": 4, "queued": 0,
                                    "queue_depth": 0, "tokens": 1.0,
                                    "cls": "a", "weight": 2.0,
                                    "rps": 10.0, "burst": 10.0}},
                  "inflight": 1, "inflight_limit": 256, "queued": 0,
                  "shed_level": 2, "ladder": [1.0],
                  "probes": {"lag_ms": 80.0, "wait_ms": 3.0},
                  "arbiter": {"budget_mbps": 10.0, "floor": 0.25,
                              "foreground_bps": 50.0,
                              "consumers": {"scrub": {
                                  "base_bps": 100, "rate_bps": 25,
                                  "granted_bytes": 5, "yields": 2,
                                  "slept_s": 0.25}},
                              "grants": [{"wall_ms": 2}]}}}
    m = qos.merge_payloads([p1, p2])
    assert m["workers"] == 2
    t = m["qos"]["tenants"]["a"]
    assert t["admitted"] == 5 and t["shed"] == 4 and t["throttled"] == 1
    assert m["qos"]["inflight"] == 3
    assert m["qos"]["inflight_limit"] == 512
    assert m["qos"]["shed_level"] == 2       # worst worker wins
    assert m["qos"]["probes"]["lag_ms"] == 80.0
    a = m["qos"]["arbiter"]
    assert a["foreground_bps"] == 150.0
    assert a["consumers"]["scrub"]["granted_bytes"] == 15
    assert a["consumers"]["scrub"]["yields"] == 3
    assert [g["wall_ms"] for g in a["grants"]] == [1, 2]


def test_tenant_from_headers_extracts_sigv4_and_jwt_sub():
    import base64
    import json as j
    h = {"Authorization": "AWS4-HMAC-SHA256 Credential=AKEY/20260807/"
                          "us-east-1/s3/aws4_request, SignedHeaders=x,"
                          " Signature=y"}
    assert qos.tenant_from_headers(h) == "AKEY"
    payload = base64.urlsafe_b64encode(
        j.dumps({"sub": "team-a"}).encode()).rstrip(b"=").decode()
    h = {"Authorization": f"Bearer x.{payload}.y"}
    assert qos.tenant_from_headers(h) == "team-a"
    assert qos.tenant_from_headers({}) == ""
    assert qos.tenant_from_headers({"Authorization": "Bearer junk"}) == ""
