"""Replicated-log raft unit tests (chrislusf/raft parity surface:
log replication, conflict truncation, quorum commit, snapshots,
InstallSnapshot, log-freshness votes). Reference behavior contract:
/root/reference/weed/server/raft_server.go:28-97 +
/root/reference/weed/topology/cluster_commands.go:9-29."""

from __future__ import annotations

import asyncio

from seaweedfs_tpu.master.election import SNAPSHOT_THRESHOLD, Election

PEERS = ["a:1", "b:2", "c:3"]


def _follower(me: str, path=None) -> Election:
    e = Election(me, PEERS, state_path=path)
    return e


def test_append_entries_replicates_and_commits():
    f = _follower("b:2")
    adopted = []
    f.adopt_max_volume_id = adopted.append
    r = f.on_append(term=1, leader="a:1", prev_index=0, prev_term=0,
                    entries=[{"term": 1, "cmd": {"max_volume_id": 7}}],
                    leader_commit=0)
    assert r["ok"] and r["match"] == 1
    assert f.last_index() == 1 and f.commit == 0 and adopted == []
    # commit rides the next pulse (empty heartbeat)
    r = f.on_append(term=1, leader="a:1", prev_index=1, prev_term=1,
                    entries=[], leader_commit=1)
    assert r["ok"] and f.commit == 1 and adopted == [7]


def test_append_gap_is_rejected_with_hint():
    f = _follower("b:2")
    r = f.on_append(term=1, leader="a:1", prev_index=5, prev_term=1,
                    entries=[], leader_commit=0)
    assert not r["ok"] and r["last"] == 0   # leader jumps back to 1


def test_conflicting_suffix_is_truncated():
    f = _follower("b:2")
    # entries from a deposed term-1 leader, never committed
    f.on_append(1, "a:1", 0, 0,
                [{"term": 1, "cmd": {"max_volume_id": 1}},
                 {"term": 1, "cmd": {"max_volume_id": 2}}], 0)
    # new term-2 leader overwrites index 2 with its own entry
    r = f.on_append(2, "c:3", 1, 1,
                    [{"term": 2, "cmd": {"max_volume_id": 9}}], 2)
    assert r["ok"]
    assert f.last_index() == 2
    assert f._term_at(2) == 2
    assert f.applied_value == 9


def test_snapshot_compaction_and_state_restart(tmp_path):
    path = str(tmp_path / "raft_state.json")
    f = _follower("b:2", path)
    n = SNAPSHOT_THRESHOLD + 10
    entries = [{"term": 1, "cmd": {"max_volume_id": i + 1}}
               for i in range(n)]
    f.on_append(1, "a:1", 0, 0, entries, n)
    assert f.applied_value == n
    assert f.snap["last_index"] == n          # compacted
    assert len(f.entries) <= SNAPSHOT_THRESHOLD
    # the handler flushes before acking; only then is the state durable
    asyncio.run(f.flush())
    # restart: snapshot + tail reload, applied value restored
    f2 = _follower("b:2", path)
    assert f2.applied_value == n
    assert f2.last_index() == n
    # an append continuing from the snapshot point still works
    r = f2.on_append(1, "a:1", n, 1,
                     [{"term": 1, "cmd": {"max_volume_id": n + 1}}], n + 1)
    assert r["ok"] and f2.applied_value == n + 1


def test_install_snapshot_fast_forwards_lagging_follower():
    f = _follower("b:2")
    adopted = []
    f.adopt_max_volume_id = adopted.append
    r = f.on_install_snapshot(term=3, leader="a:1", last_index=120,
                              last_term=2, value=120)
    assert r["ok"]
    assert f.last_index() == 120 and f.applied_value == 120
    assert adopted == [120]
    # stale snapshot (lower index) is a no-op
    r = f.on_install_snapshot(term=3, leader="a:1", last_index=50,
                              last_term=2, value=50)
    assert r["ok"] and f.last_index() == 120


def test_vote_log_freshness_rule():
    f = _follower("b:2")
    f.on_append(2, "a:1", 0, 0,
                [{"term": 2, "cmd": {"max_volume_id": 5}}], 1)
    # candidate with a SHORTER log is refused despite the higher term
    r = f.on_vote_request(term=3, candidate="c:3",
                          last_log_index=0, last_log_term=0)
    assert not r["granted"] and f.term == 3
    # candidate at least as fresh is granted
    r = f.on_vote_request(term=4, candidate="c:3",
                          last_log_index=1, last_log_term=2)
    assert r["granted"]


def test_leader_commit_requires_current_term_entry():
    """The raft commit rule: a leader only commits entries from ITS term
    (prior-term entries commit transitively)."""
    lead = _follower("a:1")
    lead.role = Election.LEADER
    lead.term = 2
    lead.entries = [{"term": 1, "cmd": {"max_volume_id": 3}}]
    lead.match_index = {"b:2": 1, "c:3": 0}
    # majority has index 1, but it is a term-1 entry: must NOT commit
    matches = sorted([lead.last_index()]
                     + [lead.match_index[p] for p in lead.peers],
                     reverse=True)
    n = matches[lead.majority - 1]
    assert n == 1 and lead._term_at(n) != lead.term
