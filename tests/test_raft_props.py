"""Election property suite: randomized interleavings over the raft
surface (term monotonicity, log matching under torn/duplicated append
replays, snapshot-install then catch-up, restart durability) plus the
campaign per-attempt-timeout regression.

These are the invariants the chaos ha scenario relies on statistically,
driven here deterministically over seeded random schedules.
"""

from __future__ import annotations

import asyncio
import random
import time

import aiohttp

from seaweedfs_tpu.master.election import Election
from seaweedfs_tpu.util import events, failpoints

PEERS = ["a:1", "b:2", "c:3"]


def _mk(me: str = "b:2", path=None) -> Election:
    return Election(me, PEERS, state_path=path)


def _leader_log(rng: random.Random, n: int) -> list[dict]:
    """A synthetic committed leader log with term bumps and a mix of
    volume-id and fid-reservation commands."""
    log, term = [], 1
    for i in range(n):
        if rng.random() < 0.15:
            term += rng.randint(1, 2)
        cmd = ({"max_volume_id": i + 1} if rng.random() < 0.5
               else {"seq_reserve": rng.randint(1, 64), "by": "a:1"})
        log.append({"term": term, "cmd": cmd})
    return log


def _append_slice(f: Election, log: list[dict], start: int, end: int,
                  commit: int) -> dict:
    """Deliver log[start:end] with the correct prev coordinates, the
    way a (possibly stale) leader retransmission would."""
    prev_term = log[start - 1]["term"] if start else 0
    return f.on_append(log[-1]["term"], "a:1", start, prev_term,
                       [dict(e) for e in log[start:end]], commit)


def test_term_never_regresses_under_random_rpc_storm():
    rng = random.Random(1234)
    f = _mk()
    log = _leader_log(rng, 40)
    seen = 0
    for _ in range(500):
        seen = max(seen, f.term)
        op = rng.random()
        if op < 0.35:
            f.on_vote_request(term=rng.randint(0, 30),
                              candidate=rng.choice(["a:1", "c:3"]),
                              last_log_index=rng.randint(0, 60),
                              last_log_term=rng.randint(0, 30))
        elif op < 0.75:
            s = rng.randint(0, len(log))
            _append_slice(f, log, s, rng.randint(s, len(log)),
                          rng.randint(0, len(log)))
        else:
            f.on_install_snapshot(term=rng.randint(0, 30),
                                  leader="c:3",
                                  last_index=rng.randint(0, 80),
                                  last_term=rng.randint(0, 30),
                                  value=rng.randint(0, 80),
                                  seq=rng.randint(0, 500))
        assert f.term >= seen, "term regressed"


def test_log_matching_after_torn_replays():
    """Two followers fed the SAME leader log as randomly torn,
    duplicated, out-of-order slices converge to identical logs and
    identical applied state — the raft Log Matching property."""
    rng = random.Random(77)
    log = _leader_log(rng, 60)
    for trial in range(8):
        followers = [_mk("b:2"), _mk("c:3")]
        applied = [[], []]
        seqs = [[], []]
        for i, f in enumerate(followers):
            f.adopt_max_volume_id = applied[i].append
            f.adopt_seq_window = \
                lambda s, e, by, t, acc=seqs[i]: acc.append((s, e))
        for f in followers:
            # a storm of torn, duplicated, reordered retransmissions
            for _ in range(30):
                s = rng.randint(0, len(log) - 1)
                e = rng.randint(s, len(log))
                _append_slice(f, log, s, e, rng.randint(0, e))
            # the final full retransmission every live leader converges
            # on via the next_index walk-back
            _append_slice(f, log, 0, len(log), len(log))
        a, b = followers
        assert a.last_index() == b.last_index() == len(log)
        assert [a._term_at(i) for i in range(1, len(log) + 1)] == \
               [b._term_at(i) for i in range(1, len(log) + 1)]
        assert a.applied_value == b.applied_value
        assert a.applied_seq == b.applied_seq
        # reservation windows applied in identical order on both
        assert seqs[0] == seqs[1]
        # committed prefix applied exactly once per index
        assert applied[0] == applied[1]


def test_snapshot_install_then_catch_up():
    rng = random.Random(5)
    log = _leader_log(rng, 50)
    # precompute the leader's applied state at index 30
    value30 = max((e["cmd"].get("max_volume_id", 0)
                   for e in log[:30]), default=0)
    seq30 = sum(e["cmd"].get("seq_reserve", 0) for e in log[:30])
    f = _mk()
    r = f.on_install_snapshot(term=log[-1]["term"], leader="a:1",
                              last_index=30, last_term=log[29]["term"],
                              value=value30, seq=seq30)
    assert r["ok"]
    assert f.applied_seq == seq30 and f.applied_value == value30
    # catch up from the snapshot point with the remaining tail
    r = _append_slice(f, log, 30, len(log), len(log))
    assert r["ok"]
    assert f.last_index() == len(log)
    assert f.applied_seq == sum(e["cmd"].get("seq_reserve", 0)
                                for e in log)
    # a stale snapshot arriving late must not roll anything back
    r = f.on_install_snapshot(term=log[-1]["term"], leader="a:1",
                              last_index=10, last_term=log[9]["term"],
                              value=1, seq=1)
    assert r["ok"] and f.last_index() == len(log)
    assert f.applied_seq == sum(e["cmd"].get("seq_reserve", 0)
                                for e in log)


def test_restart_durability_random_schedules(tmp_path):
    """votedFor/term/log/applied-seq survive flush()+reload at every
    random cut point — the double-vote and id-reissue windows a crash
    must never open."""
    rng = random.Random(99)
    for trial in range(6):
        path = str(tmp_path / f"raft_{trial}.json")
        f = _mk(path=path)
        log = _leader_log(rng, 30)
        for _ in range(rng.randint(3, 12)):
            if rng.random() < 0.4:
                f.on_vote_request(term=rng.randint(1, 20),
                                  candidate=rng.choice(["a:1", "c:3"]),
                                  last_log_index=99, last_log_term=99)
            else:
                s = rng.randint(0, len(log) - 1)
                _append_slice(f, log, s, rng.randint(s, len(log)),
                              rng.randint(0, len(log)))
        asyncio.run(f.flush())   # what every RPC handler awaits pre-reply
        g = _mk(path=path)
        assert g.term == f.term
        assert g.voted_for == f.voted_for
        assert g.snap == f.snap
        assert g.entries == f.entries
        # applied state beyond the snapshot re-derives from the log as
        # commit re-advances; the snapshot floor itself must hold
        assert g.applied_seq == g.snap["seq"]
        r = g.on_vote_request(term=g.term, candidate="c:3",
                              last_log_index=999, last_log_term=999)
        if f.voted_for not in (None, "c:3"):
            assert not r["granted"], "double vote after restart"


def test_campaign_bounded_by_per_attempt_timeout():
    """Satellite regression: a hung/slow peer socket (latency-armed
    master.vote) must not stretch a campaign past the election
    timeout — the per-attempt wait_for bounds every vote RPC."""
    async def body():
        e = Election("127.0.0.1:1", ["127.0.0.1:1", "127.0.0.1:2",
                                     "127.0.0.1:3"],
                     election_timeout=(0.4, 0.8), pulse=0.1)
        assert e.attempt_timeout <= 0.2
        e._http = aiohttp.ClientSession()
        failpoints.arm("master.vote", "latency=5000:*")
        try:
            t0 = time.monotonic()
            await e._campaign()
            elapsed = time.monotonic() - t0
        finally:
            failpoints.reset()
            await e._http.close()
        # both vote RPCs run concurrently; the whole fan-out must fit
        # inside one election timeout with margin to spare
        assert elapsed < 0.4, f"campaign took {elapsed:.2f}s"
        assert e.role == Election.FOLLOWER   # no quorum, stepped down
    asyncio.run(body())


def test_leader_change_and_step_down_are_journaled():
    f = _mk()
    f.on_append(7, "a:1", 0, 0, [], 0)
    f.role = Election.LEADER         # pretend it won a later election
    f._step_down()
    rows = events.events_dict(n=1000)["events"]
    assert any(r["type"] == "raft_leader_change"
               and r.get("leader") == "a:1" and r.get("term") == 7
               for r in rows)
    assert any(r["type"] == "raft_step_down"
               and r.get("me") == "b:2" and r.get("term") == 7
               for r in rows)
    # both are documented vocabulary, not typo'd strays
    assert {"raft_leader_change", "raft_step_down"} <= events.TYPES
