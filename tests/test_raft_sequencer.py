"""RaftSequencer: quorum-committed fid reservation windows.

The invariant under test is the chaos ha acceptance contract: an id is
only ever handed out from a raft-COMMITTED reservation window, windows
partition the id space in log order, and a deposed leader's in-flight
/dir/assign either fails/redirects or returns an id the successor's
committed log also owns — never an id the successor could re-issue.
"""

from __future__ import annotations

import asyncio

import pytest

from seaweedfs_tpu.master.election import Election
from seaweedfs_tpu.master.sequence import (MemorySequencer, RaftSequencer,
                                           SequenceBehind)

PEERS = ["a:1", "b:2", "c:3"]


def _leader(me: str = "a:1", term: int = 1) -> Election:
    e = Election(me, PEERS)
    e.role = Election.LEADER
    e.leader = e.me
    e.term = term
    return e


async def _commit_all(e: Election) -> bool:
    """Test stand-in for a replication round that reaches a full
    quorum instantly: everything in the log commits and applies."""
    e.commit = e.last_index()
    e._apply_committed()
    return True


def _wire_quorum(e: Election) -> None:
    async def fake_round() -> int:
        await _commit_all(e)
        return len(e.peers) + 1
    e._replicate_round = fake_round


def test_ids_only_from_committed_windows():
    e = _leader()
    _wire_quorum(e)
    seq = RaftSequencer(MemorySequencer(), e, step=16)
    # nothing committed yet: allocation must refuse, not invent ids
    with pytest.raises(SequenceBehind):
        seq.next_file_id()
    assert asyncio.run(seq.reserve(1))
    first = seq.next_file_id()
    assert 1 <= first < seq.ceiling
    # the whole window drains without another commit round
    got = [first] + [seq.next_file_id() for _ in range(seq.ceiling
                                                      - first - 1)]
    assert len(set(got)) == len(got)
    with pytest.raises(SequenceBehind):
        seq.next_file_id()


def test_successor_windows_never_overlap_deposed_leaders():
    """The acceptance race, deterministically: leader A commits a
    window and keeps draining it AFTER being deposed; successor B's
    first window starts above A's ceiling, so even ids A hands out
    post-deposition are ids B's committed log owns and B will never
    re-issue."""
    a = _leader("a:1", term=1)
    _wire_quorum(a)
    seq_a = RaftSequencer(MemorySequencer(), a, step=16)
    assert asyncio.run(seq_a.reserve(1))
    issued_a = [seq_a.next_file_id() for _ in range(5)]

    # replicate A's log to follower B (the quorum path A's commit
    # certifies), then depose A and promote B
    b = Election("b:2", PEERS)
    seq_b = RaftSequencer(MemorySequencer(), b, step=16)
    r = b.on_append(1, "a:1", 0, 0, list(a.entries), a.commit)
    assert r["ok"]
    a._adopt_higher_term(2)          # A deposed (higher term observed)
    b.role = Election.LEADER
    b.leader = b.me
    b.term = 2
    _wire_quorum(b)

    # A keeps draining its committed window mid-deposition (the
    # in-flight /dir/assign case) — allowed, because...
    issued_a += [seq_a.next_file_id() for _ in range(3)]
    assert all(i < seq_a.ceiling for i in issued_a)

    # ...B's first window starts at/above A's committed ceiling
    assert asyncio.run(seq_b.reserve(1))
    issued_b = [seq_b.next_file_id() for _ in range(8)]
    assert min(issued_b) >= seq_a.ceiling
    assert not set(issued_a) & set(issued_b)

    # and once A's window is spent, A cannot reserve another
    while True:
        try:
            issued_a.append(seq_a.next_file_id())
        except SequenceBehind:
            break
    assert not asyncio.run(seq_a.reserve(1))
    assert not set(issued_a) & set(issued_b)


def test_reserve_fails_cleanly_when_deposed_mid_commit():
    """append_command loses leadership mid-round: reserve() is False
    and no window opens — the caller redirects instead of inventing
    ids."""
    e = _leader()

    async def deposed_round() -> int:
        e._adopt_higher_term(5)
        return 1
    e._replicate_round = deposed_round
    seq = RaftSequencer(MemorySequencer(), e, step=16)
    assert not asyncio.run(seq.reserve(1))
    with pytest.raises(SequenceBehind):
        seq.next_file_id()


def test_foreign_window_fences_instead_of_claiming():
    """A window authored by this node in an OLDER term (committed by a
    successor) must fence the counter past its end, never open for
    local allocation — the old leadership may have promised nothing,
    but the new one owns the space."""
    e = _leader("a:1", term=3)
    seq = RaftSequencer(MemorySequencer(), e, step=16)
    # entry authored by us at term 2, applied while we run term 3
    seq.adopt_window(0, 100, "a:1", 2)
    assert seq.ceiling == 100
    with pytest.raises(SequenceBehind):
        seq.next_file_id()
    assert seq.peek() >= 100


def test_heartbeat_watermark_burns_through_windows():
    """A volume server reporting a huge max file key (migration from a
    pre-HA cluster) pushes the counter past the open window; the next
    reserve must size its window past the watermark, and the burned
    block is never handed out."""
    e = _leader()
    _wire_quorum(e)
    seq = RaftSequencer(MemorySequencer(), e, step=16)
    assert asyncio.run(seq.reserve(1))
    seq.set_max(10_000)
    with pytest.raises(SequenceBehind):
        seq.next_file_id()
    assert asyncio.run(seq.reserve(1))
    nxt = seq.next_file_id()
    assert nxt > 10_000
    assert nxt + 1 <= seq.ceiling


def test_reserve_covers_counts_larger_than_step():
    """Review regression: the window must cover `count` ids from its
    OWN start (the claim fences the counter there) — a block bigger
    than the step used to under-reserve and fail the healthy leader's
    assign forever."""
    e = _leader()
    _wire_quorum(e)
    seq = RaftSequencer(MemorySequencer(), e, step=16)
    assert asyncio.run(seq.reserve(1))
    seq.next_file_id()                       # counter mid-window
    big = 5 * seq.step
    assert asyncio.run(seq.reserve(big))
    first = seq.next_file_id(big)
    assert first + big <= seq.ceiling


def test_install_snapshot_seq_rides_the_http_wire():
    """Review regression: h_raft_snapshot must hand the RPC's `seq` to
    on_install_snapshot — dropping it left a catching-up follower's
    applied_seq at 0, un-fenced against every folded reservation
    window (duplicate fids if it later led)."""
    import inspect

    from seaweedfs_tpu.master.server import MasterServer
    src = inspect.getsource(MasterServer.h_raft_snapshot)
    assert "seq=" in src
    f = Election("b:2", PEERS)
    sq = RaftSequencer(MemorySequencer(), f, step=16)
    r = f.on_install_snapshot(term=3, leader="a:1", last_index=40,
                              last_term=2, value=7, seq=9000)
    assert r["ok"]
    assert f.applied_seq == 9000
    assert sq.ceiling == 9000 and sq.peek() >= 9000


def test_concurrent_reserves_collapse_to_one_commit():
    e = _leader()
    rounds = 0

    async def counting_round() -> int:
        nonlocal rounds
        rounds += 1
        await _commit_all(e)
        return 3
    e._replicate_round = counting_round
    seq = RaftSequencer(MemorySequencer(), e, step=64)

    async def burst():
        return await asyncio.gather(*(seq.reserve(1) for _ in range(8)))

    assert all(asyncio.run(burst()))
    # one committed window serves all 8 waiters (one entry, one round)
    assert len(e.entries) == 1
    assert seq.reserves == 1


def test_window_survives_restart_and_refuses_reissue(tmp_path):
    """Restart durability: a leader that crashed after committing a
    window comes back (as a follower) with its counter fenced past
    every window in its durable log — even the tail it had not yet
    folded into a snapshot."""
    path = str(tmp_path / "raft_state.json")
    e = _leader()
    e.state_path = path
    _wire_quorum(e)
    seq = RaftSequencer(MemorySequencer(), e, step=16)
    assert asyncio.run(seq.reserve(1))
    issued = [seq.next_file_id() for _ in range(4)]
    e._mark_dirty()
    asyncio.run(e.flush())

    e2 = Election("a:1", PEERS, state_path=path)
    seq2 = RaftSequencer(MemorySequencer(), e2, step=16)
    # tail entries beyond the snapshot re-apply when commit re-advances
    # (here: promoted and committing its own no-window entry)
    e2.role = Election.LEADER
    e2.leader = e2.me
    e2.term = e.term + 1

    async def commit_all2() -> int:
        e2.commit = e2.last_index()
        e2._apply_committed()
        return 3
    e2._replicate_round = commit_all2
    assert asyncio.run(seq2.reserve(1))
    fresh = [seq2.next_file_id() for _ in range(4)]
    assert not set(issued) & set(fresh)
    assert min(fresh) >= seq.ceiling
