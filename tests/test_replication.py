"""Notification queues + async replication between two in-proc clusters.

Mirrors weed filer.replicate: filer meta events -> queue -> Replicator ->
sink (filer on a second cluster / S3 gateway / local dir), with
incremental chunk diff on updates and offset-file resume.
"""

import asyncio
import os

import pytest

from cluster_util import Cluster, run

from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.notification.queues import (FileQueue, SqliteQueue,
                                               attach_to_filer,
                                               load_configuration)
from seaweedfs_tpu.replication.replicator import Replicator
from seaweedfs_tpu.replication.runner import replicate_from_queue
from seaweedfs_tpu.replication.sink import (FilerSink, LocalDirSink, S3Sink)
from seaweedfs_tpu.replication.source import FilerSource


def _src_cluster(tmp_path, **kw):
    c = Cluster(str(tmp_path / "src"), **kw)
    c.with_filer = True
    return c


async def _post(c, path, data):
    async with c.http.post(f"http://{c.filer.url}{path}", data=data) as r:
        assert r.status == 201, await r.text()


def test_queue_configuration_registry(tmp_path):
    q = load_configuration(
        {"file": {"enabled": True, "path": str(tmp_path / "q.jsonl")}})
    assert isinstance(q, FileQueue)
    assert load_configuration({}) is None
    with pytest.raises(ValueError):
        load_configuration({
            "file": {"enabled": True, "path": "x"},
            "sqlite": {"enabled": True, "path": "y"}})


def test_file_queue_offsets(tmp_path):
    q = FileQueue(str(tmp_path / "q.jsonl"))
    q.send_message("/a", {"n": 1})
    q.send_message("/b", {"n": 2})
    msgs, off = q.read_from(0)
    assert [m["key"] for m in msgs] == ["/a", "/b"]
    q.send_message("/c", {"n": 3})
    msgs2, off2 = q.read_from(off)
    assert [m["key"] for m in msgs2] == ["/c"]
    assert off2 > off


def test_sqlite_queue(tmp_path):
    q = SqliteQueue(str(tmp_path / "q.db"))
    q.send_message("/x", {"n": 1})
    q.send_message("/y", {"n": 2})
    rows = q.read_after(0)
    assert [m["key"] for _, m in rows] == ["/x", "/y"]
    assert q.read_after(rows[-1][0]) == []
    q.close()


def test_replicate_to_local_dir_sink(tmp_path):
    async def body():
        async with _src_cluster(tmp_path) as c:
            queue = SqliteQueue(str(tmp_path / "events.db"))
            attach_to_filer(c.filer.filer, queue)

            await _post(c, "/docs/a.txt", b"alpha")
            await _post(c, "/docs/sub/b.txt", b"beta" * 1000)
            await _post(c, "/docs/a.txt", b"ALPHA2")  # overwrite
            await _post(c, "/other/skip.txt", b"outside")

            dest = str(tmp_path / "mirror")
            sink = LocalDirSink(dest)
            async with FilerSource(c.master.url, "/docs") as src:
                await sink.start()
                n = await replicate_from_queue(
                    queue, Replicator(src, sink),
                    str(tmp_path / "progress.json"), once=True)
                await sink.close()
            assert n > 0
            with open(os.path.join(dest, "a.txt"), "rb") as f:
                assert f.read() == b"ALPHA2"
            with open(os.path.join(dest, "sub/b.txt"), "rb") as f:
                assert f.read() == b"beta" * 1000
            assert not os.path.exists(os.path.join(dest, "skip.txt"))

            # delete propagates; progress file resumes past old events
            async with c.http.delete(
                    f"http://{c.filer.url}/docs/a.txt") as r:
                assert r.status == 204, r.status
            async with FilerSource(c.master.url, "/docs") as src:
                sink2 = LocalDirSink(dest)
                await sink2.start()
                await replicate_from_queue(
                    queue, Replicator(src, sink2),
                    str(tmp_path / "progress.json"), once=True)
                await sink2.close()
            assert not os.path.exists(os.path.join(dest, "a.txt"))
            queue.close()
    run(body())


def test_replicate_filer_to_filer(tmp_path):
    async def body():
        async with _src_cluster(tmp_path) as src_c:
            dst_c = Cluster(str(tmp_path / "dst"), n_servers=2)
            dst_c.with_filer = True
            async with dst_c:
                queue = FileQueue(str(tmp_path / "events.jsonl"))
                attach_to_filer(src_c.filer.filer, queue)

                blob = os.urandom(300 * 1024)  # multi-chunk at 256KB
                await _post(src_c, "/data/file.bin", blob)

                sink = FilerSink(dst_c.filer.url, dst_c.master.url,
                                 directory="/backup")
                async with FilerSource(src_c.master.url, "/") as src:
                    await sink.start()
                    await replicate_from_queue(
                        queue, Replicator(src, sink),
                        str(tmp_path / "p.json"), once=True)
                    await sink.close()

                # target cluster serves the bytes from its OWN volumes
                async with dst_c.http.get(
                        f"http://{dst_c.filer.url}/backup/data/file.bin"
                        ) as resp:
                    assert resp.status == 200
                    assert await resp.read() == blob
    run(body())


def test_replicate_update_incremental(tmp_path):
    """An overwrite event reaches the target as an in-place update via
    MinusChunks diff (filer_sink.go:136-209)."""
    async def body():
        async with _src_cluster(tmp_path) as src_c:
            dst_c = Cluster(str(tmp_path / "dst"), n_servers=2)
            dst_c.with_filer = True
            async with dst_c:
                queue = FileQueue(str(tmp_path / "ev.jsonl"))
                sink = FilerSink(dst_c.filer.url, dst_c.master.url)
                async with FilerSource(src_c.master.url, "/") as src:
                    await sink.start()
                    rep = Replicator(src, sink)
                    attach_to_filer(src_c.filer.filer, queue)

                    await _post(src_c, "/f.txt", b"one")
                    await replicate_from_queue(
                        queue, rep, str(tmp_path / "p.json"), once=True)
                    await _post(src_c, "/f.txt", b"two-two")
                    await replicate_from_queue(
                        queue, rep, str(tmp_path / "p.json"), once=True)
                    await sink.close()

                async with dst_c.http.get(
                        f"http://{dst_c.filer.url}/f.txt") as resp:
                    assert await resp.read() == b"two-two"
    run(body())


def test_replicate_to_s3_sink(tmp_path):
    async def body():
        async with _src_cluster(tmp_path) as src_c:
            from seaweedfs_tpu.s3.gateway import S3Gateway
            s3 = S3Gateway(Filer("memory"), src_c.master.url, port=0)
            await s3.start()
            try:
                queue = FileQueue(str(tmp_path / "e.jsonl"))
                attach_to_filer(src_c.filer.filer, queue)
                await _post(src_c, "/pics/cat.jpg", b"\xff\xd8meow")

                sink = S3Sink(f"http://{s3.url}", "mirror")
                async with FilerSource(src_c.master.url, "/") as src:
                    await sink.start()
                    await replicate_from_queue(
                        queue, Replicator(src, sink),
                        str(tmp_path / "p.json"), once=True)
                    await sink.close()

                async with src_c.http.get(
                        f"http://{s3.url}/mirror/pics/cat.jpg") as resp:
                    assert resp.status == 200
                    assert await resp.read() == b"\xff\xd8meow"
            finally:
                await s3.stop()
    run(body())
