"""Broker subscription inputs for filer.replicate (replication/sub.py),
driven by fake clients like the publisher tests.

Reference: weed/replication/sub/notification_kafka.go:88-140 (offset-file
resume), notification_aws_sqs.go (delete-on-success),
notification_google_pub_sub.go (pull/ack),
weed/command/filer_replication.go:37-130 (apply-then-ack ordering).
"""

import asyncio
import collections
import json

from seaweedfs_tpu.notification.brokers import KafkaQueue
from seaweedfs_tpu.replication.runner import replicate_from_queue
from seaweedfs_tpu.replication.sub import (GooglePubSubInput, KafkaInput,
                                           SqsInput)

TP = collections.namedtuple("TP", "topic partition")
Record = collections.namedtuple("Record", "partition offset key value")


class FakeKafkaBroker:
    """Shared log: the producer fake appends, the consumer fake polls."""

    def __init__(self):
        self.log: list[Record] = []

    def producer(self):
        broker = self

        class P:
            def send(self, topic, key=None, value=None):
                broker.log.append(Record(0, len(broker.log), key, value))

            def flush(self):
                pass

            def close(self):
                pass
        return P()

    def consumer(self):
        broker = self

        class C:
            TopicPartition = TP

            def __init__(self):
                self._pos = {}

            def partitions_for_topic(self, topic):
                return {0}

            def assign(self, tps):
                self._tps = tps

            def seek(self, tp, offset):
                self._pos[tp.partition] = offset

            def poll(self, timeout_ms=0, max_records=64):
                start = self._pos.get(0, 0)
                recs = broker.log[start:start + max_records]
                self._pos[0] = start + len(recs)
                return {TP("t", 0): recs} if recs else {}

            def close(self):
                pass
        return C()


def _event(n, path=None):
    import time

    from seaweedfs_tpu.filer.entry import Attr, Entry
    from seaweedfs_tpu.notification.queues import event_of
    now = time.time()
    e = Entry(full_path=path or f"/d/f{n}",
              attr=Attr(mtime=now, crtime=now, mode=0o660))
    return event_of(None, e)


def test_kafka_input_offset_resume(tmp_path):
    broker = FakeKafkaBroker()
    for i in range(5):
        broker.log.append(Record(0, i, f"/dir/f{i}".encode(),
                                 json.dumps(_event(i)).encode()))
    off = str(tmp_path / "kafka.offset")

    q = KafkaInput()
    q.initialize({"topic": "t", "offset_file": off},
                 client=broker.consumer())
    items = q.receive_batch(max_messages=3)
    assert [k for k, _, _ in items] == ["/dir/f0", "/dir/f1", "/dir/f2"]
    q.commit([tok for _, _, tok in items])

    # a NEW input instance resumes from the persisted offset
    q2 = KafkaInput()
    q2.initialize({"topic": "t", "offset_file": off},
                  client=broker.consumer())
    items2 = q2.receive_batch()
    assert [k for k, _, _ in items2] == ["/dir/f3", "/dir/f4"]
    # uncommitted: a third instance sees them again (at-least-once)
    q3 = KafkaInput()
    q3.initialize({"topic": "t", "offset_file": off},
                  client=broker.consumer())
    assert [k for k, _, _ in q3.receive_batch()] == ["/dir/f3", "/dir/f4"]


class FakeSqsClient:
    def __init__(self):
        self.messages = []
        self.deleted = []

    def get_queue_url(self, QueueName):
        return {"QueueUrl": f"https://sqs.fake/{QueueName}"}

    def receive_message(self, QueueUrl, MessageAttributeNames=None,
                        MaxNumberOfMessages=10, WaitTimeSeconds=0):
        return {"Messages": self.messages[:MaxNumberOfMessages]}

    def delete_message(self, QueueUrl, ReceiptHandle):
        self.deleted.append(ReceiptHandle)
        self.messages = [m for m in self.messages
                         if m["ReceiptHandle"] != ReceiptHandle]


def test_sqs_input_delete_on_commit():
    client = FakeSqsClient()
    for i in range(3):
        client.messages.append({
            "Body": json.dumps(_event(i)),
            "ReceiptHandle": f"rh{i}",
            "MessageAttributes": {"key": {"DataType": "String",
                                          "StringValue": f"/d/f{i}"}}})
    q = SqsInput()
    q.initialize({"sqs_queue_name": "weed"}, client=client)
    items = q.receive_batch()
    assert [k for k, _, _ in items] == ["/d/f0", "/d/f1", "/d/f2"]
    assert client.deleted == []          # nothing acked before commit
    q.commit([tok for _, _, tok in items])
    assert client.deleted == ["rh0", "rh1", "rh2"]
    assert q.receive_batch() == []       # queue drained


class FakePubSub:
    Msg = collections.namedtuple("Msg", "data attributes")
    RM = collections.namedtuple("RM", "ack_id message")
    Resp = collections.namedtuple("Resp", "received_messages")

    def __init__(self):
        self.pending = []
        self.acked = []
        self.subs = {}

    def subscription_path(self, project, name):
        return f"projects/{project}/subscriptions/{name}"

    def topic_path(self, project, name):
        return f"projects/{project}/topics/{name}"

    def get_subscription(self, subscription):
        if subscription not in self.subs:
            raise KeyError(subscription)

    def create_subscription(self, name, topic):
        self.subs[name] = topic

    def pull(self, subscription, max_messages, return_immediately=True):
        return self.Resp(self.pending[:max_messages])

    def acknowledge(self, subscription, ack_ids):
        self.acked.extend(ack_ids)
        self.pending = [m for m in self.pending
                        if m.ack_id not in set(ack_ids)]


def test_pubsub_input_ensure_and_ack():
    client = FakePubSub()
    for i in range(2):
        client.pending.append(client.RM(
            f"a{i}", client.Msg(json.dumps(_event(i)).encode(),
                                {"key": f"/p/f{i}"})))
    q = GooglePubSubInput()
    q.initialize({"project_id": "proj", "topic": "weed"}, client=client)
    assert "projects/proj/subscriptions/weed_sub" in client.subs
    items = q.receive_batch()
    assert [k for k, _, _ in items] == ["/p/f0", "/p/f1"]
    q.commit([tok for _, _, tok in items])
    assert client.acked == ["a0", "a1"]
    assert q.receive_batch() == []


def test_runner_roundtrip_through_fake_kafka(tmp_path):
    """Publisher -> fake broker -> KafkaInput -> runner applies to a sink
    (the full filer.replicate loop with a broker in the middle)."""
    from seaweedfs_tpu.replication.replicator import Replicator
    from seaweedfs_tpu.replication.sink import LocalDirSink

    broker = FakeKafkaBroker()
    pub = KafkaQueue()
    pub.initialize({"topic": "t"}, client=broker.producer())
    pub.send_message("/books/x.txt", _event(0, path="/books/x.txt"))

    q = KafkaInput()
    q.initialize({"topic": "t",
                  "offset_file": str(tmp_path / "off")},
                 client=broker.consumer())

    class Src:
        dir = "/"
        client = None  # entry has no chunks, so it is never dialed

    sink_dir = tmp_path / "out"
    sink = LocalDirSink(str(sink_dir))
    rep = Replicator(Src(), sink)

    async def body():
        await sink.start()
        n = await replicate_from_queue(q, rep,
                                       str(tmp_path / "progress"),
                                       once=True)
        await sink.close()
        return n

    assert asyncio.run(body()) == 1
    assert (sink_dir / "books" / "x.txt").exists()
    # committed: a fresh consumer sees nothing
    q2 = KafkaInput()
    q2.initialize({"topic": "t",
                   "offset_file": str(tmp_path / "off")},
                  client=broker.consumer())
    assert q2.receive_batch() == []
