"""Unit tests for util/resilience.py: RetryPolicy backoff/deadline/
budget and the CircuitBreaker closed/open/half-open state machine
(including the half-open recovery the chaos acceptance demands)."""

import asyncio

import pytest

from seaweedfs_tpu.util.resilience import (
    Backoff, BreakerRegistry, CircuitBreaker, RetryBudget, RetryPolicy)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class MaxRng:
    """uniform(a, b) -> b: deterministic worst-case jitter."""

    def uniform(self, a, b):
        return b


def run(coro):
    return asyncio.run(coro)


def make_policy(clock, sleeps, **kw):
    async def fake_sleep(d):
        sleeps.append(d)
        clock.t += d
    kw.setdefault("rng", MaxRng())
    return RetryPolicy(clock=clock, sleep=fake_sleep, **kw)


# ---- RetryPolicy ----

def test_retry_backoff_is_exponential_and_capped():
    clock, sleeps = FakeClock(), []
    policy = make_policy(clock, sleeps, max_attempts=5, base_delay=1.0,
                         max_delay=4.0, total_timeout=1000.0)

    async def go():
        return [a async for a in policy.attempts()]

    assert run(go()) == [0, 1, 2, 3, 4]
    # full-jitter upper bounds: min(cap, base * 2^(n-1))
    assert sleeps == [1.0, 2.0, 4.0, 4.0]


def test_retry_total_deadline_stops_attempts():
    clock, sleeps = FakeClock(), []
    # every backoff is 2s (MaxRng); deadline 3s in: one retry fits,
    # the second would land past the deadline
    policy = make_policy(clock, sleeps, max_attempts=10, base_delay=2.0,
                         max_delay=2.0, total_timeout=3.0)

    async def go():
        return [a async for a in policy.attempts()]

    assert run(go()) == [0, 1]


def test_retry_budget_denies_when_exhausted():
    clock, sleeps = FakeClock(), []
    budget = RetryBudget(ratio=0.1, burst=1.0)
    policy = make_policy(clock, sleeps, max_attempts=10, base_delay=0.01,
                         total_timeout=100.0, budget=budget)

    async def go():
        return [a async for a in policy.attempts()]

    # burst allows exactly one retry; the deposit from the first
    # attempt (0.1) is below the withdrawal unit
    assert run(go()) == [0, 1]
    # successes refill the budget ratio-by-ratio
    for _ in range(10):
        budget.record_attempt()
    assert run(go()) == [0, 1]


def test_retry_budget_is_shared_across_policies():
    budget = RetryBudget(ratio=0.2, burst=2.0)
    assert budget.allow_retry()
    assert budget.allow_retry()
    assert not budget.allow_retry()


# ---- CircuitBreaker ----

def test_breaker_opens_after_threshold_and_half_open_recovers():
    clock = FakeClock()
    br = CircuitBreaker(threshold=3, reset_timeout=10.0, clock=clock)
    assert br.state == br.CLOSED
    for _ in range(3):
        assert br.allow()
        br.record_failure()
    # OPEN: requests are shed instantly
    assert br.state == br.OPEN
    assert not br.allow()
    assert br.open_count == 1
    # reset_timeout later: HALF-OPEN lets a bounded probe through
    clock.t += 10.0
    assert br.allow()
    assert br.state == br.HALF_OPEN
    assert not br.allow()          # only half_open_max probes
    # the probe succeeds: breaker closes, failures reset
    br.record_success()
    assert br.state == br.CLOSED
    assert br.failures == 0
    assert br.allow()


def test_breaker_half_open_failure_reopens():
    clock = FakeClock()
    br = CircuitBreaker(threshold=2, reset_timeout=5.0, clock=clock)
    br.record_failure()
    br.record_failure()
    assert br.state == br.OPEN
    clock.t += 5.0
    assert br.allow()              # probe
    br.record_failure()            # probe failed
    assert br.state == br.OPEN
    assert not br.allow()          # reset clock restarted
    assert br.open_count == 2
    clock.t += 5.0
    assert br.allow()


def test_breaker_success_resets_failure_streak():
    br = CircuitBreaker(threshold=3)
    br.record_failure()
    br.record_failure()
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == br.CLOSED   # never 3 consecutive


def test_breaker_blocking_peek_is_side_effect_free():
    """read_stream orders locations with blocking(); it must never
    transition state nor consume half-open probes (the wedged-half-open
    regression: a probe consumed by a sort key was never resolved)."""
    clock = FakeClock()
    br = CircuitBreaker(threshold=1, reset_timeout=5.0, clock=clock)
    br.record_failure()
    assert br.state == br.OPEN
    assert br.blocking()
    clock.t += 5.0
    for _ in range(5):
        assert not br.blocking()   # repeated peeks consume nothing
    assert br.state == br.OPEN     # no transition from peeking
    assert br.allow()              # the real probe is still available
    assert br.state == br.HALF_OPEN
    br.record_success()
    assert br.state == br.CLOSED


def test_breaker_registry_is_per_upstream():
    clock = FakeClock()
    reg = BreakerRegistry(threshold=1, clock=clock)
    reg.get("a:1").record_failure()
    assert reg.get("a:1").state == CircuitBreaker.OPEN
    assert reg.get("b:2").state == CircuitBreaker.CLOSED
    assert "a:1" in reg.to_dict()


# ---- Backoff ----

def test_backoff_grows_and_resets():
    b = Backoff(base=1.0, cap=8.0, rng=MaxRng())
    assert [b.next() for _ in range(5)] == [1.0, 2.0, 4.0, 8.0, 8.0]
    b.reset()
    assert b.next() == 1.0


@pytest.mark.parametrize("n", [1, 3])
def test_policy_attempts_is_reusable(n):
    clock, sleeps = FakeClock(), []
    policy = make_policy(clock, sleeps, max_attempts=n, base_delay=0.01,
                         total_timeout=100.0)

    async def go():
        out = []
        for _ in range(2):          # the same policy, two operations
            out.append([a async for a in policy.attempts()])
        return out

    assert run(go()) == [list(range(n))] * 2
