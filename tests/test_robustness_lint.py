"""Tier-1 gate: no silently-swallowed broad exceptions in the data
plane (tools/lint_robustness.py), and the lint itself catches the
shapes it claims to."""

import os
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from lint_robustness import lint_file, lint_paths  # noqa: E402


def test_server_tree_is_clean():
    problems = lint_paths(
        [os.path.join(REPO, "seaweedfs_tpu", "server")])
    assert problems == []


def test_util_and_master_are_clean():
    problems = lint_paths([
        os.path.join(REPO, "seaweedfs_tpu", "util"),
        os.path.join(REPO, "seaweedfs_tpu", "master"),
    ])
    assert problems == []


def test_lint_catches_silent_broad_handlers(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        def f():
            try:
                g()
            except Exception:
                pass
            try:
                g()
            except:
                pass
            for _ in range(3):
                try:
                    g()
                except (ValueError, Exception):
                    continue
    """))
    problems = lint_file(str(bad))
    assert len(problems) == 3
    assert "except Exception" in problems[0]
    assert "bare except" in problems[1]


def test_lint_allows_narrow_and_logged_handlers(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text(textwrap.dedent("""
        import logging
        def f():
            try:
                g()
            except ValueError:
                pass                      # narrow: allowed
            try:
                g()
            except Exception as e:
                logging.warning("boom %s", e)   # logged: allowed
    """))
    assert lint_file(str(ok)) == []
