"""Tier-1 gate: no silently-swallowed broad exceptions in the data
plane (tools/lint_robustness.py), and the lint itself catches the
shapes it claims to."""

import os
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from lint_robustness import lint_file, lint_paths  # noqa: E402


def test_server_tree_is_clean():
    problems = lint_paths(
        [os.path.join(REPO, "seaweedfs_tpu", "server")])
    assert problems == []


def test_util_and_master_are_clean():
    problems = lint_paths([
        os.path.join(REPO, "seaweedfs_tpu", "util"),
        os.path.join(REPO, "seaweedfs_tpu", "master"),
    ])
    assert problems == []


def test_stats_and_wider_tree_pass_metrics_hygiene():
    """stats/ lost its exemption (the silent push_loop handler lived
    there) and every registered metric must carry the SeaweedFS_
    namespace + help text; the tracing-wired trees stay span-clean."""
    problems = lint_paths([
        os.path.join(REPO, "seaweedfs_tpu", "stats"),
        os.path.join(REPO, "seaweedfs_tpu", "storage"),
        os.path.join(REPO, "seaweedfs_tpu", "s3"),
        os.path.join(REPO, "seaweedfs_tpu", "ec"),
    ])
    assert problems == []


def test_lint_catches_metric_hygiene_violations(tmp_path):
    bad = tmp_path / "badmetrics.py"
    bad.write_text(textwrap.dedent("""
        from prometheus_client import Counter, Histogram
        A = Counter("my_requests_total", "requests")       # bad prefix
        B = Counter("SeaweedFS_Requests_total", "x")       # upper lead
        C = Histogram("SeaweedFS_request_seconds", "")     # empty help
        OK = Counter("SeaweedFS_volumeServer_request_total",
                     "needle requests")
    """))
    problems = lint_file(str(bad))
    assert len(problems) == 3
    assert "my_requests_total" in problems[0]
    assert "SeaweedFS_Requests_total" in problems[1]
    assert "help" in problems[2]


def test_lint_catches_span_finish_outside_finally(tmp_path):
    bad = tmp_path / "badspan.py"
    bad.write_text(textwrap.dedent("""
        from seaweedfs_tpu.util import tracing

        def f():
            sp = tracing.start("x", "y")
            sp.finish("ok")                     # not exception-safe

        def g():
            read_span = tracing.start("x", "y")
            try:
                work()
            finally:
                read_span.finish()              # fine

        def h():
            with tracing.start("x", "y"):       # fine: no finish at all
                work()
    """))
    problems = lint_file(str(bad))
    assert len(problems) == 1
    assert "finish() outside a finally" in problems[0]


def test_lint_catches_silent_broad_handlers(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        def f():
            try:
                g()
            except Exception:
                pass
            try:
                g()
            except:
                pass
            for _ in range(3):
                try:
                    g()
                except (ValueError, Exception):
                    continue
    """))
    problems = lint_file(str(bad))
    assert len(problems) == 3
    assert "except Exception" in problems[0]
    assert "bare except" in problems[1]


def test_lint_allows_narrow_and_logged_handlers(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text(textwrap.dedent("""
        import logging
        def f():
            try:
                g()
            except ValueError:
                pass                      # narrow: allowed
            try:
                g()
            except Exception as e:
                logging.warning("boom %s", e)   # logged: allowed
    """))
    assert lint_file(str(ok)) == []
