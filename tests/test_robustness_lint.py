"""Tier-1 gate for the legacy lint surface (tools/lint_robustness.py,
now a shim over tools/weedlint): the data-plane trees stay clean under
the original three passes, the shim keeps its string-list API and
message shapes, and its summary counts per rule instead of calling
every finding a silent-except (the old bug).

The full weedlint framework (new rules, suppressions, baseline, JSON)
is covered by tests/test_weedlint.py.
"""

import os
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from lint_robustness import lint_file, lint_paths, main  # noqa: E402


def test_server_tree_is_clean():
    problems = lint_paths(
        [os.path.join(REPO, "seaweedfs_tpu", "server")])
    assert problems == []


def test_util_and_master_are_clean():
    problems = lint_paths([
        os.path.join(REPO, "seaweedfs_tpu", "util"),
        os.path.join(REPO, "seaweedfs_tpu", "master"),
    ])
    assert problems == []


def test_stats_and_wider_tree_pass_metrics_hygiene():
    """stats/ lost its exemption (the silent push_loop handler lived
    there) and every registered metric must carry the SeaweedFS_
    namespace + help text; the tracing-wired trees stay span-clean."""
    problems = lint_paths([
        os.path.join(REPO, "seaweedfs_tpu", "stats"),
        os.path.join(REPO, "seaweedfs_tpu", "storage"),
        os.path.join(REPO, "seaweedfs_tpu", "s3"),
        os.path.join(REPO, "seaweedfs_tpu", "ec"),
    ])
    assert problems == []


def test_lint_catches_metric_hygiene_violations(tmp_path):
    bad = tmp_path / "badmetrics.py"
    bad.write_text(textwrap.dedent("""
        from prometheus_client import Counter, Histogram
        A = Counter("my_requests_total", "requests")       # bad prefix
        B = Counter("SeaweedFS_Requests_total", "x")       # upper lead
        C = Histogram("SeaweedFS_request_seconds", "")     # empty help
        OK = Counter("SeaweedFS_volumeServer_request_total",
                     "needle requests")
    """))
    problems = lint_file(str(bad))
    assert len(problems) == 3
    assert "my_requests_total" in problems[0]
    assert "SeaweedFS_Requests_total" in problems[1]
    assert "help" in problems[2]


def test_lint_catches_span_finish_outside_finally(tmp_path):
    bad = tmp_path / "badspan.py"
    bad.write_text(textwrap.dedent("""
        from seaweedfs_tpu.util import tracing

        def f():
            sp = tracing.start("x", "y")
            sp.finish("ok")                     # not exception-safe

        def g():
            read_span = tracing.start("x", "y")
            try:
                work()
            finally:
                read_span.finish()              # fine

        def h():
            with tracing.start("x", "y"):       # fine: no finish at all
                work()
    """))
    problems = lint_file(str(bad))
    assert len(problems) == 1
    assert "finish() outside a finally" in problems[0]


def test_lint_catches_silent_broad_handlers(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        def f():
            try:
                g()
            except Exception:
                pass
            try:
                g()
            except:
                pass
            for _ in range(3):
                try:
                    g()
                except (ValueError, Exception):
                    continue
    """))
    problems = lint_file(str(bad))
    assert len(problems) == 3
    assert "except Exception" in problems[0]
    assert "bare except" in problems[1]


def test_lint_allows_narrow_and_logged_handlers(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text(textwrap.dedent("""
        import logging
        def f():
            try:
                g()
            except ValueError:
                pass                      # narrow: allowed
            try:
                g()
            except Exception as e:
                logging.warning("boom %s", e)   # logged: allowed
    """))
    assert lint_file(str(ok)) == []


def test_shim_ignores_weedlint_suppressions(tmp_path):
    """The shim rides the shared driver, so a weedlint suppression
    comment silences the legacy surface too."""
    f = tmp_path / "sup.py"
    f.write_text(textwrap.dedent("""
        def f():
            try:
                g()
            # weedlint: ignore[silent-except] probe loop, outcome is the retry counter
            except Exception:
                pass
    """))
    assert lint_file(str(f)) == []


def test_summary_counts_per_rule(tmp_path, capsys):
    """The old summary printed 'N silent broad exception handler(s)'
    even when the findings were metric/span problems; now it counts
    per rule."""
    bad = tmp_path / "mixed.py"
    bad.write_text(textwrap.dedent("""
        from prometheus_client import Counter
        A = Counter("bad_name_total", "x")
        B = Counter("SeaweedFS_ok_total")
        def f():
            try:
                g()
            except Exception:
                pass
    """))
    rc = main([str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "3 finding(s)" in out
    assert "metric-name=1" in out
    assert "metric-help=1" in out
    assert "silent-except=1" in out
    assert "silent broad exception handler(s)" not in out


def test_clean_run_exit_zero(tmp_path, capsys):
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    rc = main([str(ok)])
    assert rc == 0
    assert "clean" in capsys.readouterr().out
