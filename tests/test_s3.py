"""S3 gateway e2e: buckets, objects, listing, multipart, copy, bulk delete.

Mirrors the coverage intent of s3api/filer_multipart_test.go and
s3api_objects_list_handlers_test.go, but against a live gateway + cluster.
"""

import xml.etree.ElementTree as ET

from cluster_util import Cluster, run

from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.s3.gateway import S3Gateway


class S3Cluster(Cluster):
    async def __aenter__(self):
        await super().__aenter__()
        self.s3 = S3Gateway(Filer("memory"), self.master.url, port=0,
                            chunk_size=128 * 1024)
        await self.s3.start()
        return self

    async def __aexit__(self, *exc):
        await self.s3.stop()
        await super().__aexit__(*exc)


def _tags(xml_body: bytes, tag: str) -> list[str]:
    root = ET.fromstring(xml_body)
    return [el.text for el in root.iter() if el.tag.endswith(tag)]


def test_bucket_and_object_lifecycle(tmp_path):
    async def body():
        async with S3Cluster(str(tmp_path)) as c:
            s3 = f"http://{c.s3.url}"
            async with c.http.put(f"{s3}/mybucket") as r:
                assert r.status == 200
            async with c.http.get(f"{s3}/") as r:
                assert "mybucket" in _tags(await r.read(), "Name")
            # put / get / head
            async with c.http.put(f"{s3}/mybucket/hello.txt",
                                  data=b"hello s3",
                                  headers={"Content-Type": "text/plain"}) as r:
                assert r.status == 200
                assert r.headers["ETag"]
            async with c.http.get(f"{s3}/mybucket/hello.txt") as r:
                assert r.status == 200
                assert await r.read() == b"hello s3"
                assert r.headers["Content-Type"].startswith("text/plain")
            async with c.http.head(f"{s3}/mybucket/hello.txt") as r:
                assert r.status == 200
                assert r.headers["Content-Length"] == "8"
            # range
            async with c.http.get(f"{s3}/mybucket/hello.txt",
                                  headers={"Range": "bytes=6-7"}) as r:
                assert r.status == 206
                assert await r.read() == b"s3"
            # delete
            async with c.http.delete(f"{s3}/mybucket/hello.txt") as r:
                assert r.status == 204
            async with c.http.get(f"{s3}/mybucket/hello.txt") as r:
                assert r.status == 404
            # missing bucket put
            async with c.http.put(f"{s3}/nobucket/x", data=b"z") as r:
                assert r.status == 404
    run(body())


def test_listing_prefix_delimiter(tmp_path):
    async def body():
        async with S3Cluster(str(tmp_path)) as c:
            s3 = f"http://{c.s3.url}"
            await c.http.put(f"{s3}/b")
            for key in ("docs/a.txt", "docs/b.txt", "docs/sub/c.txt",
                        "top.txt"):
                async with c.http.put(f"{s3}/b/{key}", data=b"x") as r:
                    assert r.status == 200
            # full listing (v2)
            async with c.http.get(f"{s3}/b", params={"list-type": "2"}) as r:
                keys = _tags(await r.read(), "Key")
            assert keys == ["docs/a.txt", "docs/b.txt", "docs/sub/c.txt",
                            "top.txt"]
            # prefix
            async with c.http.get(f"{s3}/b",
                                  params={"prefix": "docs/"}) as r:
                keys = _tags(await r.read(), "Key")
            assert keys == ["docs/a.txt", "docs/b.txt", "docs/sub/c.txt"]
            # delimiter folds directories
            async with c.http.get(
                    f"{s3}/b", params={"prefix": "docs/",
                                       "delimiter": "/"}) as r:
                body = await r.read()
            assert _tags(body, "Key") == ["docs/a.txt", "docs/b.txt"]
            assert _tags(body, "Prefix")[-1] == "docs/sub/"
            # max-keys truncation
            async with c.http.get(f"{s3}/b", params={"max-keys": "2",
                                                     "list-type": "2"}) as r:
                body = await r.read()
            assert _tags(body, "IsTruncated") == ["true"]
            assert len(_tags(body, "Key")) == 2
    run(body())


def test_multipart_upload(tmp_path):
    async def body():
        async with S3Cluster(str(tmp_path)) as c:
            s3 = f"http://{c.s3.url}"
            await c.http.put(f"{s3}/mp")
            async with c.http.post(f"{s3}/mp/big.bin",
                                   params={"uploads": ""}) as r:
                upload_id = _tags(await r.read(), "UploadId")[0]
            p1, p2 = b"A" * 200_000, b"B" * 123_456
            for num, data in ((1, p1), (2, p2)):
                async with c.http.put(
                        f"{s3}/mp/big.bin",
                        params={"partNumber": str(num),
                                "uploadId": upload_id},
                        data=data) as r:
                    assert r.status == 200
            async with c.http.get(f"{s3}/mp/big.bin",
                                  params={"uploadId": upload_id}) as r:
                assert _tags(await r.read(), "PartNumber") == ["1", "2"]
            # the in-progress upload shows in ListMultipartUploads
            async with c.http.get(f"{s3}/mp",
                                  params={"uploads": ""}) as r:
                body_ = await r.read()
                assert _tags(body_, "UploadId") == [upload_id]
                assert _tags(body_, "Key") == ["big.bin"]
            # ...scoped to its own bucket
            await c.http.put(f"{s3}/other")
            async with c.http.get(f"{s3}/other",
                                  params={"uploads": ""}) as r:
                assert _tags(await r.read(), "UploadId") == []
            async with c.http.post(f"{s3}/mp/big.bin",
                                   params={"uploadId": upload_id}) as r:
                assert r.status == 200
            async with c.http.get(f"{s3}/mp/big.bin") as r:
                got = await r.read()
            assert got == p1 + p2
            # parts dir cleaned up
            assert c.s3.filer.find_entry(
                f"/buckets/.uploads/{upload_id}") is None
    run(body())


def test_copy_and_bulk_delete(tmp_path):
    async def body():
        async with S3Cluster(str(tmp_path)) as c:
            s3 = f"http://{c.s3.url}"
            await c.http.put(f"{s3}/src")
            await c.http.put(f"{s3}/dst")
            async with c.http.put(f"{s3}/src/orig", data=b"copy me") as r:
                assert r.status == 200
            async with c.http.put(
                    f"{s3}/dst/copied",
                    headers={"x-amz-copy-source": "/src/orig"}) as r:
                assert r.status == 200
            async with c.http.get(f"{s3}/dst/copied") as r:
                assert await r.read() == b"copy me"
            # bulk delete
            xml_body = (b"<Delete><Object><Key>orig</Key></Object>"
                        b"<Object><Key>ghost</Key></Object></Delete>")
            async with c.http.post(f"{s3}/src", params={"delete": ""},
                                   data=xml_body) as r:
                deleted = _tags(await r.read(), "Key")
            assert "orig" in deleted
            async with c.http.get(f"{s3}/src/orig") as r:
                assert r.status == 404
            # copy unaffected by source delete
            async with c.http.get(f"{s3}/dst/copied") as r:
                assert await r.read() == b"copy me"
    run(body())


def test_virtual_host_style_addressing(tmp_path):
    """-domainName: Host: <bucket>.<domain> addressing
    (s3api_server.go:35-37)."""
    async def body():
        async with S3Cluster(str(tmp_path)) as c:
            c.s3.domain_name = "s3.example.com"
            s3 = f"http://{c.s3.url}"
            vh = {"Host": "vbuck.s3.example.com"}
            # PUT bucket.domain/ creates the bucket
            async with c.http.put(f"{s3}/", headers=vh) as r:
                assert r.status == 200
            # object lifecycle entirely host-style, incl. a nested key
            # whose first segment must not be mistaken for a bucket
            async with c.http.put(f"{s3}/a/b.txt", headers=vh,
                                  data=b"vh-bytes") as r:
                assert r.status in (200, 201), await r.text()
            async with c.http.get(f"{s3}/a/b.txt", headers=vh) as r:
                assert r.status == 200
                assert await r.read() == b"vh-bytes"
            # path-style still works side by side
            async with c.http.get(f"{s3}/vbuck/a/b.txt") as r:
                assert r.status == 200
                assert await r.read() == b"vh-bytes"
            # host-style bucket listing sees the key
            async with c.http.get(f"{s3}/", headers=vh,
                                  params={"list-type": "2"}) as r:
                assert "a/b.txt" in _tags(await r.read(), "Key")
            # a plain Host (no domain suffix) still lists buckets
            async with c.http.get(f"{s3}/") as r:
                assert "vbuck" in _tags(await r.read(), "Name")
            # host-style single-segment key (the h_bucket route)
            async with c.http.put(f"{s3}/top.txt", headers=vh,
                                  data=b"t") as r:
                assert r.status in (200, 201), await r.text()
            async with c.http.get(f"{s3}/top.txt", headers=vh) as r:
                assert await r.read() == b"t"
    run(body())
