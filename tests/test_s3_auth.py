"""S3 SigV4 auth (s3api_auth.go analog) + aws-chunked decoding tests."""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse

import aiohttp
from cluster_util import Cluster, run

from seaweedfs_tpu.s3.auth import (ALGORITHM, UNSIGNED, AuthError,
                                   SigV4Verifier, decode_aws_chunked,
                                   signing_key)

AK, SK = "TESTKEY", "TESTSECRET"
REGION = "us-east-1"


def _sign_headers(method: str, host: str, path: str,
                  query: dict | None = None,
                  payload_hash: str = UNSIGNED,
                  secret: str = SK, access_key: str = AK) -> dict:
    """Client-side V4 signing, the way an SDK does it."""
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    headers = {"host": host, "x-amz-date": amz_date,
               "x-amz-content-sha256": payload_hash}
    signed = sorted(headers)
    cq = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted((query or {}).items()))
    canon = "\n".join([
        method, path, cq,  # path = raw wire form, signed verbatim
        "".join(f"{h}:{headers[h]}\n" for h in signed),
        ";".join(signed), payload_hash])
    scope = f"{date}/{REGION}/s3/aws4_request"
    sts = "\n".join([ALGORITHM, amz_date, scope,
                     hashlib.sha256(canon.encode()).hexdigest()])
    sig = hmac.new(signing_key(secret, date, REGION), sts.encode(),
                   hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"{ALGORITHM} Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return headers


def _chunked_body(signed_headers: dict, chunks: list[bytes]) -> bytes:
    """Frame chunks as STREAMING-AWS4-HMAC-SHA256-PAYLOAD with a correct
    signature chain, the way an SDK streams."""
    from seaweedfs_tpu.s3.auth import AuthContext

    auth = signed_headers["Authorization"]
    seed = auth.split("Signature=")[1]
    amz_date = signed_headers["x-amz-date"]
    date = amz_date[:8]
    scope = f"{date}/{REGION}/s3/aws4_request"
    ctx = AuthContext(AK, signing_key(SK, date, REGION), scope,
                      amz_date, seed, "")
    out = bytearray()
    prev = seed
    for data in list(chunks) + [b""]:
        sig = ctx.chunk_signature(prev, data)
        prev = sig
        out += f"{len(data):x};chunk-signature={sig}\r\n".encode()
        out += data
        out += b"\r\n"
    return bytes(out)


def test_verifier_accepts_valid_and_rejects_tampered():
    v = SigV4Verifier({AK: SK})
    h = _sign_headers("GET", "h:1", "/bucket/key")
    assert v.verify("GET", "/bucket/key", {}, h, None).access_key == AK

    # tampered path
    try:
        v.verify("GET", "/bucket/other", {}, h, None)
        raise AssertionError("accepted tampered path")
    except AuthError as e:
        assert e.code == "SignatureDoesNotMatch"

    # wrong secret
    h2 = _sign_headers("GET", "h:1", "/bucket/key", secret="WRONG")
    try:
        v.verify("GET", "/bucket/key", {}, h2, None)
        raise AssertionError("accepted wrong secret")
    except AuthError as e:
        assert e.code == "SignatureDoesNotMatch"

    # unknown access key
    h3 = _sign_headers("GET", "h:1", "/bucket/key", access_key="NOPE")
    try:
        v.verify("GET", "/bucket/key", {}, h3, None)
        raise AssertionError("accepted unknown key")
    except AuthError as e:
        assert e.code == "InvalidAccessKeyId"

    # anonymous
    try:
        v.verify("GET", "/bucket/key", {}, {}, None)
        raise AssertionError("accepted anonymous")
    except AuthError as e:
        assert e.code == "AccessDenied"


def test_decode_aws_chunked():
    payload = (b"5;chunk-signature=aaaa\r\nhello\r\n"
               b"6;chunk-signature=bbbb\r\n world\r\n"
               b"0;chunk-signature=cccc\r\n\r\n")
    assert decode_aws_chunked(payload) == b"hello world"


class _AuthS3Cluster(Cluster):
    async def __aenter__(self):
        await super().__aenter__()
        from seaweedfs_tpu.filer.filer import Filer
        from seaweedfs_tpu.s3.gateway import S3Gateway
        self.s3 = S3Gateway(Filer("memory"), self.master.url, port=0,
                            chunk_size=128 * 1024,
                            identities={AK: SK})
        await self.s3.start()
        return self

    async def __aexit__(self, *exc):
        await self.s3.stop()
        await super().__aexit__(*exc)


def test_s3_gateway_enforces_auth(tmp_path):
    async def body():
        async with _AuthS3Cluster(str(tmp_path)) as c:
            host = c.s3.url
            async with aiohttp.ClientSession() as http:
                # unsigned request is refused
                async with http.put(f"http://{host}/authb") as resp:
                    assert resp.status == 403
                    assert b"AccessDenied" in await resp.read()

                # signed bucket create + object put + get round trip
                h = _sign_headers("PUT", host, "/authb")
                async with http.put(f"http://{host}/authb",
                                    headers=h) as resp:
                    assert resp.status == 200, await resp.text()

                h = _sign_headers("PUT", host, "/authb/hello.txt")
                async with http.put(f"http://{host}/authb/hello.txt",
                                    headers=h, data=b"signed!") as resp:
                    assert resp.status == 200, await resp.text()

                h = _sign_headers("GET", host, "/authb/hello.txt")
                async with http.get(f"http://{host}/authb/hello.txt",
                                    headers=h) as resp:
                    assert resp.status == 200
                    assert await resp.read() == b"signed!"

                # bad signature refused
                h = _sign_headers("GET", host, "/authb/hello.txt",
                                  secret="WRONG")
                async with http.get(f"http://{host}/authb/hello.txt",
                                    headers=h) as resp:
                    assert resp.status == 403

                # aws-chunked upload (SDK streaming style) with a REAL
                # chunk-signature chain seeded by the request signature
                h = _sign_headers(
                    "PUT", host, "/authb/stream.bin",
                    payload_hash="STREAMING-AWS4-HMAC-SHA256-PAYLOAD")
                h["Content-Encoding"] = "aws-chunked"
                chunked = _chunked_body(h, [b"chunked"])
                async with http.put(f"http://{host}/authb/stream.bin",
                                    headers=h, data=chunked) as resp:
                    assert resp.status == 200, await resp.text()
                h = _sign_headers("GET", host, "/authb/stream.bin")
                async with http.get(f"http://{host}/authb/stream.bin",
                                    headers=h) as resp:
                    assert await resp.read() == b"chunked"

                # tampered chunk data must be rejected mid-stream
                h = _sign_headers(
                    "PUT", host, "/authb/evil.bin",
                    payload_hash="STREAMING-AWS4-HMAC-SHA256-PAYLOAD")
                h["Content-Encoding"] = "aws-chunked"
                bad = _chunked_body(h, [b"chunked"]).replace(
                    b"chunked\r\n", b"tampred\r\n", 1)
                async with http.put(f"http://{host}/authb/evil.bin",
                                    headers=h, data=bad) as resp:
                    assert resp.status == 403, await resp.text()

    run(body())
