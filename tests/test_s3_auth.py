"""S3 SigV4 auth (s3api_auth.go analog) + aws-chunked decoding tests."""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse

import aiohttp
from cluster_util import Cluster, run

from seaweedfs_tpu.s3.auth import (ALGORITHM, UNSIGNED, AuthError,
                                   SigV4Verifier, decode_aws_chunked,
                                   signing_key)

AK, SK = "TESTKEY", "TESTSECRET"
REGION = "us-east-1"


def _sign_headers(method: str, host: str, path: str,
                  query: dict | None = None,
                  payload_hash: str = UNSIGNED,
                  secret: str = SK, access_key: str = AK) -> dict:
    """Client-side V4 signing, the way an SDK does it."""
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    headers = {"host": host, "x-amz-date": amz_date,
               "x-amz-content-sha256": payload_hash}
    signed = sorted(headers)
    cq = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted((query or {}).items()))
    canon = "\n".join([
        method, path, cq,  # path = raw wire form, signed verbatim
        "".join(f"{h}:{headers[h]}\n" for h in signed),
        ";".join(signed), payload_hash])
    scope = f"{date}/{REGION}/s3/aws4_request"
    sts = "\n".join([ALGORITHM, amz_date, scope,
                     hashlib.sha256(canon.encode()).hexdigest()])
    sig = hmac.new(signing_key(secret, date, REGION), sts.encode(),
                   hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"{ALGORITHM} Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return headers


def _chunked_body(signed_headers: dict, chunks: list[bytes]) -> bytes:
    """Frame chunks as STREAMING-AWS4-HMAC-SHA256-PAYLOAD with a correct
    signature chain, the way an SDK streams."""
    from seaweedfs_tpu.s3.auth import AuthContext

    auth = signed_headers["Authorization"]
    seed = auth.split("Signature=")[1]
    amz_date = signed_headers["x-amz-date"]
    date = amz_date[:8]
    scope = f"{date}/{REGION}/s3/aws4_request"
    ctx = AuthContext(AK, signing_key(SK, date, REGION), scope,
                      amz_date, seed, "")
    out = bytearray()
    prev = seed
    for data in list(chunks) + [b""]:
        sig = ctx.chunk_signature(prev, data)
        prev = sig
        out += f"{len(data):x};chunk-signature={sig}\r\n".encode()
        out += data
        out += b"\r\n"
    return bytes(out)


def test_verifier_accepts_valid_and_rejects_tampered():
    v = SigV4Verifier({AK: SK})
    h = _sign_headers("GET", "h:1", "/bucket/key")
    assert v.verify("GET", "/bucket/key", {}, h, None).access_key == AK

    # tampered path
    try:
        v.verify("GET", "/bucket/other", {}, h, None)
        raise AssertionError("accepted tampered path")
    except AuthError as e:
        assert e.code == "SignatureDoesNotMatch"

    # wrong secret
    h2 = _sign_headers("GET", "h:1", "/bucket/key", secret="WRONG")
    try:
        v.verify("GET", "/bucket/key", {}, h2, None)
        raise AssertionError("accepted wrong secret")
    except AuthError as e:
        assert e.code == "SignatureDoesNotMatch"

    # unknown access key
    h3 = _sign_headers("GET", "h:1", "/bucket/key", access_key="NOPE")
    try:
        v.verify("GET", "/bucket/key", {}, h3, None)
        raise AssertionError("accepted unknown key")
    except AuthError as e:
        assert e.code == "InvalidAccessKeyId"

    # anonymous
    try:
        v.verify("GET", "/bucket/key", {}, {}, None)
        raise AssertionError("accepted anonymous")
    except AuthError as e:
        assert e.code == "AccessDenied"


def test_decode_aws_chunked():
    payload = (b"5;chunk-signature=aaaa\r\nhello\r\n"
               b"6;chunk-signature=bbbb\r\n world\r\n"
               b"0;chunk-signature=cccc\r\n\r\n")
    assert decode_aws_chunked(payload) == b"hello world"


class _AuthS3Cluster(Cluster):
    async def __aenter__(self):
        await super().__aenter__()
        from seaweedfs_tpu.filer.filer import Filer
        from seaweedfs_tpu.s3.gateway import S3Gateway
        self.s3 = S3Gateway(Filer("memory"), self.master.url, port=0,
                            chunk_size=128 * 1024,
                            identities={AK: SK})
        await self.s3.start()
        return self

    async def __aexit__(self, *exc):
        await self.s3.stop()
        await super().__aexit__(*exc)


def test_s3_gateway_enforces_auth(tmp_path):
    async def body():
        async with _AuthS3Cluster(str(tmp_path)) as c:
            host = c.s3.url
            async with aiohttp.ClientSession() as http:
                # unsigned request is refused
                async with http.put(f"http://{host}/authb") as resp:
                    assert resp.status == 403
                    assert b"AccessDenied" in await resp.read()

                # signed bucket create + object put + get round trip
                h = _sign_headers("PUT", host, "/authb")
                async with http.put(f"http://{host}/authb",
                                    headers=h) as resp:
                    assert resp.status == 200, await resp.text()

                h = _sign_headers("PUT", host, "/authb/hello.txt")
                async with http.put(f"http://{host}/authb/hello.txt",
                                    headers=h, data=b"signed!") as resp:
                    assert resp.status == 200, await resp.text()

                h = _sign_headers("GET", host, "/authb/hello.txt")
                async with http.get(f"http://{host}/authb/hello.txt",
                                    headers=h) as resp:
                    assert resp.status == 200
                    assert await resp.read() == b"signed!"

                # bad signature refused
                h = _sign_headers("GET", host, "/authb/hello.txt",
                                  secret="WRONG")
                async with http.get(f"http://{host}/authb/hello.txt",
                                    headers=h) as resp:
                    assert resp.status == 403

                # aws-chunked upload (SDK streaming style) with a REAL
                # chunk-signature chain seeded by the request signature
                h = _sign_headers(
                    "PUT", host, "/authb/stream.bin",
                    payload_hash="STREAMING-AWS4-HMAC-SHA256-PAYLOAD")
                h["Content-Encoding"] = "aws-chunked"
                chunked = _chunked_body(h, [b"chunked"])
                async with http.put(f"http://{host}/authb/stream.bin",
                                    headers=h, data=chunked) as resp:
                    assert resp.status == 200, await resp.text()
                h = _sign_headers("GET", host, "/authb/stream.bin")
                async with http.get(f"http://{host}/authb/stream.bin",
                                    headers=h) as resp:
                    assert await resp.read() == b"chunked"

                # tampered chunk data must be rejected mid-stream
                h = _sign_headers(
                    "PUT", host, "/authb/evil.bin",
                    payload_hash="STREAMING-AWS4-HMAC-SHA256-PAYLOAD")
                h["Content-Encoding"] = "aws-chunked"
                bad = _chunked_body(h, [b"chunked"]).replace(
                    b"chunked\r\n", b"tampred\r\n", 1)
                async with http.put(f"http://{host}/authb/evil.bin",
                                    headers=h, data=bad) as resp:
                    assert resp.status == 403, await resp.text()

    run(body())


def test_presigned_expires_bounds():
    """X-Amz-Expires outside 1..604800 and far-future X-Amz-Date are
    rejected (AWS bounds presigned lifetime to 7 days)."""
    v = SigV4Verifier({AK: SK})
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]

    def q(**over):
        qd = {"X-Amz-Algorithm": ALGORITHM,
              "X-Amz-Credential": f"{AK}/{date}/{REGION}/s3/aws4_request",
              "X-Amz-Date": amz_date, "X-Amz-Expires": "300",
              "X-Amz-SignedHeaders": "host", "X-Amz-Signature": "00"}
        qd.update(over)
        return qd

    for bad in ("0", "-5", "604801", "99999999"):
        try:
            v.verify("GET", "/b/k", q(**{"X-Amz-Expires": bad}),
                     {"host": "h:1"}, None)
            raise AssertionError(f"accepted X-Amz-Expires={bad}")
        except AuthError as e:
            assert e.code == "AuthorizationQueryParametersError", bad

    future = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(time.time() + 3600))
    try:
        v.verify("GET", "/b/k", q(**{"X-Amz-Date": future}),
                 {"host": "h:1"}, None)
        raise AssertionError("accepted far-future X-Amz-Date")
    except AuthError as e:
        assert e.code == "RequestTimeTooSkewed"


def test_chunked_size_cap():
    """A client-declared multi-GB chunk must be refused before buffering
    (bounds gateway memory; streaming bypasses client_max_size)."""
    huge = (b"40000000;chunk-signature=aaaa\r\n")
    try:
        decode_aws_chunked(huge)
        raise AssertionError("accepted oversized chunk claim")
    except AuthError as e:
        assert e.code == "InvalidRequest"
    # boundary: a legitimate large-ish chunk still decodes
    ok = (b"5;chunk-signature=aaaa\r\nhello\r\n"
          b"0;chunk-signature=cccc\r\n\r\n")
    assert decode_aws_chunked(ok) == b"hello"


def test_multivalue_header_canonicalization():
    """Repeated headers must comma-join in the canonical form (SigV4 spec)
    instead of collapsing to the last value."""
    from multidict import CIMultiDict

    from seaweedfs_tpu.s3.auth import _lower_headers

    md = CIMultiDict()
    md.add("X-Amz-Meta-Tag", "  a  b ")
    md.add("x-amz-meta-tag", "c")
    md.add("Host", "h:1")
    low = _lower_headers(md)
    assert low["x-amz-meta-tag"] == "a b,c"
    assert low["host"] == "h:1"

    # end-to-end: sign WITH the comma-joined value, verify with the
    # multidict carrying the duplicated header
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    headers = {"host": "h:1", "x-amz-date": amz_date,
               "x-amz-content-sha256": UNSIGNED,
               "x-amz-meta-tag": "a b,c"}
    signed = sorted(headers)
    canon = "\n".join([
        "GET", "/b/k", "",
        "".join(f"{h}:{headers[h]}\n" for h in signed),
        ";".join(signed), UNSIGNED])
    scope = f"{date}/{REGION}/s3/aws4_request"
    sts = "\n".join([ALGORITHM, amz_date, scope,
                     hashlib.sha256(canon.encode()).hexdigest()])
    sig = hmac.new(signing_key(SK, date, REGION), sts.encode(),
                   hashlib.sha256).hexdigest()
    wire = CIMultiDict()
    wire.add("host", "h:1")
    wire.add("x-amz-date", amz_date)
    wire.add("x-amz-content-sha256", UNSIGNED)
    wire.add("X-Amz-Meta-Tag", "  a  b ")
    wire.add("X-Amz-Meta-Tag", "c")
    wire.add("Authorization",
             f"{ALGORITHM} Credential={AK}/{scope}, "
             f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    v = SigV4Verifier({AK: SK})
    assert v.verify("GET", "/b/k", {}, wire, None).access_key == AK
