"""ec/scrub.py: the paced background parity scrubber detects real
on-disk bit-rot and failpoint-injected corruption, paces itself under
its token-bucket byte budget, pauses behind hot foreground traffic,
and exposes /debug/scrub (+ POST ?run=1) on the volume server."""

import asyncio
import os
import random
import shutil

import pytest

from seaweedfs_tpu.ec import gf
from seaweedfs_tpu.ec import pipeline as pl
from seaweedfs_tpu.ec.scrub import ForegroundLoad, Scrubber, TokenBucket
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.util import failpoints as fp

from cluster_util import Cluster, run

LB = 16 * 1024
SB = 1024
WINDOW = 8 * 1024


@pytest.fixture(autouse=True)
def _clean_registry():
    fp.reset()
    yield
    fp.reset()


@pytest.fixture()
def ec_store(tmp_path):
    """A Store with one fully-local mounted EC volume (vid 3)."""
    build = str(tmp_path / "build")
    os.makedirs(build)
    v = Volume(build, "", 3)
    rng = random.Random(5)
    for i in range(1, 41):
        v.write_needle(Needle(cookie=i, id=i,
                              data=rng.randbytes(rng.randint(500, 4000))))
    v.close()
    base = os.path.join(build, "3")
    pl.write_ec_files(base, encoder=pl.get_encoder("cpu"),
                      large_block=LB, small_block=SB, buffer_size=SB)
    pl.write_sorted_file_from_idx(base)
    d = str(tmp_path / "store")
    os.makedirs(d)
    for sid in range(gf.TOTAL_SHARDS):
        shutil.copy(base + pl.to_ext(sid),
                    os.path.join(d, "3" + pl.to_ext(sid)))
    shutil.copy(base + ".ecx", os.path.join(d, "3.ecx"))
    store = Store([d], ec_large_block=LB, ec_small_block=SB)
    assert 3 in store.ec_volumes
    yield d, store
    store.close()


# ---------------------------------------------------------------------
# pacing primitives
# ---------------------------------------------------------------------

def test_token_bucket_paces_to_budget():
    clock = {"t": 0.0}
    slept = []

    async def fake_sleep(s):
        slept.append(s)
        clock["t"] += s

    bucket = TokenBucket(1000.0, burst_bytes=1000.0,
                         now=lambda: clock["t"], sleep=fake_sleep)

    async def go():
        await bucket.consume(500)      # burst covers it
        assert slept == []
        await bucket.consume(1000)     # 500 left -> wait 0.5s
        assert slept == [pytest.approx(0.5)]
        await bucket.consume(2500)     # oversized: waits, never wedges
        assert len(slept) == 2
    run(go())


def test_token_bucket_unpaced_when_rate_zero():
    async def go():
        bucket = TokenBucket(0.0, sleep=None)  # sleep never called
        assert await bucket.consume(1 << 30) == 0.0
    run(go())


def test_foreground_load_windows():
    load = ForegroundLoad()
    assert not load.hot(50.0, 2.0)
    load.note(0.002)
    assert not load.hot(50.0, 2.0)     # 2ms < 50ms threshold
    load.note(0.5)
    assert load.hot(50.0, 2.0)
    assert load.hot(0.0, 2.0) is False  # 0 disables pausing
    count, worst_ms = load.snapshot(2.0)
    assert count == 2 and worst_ms == pytest.approx(500.0)
    # a flood of fast requests must NOT evict the recent slow outlier
    # (per-second max buckets, not a request-count-bounded ring)
    for _ in range(5000):
        load.note(0.001)
    assert load.hot(50.0, 2.0)
    count, worst_ms = load.snapshot(2.0)
    assert count == 5002 and worst_ms == pytest.approx(500.0)


# ---------------------------------------------------------------------
# detection
# ---------------------------------------------------------------------

def test_clean_volume_scrubs_clean(ec_store):
    _, store = ec_store
    s = Scrubber(store, mbps=0.0, window_bytes=WINDOW, pause_ms=0.0)

    async def go():
        report = await s.run_cycle()
        assert report["volumes"] == 1
        assert report["corrupt"] == 0
        assert report["windows"] > 1
        assert report["bytes"] > 0
        assert s.status()["corrupt_windows"] == 0
    run(go())


def test_scrub_detects_on_disk_bit_rot_in_every_planted_window(ec_store):
    d, store = ec_store
    ssize = store.ec_volumes[3].shard_size
    # flip one byte in window 1 of a parity shard and one byte in the
    # LAST window of a data shard — silent corruption a foreground
    # needle read (data shards, CRC-checked) may never visit
    planted = [(pl.to_ext(12), WINDOW + 17), (pl.to_ext(4), ssize - 9)]
    for ext, off in planted:
        p = os.path.join(d, "3" + ext)
        with open(p, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
    s = Scrubber(store, mbps=0.0, window_bytes=WINDOW, pause_ms=0.0)

    async def go():
        report = await s.run_cycle()
        want = sorted({(off // WINDOW) * WINDOW for _, off in planted})
        found = sorted(c["offset"] for c in s.corruptions)
        assert found == want, (found, want)
        assert report["corrupt"] == len(want)
        assert s.status()["corrupt_windows"] == len(want)
    run(go())


def test_reported_windows_schema_and_localization(ec_store):
    """Satellite: /debug/scrub corruption reports carry a
    machine-readable `reported_windows` list — (vid, window index,
    offset, size, LOCALIZED shard ids) — so the autopilot observer
    consumes structure instead of parsing prose. Rot planted in a
    parity shard AND a data shard must both be pinned to the right
    shard id by the hypothesis test."""
    d, store = ec_store
    ssize = store.ec_volumes[3].shard_size
    # window 0: parity-shard rot; a DIFFERENT window: data-shard rot
    planted = {(pl.to_ext(12), 17): 12,
               (pl.to_ext(4), ssize - 9): 4}
    assert (ssize - 9) // WINDOW != 0
    for (ext, off), _sid in planted.items():
        p = os.path.join(d, "3" + ext)
        with open(p, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
    s = Scrubber(store, mbps=0.0, window_bytes=WINDOW, pause_ms=0.0)

    async def go():
        report = await s.run_cycle()
        rows = report["corrupt_windows"]
        assert len(rows) == 2, rows
        # the same structured rows ride the cumulative status ring
        assert s.status()["reported_windows"] == rows
        for row in rows:
            for key in ("volume", "window", "offset", "size",
                        "shards", "wall"):
                assert key in row, (key, row)
            assert row["offset"] == row["window"] * WINDOW
        by_window = {r["window"]: r["shards"] for r in rows}
        assert by_window[0] == [12]                     # parity rot
        assert by_window[(ssize - 9) // WINDOW] == [4]  # data rot
    run(go())


def test_multi_shard_rot_in_one_window_stays_unlocalized(ec_store):
    """Two shards rotten in the SAME window: no single-corruption
    hypothesis holds, so `shards` must be [] — the autopilot defers
    instead of guessing which copy to destroy."""
    d, store = ec_store
    for ext in (pl.to_ext(12), pl.to_ext(4)):
        p = os.path.join(d, "3" + ext)
        with open(p, "r+b") as f:
            f.seek(40)
            b = f.read(1)
            f.seek(40)
            f.write(bytes([b[0] ^ 0xFF]))
    s = Scrubber(store, mbps=0.0, window_bytes=WINDOW, pause_ms=0.0)

    async def go():
        report = await s.run_cycle()
        rows = [r for r in report["corrupt_windows"]
                if r["window"] == 0]
        assert rows and rows[0]["shards"] == [], rows
    run(go())


def test_scrub_detects_failpoint_injected_flip(ec_store):
    """scrub.read armed with `flip` corrupts scrub-side reads only:
    the scrubber must flag the window; a foreground needle read sees
    clean bytes."""
    _, store = ec_store
    fp.arm("scrub.read", "flip:3")    # 3 row reads -> all in window 0
    s = Scrubber(store, mbps=0.0, window_bytes=WINDOW, pause_ms=0.0)

    async def go():
        report = await s.run_cycle()
        assert report["corrupt"] == 1
        assert s.corruptions[0]["offset"] == 0
        # spent: second cycle is clean again
        report = await s.run_cycle()
        assert report["corrupt"] == 0
        n = store.read_needle(3, 7, 7)    # foreground read unaffected
        assert n.data
    run(go())


def test_flip_failpoint_corrupts_payload_only():
    fp.arm("x", "flip=4:1")
    out = fp.corrupt("x", b"\x00" * 8)
    assert out == b"\xff" * 4 + b"\x00" * 4
    assert fp.corrupt("x", b"\x00" * 8) == b"\x00" * 8  # spent
    # non-payload sites treat flip as a consumed no-op
    fp.arm("y", "flip:1")
    fp.sync_fail("y")
    assert not fp.pending("y")
    with pytest.raises(ValueError):
        fp.parse_spec("z", "flip=0")


def test_scrub_never_reports_clean_from_reconstructed_rows(ec_store):
    """Review regression: a holder dying MID-CYCLE (after the
    missing-shards probe passed) must not let the scrubber verify a
    window against a row it reconstructed itself — parity recomputed
    from derived rows matches trivially. The volume lands in the
    cycle's errors, never in its clean windows."""
    d, store = ec_store
    ev = store.ec_volumes[3]
    f = ev.shards.pop(6)
    f.close()
    # the holder still answers the cycle-start 1-byte probe, then
    # stops serving window reads (restart mid-cycle)
    ev.fetch_remote = lambda sid, off, size: \
        b"\x00" if size == 1 else None
    s = Scrubber(store, mbps=0.0, window_bytes=WINDOW, pause_ms=0.0)

    async def go():
        report = await s.run_cycle()
        assert report["windows"] == 0          # no false evidence
        assert report["corrupt"] == 0
        assert [e["volume"] for e in report["errors"]] == [3]
        assert "unreachable mid-scrub" in report["errors"][0]["error"]
    run(go())


def test_scrub_only_lowest_shard_holder_owns_the_volume(ec_store):
    """With shards spread across holders, exactly ONE server scrubs a
    volume (the holder of shard 0) — otherwise every holder would move
    the same stripe bytes over the network once per cycle."""
    d, store = ec_store
    ev = store.ec_volumes[3]
    f = ev.shards.pop(0)
    f.close()
    path = os.path.join(d, "3" + pl.to_ext(0))

    def remote(sid, off, size):     # shard 0 alive on a peer
        if sid != 0:
            return None
        with open(path, "rb") as fh:
            fh.seek(off)
            return fh.read(size)

    ev.fetch_remote = remote
    s = Scrubber(store, mbps=0.0, window_bytes=WINDOW, pause_ms=0.0)

    async def go():
        report = await s.run_cycle()
        assert report["volumes"] == 0
        assert report["windows"] == 0
        assert report["skipped"] == [{"volume": 3,
                                      "reason": "not-owner"}]
    run(go())


def test_scrub_skips_volumes_with_missing_shards(ec_store):
    d, store = ec_store
    store.unmount_ec_shards(3, [5])
    os.remove(os.path.join(d, "3" + pl.to_ext(5)))
    s = Scrubber(store, mbps=0.0, window_bytes=WINDOW, pause_ms=0.0)

    async def go():
        report = await s.run_cycle()
        assert report["volumes"] == 0
        assert report["skipped"] == [{"volume": 3, "missing_shards": [5]}]
    run(go())


# ---------------------------------------------------------------------
# pacing behavior
# ---------------------------------------------------------------------

def test_scrub_stays_under_byte_budget(ec_store):
    """With the budget set so one cycle needs multiple refills, the
    paced sleep accounts for (total bytes - burst) at the configured
    rate — the scrubber cannot read faster than -scrub.mbps."""
    _, store = ec_store
    s = Scrubber(store, mbps=4.0, window_bytes=WINDOW, pause_ms=0.0)
    rate = 4.0 * (1 << 20)
    burst = float(WINDOW * gf.TOTAL_SHARDS)   # exactly one window
    clock = {"t": 0.0}
    slept = []
    real_sleep = asyncio.sleep

    async def counting_sleep(t):
        slept.append(t)
        clock["t"] += t           # deterministic: time advances only
        await real_sleep(0)       # by the paced sleeps themselves

    s.bucket = TokenBucket(rate, burst_bytes=burst,
                           now=lambda: clock["t"], sleep=counting_sleep)

    async def go():
        report = await s.run_cycle()
        assert report["windows"] > 1, "fixture too small to pace"
        # every byte beyond the initial burst was paid for at the
        # configured rate — sustained scrub I/O == the budget
        expect = (report["bytes"] - burst) / rate
        assert sum(slept) == pytest.approx(expect, rel=1e-6)
        assert s.paced_sleep_s == pytest.approx(sum(slept), rel=1e-6)
    run(go())


def test_scrub_pauses_while_foreground_hot(ec_store):
    _, store = ec_store
    load = ForegroundLoad()
    s = Scrubber(store, mbps=0.0, window_bytes=WINDOW,
                 pause_ms=50.0, pause_window_s=30.0, load=load)
    load.note(0.2)    # a slow foreground request just happened

    async def clear_later():
        await asyncio.sleep(0.3)
        load.clear()

    async def go():
        t = asyncio.create_task(clear_later())
        report = await s.run_cycle()
        await t
        assert s.pauses >= 1          # parked at least once
        assert report["corrupt"] == 0  # then finished the pass
    run(go())


# ---------------------------------------------------------------------
# /debug/scrub on a live volume server
# ---------------------------------------------------------------------

def test_debug_scrub_endpoint(tmp_path):
    async def go():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            async with c.http.get(
                    f"http://{vs.url}/debug/scrub") as r:
                assert r.status == 200
                body = await r.json()
            st = body["scrub"]
            assert st["enabled"] is False     # default interval 0
            assert st["state"] == "idle"
            # POST ?run=1 forces a cycle even with the loop disabled
            async with c.http.post(
                    f"http://{vs.url}/debug/scrub?run=1") as r:
                assert r.status == 200
                body = await r.json()
            assert body["cycle"]["volumes"] == 0
            assert body["status"]["cycles"] == 1
            async with c.http.post(
                    f"http://{vs.url}/debug/scrub") as r:
                assert r.status == 400
    run(go())
