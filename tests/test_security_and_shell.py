"""JWT write-token enforcement + shell command dispatch."""

import time

import pytest

from cluster_util import Cluster, run

from seaweedfs_tpu.security import jwt as J


def test_jwt_roundtrip():
    tok = J.gen_jwt("secret", "3,01abc", expires_seconds=60)
    claims = J.decode_jwt("secret", tok)
    assert claims["fid"] == "3,01abc"
    J.check_write_jwt("secret", tok, "3,01abc")
    with pytest.raises(J.JwtError):
        J.check_write_jwt("secret", tok, "4,ffff")
    with pytest.raises(J.JwtError):
        J.decode_jwt("wrongkey", tok)
    expired = J.gen_jwt("secret", "3,01abc", expires_seconds=-5)
    with pytest.raises(J.JwtError):
        J.decode_jwt("secret", expired)


def test_cluster_enforces_jwt(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            # flip on jwt after boot (both sides share the key)
            c.master.jwt_key = "s3cret"
            for vs in c.servers:
                vs.jwt_key = "s3cret"
            a = await c.assign()
            assert "auth" in a
            # write without token -> 401
            st, body_ = await c.put(a["fid"], a["url"], b"x")
            assert st == 401, body_
            # write with token -> 201
            async with c.http.post(
                    f"http://{a['url']}/{a['fid']}", data=b"x",
                    headers={"Authorization": f"Bearer {a['auth']}"}) as r:
                assert r.status == 201
            # reads stay open (read jwt optional in reference too)
            st, data = await c.get(a["fid"], a["url"])
            assert st == 200 and data == b"x"
            # delete without token -> 401
            assert await c.delete(a["fid"], a["url"]) == 401
            # batch delete must not bypass the write-token guard
            async with c.http.post(
                    f"http://{a['url']}/admin/batch_delete",
                    json={"fileIds": [a["fid"]]}) as r:
                assert r.status == 200
                rows = (await r.json())["results"]
            assert rows[0]["status"] == 401
            st, _ = await c.get(a["fid"], a["url"])
            assert st == 200  # still there
            # with a per-fid token the batch tombstones it
            async with c.http.post(
                    f"http://{a['url']}/admin/batch_delete",
                    json={"fileIds": [a["fid"]],
                          "tokens": {a["fid"]: a["auth"]}}) as r:
                rows = (await r.json())["results"]
            assert rows[0]["status"] == 202
            st, _ = await c.get(a["fid"], a["url"])
            assert st == 404
    run(body())


def test_shell_runner_dispatch(tmp_path, capsys):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()
            await c.put(a["fid"], a["url"], b"listed")
            await c.heartbeat_all()
            from seaweedfs_tpu.shell.runner import run_command
            res = await run_command(c.master.url, "volume.list")
            assert res and res[0]["volumes"]
            with pytest.raises(ValueError):
                await run_command(c.master.url, "bogus.command")
    run(body())
