"""Shell commands added for full command_*.go registry parity: fs.cd /
fs.pwd session state, fs.meta.cat, fs.meta.notify, and the volume
mount/unmount/copy/delete admin commands."""

from __future__ import annotations

import pytest

from cluster_util import Cluster, run

from seaweedfs_tpu.filer.entry import Entry
from seaweedfs_tpu.notification.queues import FileQueue
from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell.runner import dispatch


def test_fs_cd_pwd_meta_cat_notify(tmp_path):
    async def body():
        c = Cluster(str(tmp_path))
        c.with_filer = True
        async with c:
            furl = c.filer.url

            async def fput(path, data):
                async with c.http.post(
                        f"http://{furl}{path}", data=data) as resp:
                    assert resp.status in (200, 201), await resp.text()

            await fput("/docs/a.txt", b"alpha")
            await fput("/docs/sub/b.txt", b"beta")

            async with CommandEnv(c.master.url) as env:
                # fs.* before any fs.cd and without -filer must not
                # guess a server
                with pytest.raises(ValueError, match="-filer"):
                    await dispatch(env, "fs.ls")

                res = await dispatch(env,
                                     f"fs.cd -filer {furl} -path /docs")
                assert res == {"filer": furl, "cwd": "/docs"}
                assert (await dispatch(env, "fs.pwd"))["cwd"] == "/docs"

                # session defaults: no -filer, relative -path
                names = await dispatch(env, "fs.ls")
                assert set(names) == {"a.txt", "sub/"}
                meta = await dispatch(env, "fs.meta.cat -path a.txt")
                assert meta["FullPath"] == "/docs/a.txt"
                assert meta["chunks"] and not meta["IsDirectory"]

                # relative cd + normalisation, reference positional style
                res = await dispatch(env, "fs.cd sub")
                assert res["cwd"] == "/docs/sub"
                assert set(await dispatch(env, "fs.ls /docs")) == \
                    {"a.txt", "sub/"}
                meta = await dispatch(env, "fs.meta.cat -path ../a.txt")
                assert meta["FullPath"] == "/docs/a.txt"

                # cd to a file is rejected and state is unchanged
                with pytest.raises(ValueError, match="not a directory"):
                    await dispatch(env, "fs.cd -path b.txt")
                assert (await dispatch(env, "fs.pwd"))["cwd"] == "/docs/sub"

                # fs.meta.notify primes a queue with create events the
                # replication pipeline can parse
                qpath = str(tmp_path / "notify.q")
                res = await dispatch(
                    env, f"fs.meta.notify -path / -notify file:{qpath}")
                assert res["notified_files"] == 2
                assert res["notified_dirs"] >= 2  # /docs, /docs/sub
                msgs, _ = FileQueue(qpath).read_from(0)
                paths = set()
                for m in msgs:
                    e = Entry.from_dict(m["event"]["new_entry"])
                    assert m["event"]["old_entry"] is None
                    paths.add(e.full_path)
                assert {"/docs/a.txt", "/docs/sub/b.txt"} <= paths

    run(body())


def test_volume_mount_unmount_copy_delete(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            a = await c.assign()
            vid = int(a["fid"].split(",")[0])
            st, _ = await c.put(a["fid"], a["url"], b"payload")
            assert st == 201
            src = a["url"]
            dst = next(s.url for s in c.servers if s.url != src)

            async with CommandEnv(c.master.url) as env:
                await dispatch(
                    env, f"volume.copy -volumeId {vid} "
                         f"-source {src} -target {dst}")
                st, body_ = await c.get(a["fid"], dst)
                assert (st, body_) == (200, b"payload")

                async def get_local(url):
                    # no redirects: a server without the volume must not
                    # silently answer via its replica
                    async with c.http.get(f"http://{url}/{a['fid']}",
                                          allow_redirects=False) as resp:
                        return resp.status, await resp.read()

                await dispatch(env,
                               f"volume.unmount -volumeId {vid} -node {dst}")
                st, _ = await get_local(dst)
                assert st != 200

                await dispatch(env,
                               f"volume.mount -volumeId {vid} -node {dst}")
                st, body_ = await get_local(dst)
                assert (st, body_) == (200, b"payload")

                # unmount THEN delete: the files must still be destroyed
                # (a silently-ok no-op would resurrect the volume on the
                # next mount — the volume_move hazard, user-reachable)
                await dispatch(env,
                               f"volume.unmount -volumeId {vid} -node {dst}")
                await dispatch(env,
                               f"volume.delete -volumeId {vid} -node {dst}")
                with pytest.raises(RuntimeError, match="not on disk"):
                    await dispatch(
                        env, f"volume.mount -volumeId {vid} -node {dst}")
                # deleting what is already gone reports failure, not ok
                with pytest.raises(RuntimeError, match="not found"):
                    await dispatch(
                        env, f"volume.delete -volumeId {vid} -node {dst}")
                # the copy source is untouched
                st, body_ = await get_local(src)
                assert (st, body_) == (200, b"payload")

    run(body())


def test_volume_mount_collection_volume(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign(collection="pics")
            vid = int(a["fid"].split(",")[0])
            st, _ = await c.put(a["fid"], a["url"], b"pic-bytes")
            assert st == 201
            node = a["url"]
            async with CommandEnv(c.master.url) as env:
                await dispatch(env,
                               f"volume.unmount -volumeId {vid} -node {node}")
                # without the collection the file name cannot resolve
                with pytest.raises(RuntimeError, match="not on disk"):
                    await dispatch(
                        env, f"volume.mount -volumeId {vid} -node {node}")
                await dispatch(env, f"volume.mount -volumeId {vid} "
                                    f"-node {node} -collection pics")
                async with c.http.get(f"http://{node}/{a['fid']}",
                                      allow_redirects=False) as resp:
                    assert resp.status == 200
                    assert await resp.read() == b"pic-bytes"

    run(body())
