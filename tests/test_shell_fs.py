"""fs.*/collection.* shell commands, FileSequencer, status UIs."""

from __future__ import annotations

import os

from cluster_util import Cluster, run

from seaweedfs_tpu.master.sequence import FileSequencer
from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell import fs_commands as fs


def test_file_sequencer_survives_restart(tmp_path):
    p = str(tmp_path / "seq")
    s = FileSequencer(p, step=10)
    ids = [s.next_file_id() for _ in range(25)]
    assert ids == list(range(1, 26))
    # restart: resumes at/after the checkpoint, never reissues
    s2 = FileSequencer(p, step=10)
    nxt = s2.next_file_id()
    assert nxt > max(ids)
    # set_max from heartbeats pushes forward, not back
    s2.set_max(1000)
    assert s2.next_file_id() == 1001
    s3 = FileSequencer(p, step=10)
    assert s3.next_file_id() > 1001


def test_fs_commands_and_ui(tmp_path):
    async def body():
        c = Cluster(str(tmp_path))
        c.with_filer = True
        async with c:
            furl = c.filer.url

            async def fput(path, data):
                async with c.http.post(
                        f"http://{furl}{path}", data=data) as resp:
                    assert resp.status in (200, 201), await resp.text()

            await fput("/docs/a.txt", b"alpha")
            await fput("/docs/sub/b.txt", b"b" * 1000)
            await fput("/top.txt", b"t")

            async with CommandEnv(c.master.url) as env:
                names = await fs.fs_ls(env, furl, "/docs")
                assert set(names) == {"a.txt", "sub/"}
                long = await fs.fs_ls(env, furl, "/docs", long_format=True)
                assert any(e["name"] == "a.txt" and e["size"] == 5
                           for e in long)

                assert await fs.fs_cat(env, furl, "/docs/a.txt") == b"alpha"

                du = await fs.fs_du(env, furl, "/docs")
                assert du["files"] == 2 and du["bytes"] == 1005
                assert du["dirs"] == 1

                tree = await fs.fs_tree(env, furl, "/docs")
                assert "a.txt" in tree and "sub/" in tree

                await fs.fs_mv(env, furl, "/docs/a.txt", "/docs/a2.txt")
                assert await fs.fs_cat(env, furl, "/docs/a2.txt") == b"alpha"

                # meta save / restore round trip
                meta = str(tmp_path / "meta.jsonl")
                saved = await fs.fs_meta_save(env, furl, "/docs", meta)
                assert saved["saved"] >= 3  # a2, sub, sub/b
                await fs.fs_rm(env, furl, "/docs", recursive=True)
                assert await fs.fs_ls(env, furl, "/docs") == []
                loaded = await fs.fs_meta_load(env, furl, meta)
                assert loaded["loaded"] >= 3
                # same cluster: chunks still exist, so content is restored
                assert await fs.fs_cat(env, furl, "/docs/a2.txt") == b"alpha"

                cols = await fs.collection_list(env)
                assert "" in cols

            # status UIs render
            async with c.http.get(
                    f"http://{c.master.url}/ui") as resp:
                page = await resp.text()
                assert resp.status == 200
                assert "seaweedfs_tpu master" in page
            async with c.http.get(
                    f"http://{c.servers[0].url}/ui") as resp:
                page = await resp.text()
                assert resp.status == 200 and "volume server" in page

    run(body())
