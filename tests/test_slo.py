"""SLO burn-rate engine (stats/slo.py): spec grammar, exact
over-threshold fractions from histogram deltas, multi-window page/warn
verdicts, the min-count guard, and evidence correlation (violating
slice + journal events + worst trace)."""

from __future__ import annotations

import pytest

from seaweedfs_tpu.stats import slo
from seaweedfs_tpu.util import events, tracing


@pytest.fixture(autouse=True)
def _fresh():
    events.reset()
    tracing.init(sample=1.0)
    tracing.reset()
    yield
    events.reset()
    tracing.reset()
    slo.init([])


# ---------------------------------------------------------------------------
# spec grammar


def test_spec_parses():
    s = slo.SloSpec("volume.read:p99<50ms@99.9")
    assert (s.tier, s.op) == ("volume", "read")
    assert s.quantile == pytest.approx(0.99)
    assert s.threshold_s == pytest.approx(0.05)
    assert s.objective == pytest.approx(0.999)
    assert s.budget == pytest.approx(0.001)
    s2 = slo.SloSpec("filer.stream:p95<2s@99")
    assert s2.threshold_s == 2.0 and s2.objective == pytest.approx(0.99)


@pytest.mark.parametrize("bad", [
    "volume.read",                  # no objective
    "volume.read:p99<50ms",         # no @
    "volume:p99<50ms@99",           # no op
    "volume.read:q99<50ms@99",      # not pNN
    "volume.read:p99<50m@99",       # bad unit
    "volume.read:p99<50ms@0",       # objective out of range
    "volume.read:p99<50ms@100",
    "",
])
def test_spec_rejects(bad):
    with pytest.raises(ValueError):
        slo.SloSpec(bad)


def test_init_raises_on_bad_spec():
    with pytest.raises(ValueError):
        slo.init(["volume.read:p99<50ms@99", "garbage"])


def test_cli_refuses_slo_with_recorder_disabled(tmp_path):
    # -slo with -timeline.interval 0 would guard nothing: no window is
    # ever snapped, tick() never runs, /debug/health stays ok forever
    # — the same silent-pass hazard as a typo'd spec, refused the same
    # way (regression: the daemon used to start cleanly)
    from seaweedfs_tpu import cli
    from seaweedfs_tpu.stats import timeline
    with pytest.raises(SystemExit, match="flight recorder"):
        cli.main(["volume", "-port", "0", "-dir", str(tmp_path),
                  "-slo", "volume.read:p99<50ms@99",
                  "-timeline.interval", "0"])
    timeline.init()                      # restore process defaults


# ---------------------------------------------------------------------------
# histogram math


def test_frac_over_interpolates():
    buckets = {"0.01": 90.0, "0.1": 100.0, "+Inf": 100.0}
    # threshold on a bucket edge: exact
    assert slo._frac_over(buckets, 0.01, 100.0) == pytest.approx(0.10)
    # inside the (0.01, 0.1] bucket: linear
    assert slo._frac_over(buckets, 0.055, 100.0) == pytest.approx(0.05)
    # +Inf mass always counts as over
    assert slo._frac_over({"0.01": 0.0, "+Inf": 10.0}, 0.05, 10.0) \
        == pytest.approx(1.0)
    assert slo._frac_over({}, 0.05, 0.0) == 0.0


# ---------------------------------------------------------------------------
# verdicts


BASE = ('SeaweedFS_request_duration_seconds'
        '{op="read",status="ok",tier="volume"}')


def _win(wall_ms: float, under: float, over: float) -> dict:
    total = under + over
    return {"wall_ms": wall_ms, "dt_s": 1.0, "rates": {}, "gauges": {},
            "hist": {BASE: {"buckets": {"0.025": under, "+Inf": total},
                            "sum": 0.0, "count": total}}}


def _engine():
    return slo.SloEngine([slo.SloSpec("volume.read:p99<50ms@99")])


def test_ok_when_healthy():
    now = 1_000_000.0
    wins = [_win(now - i * 1000, 100, 0) for i in range(30)]
    out = _engine().evaluate(wins, now_ms=now)
    assert out["status"] == "ok"
    obj = out["objectives"][0]
    assert obj["status"] == "ok" and obj["fast"]["burn"] == 0.0
    assert "evidence" not in obj


def test_page_with_evidence():
    now = 1_000_000.0
    events.record("breaker_open", upstream="w1")
    ev_rows = events.events_dict()["events"]
    for r in ev_rows:
        r["wall_ms"] = now - 5_000          # inside the fast window
    # every request over threshold: burn = 1.0/0.01 = 100 >> 14.4 in
    # both windows
    wins = [_win(now - i * 1000, 0, 10) for i in range(30)]
    out = _engine().evaluate(wins, events=ev_rows, now_ms=now)
    assert out["status"] == "page"
    obj = out["objectives"][0]
    assert obj["fast"]["burn"] >= slo.PAGE_BURN
    assert obj["slow"]["burn"] >= slo.PAGE_BURN
    ev = obj["evidence"]
    assert ev["violating_windows"], "violating slice must be present"
    assert all(w["frac_over"] > 0 for w in ev["violating_windows"])
    assert any(e["type"] == "breaker_open" for e in ev["events"])
    assert ev["window"]["from_ms"] < ev["window"]["to_ms"]


def test_evidence_spans_the_whole_burn_episode():
    # a slow-burn page can land minutes after the breaker trip that
    # explains it (regression: evidence was clipped to the fast 60s
    # window, so a soak that paged late correlated ZERO events)
    now = 1_000_000.0
    events.record("breaker_open", upstream="w1")
    ev_rows = events.events_dict()["events"]
    for r in ev_rows:
        r["wall_ms"] = now - 200_000        # 200s ago: damage onset
    # violation running for 240s: the earliest violating windows sit
    # far outside the fast horizon but inside the slow one
    wins = [_win(now - i * 1000, 0, 10) for i in range(240)]
    out = _engine().evaluate(wins, events=ev_rows, now_ms=now)
    assert out["status"] == "page"
    ev = out["objectives"][0]["evidence"]
    assert ev["violating_total"] == 240
    assert len(ev["violating_windows"]) == 200      # capped, newest
    assert any(e["type"] == "breaker_open" for e in ev["events"])
    # the correlation window opens at the start of the damage, not 60s
    # before the page
    assert ev["window"]["from_ms"] <= now - 200_000


def test_warn_between_burn_thresholds():
    now = 1_000_000.0
    # 8% violating, p99 allows 1% free, 1% budget -> burn 7: warn
    # (>=6) but not page
    wins = [_win(now - i * 1000, 92, 8) for i in range(30)]
    out = _engine().evaluate(wins, now_ms=now)
    assert out["objectives"][0]["status"] == "warn"
    assert out["status"] == "warn"


def test_quantile_is_honored():
    # regression: the pQQ in a spec used to be parsed and echoed but
    # never evaluated, so p50 and p99 behaved identically.  p99<25ms
    # permits 1% of requests over the threshold; p50<25ms permits 50%.
    now = 1_000_000.0
    wins = [_win(now - i * 1000, 70, 30) for i in range(30)]
    strict = slo.SloEngine([slo.SloSpec("volume.read:p99<50ms@99")])
    lax = slo.SloEngine([slo.SloSpec("volume.read:p50<50ms@99")])
    s = strict.evaluate(wins, now_ms=now)["objectives"][0]
    l = lax.evaluate(wins, now_ms=now)["objectives"][0]
    # 30% over: p99 burns (0.30-0.01)/0.01=29 -> page; p50 has 20%
    # headroom left -> burn 0, ok
    assert s["status"] == "page" and s["fast"]["burn"] >= slo.PAGE_BURN
    assert l["status"] == "ok" and l["fast"]["burn"] == 0.0


def test_merged_evaluate_does_not_flap_transition_state():
    # regression: /debug/health evaluates whole-host MERGED windows
    # against the same engine the local tick() uses; it must not touch
    # _last_status or every poll would log phantom ok->page->ok flaps
    # whenever local and merged verdicts disagree
    now = 1_000_000.0
    eng = _engine()
    bad = [_win(now - i * 1000, 0, 10) for i in range(30)]
    good = [_win(now - i * 1000, 100, 0) for i in range(30)]
    eng.evaluate(good, now_ms=now, update_metrics=True)
    assert eng._last_status.get("volume.read:p99<50ms@99", "ok") == "ok"
    # a merged-view page (the handler path: update_metrics=False)
    out = eng.evaluate(bad, now_ms=now)
    assert out["status"] == "page"
    assert eng._last_status.get("volume.read:p99<50ms@99", "ok") == "ok"
    # the canonical tick path still records it
    eng.evaluate(bad, now_ms=now, update_metrics=True)
    assert eng._last_status["volume.read:p99<50ms@99"] == "page"


def test_min_count_guard():
    now = 1_000_000.0
    # one catastrophically slow request on an idle daemon: no page
    wins = [_win(now - 1000, 0, 1)]
    out = _engine().evaluate(wins, now_ms=now)
    assert out["status"] == "ok"


def test_slow_window_guards_against_blips():
    now = 1_000_000.0
    # a fully-violating fast window inside an otherwise-healthy 10
    # minutes: fast burns hot but the slow window stays under the page
    # threshold -> no page (one blip is not an incident)
    wins = [_win(now - i * 1000, 0, 20) for i in range(60)]
    wins += [_win(now - (i + 60) * 1000, 2000, 0) for i in range(540)]
    eng = _engine()
    out = eng.evaluate(wins, now_ms=now)
    assert out["objectives"][0]["fast"]["burn"] >= slo.PAGE_BURN
    assert out["objectives"][0]["status"] != "page"


def test_worst_trace_in_evidence():
    now_ms = None
    with tracing.start_root("volume", "read") as sp:
        pass
    import time
    now_ms = time.time() * 1000.0
    wins = [_win(now_ms - 1000, 0, 100)]
    out = _engine().evaluate(wins, now_ms=now_ms)
    worst = out["objectives"][0]["evidence"].get("worst_trace")
    assert worst is not None and worst["trace"] == sp.trace


def test_health_dict_without_engine_is_stable_schema():
    slo.init([])
    out = slo.health_dict([])
    assert out["status"] == "ok" and out["objectives"] == []
    assert "now_ms" in out


def test_engine_matches_only_its_tier_op():
    other = ('SeaweedFS_request_duration_seconds'
             '{op="write",status="ok",tier="volume"}')
    now = 1_000_000.0
    win = {"wall_ms": now - 1000, "dt_s": 1.0, "rates": {}, "gauges": {},
           "hist": {other: {"buckets": {"+Inf": 100.0}, "sum": 0.0,
                            "count": 100.0}}}
    out = _engine().evaluate([win], now_ms=now)
    assert out["objectives"][0]["fast"]["count"] == 0
    assert out["status"] == "ok"


def test_tick_exports_metrics():
    from seaweedfs_tpu.stats import metrics, timeline
    if not metrics.HAVE_PROMETHEUS:
        pytest.skip("prometheus_client unavailable")
    timeline.init(interval_s=1.0, ring=16)
    timeline.reset()
    slo.init(["volume.read:p99<50ms@99"])
    timeline.snap()
    metrics.REQUEST_DURATION.labels("volume", "read", "ok").observe(5.0)
    timeline.snap()
    slo.tick()
    text = metrics.metrics_text().decode()
    assert 'SeaweedFS_slo_burn_rate{' in text
    assert 'SeaweedFS_slo_status{' in text
