"""Storage engine unit tests: needle format, maps, volume lifecycle.

Modeled on the reference's round-trip tests
(storage/needle/needle_read_write_test.go, file_id_test.go,
volume_ttl_test.go, storage/volume_vacuum_test.go patterns).
"""

import os

import pytest

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import (
    FLAG_HAS_NAME, CrcMismatch, Needle)
from seaweedfs_tpu.storage.needle_map import (
    MemoryNeedleMap, SortedFileNeedleMap, write_sorted_index)
from seaweedfs_tpu.storage.super_block import ReplicaPlacement, SuperBlock
from seaweedfs_tpu.storage.volume import (
    AlreadyDeleted, NotFound, Volume, VolumeError)
from seaweedfs_tpu.util import crc32c


def test_crc32c_known_vectors():
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8A9136AA
    assert crc32c.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c.crc32c(b"123456789") == 0xE3069283
    # python fallback must agree with native
    assert crc32c._crc32c_py(0, b"123456789") == 0xE3069283


def test_needle_roundtrip_v3():
    n = Needle(cookie=0x12345678, id=0xABCDEF, data=b"hello world",
               name=b"x.txt", mime=b"text/plain", last_modified=1700000000,
               ttl=t.TTL.parse("3h"), pairs=b'{"a":"b"}')
    blob = n.to_bytes(t.VERSION3)
    assert len(blob) % 8 == 0
    m = Needle.from_bytes(blob, t.VERSION3)
    assert (m.cookie, m.id, m.data) == (n.cookie, n.id, b"hello world")
    assert m.name == b"x.txt"
    assert m.mime == b"text/plain"
    assert m.last_modified == 1700000000
    assert m.ttl == t.TTL.parse("3h")
    assert m.pairs == b'{"a":"b"}'
    assert m.append_at_ns == n.append_at_ns


def test_needle_roundtrip_versions():
    for version in (t.VERSION1, t.VERSION2, t.VERSION3):
        n = Needle(cookie=7, id=42, data=b"payload")
        m = Needle.from_bytes(n.to_bytes(version), version)
        assert m.data == b"payload", version


def test_needle_crc_check():
    n = Needle(cookie=1, id=2, data=b"data")
    blob = bytearray(n.to_bytes(t.VERSION3))
    blob[t.NEEDLE_HEADER_SIZE + 4] ^= 0xFF  # corrupt data byte
    with pytest.raises(CrcMismatch):
        Needle.from_bytes(bytes(blob), t.VERSION3)


def test_needle_empty_data_tombstone_shape():
    n = Needle(cookie=1, id=2, data=b"")
    blob = n.to_bytes(t.VERSION3)
    m = Needle.from_bytes(blob, t.VERSION3)
    assert m.size == 0 and m.data == b""


def test_file_id_roundtrip():
    fid = t.FileId(3, 0x01637037, 0xD6000000)
    s = str(fid)
    assert s.startswith("3,")
    back = t.FileId.parse(s)
    assert back == fid
    # known reference formatting: leading zero bytes of the key stripped
    assert t.FileId.parse("3,01637037d6aabbcc") is not None
    with pytest.raises(ValueError):
        t.FileId.parse("nocomma")


def test_ttl_parse_format():
    assert t.TTL.parse("") == t.TTL()
    assert str(t.TTL.parse("5d")) == "5d"
    assert t.TTL.parse("90") == t.TTL(90, t.TTL_MINUTE)
    tt = t.TTL.parse("7M")
    assert t.TTL.from_uint32(tt.to_uint32()) == tt
    assert t.TTL.parse("2w").minutes == 2 * 10080


def test_replica_placement():
    rp = ReplicaPlacement.parse("012")
    assert rp.copy_count == 4
    assert str(ReplicaPlacement.from_byte(rp.to_byte())) == "012"
    with pytest.raises(ValueError):
        ReplicaPlacement.parse("9zz")


def test_super_block_roundtrip():
    sb = SuperBlock(version=3, replica_placement=ReplicaPlacement.parse("001"),
                    ttl=t.TTL.parse("1h"), compaction_revision=5)
    back = SuperBlock.from_bytes(sb.to_bytes())
    assert back == sb


def test_memory_needle_map_idx_replay(tmp_path):
    idx = str(tmp_path / "1.idx")
    nm = MemoryNeedleMap(idx)
    nm.put(1, 8, 100)
    nm.put(2, 120, 50)
    nm.put(1, 256, 120)   # overwrite
    nm.delete(2, 512)
    nm.close()

    nm2 = MemoryNeedleMap(idx)
    assert nm2.get(1).offset == 256
    assert nm2.get(1).size == 120
    assert nm2.get(2).size == t.TOMBSTONE_FILE_SIZE  # tombstone retained
    assert nm2.file_count == 2
    assert nm2.deleted_count == 2  # one overwrite + one delete
    nm2.destroy()
    assert not os.path.exists(idx)


def test_sorted_file_map(tmp_path):
    path = str(tmp_path / "1.sdx")
    entries = [(k, k * 64, 10 + k) for k in range(0, 200, 3)]
    write_sorted_index(entries, path)
    sm = SortedFileNeedleMap(path)
    assert sm.get(3).size == 13
    assert sm.get(198).offset == 198 * 64
    assert sm.get(4) is None
    sm.close()


def test_volume_write_read_delete(tmp_path):
    v = Volume(str(tmp_path), "", 1)
    n = Needle(cookie=0xAA, id=1, data=b"first")
    off, size = v.write_needle(n)
    assert off == 8  # right after superblock
    got = v.read_needle(1, cookie=0xAA)
    assert got.data == b"first"

    # overwrite with same cookie
    v.write_needle(Needle(cookie=0xAA, id=1, data=b"second"))
    assert v.read_needle(1).data == b"second"

    # overwrite with wrong cookie rejected
    with pytest.raises(VolumeError):
        v.write_needle(Needle(cookie=0xBB, id=1, data=b"evil"))

    # delete -> AlreadyDeleted on read
    reclaimed = v.delete_needle(Needle(cookie=0xAA, id=1))
    assert reclaimed > 0
    with pytest.raises(AlreadyDeleted):
        v.read_needle(1)
    with pytest.raises(NotFound):
        v.read_needle(999)
    v.close()


def test_volume_reload_after_crash(tmp_path):
    v = Volume(str(tmp_path), "c", 7)
    for i in range(10):
        v.write_needle(Needle(cookie=i, id=i, data=bytes([i]) * (i + 1)))
    v.delete_needle(Needle(cookie=3, id=3))
    v.close()

    v2 = Volume(str(tmp_path), "c", 7, create_if_missing=False)
    assert v2.read_needle(5).data == b"\x05" * 6
    with pytest.raises(AlreadyDeleted):
        v2.read_needle(3)
    st = v2.stat()
    assert st.file_count == 10
    assert st.deleted_count == 1
    v2.close()


def test_volume_torn_tail_truncated(tmp_path):
    v = Volume(str(tmp_path), "", 9)
    v.write_needle(Needle(cookie=1, id=1, data=b"good"))
    end = v.data_size()
    v.close()
    # simulate a torn write past the last indexed needle
    with open(str(tmp_path / "9.dat"), "ab") as f:
        f.write(b"\xde\xad\xbe\xef" * 3)
    v2 = Volume(str(tmp_path), "", 9, create_if_missing=False)
    assert v2.data_size() == end
    assert v2.read_needle(1).data == b"good"
    v2.close()


def test_volume_scan(tmp_path):
    v = Volume(str(tmp_path), "", 11)
    for i in range(1, 4):
        v.write_needle(Needle(cookie=i, id=i, data=b"x" * i))
    v.delete_needle(Needle(cookie=2, id=2))
    seen = []
    v.scan(lambda n, off: seen.append((n.id, n.size, off)))
    assert len(seen) == 4  # 3 writes + 1 tombstone
    assert seen[-1][1] == 0  # tombstone has size 0
    v.close()


def test_volume_rewrite_after_delete(tmp_path):
    v = Volume(str(tmp_path), "", 21)
    v.write_needle(Needle(cookie=1, id=7, data=b"one"))
    v.delete_needle(Needle(cookie=1, id=7))
    # re-writing a deleted id must succeed, even with a new cookie
    v.write_needle(Needle(cookie=2, id=7, data=b"two"))
    assert v.read_needle(7, cookie=2).data == b"two"
    v.close()


def test_volume_reopen_all_deleted(tmp_path):
    v = Volume(str(tmp_path), "", 22)
    v.write_needle(Needle(cookie=1, id=1, data=b"x"))
    v.delete_needle(Needle(cookie=1, id=1))
    v.close()
    v2 = Volume(str(tmp_path), "", 22, create_if_missing=False)
    with pytest.raises(AlreadyDeleted):
        v2.read_needle(1)
    v2.close()


def test_volume_reopen_keeps_trailing_tombstone(tmp_path):
    v = Volume(str(tmp_path), "", 23)
    v.write_needle(Needle(cookie=1, id=1, data=b"a"))
    v.write_needle(Needle(cookie=2, id=2, data=b"b"))
    v.delete_needle(Needle(cookie=1, id=1))
    end = v.data_size()
    v.close()
    v2 = Volume(str(tmp_path), "", 23, create_if_missing=False)
    assert v2.data_size() == end  # tombstone record NOT truncated
    records = []
    v2.scan(lambda n, off: records.append((n.id, n.size)))
    assert records[-1] == (1, 0)
    v2.close()


def test_needle_field_limits():
    from seaweedfs_tpu.storage.needle import NeedleError
    with pytest.raises(NeedleError):
        Needle(cookie=1, id=1, data=b"x", mime=b"m" * 256).to_bytes()
    with pytest.raises(NeedleError):
        Needle(cookie=1, id=1, data=b"x", pairs=b"p" * 65536).to_bytes()
    # name is clamped, not an error (reference truncates at 255)
    n = Needle(cookie=1, id=1, data=b"x", name=b"n" * 300)
    m = Needle.from_bytes(n.to_bytes(), 3)
    assert len(m.name) == 255


def test_volume_ttl_expiry(tmp_path):
    v = Volume(str(tmp_path), "", 13, ttl=t.TTL.parse("1m"))
    n = Needle(cookie=1, id=1, data=b"z", last_modified=100)  # long ago
    n.set_flag(0x08)  # has last modified
    v.write_needle(n)
    with pytest.raises(NotFound):
        v.read_needle(1)
    v.close()


def test_preallocate_keeps_append_offsets(tmp_path):
    """Preallocation must reserve blocks WITHOUT moving the append tail
    (FALLOC_FL_KEEP_SIZE, volume_create_linux.go:19): appends derive
    their offset from st_size."""
    from seaweedfs_tpu.storage.volume import Volume
    from seaweedfs_tpu.storage.needle import Needle

    v = Volume(str(tmp_path), "", 31, preallocate=1 << 20)
    off, _ = v.write_needle(Needle(cookie=5, id=1, data=b"pre" * 50))
    assert off < 4096, "append landed past the preallocated region"
    assert v.data_size() < 4096
    assert v.read_needle(1, cookie=5).data == b"pre" * 50
    v.close()
    # reload: integrity check passes, appends continue at the tail
    v2 = Volume(str(tmp_path), "", 31, create_if_missing=False)
    off2, _ = v2.write_needle(Needle(cookie=6, id=2, data=b"y"))
    assert off < off2 < 8192
    v2.close()


def test_ttl_volume_expiry_reclaims(tmp_path):
    """Whole-volume TTL reclamation rides the heartbeat walk
    (store.go:165-200 + volume.go expired/expiredLongEnough)."""
    import os
    import time as _time

    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.store import Store

    st = Store([str(tmp_path)])
    v = st.add_volume(5, ttl="1m")
    v.write_needle(Needle(cookie=1, id=1, data=b"short-lived"))
    hb = st.collect_heartbeat()
    assert [m.id for m in hb.volumes] == [5]

    # age past ttl but inside the grace window: no longer advertised,
    # files still on disk
    v.last_modified_ts = _time.time() - 63  # just past the 1m ttl
    hb = st.collect_heartbeat()
    assert hb.volumes == []
    assert os.path.exists(os.path.join(str(tmp_path), "5.dat"))
    assert 5 in st.volumes

    # age past ttl + removal delay: destroyed and reported deleted
    v.last_modified_ts = _time.time() - 3600
    hb = st.collect_heartbeat()
    assert hb.volumes == []
    assert [m.id for m in hb.deleted_volumes] == [5]
    assert 5 not in st.volumes
    assert not os.path.exists(os.path.join(str(tmp_path), "5.dat"))

    # non-TTL volumes are never reclaimed
    v2 = st.add_volume(6)
    v2.write_needle(Needle(cookie=1, id=1, data=b"eternal"))
    v2.last_modified_ts = _time.time() - 10_000_000
    hb = st.collect_heartbeat()
    assert [m.id for m in hb.volumes] == [6]
    st.close()


def test_ttl_watermark_survives_restart(tmp_path):
    """last_modified_ts must be restored on load, or TTL reclamation
    (store.go expired()) goes dead after a volume-server restart."""
    import time as _time

    from seaweedfs_tpu.storage.needle import (FLAG_HAS_LAST_MODIFIED,
                                              Needle)
    from seaweedfs_tpu.storage.volume import Volume

    v = Volume(str(tmp_path), "", 9)
    n = Needle(cookie=1, id=1, data=b"x",
               last_modified=int(_time.time()))
    n.set_flag(FLAG_HAS_LAST_MODIFIED)
    v.write_needle(n)
    wm = v.last_modified_ts
    assert wm > 0
    v.close()
    v2 = Volume(str(tmp_path), "", 9, create_if_missing=False)
    assert v2.last_modified_ts == wm
    v2.close()
