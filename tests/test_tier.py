"""Tiered storage: move sealed .dat to an S3-compatible remote; reads
flow through ranged GETs transparently.

Reference: weed/storage/backend/ (BackendStorage abstraction,
s3_backend.go ReadAt-over-ranged-GET), weed/storage/volume_tier.go,
server/volume_grpc_tier_upload.go/_download.go. The remote here is this
package's own S3 gateway — dogfooding the gateway as the object tier.
"""

import asyncio
import os

import pytest

from cluster_util import Cluster, run

from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.s3.gateway import S3Gateway
from seaweedfs_tpu.storage import backend as bk
from seaweedfs_tpu.storage import volume_tier
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume, VolumeError


@pytest.fixture(autouse=True)
def _clean_backends():
    bk.clear_backends()
    yield
    bk.clear_backends()


def test_tier_upload_read_download(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            # stand up an S3 gateway on the same cluster as the tier target
            s3 = S3Gateway(Filer("memory"), c.master.url, port=0)
            await s3.start()
            try:
                bk.load_backends({"s3": {"default": {
                    "endpoint": s3.url, "bucket": "tier"}}})
                # write some needles
                a = await c.assign()
                fids = [a["fid"]]
                st, _ = await c.put(a["fid"], a["url"], b"tiered-0")
                assert st == 201
                vid = a["fid"].split(",")[0]
                for i in range(1, 4):
                    f2 = f"{vid},{i+1:02x}deadbeef"
                    st, _ = await c.put(f2, a["url"], f"tiered-{i}".encode())
                    assert st == 201
                    fids.append(f2)

                # upload the volume's .dat to the s3 tier
                async with c.http.post(
                        f"http://{a['url']}/admin/tier/upload",
                        params={"volume": vid,
                                "backend": "s3.default"}) as resp:
                    body_ = await resp.json()
                    assert resp.status == 200, body_
                    assert body_["uploaded"] > 0
                vs = c.servers[0]
                v = vs.store.volumes[int(vid)]
                assert v.is_remote
                base = v.file_name()
                assert not os.path.exists(base + ".dat")  # moved away
                assert os.path.exists(base + ".vif")

                # reads now go through ranged GETs against the gateway
                for i, fid in enumerate(fids):
                    stc, data = await c.get(fid, a["publicUrl"])
                    assert stc == 200 and data == f"tiered-{i}".encode()

                # writes are rejected: volume is sealed
                st, _ = await c.put(f"{vid},77feedface", a["url"], b"nope")
                assert st in (409, 500)

                # bring it back down
                async with c.http.post(
                        f"http://{a['url']}/admin/tier/download",
                        params={"volume": vid}) as resp:
                    body_ = await resp.json()
                    assert resp.status == 200, body_
                assert not v.is_remote
                assert os.path.exists(base + ".dat")
                assert not os.path.exists(base + ".vif")
                stc, data = await c.get(fids[2], a["publicUrl"])
                assert stc == 200 and data == b"tiered-2"
            finally:
                await s3.stop()
    run(body())


def test_remote_volume_reload_from_vif(tmp_path):
    """A store restart re-opens tiered volumes from .idx + .vif alone."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            s3 = S3Gateway(Filer("memory"), c.master.url, port=0)
            await s3.start()
            try:
                bk.load_backends({"s3": {"default": {
                    "endpoint": s3.url, "bucket": "tier2"}}})
                vdir = str(tmp_path / "offline")

                # sync volume I/O does blocking HTTP to the in-loop
                # gateway, so it must run off the event loop
                def offline_work() -> None:
                    v = Volume(vdir, "", 9)
                    v.write_needle(
                        Needle(cookie=1, id=5, data=b"persisted"))
                    volume_tier.tier_upload(v, "s3.default")
                    v.close()
                    # reopen purely from .idx/.vif
                    v2 = Volume(vdir, "", 9, create_if_missing=False)
                    assert v2.is_remote
                    assert v2.read_needle(5).data == b"persisted"
                    with pytest.raises(VolumeError):
                        v2.write_needle(Needle(cookie=1, id=6, data=b"x"))
                    v2.close()

                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, offline_work)
            finally:
                await s3.stop()
    run(body())


def test_mmap_backend_tier_roundtrip(tmp_path):
    """The memory-mapped local backend (backend.py MmapBackendStorage,
    reference weed/storage/backend/memory_map/) as a tier target:
    upload -> mmap reads -> scan -> download, proving BackendStorage
    factory plurality beyond s3."""
    bk.load_backends({"mmap": {"hot": {"dir": str(tmp_path / "ram")}}})
    vdir = str(tmp_path / "vols")
    v = Volume(vdir, "", 6)
    for i in range(1, 6):
        v.write_needle(Needle(cookie=3, id=i, data=bytes([i]) * 500))
    uploaded = volume_tier.tier_upload(v, "mmap.hot")
    assert uploaded > 0
    assert v.is_remote
    assert not os.path.exists(os.path.join(vdir, "6.dat"))
    # reads flow through the mmap
    for i in range(1, 6):
        assert v.read_needle(i).data == bytes([i]) * 500
    # sequential scan over the mapped file
    seen = {}
    v.scan(lambda n, off: seen.__setitem__(n.id, len(n.data)))
    assert seen == {i: 500 for i in range(1, 6)}
    with pytest.raises(VolumeError):
        v.write_needle(Needle(cookie=3, id=9, data=b"x"))
    # bring it back
    volume_tier.tier_download(v)
    assert not v.is_remote and not v.read_only
    assert v.read_needle(2).data == b"\x02" * 500
    v.write_needle(Needle(cookie=3, id=9, data=b"back-home"))
    assert v.read_needle(9).data == b"back-home"
    v.close()


def test_remote_volume_scan_readahead(tmp_path):
    """scan() over a tiered volume walks every record through coalesced
    ranged GETs (the export/fix CLI path)."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            s3 = S3Gateway(Filer("memory"), c.master.url, port=0)
            await s3.start()
            try:
                bk.load_backends({"s3": {"default": {
                    "endpoint": s3.url, "bucket": "tier4"}}})
                vdir = str(tmp_path / "scanme")

                def work():
                    v = Volume(vdir, "", 4)
                    for i in range(1, 21):
                        v.write_needle(Needle(
                            cookie=7, id=i, data=bytes([i]) * (100 * i)))
                    volume_tier.tier_upload(v, "s3.default")
                    v.close()
                    v2 = Volume(vdir, "", 4, create_if_missing=False)
                    assert v2.is_remote
                    gets = 0
                    inner = v2._pread

                    def counting(nbytes, offset):
                        nonlocal gets
                        gets += 1
                        return inner(nbytes, offset)
                    v2._pread = counting
                    seen = {}
                    v2.scan(lambda n, off: seen.__setitem__(n.id, n.data))
                    assert set(seen) == set(range(1, 21))
                    assert seen[20] == b"\x14" * 2000
                    # coalesced: whole ~30 KB volume in one ranged GET,
                    # not 2 per record
                    assert gets < 5, gets
                    v2.close()

                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, work)
            finally:
                await s3.stop()
    run(body())


def test_keep_local_stays_sealed_after_reopen(tmp_path):
    """tier.upload -keepLocal keeps the local .dat, but a restart must not
    resurrect the volume as writable (it would diverge from the remote)."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            s3 = S3Gateway(Filer("memory"), c.master.url, port=0)
            await s3.start()
            try:
                bk.load_backends({"s3": {"default": {
                    "endpoint": s3.url, "bucket": "tier3"}}})
                vdir = str(tmp_path / "keep")

                def work():
                    v = Volume(vdir, "", 3)
                    v.write_needle(Needle(cookie=9, id=1, data=b"kept"))
                    volume_tier.tier_upload(v, "s3.default",
                                            keep_local=True)
                    v.close()
                    assert os.path.exists(os.path.join(vdir, "3.dat"))
                    v2 = Volume(vdir, "", 3, create_if_missing=False)
                    assert v2.read_only  # sealed via .vif presence
                    assert v2.read_needle(1).data == b"kept"
                    with pytest.raises(VolumeError):
                        v2.write_needle(Needle(cookie=9, id=2, data=b"x"))
                    # download restores writability and drops the .vif
                    volume_tier.tier_download(v2)
                    assert not v2.read_only
                    v2.write_needle(Needle(cookie=9, id=2, data=b"y"))
                    v2.close()

                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, work)
            finally:
                await s3.stop()
    run(body())
