"""Tiered storage: move sealed .dat to an S3-compatible remote; reads
flow through ranged GETs transparently.

Reference: weed/storage/backend/ (BackendStorage abstraction,
s3_backend.go ReadAt-over-ranged-GET), weed/storage/volume_tier.go,
server/volume_grpc_tier_upload.go/_download.go. The remote here is this
package's own S3 gateway — dogfooding the gateway as the object tier.
"""

import asyncio
import concurrent.futures
import os
import time

import pytest

from cluster_util import Cluster, run

from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.s3.gateway import S3Gateway
from seaweedfs_tpu.storage import backend as bk
from seaweedfs_tpu.storage import volume_tier
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume, VolumeError
from seaweedfs_tpu.util import failpoints
from seaweedfs_tpu.util.batchframe import parse_all


@pytest.fixture(autouse=True)
def _clean_backends():
    bk.clear_backends()
    yield
    bk.clear_backends()


def test_tier_upload_read_download(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            # stand up an S3 gateway on the same cluster as the tier target
            s3 = S3Gateway(Filer("memory"), c.master.url, port=0)
            await s3.start()
            try:
                bk.load_backends({"s3": {"default": {
                    "endpoint": s3.url, "bucket": "tier"}}})
                # write some needles
                a = await c.assign()
                fids = [a["fid"]]
                st, _ = await c.put(a["fid"], a["url"], b"tiered-0")
                assert st == 201
                vid = a["fid"].split(",")[0]
                for i in range(1, 4):
                    f2 = f"{vid},{i+1:02x}deadbeef"
                    st, _ = await c.put(f2, a["url"], f"tiered-{i}".encode())
                    assert st == 201
                    fids.append(f2)

                # upload the volume's .dat to the s3 tier
                async with c.http.post(
                        f"http://{a['url']}/admin/tier/upload",
                        params={"volume": vid,
                                "backend": "s3.default"}) as resp:
                    body_ = await resp.json()
                    assert resp.status == 200, body_
                    assert body_["uploaded"] > 0
                vs = c.servers[0]
                v = vs.store.volumes[int(vid)]
                assert v.is_remote
                base = v.file_name()
                assert not os.path.exists(base + ".dat")  # moved away
                assert os.path.exists(base + ".vif")

                # reads now go through ranged GETs against the gateway
                for i, fid in enumerate(fids):
                    stc, data = await c.get(fid, a["publicUrl"])
                    assert stc == 200 and data == f"tiered-{i}".encode()

                # writes are rejected: volume is sealed
                st, _ = await c.put(f"{vid},77feedface", a["url"], b"nope")
                assert st in (409, 500)

                # bring it back down
                async with c.http.post(
                        f"http://{a['url']}/admin/tier/download",
                        params={"volume": vid}) as resp:
                    body_ = await resp.json()
                    assert resp.status == 200, body_
                assert not v.is_remote
                assert os.path.exists(base + ".dat")
                assert not os.path.exists(base + ".vif")
                stc, data = await c.get(fids[2], a["publicUrl"])
                assert stc == 200 and data == b"tiered-2"
            finally:
                await s3.stop()
    run(body())


def test_remote_volume_reload_from_vif(tmp_path):
    """A store restart re-opens tiered volumes from .idx + .vif alone."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            s3 = S3Gateway(Filer("memory"), c.master.url, port=0)
            await s3.start()
            try:
                bk.load_backends({"s3": {"default": {
                    "endpoint": s3.url, "bucket": "tier2"}}})
                vdir = str(tmp_path / "offline")

                # sync volume I/O does blocking HTTP to the in-loop
                # gateway, so it must run off the event loop
                def offline_work() -> None:
                    v = Volume(vdir, "", 9)
                    v.write_needle(
                        Needle(cookie=1, id=5, data=b"persisted"))
                    volume_tier.tier_upload(v, "s3.default")
                    v.close()
                    # reopen purely from .idx/.vif
                    v2 = Volume(vdir, "", 9, create_if_missing=False)
                    assert v2.is_remote
                    assert v2.read_needle(5).data == b"persisted"
                    with pytest.raises(VolumeError):
                        v2.write_needle(Needle(cookie=1, id=6, data=b"x"))
                    v2.close()

                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, offline_work)
            finally:
                await s3.stop()
    run(body())


def test_mmap_backend_tier_roundtrip(tmp_path):
    """The memory-mapped local backend (backend.py MmapBackendStorage,
    reference weed/storage/backend/memory_map/) as a tier target:
    upload -> mmap reads -> scan -> download, proving BackendStorage
    factory plurality beyond s3."""
    bk.load_backends({"mmap": {"hot": {"dir": str(tmp_path / "ram")}}})
    vdir = str(tmp_path / "vols")
    v = Volume(vdir, "", 6)
    for i in range(1, 6):
        v.write_needle(Needle(cookie=3, id=i, data=bytes([i]) * 500))
    uploaded = volume_tier.tier_upload(v, "mmap.hot")
    assert uploaded > 0
    assert v.is_remote
    assert not os.path.exists(os.path.join(vdir, "6.dat"))
    # reads flow through the mmap
    for i in range(1, 6):
        assert v.read_needle(i).data == bytes([i]) * 500
    # sequential scan over the mapped file
    seen = {}
    v.scan(lambda n, off: seen.__setitem__(n.id, len(n.data)))
    assert seen == {i: 500 for i in range(1, 6)}
    with pytest.raises(VolumeError):
        v.write_needle(Needle(cookie=3, id=9, data=b"x"))
    # bring it back
    volume_tier.tier_download(v)
    assert not v.is_remote and not v.read_only
    assert v.read_needle(2).data == b"\x02" * 500
    v.write_needle(Needle(cookie=3, id=9, data=b"back-home"))
    assert v.read_needle(9).data == b"back-home"
    v.close()


def test_remote_volume_scan_readahead(tmp_path):
    """scan() over a tiered volume walks every record through coalesced
    ranged GETs (the export/fix CLI path)."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            s3 = S3Gateway(Filer("memory"), c.master.url, port=0)
            await s3.start()
            try:
                bk.load_backends({"s3": {"default": {
                    "endpoint": s3.url, "bucket": "tier4"}}})
                vdir = str(tmp_path / "scanme")

                def work():
                    v = Volume(vdir, "", 4)
                    for i in range(1, 21):
                        v.write_needle(Needle(
                            cookie=7, id=i, data=bytes([i]) * (100 * i)))
                    volume_tier.tier_upload(v, "s3.default")
                    v.close()
                    v2 = Volume(vdir, "", 4, create_if_missing=False)
                    assert v2.is_remote
                    gets = 0
                    inner = v2._pread

                    def counting(nbytes, offset):
                        nonlocal gets
                        gets += 1
                        return inner(nbytes, offset)
                    v2._pread = counting
                    seen = {}
                    v2.scan(lambda n, off: seen.__setitem__(n.id, n.data))
                    assert set(seen) == set(range(1, 21))
                    assert seen[20] == b"\x14" * 2000
                    # coalesced: whole ~30 KB volume in one ranged GET,
                    # not 2 per record
                    assert gets < 5, gets
                    v2.close()

                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, work)
            finally:
                await s3.stop()
    run(body())


def test_tier_read_failpoint_surfaces_error_not_hang(tmp_path):
    """Satellite: a degraded remote tier (tier.read armed with error /
    latency) must surface as a bounded read error through the normal
    OSError paths — never a wedged executor thread or a stale byte."""
    bk.load_backends({"mmap": {"hot": {"dir": str(tmp_path / "ram")}}})
    v = Volume(str(tmp_path / "vols"), "", 8)
    v.write_needle(Needle(cookie=4, id=1, data=b"cold-bytes" * 50))
    volume_tier.tier_upload(v, "mmap.hot")
    assert v.read_needle(1).data == b"cold-bytes" * 50
    try:
        failpoints.arm("tier.read", "error:*")
        t0 = time.monotonic()
        with pytest.raises(OSError):
            v.read_needle(1)
        assert time.monotonic() - t0 < 5.0, "degraded read hung"
        # latency action delays but completes, bytes still correct
        failpoints.arm("tier.read", "latency=50:2")
        assert v.read_needle(1).data == b"cold-bytes" * 50
    finally:
        failpoints.reset()
    assert v.read_needle(1).data == b"cold-bytes" * 50
    v.close()


def test_degraded_tier_read_keeps_server_responsive(tmp_path):
    """Cluster-level: with the remote tier erroring, reads of the
    tiered volume fail fast with an HTTP error while reads of a
    healthy local volume on the same server keep succeeding."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            bk.load_backends({"mmap": {"hot": {
                "dir": str(tmp_path / "ram")}}})
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"tiered-data")
            assert st == 201
            vid = a["fid"].split(",")[0]
            # a second volume that stays local
            b = await c.assign(collection="hot")
            st, _ = await c.put(b["fid"], b["url"], b"local-data")
            assert st == 201
            async with c.http.post(
                    f"http://{a['url']}/admin/tier/upload",
                    params={"volume": vid,
                            "backend": "mmap.hot"}) as resp:
                assert resp.status == 200, await resp.text()
            try:
                failpoints.arm("tier.read", "error:*")
                t0 = time.monotonic()
                stc, _ = await c.get(a["fid"], a["publicUrl"])
                assert stc >= 500, stc     # surfaced, not hung/stale
                assert time.monotonic() - t0 < 10.0
                stc, data = await c.get(b["fid"], b["publicUrl"])
                assert stc == 200 and data == b"local-data"
            finally:
                failpoints.reset()
            stc, data = await c.get(a["fid"], a["publicUrl"])
            assert stc == 200 and data == b"tiered-data"
    run(body())


def test_tier_upload_racing_reads_offline(tmp_path):
    """Satellite: tier_upload sealing a volume while reader threads
    hammer read_needle must stay byte-identical before/during/after
    the local->remote switch."""
    bk.load_backends({"mmap": {"hot": {"dir": str(tmp_path / "ram")}}})
    v = Volume(str(tmp_path / "vols"), "", 9)
    want = {i: bytes([i % 251]) * (400 + i * 13) for i in range(1, 41)}
    for i, data in want.items():
        v.write_needle(Needle(cookie=2, id=i, data=data))
    stop = False
    mismatches = []
    reads = [0]

    def reader():
        while not stop:
            for i, data in want.items():
                got = v.read_needle(i).data
                reads[0] += 1
                if got != data:
                    mismatches.append(i)
                    return

    with concurrent.futures.ThreadPoolExecutor(4) as pool:
        futs = [pool.submit(reader) for _ in range(3)]
        time.sleep(0.05)
        volume_tier.tier_upload(v, "mmap.hot")
        time.sleep(0.15)            # readers cross the switch
        stop = True
        for f in futs:
            f.result(timeout=30)
    assert not mismatches, mismatches
    assert reads[0] > len(want), "readers never overlapped the switch"
    assert v.is_remote
    for i, data in want.items():    # and after
        assert v.read_needle(i).data == data
    v.close()


def test_tier_upload_racing_batch_reads_cluster(tmp_path):
    """Satellite: concurrent single-GET and /batch requests in flight
    while /admin/tier/upload seals the volume stay byte-identical."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            bk.load_backends({"mmap": {"hot": {
                "dir": str(tmp_path / "ram")}}})
            a = await c.assign()
            vid = a["fid"].split(",")[0]
            fids = [a["fid"]]
            want = {a["fid"]: b"race-0" * 100}
            st, _ = await c.put(a["fid"], a["url"], want[a["fid"]])
            assert st == 201
            for i in range(1, 12):
                fid = f"{vid},{i + 1:02x}0badc0de"
                data = f"race-{i}".encode() * 100
                st, _ = await c.put(fid, a["url"], data)
                assert st == 201
                fids.append(fid)
                want[fid] = data
            stop = asyncio.Event()
            bad = []

            async def single_reader():
                while not stop.is_set():
                    for fid in fids:
                        stc, got = await c.get(fid, a["url"])
                        if stc != 200 or got != want[fid]:
                            bad.append(("single", fid, stc))
                            return

            async def batch_reader():
                url = f"http://{a['url']}/batch?fids=" + ",".join(fids)
                while not stop.is_set():
                    async with c.http.get(url) as resp:
                        blob = await resp.read()
                        if resp.status != 200:
                            bad.append(("batch", resp.status))
                            return
                    for meta, body_ in parse_all(blob):
                        if meta.get("status") != 200 or \
                                body_ != want[meta["fid"]]:
                            bad.append(("batch-row", meta))
                            return

            readers = [asyncio.create_task(single_reader()),
                       asyncio.create_task(batch_reader())]
            await asyncio.sleep(0.05)
            async with c.http.post(
                    f"http://{a['url']}/admin/tier/upload",
                    params={"volume": vid,
                            "backend": "mmap.hot"}) as resp:
                assert resp.status == 200, await resp.text()
            await asyncio.sleep(0.3)   # reads keep racing post-switch
            stop.set()
            await asyncio.gather(*readers)
            assert not bad, bad
            # and afterwards, straight through the remote tier
            for fid in fids:
                stc, got = await c.get(fid, a["url"])
                assert stc == 200 and got == want[fid]
    run(body())


def test_keep_local_stays_sealed_after_reopen(tmp_path):
    """tier.upload -keepLocal keeps the local .dat, but a restart must not
    resurrect the volume as writable (it would diverge from the remote)."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            s3 = S3Gateway(Filer("memory"), c.master.url, port=0)
            await s3.start()
            try:
                bk.load_backends({"s3": {"default": {
                    "endpoint": s3.url, "bucket": "tier3"}}})
                vdir = str(tmp_path / "keep")

                def work():
                    v = Volume(vdir, "", 3)
                    v.write_needle(Needle(cookie=9, id=1, data=b"kept"))
                    volume_tier.tier_upload(v, "s3.default",
                                            keep_local=True)
                    v.close()
                    assert os.path.exists(os.path.join(vdir, "3.dat"))
                    v2 = Volume(vdir, "", 3, create_if_missing=False)
                    assert v2.read_only  # sealed via .vif presence
                    assert v2.read_needle(1).data == b"kept"
                    with pytest.raises(VolumeError):
                        v2.write_needle(Needle(cookie=9, id=2, data=b"x"))
                    # download restores writability and drops the .vif
                    volume_tier.tier_download(v2)
                    assert not v2.read_only
                    v2.write_needle(Needle(cookie=9, id=2, data=b"y"))
                    v2.close()

                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, work)
            finally:
                await s3.stop()
    run(body())
