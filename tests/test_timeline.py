"""Metrics timelines (stats/timeline.py): snapshot ring, histogram-
delta quantiles, whole-host merging, saturation probes, query-param
clamping (the /debug surfaces share one parser), and the
merge_metrics_texts histogram semantics the timeline merger relies on.
"""

from __future__ import annotations

import time

import pytest

from seaweedfs_tpu.stats import metrics, saturation, timeline
from seaweedfs_tpu.util import tracing


@pytest.fixture(autouse=True)
def _fresh_ring():
    timeline.init(interval_s=1.0, ring=64)
    timeline.reset()
    yield
    timeline.reset()


# ---------------------------------------------------------------------------
# quantile math


def test_quantiles_linear_interpolation():
    # 100 requests: 90 under 10ms, 10 between 10ms and 100ms
    buckets = {"0.01": 90.0, "0.1": 100.0, "+Inf": 100.0}
    q = timeline.quantiles_from_buckets(buckets)
    assert q["p50"] == pytest.approx(0.01 * 50 / 90, abs=1e-6)
    # p95 = halfway through the (0.01, 0.1] bucket
    assert q["p95"] == pytest.approx(0.055, abs=1e-6)
    assert q["p99"] == pytest.approx(0.091, abs=1e-6)


def test_quantiles_inf_bucket_reports_floor():
    # everything slower than the largest finite bound: the quantile is
    # the largest finite edge — an honest "at least this slow" floor
    q = timeline.quantiles_from_buckets({"0.01": 0.0, "+Inf": 10.0})
    assert q["p99"] == 0.01


def test_quantiles_empty_and_malformed():
    assert timeline.quantiles_from_buckets({}) == {}
    assert timeline.quantiles_from_buckets({"+Inf": 0.0}) == {}
    assert timeline.quantiles_from_buckets({"junk": 1.0}) == {}


# ---------------------------------------------------------------------------
# snapshot ring


@pytest.mark.skipif(not metrics.HAVE_PROMETHEUS,
                    reason="prometheus_client unavailable")
def test_snap_counter_rates_and_hist_deltas():
    assert timeline.snap() is None          # baseline only
    metrics.CACHE_HITS.labels("timeline_test").inc(10)
    metrics.REQUEST_DURATION.labels("volume", "read", "ok").observe(0.02)
    metrics.REQUEST_DURATION.labels("volume", "read", "ok").observe(0.2)
    time.sleep(0.02)
    win = timeline.snap()
    assert win is not None
    key = 'SeaweedFS_cache_hits_total{cache="timeline_test"}'
    assert win["rates"][key] > 0
    base = ('SeaweedFS_request_duration_seconds'
            '{op="read",status="ok",tier="volume"}')
    assert win["hist"][base]["count"] == 2.0
    assert win["hist"][base]["buckets"]["+Inf"] == 2.0
    # the NEXT window must contain only new increments
    metrics.CACHE_HITS.labels("timeline_test").inc(1)
    time.sleep(0.01)
    win2 = timeline.snap()
    d = timeline.timeline_dict(n=10)
    assert len(d["windows"]) == 2
    assert base not in win2["hist"]         # no new observations
    # derived quantiles only on windows with histogram mass
    assert base in d["windows"][0]["quantiles"]
    assert d["windows"][0]["quantiles"][base]["count"] == 2.0
    assert "avg" in d["windows"][0]["quantiles"][base]


@pytest.mark.skipif(not metrics.HAVE_PROMETHEUS,
                    reason="prometheus_client unavailable")
def test_gauges_snapshot_last_value():
    timeline.snap()
    metrics.EVENTLOOP_LAG.set(0.25)
    win = timeline.snap()
    assert win["gauges"]["SeaweedFS_eventloop_lag_seconds"] == 0.25
    # build info + process start ride every window (restart detection)
    assert any(k.startswith("SeaweedFS_build_info") for k in win["gauges"])
    assert win["gauges"]["SeaweedFS_process_start_time_seconds"] > 0


def test_ring_bound():
    timeline.init(interval_s=1.0, ring=4)
    timeline.snap()
    for _ in range(10):
        timeline.snap()
    assert len(timeline.timeline_dict(n=100)["windows"]) == 4


# ---------------------------------------------------------------------------
# whole-host merge


def _mkwin(wall_s: float, rate: float, bucket_counts: dict) -> dict:
    total = max(bucket_counts.values(), default=0.0)
    return {"wall_ms": wall_s * 1000.0, "dt_s": 1.0,
            "rates": {"SeaweedFS_x_total": rate},
            "gauges": {"SeaweedFS_g": rate},
            "hist": {'SeaweedFS_request_duration_seconds'
                     '{op="read",status="ok",tier="volume"}':
                     {"buckets": dict(bucket_counts), "sum": 0.0,
                      "count": total}}}


def test_merge_aligns_and_sums():
    p1 = {"interval_s": 1.0, "ring": 64,
          "windows": [_mkwin(100.0, 5.0, {"0.01": 8, "+Inf": 10})]}
    p2 = {"interval_s": 1.0, "ring": 64,
          "windows": [_mkwin(100.3, 7.0, {"0.01": 2, "+Inf": 10})]}
    m = timeline.merge_payloads([p1, p2], n=10)
    assert len(m["windows"]) == 1           # same wall bucket
    w = m["windows"][0]
    assert w["rates"]["SeaweedFS_x_total"] == 12.0
    assert w["gauges"]["SeaweedFS_g"] == 12.0
    base = ('SeaweedFS_request_duration_seconds'
            '{op="read",status="ok",tier="volume"}')
    assert w["hist"][base]["buckets"]["+Inf"] == 20.0
    # host-level p50: 10/20 under 10ms -> exactly the 0.01 edge
    assert w["quantiles"][base]["p50"] == pytest.approx(0.01)
    # distinct wall buckets stay distinct windows
    p3 = {"interval_s": 1.0, "ring": 64,
          "windows": [_mkwin(105.0, 1.0, {"+Inf": 1})]}
    m2 = timeline.merge_payloads([p1, p3], n=10)
    assert len(m2["windows"]) == 2


def test_merge_folds_same_process_windows_before_summing():
    # a forced ?snap=1 lands a few hundred ms after the periodic snap:
    # the SAME worker contributes two windows to one wall bucket whose
    # dt_s are disjoint sub-intervals (regression: the merge summed
    # their per-second rates to ~2x the true rate and added the same
    # process's gauges twice)
    base = ('SeaweedFS_request_duration_seconds'
            '{op="read",status="ok",tier="volume"}')

    def w(wall_ms, dt, rate, fds, inf):
        return {"wall_ms": wall_ms, "dt_s": dt,
                "rates": {"SeaweedFS_x_total": rate},
                "gauges": {"SeaweedFS_open_fds": fds},
                "hist": {base: {"buckets": {"+Inf": inf}, "sum": 0.0,
                                "count": inf}}}
    # 100/s over 0.7s then 100/s over 0.3s = 100 events in the 1s
    # bucket: the honest rate is 100/s, the gauge is the newest sample
    p1 = {"interval_s": 1.0, "ring": 64, "windows": [
        w(100_100.0, 0.7, 100.0, 40, 70.0),
        w(100_400.0, 0.3, 100.0, 42, 30.0)]}
    m = timeline.merge_payloads([p1], n=10)
    assert len(m["windows"]) == 1
    win = m["windows"][0]
    assert win["rates"]["SeaweedFS_x_total"] == pytest.approx(100.0)
    assert win["gauges"]["SeaweedFS_open_fds"] == 42
    assert win["hist"][base]["buckets"]["+Inf"] == 100.0
    assert win["hist"][base]["count"] == 100.0
    # a second WORKER in the same bucket still sums across processes
    p2 = {"interval_s": 1.0, "ring": 64,
          "windows": [w(100_200.0, 1.0, 50.0, 10, 50.0)]}
    win2 = timeline.merge_payloads([p1, p2], n=10)["windows"][0]
    assert win2["rates"]["SeaweedFS_x_total"] == pytest.approx(150.0)
    assert win2["gauges"]["SeaweedFS_open_fds"] == 52
    assert win2["hist"][base]["buckets"]["+Inf"] == 150.0


def test_merge_non_additive_gauges_take_max():
    # every worker samples the SAME filesystem and its OWN event loop:
    # summing would report a half-full disk as empty-on-paper and two
    # 50ms loop lags as one 100ms lag (regression: merge used to sum
    # every gauge unconditionally)
    def win(free, lag, fds, start):
        return {"wall_ms": 100_000.0, "dt_s": 1.0, "rates": {},
                "gauges": {
                    'SeaweedFS_disk_free_bytes{path="/data"}': free,
                    "SeaweedFS_eventloop_lag_seconds": lag,
                    "SeaweedFS_executor_wait_seconds": lag / 2,
                    'SeaweedFS_build_info{pyver="3.10",version="0.1.0"}': 1,
                    "SeaweedFS_process_start_time_seconds": start,
                    "SeaweedFS_open_fds": fds},
                "hist": {}}
    p1 = {"interval_s": 1.0, "ring": 64,
          "windows": [win(1e9, 0.05, 40, 1.75e9)]}
    p2 = {"interval_s": 1.0, "ring": 64,
          "windows": [win(1e9, 0.02, 60, 1.75e9 + 30)]}
    w = timeline.merge_payloads([p1, p2], n=10)["windows"][0]
    g = w["gauges"]
    assert g['SeaweedFS_disk_free_bytes{path="/data"}'] == 1e9
    assert g["SeaweedFS_eventloop_lag_seconds"] == 0.05
    assert g["SeaweedFS_executor_wait_seconds"] == 0.025
    # build identity stays the constant 1; start time is the youngest
    # worker's birth (ANY respawn moves it), never a summed timestamp
    assert g['SeaweedFS_build_info{pyver="3.10",version="0.1.0"}'] == 1
    assert g["SeaweedFS_process_start_time_seconds"] == 1.75e9 + 30
    # per-process resources still sum like /metrics
    assert g["SeaweedFS_open_fds"] == 100


# ---------------------------------------------------------------------------
# query-param clamping (regression: ?n=/?slowest= were unguarded)


def test_traces_query_clamps_negative_and_huge():
    tracing.init(sample=1.0)
    tracing.reset()
    with tracing.start_root("volume", "read"):
        pass
    out = tracing.traces_query({"n": "-5", "slowest": "-1"})
    assert out["traces"] == [] and out["slowest"] == []
    out = tracing.traces_query({"n": "999999999", "slowest": "10**9"
                                if False else "999999999"})
    assert len(out["traces"]) <= tracing.MAX_QUERY_COUNT
    with pytest.raises(ValueError):
        tracing.traces_query({"n": "bogus"})
    assert tracing.clamp_count(-7) == 0
    assert tracing.clamp_count(10 ** 9) == tracing.MAX_QUERY_COUNT


def test_timeline_query_clamps():
    timeline.snap()
    timeline.snap()
    assert timeline.timeline_dict(n=-3)["windows"] == []
    assert len(timeline.timeline_dict(n=10 ** 9)["windows"]) == 1
    with pytest.raises(ValueError):
        timeline.timeline_query({"n": "x"})


# ---------------------------------------------------------------------------
# saturation probes


@pytest.mark.skipif(not metrics.HAVE_PROMETHEUS,
                    reason="prometheus_client unavailable")
def test_saturation_probes_set_gauges(tmp_path):
    saturation.note_loop_lag(0.5)
    saturation.note_loop_lag(0.1)       # max wins
    saturation.sample_loop_lag()
    timeline.snap()
    win = timeline.snap()
    assert win["gauges"]["SeaweedFS_eventloop_lag_seconds"] == 0.5
    # flushing resets the max
    saturation.sample_loop_lag()
    win = timeline.snap()
    assert win["gauges"]["SeaweedFS_eventloop_lag_seconds"] == 0.0
    saturation.sample_process()
    probe = saturation.disk_probe([str(tmp_path)])
    probe()
    win = timeline.snap()
    assert win["gauges"].get("SeaweedFS_open_fds", 0) > 0
    key = f'SeaweedFS_disk_free_bytes{{path="{tmp_path}"}}'
    assert win["gauges"][key] > 0


@pytest.mark.skipif(not metrics.HAVE_PROMETHEUS,
                    reason="prometheus_client unavailable")
def test_cache_budget_gauge():
    from seaweedfs_tpu.util.chunk_cache import LruByteCache
    LruByteCache(12345, name="budget_test")
    timeline.snap()
    win = timeline.snap()
    assert win["gauges"][
        'SeaweedFS_cache_budget_bytes{cache="budget_test"}'] == 12345


# ---------------------------------------------------------------------------
# merge_metrics_texts histogram semantics (the timeline merger's
# sibling: both must sum buckets per key and keep sum/count consistent)


@pytest.mark.skipif(not metrics.HAVE_PROMETHEUS,
                    reason="prometheus_client unavailable")
def test_merge_metrics_texts_histograms():
    t1 = (b"# HELP SeaweedFS_h_seconds h\n"
          b"# TYPE SeaweedFS_h_seconds histogram\n"
          b'SeaweedFS_h_seconds_bucket{le="0.01"} 3\n'
          b'SeaweedFS_h_seconds_bucket{le="0.1"} 5\n'
          b'SeaweedFS_h_seconds_bucket{le="+Inf"} 6\n'
          b"SeaweedFS_h_seconds_sum 0.5\n"
          b"SeaweedFS_h_seconds_count 6\n")
    t2 = (b"# HELP SeaweedFS_h_seconds h\n"
          b"# TYPE SeaweedFS_h_seconds histogram\n"
          b'SeaweedFS_h_seconds_bucket{le="0.01"} 1\n'
          b'SeaweedFS_h_seconds_bucket{le="0.1"} 1\n'
          b'SeaweedFS_h_seconds_bucket{le="+Inf"} 4\n'
          b"SeaweedFS_h_seconds_sum 2.25\n"
          b"SeaweedFS_h_seconds_count 4\n")
    from seaweedfs_tpu.stats.metrics import merge_metrics_texts
    merged = merge_metrics_texts([t1, t2]).decode()
    lines = dict(ln.rsplit(" ", 1) for ln in merged.splitlines()
                 if not ln.startswith("#"))
    # buckets summed per le, INCLUDING +Inf
    assert lines['SeaweedFS_h_seconds_bucket{le="0.01"}'] == "4"
    assert lines['SeaweedFS_h_seconds_bucket{le="0.1"}'] == "6"
    assert lines['SeaweedFS_h_seconds_bucket{le="+Inf"}'] == "10"
    # sum/count consistency: count == +Inf bucket, sum is the float sum
    assert lines["SeaweedFS_h_seconds_count"] == "10"
    assert float(lines["SeaweedFS_h_seconds_sum"]) == pytest.approx(2.75)
    # cumulative monotonicity survives the merge
    assert (float(lines['SeaweedFS_h_seconds_bucket{le="0.01"}'])
            <= float(lines['SeaweedFS_h_seconds_bucket{le="0.1"}'])
            <= float(lines['SeaweedFS_h_seconds_bucket{le="+Inf"}']))
    # parses back through the reference text parser
    from prometheus_client.parser import text_string_to_metric_families
    fams = {f.name: f for f in
            text_string_to_metric_families(merged + "\n")}
    h = fams["SeaweedFS_h_seconds"]
    by_le = {s.labels.get("le"): s.value for s in h.samples
             if s.name.endswith("_bucket")}
    assert by_le == {"0.01": 4.0, "0.1": 6.0, "+Inf": 10.0}


@pytest.mark.skipif(not metrics.HAVE_PROMETHEUS,
                    reason="prometheus_client unavailable")
def test_merge_metrics_texts_histogram_bucket_misalignment():
    # a worker exporting an extra bucket edge (version skew) must not
    # corrupt the shared edges: each le key sums independently
    t1 = (b'SeaweedFS_h2_seconds_bucket{le="0.01"} 2\n'
          b'SeaweedFS_h2_seconds_bucket{le="+Inf"} 2\n'
          b"SeaweedFS_h2_seconds_count 2\n")
    t2 = (b'SeaweedFS_h2_seconds_bucket{le="0.01"} 1\n'
          b'SeaweedFS_h2_seconds_bucket{le="0.05"} 3\n'
          b'SeaweedFS_h2_seconds_bucket{le="+Inf"} 3\n'
          b"SeaweedFS_h2_seconds_count 3\n")
    from seaweedfs_tpu.stats.metrics import merge_metrics_texts
    merged = merge_metrics_texts([t1, t2]).decode()
    lines = dict(ln.rsplit(" ", 1) for ln in merged.splitlines())
    assert lines['SeaweedFS_h2_seconds_bucket{le="0.01"}'] == "3"
    assert lines['SeaweedFS_h2_seconds_bucket{le="0.05"}'] == "3"
    assert lines['SeaweedFS_h2_seconds_bucket{le="+Inf"}'] == "5"
    assert lines["SeaweedFS_h2_seconds_count"] == "5"


def test_merge_metrics_texts_non_additive_gauges():
    # the scrape merge shares the timeline's non-additive policy: a
    # merged build_info must stay 1 and a merged start time must be an
    # actual birth instant (regression: both were summed, reporting
    # build_info=2 and a ~3.5e9 "start time" for a 2-worker host)
    t1 = (b'SeaweedFS_build_info{pyver="3.10",version="0.1.0"} 1\n'
          b"SeaweedFS_process_start_time_seconds 1750000000.25\n"
          b'SeaweedFS_disk_free_bytes{path="/data"} 1000000000\n'
          b"SeaweedFS_eventloop_lag_seconds 0.05\n"
          b"SeaweedFS_open_fds 40\n")
    t2 = (b'SeaweedFS_build_info{pyver="3.10",version="0.1.0"} 1\n'
          b"SeaweedFS_process_start_time_seconds 1750000030.5\n"
          b'SeaweedFS_disk_free_bytes{path="/data"} 1000000000\n'
          b"SeaweedFS_eventloop_lag_seconds 0.02\n"
          b"SeaweedFS_open_fds 60\n")
    from seaweedfs_tpu.stats.metrics import merge_metrics_texts
    merged = merge_metrics_texts([t1, t2]).decode()
    lines = dict(ln.rsplit(" ", 1) for ln in merged.splitlines())
    assert lines['SeaweedFS_build_info{pyver="3.10",version="0.1.0"}'] == "1"
    assert lines["SeaweedFS_process_start_time_seconds"] == "1750000030.5"
    assert lines['SeaweedFS_disk_free_bytes{path="/data"}'] == "1000000000"
    assert lines["SeaweedFS_eventloop_lag_seconds"] == "0.05"
    # per-process resources still sum
    assert lines["SeaweedFS_open_fds"] == "100"


# ---------------------------------------------------------------------------
# exemplars: each window links its worst trace per (tier, op)


def test_snap_attaches_worst_trace_exemplars():
    tracing.init(sample=1.0, slow_ms=0.0)
    tracing.reset()
    timeline.snap()                         # baseline drains + drops
    with tracing.start_root("volume", "read") as fast:
        time.sleep(0.002)
    with tracing.start_root("volume", "read") as slow:
        time.sleep(0.03)
    win = timeline.snap()
    ex = win.get("exemplars", {})
    assert "volume.read" in ex, win.keys()
    # the WORST trace of the window wins the exemplar slot
    assert ex["volume.read"]["trace"] == slow.trace != fast.trace
    assert ex["volume.read"]["dur_ms"] >= 25.0
    # drained: the next window starts fresh
    win2 = timeline.snap()
    assert "exemplars" not in win2
    tracing.reset()


def _exwin(wall_s: float, trace: str, dur: float) -> dict:
    return {"wall_ms": wall_s * 1000.0, "dt_s": 1.0, "rates": {},
            "gauges": {}, "hist": {},
            "exemplars": {"s3.get": {"trace": trace, "dur_ms": dur}}}


def test_merge_keeps_max_duration_exemplar_per_key():
    # cross-process merge: the slower host's trace wins the key
    p1 = {"interval_s": 1.0, "ring": 64,
          "windows": [_exwin(100.0, "aa" * 16, 10.0)]}
    p2 = {"interval_s": 1.0, "ring": 64,
          "windows": [_exwin(100.2, "bb" * 16, 90.0)]}
    m = timeline.merge_payloads([p1, p2], n=10)
    assert len(m["windows"]) == 1
    assert m["windows"][0]["exemplars"]["s3.get"] == {
        "trace": "bb" * 16, "dur_ms": 90.0}
    # same-process fold (forced ?snap=1 sub-windows) keeps the max too
    p3 = {"interval_s": 1.0, "ring": 64,
          "windows": [_exwin(100.0, "cc" * 16, 50.0),
                      _exwin(100.4, "dd" * 16, 20.0)]}
    m2 = timeline.merge_payloads([p3], n=10)
    assert m2["windows"][0]["exemplars"]["s3.get"]["trace"] == "cc" * 16
