"""Mutual-TLS transport (security/tls.py, reference weed/security/tls.go).

Generates a throwaway CA + role cert with the openssl CLI, then runs a
master + volume server over HTTPS with client-cert verification and
exercises assign/write/read; a certificate-less client must be refused.
"""

from __future__ import annotations

import os
import shutil
import ssl
import subprocess

import aiohttp
import pytest

from cluster_util import run

from seaweedfs_tpu.security import tls
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.storage.store import Store

pytestmark = pytest.mark.skipif(
    shutil.which("openssl") is None, reason="openssl unavailable")


def _gen_certs(d: str) -> tuple[str, str, str]:
    def sh(*cmd):
        subprocess.run(cmd, check=True, capture_output=True, cwd=d)

    sh("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
       "-keyout", "ca.key", "-out", "ca.crt", "-days", "1",
       "-subj", "/CN=swtpu-test-ca")
    sh("openssl", "req", "-newkey", "rsa:2048", "-nodes",
       "-keyout", "node.key", "-out", "node.csr", "-subj", "/CN=swtpu-node")
    sh("openssl", "x509", "-req", "-in", "node.csr", "-CA", "ca.crt",
       "-CAkey", "ca.key", "-CAcreateserial", "-out", "node.crt",
       "-days", "1")
    return (os.path.join(d, "ca.crt"), os.path.join(d, "node.crt"),
            os.path.join(d, "node.key"))


def test_mtls_cluster_write_read(tmp_path):
    ca, cert, key = _gen_certs(str(tmp_path))

    async def body():
        tls.configure(ca, cert, key)
        master = vs = None
        try:
            master = MasterServer(port=0, pulse_seconds=0.2,
                                  volume_size_limit_mb=64)
            await master.start()
            store = Store([os.path.join(str(tmp_path), "v0")],
                          max_volume_counts=[8])
            vs = VolumeServer(store, master.url, port=0, pulse_seconds=0.2)
            await vs.start()
            await vs.heartbeat_once()

            http = tls.make_session()
            try:
                async with http.post(
                        tls.url(master.url, "/dir/assign")) as resp:
                    a = await resp.json()
                assert "fid" in a, a
                body_bytes = (b"--B\r\nContent-Disposition: form-data; "
                              b"name=\"file\"; filename=\"t\"\r\n\r\n"
                              b"tls payload\r\n--B--\r\n")
                async with http.post(
                        tls.url(a["url"], f"/{a['fid']}"),
                        data=body_bytes,
                        headers={"Content-Type":
                                 "multipart/form-data; boundary=B"}) as resp:
                    assert resp.status == 201, await resp.text()
                async with http.get(
                        tls.url(a["url"], f"/{a['fid']}")) as resp:
                    assert resp.status == 200
                    assert await resp.read() == b"tls payload"
            finally:
                await http.close()

            # a client WITHOUT a certificate must be refused by the
            # handshake (CERT_REQUIRED on the server side)
            anon_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            anon_ctx.load_verify_locations(ca)
            anon_ctx.check_hostname = False
            anon = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(ssl=anon_ctx))
            try:
                with pytest.raises(aiohttp.ClientError):
                    async with anon.get(
                            tls.url(master.url, "/stats/health")) as resp:
                        await resp.read()
            finally:
                await anon.close()

            # plain http must not work either
            plain = aiohttp.ClientSession()
            try:
                with pytest.raises(aiohttp.ClientError):
                    async with plain.get(
                            f"http://{master.url}/stats/health") as resp:
                        await resp.read()
            finally:
                await plain.close()
        finally:
            if vs:
                await vs.stop()
            if master:
                await master.stop()

    try:
        run(body())
    finally:
        tls.reset()


def test_configure_from_toml(tmp_path):
    ca, cert, key = _gen_certs(str(tmp_path))
    toml = tmp_path / "security.toml"
    toml.write_text(
        f'[tls]\nca = "{ca}"\ncert = "{cert}"\nkey = "{key}"\n')
    try:
        assert tls.configure_from_toml(str(toml)) is True
        assert tls.enabled() and tls.scheme() == "https"
        assert tls.url("h:1", "/x") == "https://h:1/x"
    finally:
        tls.reset()
    assert tls.scheme() == "http"
    # empty/absent [tls] leaves plaintext
    (tmp_path / "empty.toml").write_text("[jwt.signing]\nkey = \"\"\n")
    assert tls.configure_from_toml(str(tmp_path / "empty.toml")) is False
    assert not tls.enabled()
