"""End-to-end trace propagation (the ISSUE 4 acceptance shape):

- one trace ID spans gateway -> filer(stream) -> client -> volume ->
  sibling-proxy -> volume(owner) -> store across an S3 gateway over a
  2-worker-partitioned volume fleet;
- a forced replica failover (failpoint volume.read.http truncating a
  holder mid-body) shows up as replica_rotate / range_resume events on
  the client read span;
- /debug/traces answered by one worker aggregates the sibling rings
  (merged, deduped) and /debug/requests lists in-flight spans.
"""

from __future__ import annotations

import json
import os

import pytest

from seaweedfs_tpu.util import failpoints as fp
from seaweedfs_tpu.util import tracing

from cluster_util import Cluster, run


@pytest.fixture(autouse=True)
def _clean():
    tracing.init(sample=1.0, slow_ms=0.0)
    tracing.reset()
    fp.reset()
    yield
    tracing.init(sample=1.0, slow_ms=0.0)
    tracing.reset()
    fp.reset()


async def _start_worker_fleet(c: Cluster, tmp_path, n: int = 2):
    """n in-proc volume workers partitioned vid %% n over one shared
    dir, all advertising worker 0 as their publicUrl — so every
    client read enters at worker 0 and a sibling-owned vid is
    DETERMINISTICALLY served via the worker proxy."""
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.server.workers import WorkerContext
    from seaweedfs_tpu.storage.store import Store
    state_dir = str(tmp_path / "wstate")
    d = str(tmp_path / "wdata")
    workers = []
    for i in range(n):
        ctx = WorkerContext(i, n, 0, state_dir, token="tok")
        store = Store([os.path.join(d)], max_volume_counts=[16],
                      partition=(i, n))
        vs = VolumeServer(store, c.master.url, port=0,
                          pulse_seconds=0.2, worker_ctx=ctx)
        await vs.start()
        workers.append(vs)
    for vs in workers:
        vs.store.public_url = workers[0].url
        await vs.heartbeat_once()
    return workers


def test_one_trace_spans_gateway_filer_proxy_store(tmp_path):
    async def go():
        from seaweedfs_tpu.filer.filer import Filer
        from seaweedfs_tpu.s3.gateway import S3Gateway
        async with Cluster(str(tmp_path), n_servers=0) as c:
            workers = await _start_worker_fleet(c, tmp_path)
            s3 = S3Gateway(Filer("memory"), c.master.url, port=0)
            await s3.start()
            try:
                base = f"http://{s3.url}/tbucket"
                async with c.http.put(base) as r:
                    assert r.status == 200
                # write objects until one's chunk sits on an ODD vid
                # (owned by worker 1 => read via worker 0 is proxied)
                target = data = None
                for i in range(16):
                    body = (b"trace-me-%d." % i) * 4096
                    async with c.http.put(f"{base}/obj{i}",
                                          data=body) as r:
                        assert r.status == 200, await r.text()
                    e = s3.filer.find_entry(f"/buckets/tbucket/obj{i}")
                    if int(e.chunks[0].file_id.split(",")[0]) % 2 == 1:
                        target, data = f"obj{i}", body
                        break
                assert target is not None, "no odd-vid chunk in 16 tries"

                tracing.reset()
                trace_id = "ab" * 16
                tp = f"00-{trace_id}-{'cd' * 8}-01"
                async with c.http.get(f"{base}/{target}",
                                      headers={"traceparent": tp}) as r:
                    assert r.status == 200
                    assert await r.read() == data

                d = tracing.traces_dict(recent=100)
                ours = [g for g in d["traces"]
                        if g["trace_id"] == trace_id]
                assert ours, [g["trace_id"] for g in d["traces"]]
                g = ours[0]
                tiers = set(g["tiers"])
                assert {"s3", "filer", "client", "volume", "proxy",
                        "store"} <= tiers, tiers
                by_id = {s["span"]: s for s in g["spans"]}
                # the owner-side volume span hangs off the proxy span,
                # and the store span off the owner-side volume span:
                # the cross-worker hop stayed in ONE parent chain
                proxy = [s for s in g["spans"] if s["tier"] == "proxy"][0]
                owner_vol = [s for s in g["spans"]
                             if s["parent"] == proxy["span"]]
                assert owner_vol and owner_vol[0]["tier"] == "volume"
                store = [s for s in g["spans"] if s["tier"] == "store"][0]
                assert store["parent"] == owner_vol[0]["span"]
                assert store["attrs"]["source"] in ("pread", "cache")
                # non-overlapping attribution ~= wall time
                assert abs(sum(s["self_ms"] for s in g["spans"])
                           - g["dur_ms"]) < 0.25 * g["dur_ms"] + 5.0
                # every span chains to a parent inside the trace except
                # the entry span (parent = the synthetic header span)
                roots = [s for s in g["spans"]
                         if s["parent"] not in by_id]
                assert all(s["parent"] == "cd" * 8 for s in roots)

                # -- /debug/traces on worker 0 merges the sibling ring
                async with c.http.get(
                        f"http://{workers[0].url}/debug/traces",
                        params={"n": "100"}) as r:
                    assert r.status == 200
                    merged = await r.json()
                mg = [t for t in merged["traces"]
                      if t["trace_id"] == trace_id]
                assert mg, "merged /debug/traces lost the trace"
                # deduped: the sibling's ring is this same process's
                # ring, so merging must not double any span
                assert len(mg[0]["spans"]) == len(g["spans"])

                # -- the gateway's reserved-path twin serves its ring
                async with c.http.get(
                        f"http://{s3.url}/__debug__/traces",
                        params={"n": "100"}) as r:
                    assert r.status == 200
                    gw = await r.json()
                assert any(t["trace_id"] == trace_id
                           for t in gw["traces"])

                # -- /debug/requests: shape check (nothing wedged now)
                async with c.http.get(
                        f"http://{workers[0].url}/debug/requests") as r:
                    body = await r.json()
                assert "inflight" in body and "requests" in body
            finally:
                await s3.stop()
                for vs in workers:
                    await vs.stop()
    run(go())


def test_replica_failover_appears_as_retry_span_events(tmp_path):
    """A holder truncating mid-body (volume.read.http) must surface on
    the client read span as replica_rotate + range_resume events, with
    the read still byte-exact."""
    from seaweedfs_tpu.util.client import WeedClient

    async def go():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            data = bytes(range(256)) * 2048          # 512 KiB positional
            a = await c.assign(replication="001")
            st, _ = await c.put(a["fid"], a["url"], data)
            assert st == 201
            # one mid-body truncation: whichever holder serves first
            # dies at 50%; count=1 so the rotation target serves clean
            fp.arm("volume.read.http", "truncate=0.5:1")
            tracing.reset()
            async with WeedClient(c.master.url) as wc:
                with tracing.start_root("test", "read") as root:
                    got = await wc.read(a["fid"], offset=0,
                                        size=len(data))
            assert got == data
            assert not fp.pending("volume.read.http")   # it fired
            g = [t for t in tracing.traces_dict(recent=50)["traces"]
                 if t["trace_id"] == root.trace][0]
            reads = [s for s in g["spans"]
                     if s["tier"] == "client" and s["op"] == "read"]
            assert reads, g["spans"]
            events = [e["name"] for s in reads
                      for e in s.get("events", ())]
            assert "replica_rotate" in events, events
            assert "range_resume" in events, events
            assert sum(s["bytes"] for s in reads) == len(data)
    run(go())


def test_breaker_rejection_appears_as_span_event(tmp_path):
    """An upload aimed at an upstream with an OPEN breaker records a
    breaker_open event before failing fast."""
    from seaweedfs_tpu.util.client import OperationError, WeedClient
    from seaweedfs_tpu.util.resilience import BreakerRegistry

    async def go():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()
            breakers = BreakerRegistry(threshold=1, reset_timeout=60.0)
            br = breakers.get(a["url"])
            br.record_failure()                    # force OPEN
            assert not br.allow() or True
            tracing.reset()
            async with WeedClient(c.master.url,
                                  breakers=breakers) as wc:
                with tracing.start_root("test", "write") as root:
                    with pytest.raises(OperationError):
                        await wc.upload(a["fid"], a["url"], b"x" * 64)
            g = [t for t in tracing.traces_dict(recent=50)["traces"]
                 if t["trace_id"] == root.trace][0]
            ups = [s for s in g["spans"]
                   if s["tier"] == "client" and s["op"] == "upload"]
            assert ups and ups[0]["status"] == "error"
            assert any(e["name"] == "breaker_open"
                       for e in ups[0].get("events", ())), ups[0]
    run(go())


def test_volume_fast_path_records_root_span(tmp_path):
    """The raw fasthttp GET/POST path produces volume+store spans (and
    a cache-source annotation on a hot re-read)."""
    async def go():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()
            tracing.reset()
            st, _ = await c.put(a["fid"], a["url"], b"fastpath-needle")
            assert st == 201
            st, got = await c.get(a["fid"], a["url"])
            assert st == 200 and got == b"fastpath-needle"
            st, got = await c.get(a["fid"], a["url"])   # cache-hot
            assert st == 200
            d = tracing.traces_dict(recent=100)
            ops = {(s["tier"], s["op"], s["status"])
                   for g in d["traces"] for s in g["spans"]}
            assert ("volume", "write", "ok") in ops, ops
            assert ("volume", "read", "ok") in ops, ops
            assert ("store", "write", "ok") in ops, ops
            sources = {s["attrs"].get("source")
                       for g in d["traces"] for s in g["spans"]
                       if s["tier"] in ("volume", "store")
                       and "attrs" in s}
            assert "cache" in sources or "pread" in sources, sources
    run(go())


def test_unrouted_admin_paths_mint_no_op_labels(tmp_path):
    """Probes of /admin/<junk> must not become spans (their op feeds
    prometheus label values — unbounded cardinality otherwise); the
    registered admin mesh still traces."""
    async def go():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            tracing.reset()
            for i in range(5):
                async with c.http.get(
                        f"http://{vs.url}/admin/scan{i}/x") as r:
                    assert r.status in (404, 405)
            ops = {s["op"] for g in tracing.traces_dict()["traces"]
                   for s in g["spans"]}
            assert not any(o.startswith("scan") for o in ops), ops
            async with c.http.get(
                    f"http://{vs.url}/admin/volume/status",
                    params={"volume": "999"}) as r:
                await r.read()
            ops = {s["op"] for g in tracing.traces_dict()["traces"]
                   for s in g["spans"]}
            assert "volume.status" in ops, ops
    run(go())


def test_trace_sample_zero_records_nothing(tmp_path):
    """-trace.sample 0: the entire pipeline is a no-op — no spans, no
    in-flight entries, reads unaffected."""
    async def go():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            tracing.init(sample=0.0)
            tracing.reset()
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"untraced")
            assert st == 201
            st, got = await c.get(a["fid"], a["url"])
            assert st == 200 and got == b"untraced"
            assert tracing.traces_dict()["spans"] == 0
            assert tracing.requests_dict()["inflight"] == 0
    run(go())


def test_frame_hop_stays_one_trace_with_transport_attr(tmp_path):
    """The binary sibling wire keeps the cross-worker hop in ONE
    trace: the proxy span carries transport=frame, the owner-side
    volume span (minted by the frame adapter) chains under it — and
    with worker.frame armed, the SAME read downgrades to the HTTP hop
    (transport=http + frame_fallback event), bytes identical."""
    async def go():
        async with Cluster(str(tmp_path), n_servers=0) as c:
            workers = await _start_worker_fleet(c, tmp_path)
            try:
                # a fid on an ODD vid: reads entering worker 0 must hop
                fid = body = None
                for _ in range(16):
                    a = await c.assign()
                    if int(a["fid"].split(",")[0]) % 2 == 1:
                        fid, body = a["fid"], b"frame-hop-trace" * 100
                        st, _ = await c.put(fid, workers[0].url, body)
                        assert st == 201
                        break
                assert fid is not None, "no odd-vid assign in 16 tries"

                tracing.reset()
                trace_id = "ef" * 16
                tp = f"00-{trace_id}-{'ad' * 8}-01"
                async with c.http.get(f"http://{workers[0].url}/{fid}",
                                      headers={"traceparent": tp}) as r:
                    assert r.status == 200
                    assert await r.read() == body
                g = [t for t in tracing.traces_dict(recent=100)["traces"]
                     if t["trace_id"] == trace_id][0]
                proxy = [s for s in g["spans"]
                         if s["tier"] == "proxy"][0]
                assert proxy["attrs"]["transport"] == "frame", proxy
                owner_vol = [s for s in g["spans"]
                             if s["parent"] == proxy["span"]]
                assert owner_vol and owner_vol[0]["tier"] == "volume"
                assert owner_vol[0]["attrs"]["transport"] == "frame"
                # the store span chains under the frame-served read
                store = [s for s in g["spans"] if s["tier"] == "store"]
                assert store and store[0]["parent"] == \
                    owner_vol[0]["span"]

                # sever the frame hop: same read, same bytes, but the
                # proxy span records the downgrade
                fp.arm("worker.frame", "error")
                tracing.reset()
                trace_id2 = "f0" * 16
                tp2 = f"00-{trace_id2}-{'ad' * 8}-01"
                async with c.http.get(f"http://{workers[0].url}/{fid}",
                                      headers={"traceparent": tp2}) as r:
                    assert r.status == 200
                    assert await r.read() == body
                g2 = [t for t in
                      tracing.traces_dict(recent=100)["traces"]
                      if t["trace_id"] == trace_id2][0]
                proxy2 = [s for s in g2["spans"]
                          if s["tier"] == "proxy"][0]
                assert proxy2["attrs"]["transport"] == "http", proxy2
                assert any(e["name"] == "frame_fallback"
                           for e in proxy2.get("events", [])), proxy2
            finally:
                for vs in workers:
                    await vs.stop()
    run(go())


def test_replication_fanout_frame_hop_keeps_one_trace(tmp_path):
    """PR 14 hop audit — the replica fan-out: a replicated write's
    traceparent rides the inter-host frame channel, so the replica's
    own (volume, store) spans chain under the primary's replicate
    span. With replication.frame armed the SAME write downgrades to
    the HTTP fallback and the chain still holds."""
    async def go():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            for armed in (False, True):
                if armed:
                    fp.arm("replication.frame", "error:100")
                a = await c.assign(replication="001")
                tracing.reset()
                trace_id = ("9a" if armed else "8b") * 16
                tp = f"00-{trace_id}-{'7c' * 8}-01"
                async with c.http.post(
                        f"http://{a['url']}/{a['fid']}",
                        data=b"replicate-trace" * 64,
                        headers={"traceparent": tp}) as r:
                    assert r.status == 201, await r.text()
                g = [t for t in
                     tracing.traces_dict(recent=100)["traces"]
                     if t["trace_id"] == trace_id]
                assert g, "write minted no trace"
                spans = g[0]["spans"]
                rep = [s for s in spans if s["tier"] == "replicate"]
                assert rep, {s["tier"] for s in spans}
                # the replica-side volume write chains UNDER the
                # fan-out span: the header crossed the wire
                replica_writes = [
                    s for s in spans
                    if s["tier"] == "volume" and s["op"] == "write"
                    and s["parent"] == rep[0]["span"]]
                assert replica_writes, spans
                transport = replica_writes[0].get("attrs", {}).get(
                    "transport")
                # the frame-served replica stamps its transport; the
                # HTTP fallback is the plain listener path (no stamp)
                assert transport == (None if armed else "frame"), \
                    (armed, replica_writes[0])
                fp.reset()
    run(go())


def test_ec_shard_gather_hop_keeps_one_trace(tmp_path):
    """PR 14 hop audit — the EC shard gather: reconstructing a needle
    pulls shard intervals from remote holders; every remote
    ec.shard_read span must join the reading trace (the gather's
    injected traceparent rode the fetch)."""
    import random

    from seaweedfs_tpu.shell.env import CommandEnv
    from seaweedfs_tpu.shell import ec_commands as ec

    async def go():
        async with Cluster(str(tmp_path), n_servers=4) as c:
            rng = random.Random(7)
            files = []
            for _ in range(8):
                a = await c.assign(collection="ectrace")
                data = bytes(rng.getrandbits(8)
                             for _ in range(rng.randint(2000, 9000)))
                st, _ = await c.put(a["fid"], a["url"], data)
                assert st == 201
                files.append((a["fid"], data))
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                vids = sorted({int(f.split(",")[0]) for f, _ in files})
                res = await ec.ec_encode(env, collection="ectrace",
                                         vids=vids)
                assert res

            fid, data = files[0]
            tracing.reset()
            trace_id = "6e" * 16
            tp = f"00-{trace_id}-{'5f' * 8}-01"
            st, got = None, None
            async with c.http.get(
                    f"http://{c.servers[0].url}/{fid}",
                    headers={"traceparent": tp},
                    allow_redirects=True) as r:
                st, got = r.status, await r.read()
            assert st == 200 and got == data
            g = [t for t in tracing.traces_dict(recent=100)["traces"]
                 if t["trace_id"] == trace_id]
            assert g, "EC read minted no trace"
            spans = g[0]["spans"]
            by_id = {s["span"]: s for s in spans}
            gathers = [s for s in spans if s["op"] == "ec.shard_read"]
            assert gathers, {(s["tier"], s["op"]) for s in spans}
            # every remote shard read chains to a parent INSIDE the
            # trace — no orphaned roots from a dropped header
            for s in gathers:
                assert s["parent"] in by_id, s
    run(go())
