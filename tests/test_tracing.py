"""util/tracing.py span recorder + the observability satellites:
traceparent parse/format, context parenthood, ring + in-flight
introspection payloads, cross-worker merge, the no-op fast path,
slow-request logging, the prometheus bridge, metrics push-loop error
accounting, merged-metrics integer formatting, and per-worker pprof
dump paths."""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys
import time

import pytest

from seaweedfs_tpu.util import tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_tracing():
    tracing.init(sample=1.0, slow_ms=0.0)
    tracing.reset()
    yield
    tracing.init(sample=1.0, slow_ms=0.0)
    tracing.reset()


# ---------------------------------------------------------------------------
# traceparent


def test_traceparent_roundtrip():
    with tracing.start_root("volume", "read") as sp:
        tp = sp.traceparent()
    parsed = tracing.parse_traceparent(tp)
    assert parsed is not None
    trace, parent, flags = parsed
    assert trace == sp.trace and parent == sp.span_id and flags & 1


@pytest.mark.parametrize("bad", [
    "", "00", "00-short-span-01", "zz-" + "0" * 32 + "-" + "1" * 16,
    "00-" + "g" * 32 + "-" + "1" * 16 + "-01",
    "ff-" + "0" * 32 + "-" + "1" * 16 + "-01",
])
def test_traceparent_malformed(bad):
    assert tracing.parse_traceparent(bad) is None


def test_unsampled_traceparent_is_noop():
    tp = "00-" + "a" * 32 + "-" + "b" * 16 + "-00"
    assert not tracing.start_root("volume", "read", traceparent=tp)


def test_incoming_sampled_trace_joins_even_at_sample_zero():
    tracing.init(sample=0.0)
    assert not tracing.start_root("volume", "read")   # local roll: off
    tp = "00-" + "a" * 32 + "-" + "b" * 16 + "-01"
    sp = tracing.start_root("volume", "read", traceparent=tp)
    assert sp and sp.trace == "a" * 32 and sp.parent == "b" * 16
    sp.cancel()


# ---------------------------------------------------------------------------
# parenthood + ring payloads


def test_child_spans_nest_and_self_time_sums_to_wall():
    with tracing.start_root("s3", "get") as root:
        with tracing.start("filer", "stream") as mid:
            with tracing.start("client", "read") as leaf:
                leaf.nbytes = 42
            assert leaf.trace == root.trace
            assert leaf.parent == mid.span_id
        assert mid.parent == root.span_id
    d = tracing.traces_dict()
    assert d["spans"] == 3 and len(d["traces"]) == 1
    g = d["traces"][0]
    assert g["trace_id"] == root.trace
    # non-overlapping attribution: per-span self time sums to ~the
    # trace's wall time
    assert abs(sum(s["self_ms"] for s in g["spans"]) - g["dur_ms"]) < 1.0
    assert set(g["tiers"]) == {"s3", "filer", "client"}


def test_no_active_parent_means_noop_child():
    assert not tracing.start("store", "read")


def test_events_and_attrs_recorded_and_bounded():
    with tracing.start_root("client", "read", fid="3,01ab") as sp:
        for i in range(200):
            sp.event("retry", attempt=i)
        sp.set("source", "cache")
    g = tracing.traces_dict()["traces"][0]
    s = g["spans"][0]
    assert s["attrs"]["fid"] == "3,01ab"
    assert s["attrs"]["source"] == "cache"
    assert len(s["events"]) == 64          # bounded
    assert s["events"][0]["name"] == "retry"
    assert "t_ms" in s["events"][0]


def test_cancel_discards_span():
    sp = tracing.start_root("volume", "read")
    sp.cancel()
    sp.finish()
    assert tracing.traces_dict()["spans"] == 0
    assert tracing.requests_dict()["inflight"] == 0


def test_requests_dict_shows_inflight_with_age():
    sp = tracing.start_root("volume", "read")
    try:
        time.sleep(0.01)
        r = tracing.requests_dict()
        assert r["inflight"] == 1
        assert r["requests"][0]["tier"] == "volume"
        assert r["requests"][0]["age_ms"] >= 10
    finally:
        sp.finish()
    assert tracing.requests_dict()["inflight"] == 0


def test_explicit_status_survives_exception_exit():
    with pytest.raises(ValueError):
        with tracing.start_root("volume", "read") as sp:
            sp.status = "404"
            raise ValueError("gone")
    s = tracing.traces_dict()["traces"][0]["spans"][0]
    assert s["status"] == "404"
    with pytest.raises(ValueError):
        with tracing.start_root("volume", "read"):
            raise ValueError("boom")
    statuses = {s["status"]
                for g in tracing.traces_dict()["traces"]
                for s in g["spans"]}
    assert "error" in statuses


def test_ring_is_bounded():
    tracing.init(sample=1.0, ring=32)
    for _ in range(100):
        tracing.start_root("volume", "read").finish()
    assert tracing.traces_dict(recent=1000)["spans"] == 32
    tracing.init(sample=1.0, ring=2048)


def test_traces_query_clamps_zero_and_negative_counts():
    for _ in range(3):
        tracing.start_root("volume", "read").finish()
    full = tracing.traces_dict()
    assert len(full["traces"]) == 3
    # ?n=0 must be EMPTY (a -0 slice would return the whole ring)
    z = tracing.traces_query({"n": "0", "slowest": "0"})
    assert z["traces"] == [] and z["slowest"] == []
    neg = tracing.traces_query({"n": "-5", "slowest": "-1"})
    assert neg["traces"] == [] and neg["slowest"] == []
    with pytest.raises(ValueError):
        tracing.traces_query({"n": "bogus"})


def test_merge_payloads_dedupes_and_merges():
    with tracing.start_root("volume", "read") as a:
        pass
    p1 = tracing.traces_dict()
    tracing.reset()
    # a second "worker" carries a different span of the SAME trace
    with tracing.start_root("volume", "read",
                            traceparent=a.traceparent()):
        with tracing.start("store", "read"):
            pass
    p2 = tracing.traces_dict()
    merged = tracing.merge_payloads([p1, p2, p2])   # p2 twice: dedupe
    assert merged["spans"] == 3
    assert len(merged["traces"]) == 1
    assert merged["traces"][0]["trace_id"] == a.trace


def test_executor_context_propagation_pattern():
    """The volume server carries the request context into executor
    threads via contextvars.copy_context — store spans must parent
    under the request span."""
    import contextvars

    async def body():
        with tracing.start_root("volume", "read") as root:
            ctx = contextvars.copy_context()

            def work():
                with tracing.start("store", "read") as sp:
                    sp.set("source", "pread")

            await asyncio.get_running_loop().run_in_executor(
                None, lambda: ctx.run(work))
        return root

    root = asyncio.run(body())
    g = tracing.traces_dict()["traces"][0]
    store = [s for s in g["spans"] if s["tier"] == "store"][0]
    assert store["parent"] == root.span_id


def test_slow_request_glog_line(capsys):
    tracing.init(sample=1.0, slow_ms=1.0)
    with tracing.start_root("volume", "read"):
        time.sleep(0.01)
    err = capsys.readouterr().err
    assert "slow request" in err and "trace=" in err
    # child spans of a fast parent never log
    tracing.init(sample=1.0, slow_ms=10_000.0)
    with tracing.start_root("volume", "read"):
        pass
    assert "slow request" not in capsys.readouterr().err


def test_prometheus_histogram_agrees_with_ring():
    metrics = pytest.importorskip("seaweedfs_tpu.stats.metrics")
    if not metrics.HAVE_PROMETHEUS:
        pytest.skip("prometheus_client unavailable")
    before = metrics.REQUEST_DURATION.labels(
        "testtier", "testop", "ok")._sum.get()
    with tracing.start_root("testtier", "testop"):
        pass
    after = metrics.REQUEST_DURATION.labels(
        "testtier", "testop", "ok")._sum.get()
    assert after > before


# ---------------------------------------------------------------------------
# satellites: metrics push loop, merge formatting, pprof


def test_push_loop_counts_and_logs_failures(capsys, monkeypatch):
    metrics = pytest.importorskip("seaweedfs_tpu.stats.metrics")
    if not metrics.HAVE_PROMETHEUS:
        pytest.skip("prometheus_client unavailable")

    async def body():
        # unresolvable gateway: every push fails fast-ish; two loop
        # turns prove the counter moves and the first failure logs
        task = asyncio.get_event_loop().create_task(
            metrics.push_loop("127.0.0.1:1", "testjob",
                              interval_seconds=0.05))
        for _ in range(100):
            await asyncio.sleep(0.05)
            if metrics.METRICS_PUSH_ERRORS._value.get() >= 1:
                break
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass

    before = metrics.METRICS_PUSH_ERRORS._value.get()
    asyncio.run(body())
    assert metrics.METRICS_PUSH_ERRORS._value.get() > before
    assert "metrics push to 127.0.0.1:1 failed" in capsys.readouterr().err


def test_merge_metrics_integer_roundtrip_with_histograms():
    prometheus_client = pytest.importorskip("prometheus_client")
    from prometheus_client import CollectorRegistry, Histogram
    from prometheus_client.parser import text_string_to_metric_families
    from seaweedfs_tpu.stats.metrics import merge_metrics_texts

    texts = []
    for observations in ([0.1, 0.2, 3.0], [0.4]):
        reg = CollectorRegistry()
        h = Histogram("SeaweedFS_test_merge_seconds", "merge test",
                      registry=reg)
        for v in observations:
            h.observe(v)
        texts.append(prometheus_client.generate_latest(reg))
    merged = merge_metrics_texts(texts).decode()
    # bucket/count values are integral: no trailing .0, no exponent
    for line in merged.splitlines():
        if line.startswith("SeaweedFS_test_merge_seconds_count"):
            assert line.endswith(" 4"), line
        if line.startswith("SeaweedFS_test_merge_seconds_bucket"):
            val = line.rsplit(" ", 1)[1]
            assert "." not in val and "e" not in val, line
    # and the merged exposition still parses as prometheus text
    fams = {f.name: f for f in
            text_string_to_metric_families(merged)}
    fam = fams["SeaweedFS_test_merge_seconds"]
    samples = {(s.name, s.labels.get("le")): s.value
               for s in fam.samples}
    assert samples[("SeaweedFS_test_merge_seconds_count", None)] == 4
    assert samples[("SeaweedFS_test_merge_seconds_sum", None)] == \
        pytest.approx(3.7)
    # a large counter sum renders as plain digits, never 9.0072e+15
    big = merge_metrics_texts(
        [b"c_total 9007199254740992.0\n", b"c_total 1024.0\n"]).decode()
    assert big.startswith("c_total 9007199254742016\n"), big


def test_pprof_worker_suffix(tmp_path):
    from seaweedfs_tpu.util.pprof import profile_path
    assert profile_path("/x/prof.out", -1) == "/x/prof.out"
    assert profile_path("/x/prof.out", 2) == "/x/prof.out.w2"

    # smoke: the dump file actually appears at the suffixed path when
    # a profiled process exits (atexit-driven, so a real subprocess)
    cpu = tmp_path / "cpu.prof"
    code = (
        "from seaweedfs_tpu.util.pprof import setup_profiling\n"
        f"setup_profiling({str(cpu)!r}, worker_index=1)\n"
        "sum(range(1000))\n")
    subprocess.run([sys.executable, "-c", code], check=True,
                   env=dict(os.environ, PYTHONPATH=REPO), timeout=60)
    assert (tmp_path / "cpu.prof.w1").exists()
    assert not cpu.exists()
    import pstats
    pstats.Stats(str(tmp_path / "cpu.prof.w1"))  # parseable dump
