"""Vacuum/compaction (incl. racing-write replay) + volume repair/balance."""

import asyncio
import os

import pytest

from cluster_util import Cluster, run

from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell import volume_commands as vc
from seaweedfs_tpu.storage import vacuum
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import AlreadyDeleted, Volume


def test_compact_reclaims_space(tmp_path):
    v = Volume(str(tmp_path), "", 31)
    for i in range(1, 21):
        v.write_needle(Needle(cookie=i, id=i, data=bytes([i]) * 1000))
    for i in range(1, 11):
        v.delete_needle(Needle(cookie=i, id=i))
    big = v.data_size()
    assert v.garbage_level() > 0.3
    vacuum.compact(v)
    vacuum.commit_compact(v)
    assert v.data_size() < big
    assert v.garbage_level() == 0.0
    # survivors readable, deleted still gone, revision bumped
    for i in range(11, 21):
        assert v.read_needle(i).data == bytes([i]) * 1000
    for i in range(1, 11):
        with pytest.raises(Exception):
            v.read_needle(i)
    assert v.super_block.compaction_revision == 1
    v.close()


def test_compact_with_racing_writes(tmp_path):
    """The makeupDiff path: writes and deletes that land between compact()
    and commit_compact() survive the swap (volume_vacuum_test.go pattern)."""
    v = Volume(str(tmp_path), "", 32)
    for i in range(1, 6):
        v.write_needle(Needle(cookie=i, id=i, data=b"orig-%d" % i))
    v.delete_needle(Needle(cookie=2, id=2))
    vacuum.compact(v)
    # racing traffic after the snapshot:
    v.write_needle(Needle(cookie=9, id=9, data=b"new-needle"))     # create
    v.write_needle(Needle(cookie=3, id=3, data=b"overwritten!"))   # update
    v.delete_needle(Needle(cookie=4, id=4))                        # delete
    vacuum.commit_compact(v)
    assert v.read_needle(9).data == b"new-needle"
    assert v.read_needle(3).data == b"overwritten!"
    with pytest.raises(AlreadyDeleted):
        v.read_needle(4)
    assert v.read_needle(1).data == b"orig-1"
    v.close()


def test_cluster_vacuum_command(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            a = await c.assign(replication="001")
            fids = []
            for i in range(20):
                aa = await c.assign(replication="001")
                await c.put(aa["fid"], aa["url"], b"x" * 2000)
                fids.append(aa["fid"])
            for fid in fids[:15]:
                await c.delete(fid, a["url"])
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                res = await vc.volume_vacuum(env, garbage_threshold=0.3)
            assert any(r.get("vacuumed") for r in res), res
            # surviving files still readable on both replicas
            for fid in fids[15:]:
                st, data = await c.get(fid, a["url"])
                assert st == 200 and data == b"x" * 2000
    run(body())


def test_fix_replication(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=3) as c:
            a = await c.assign(replication="001")
            await c.put(a["fid"], a["url"], b"fragile")
            await c.heartbeat_all()
            vid = int(a["fid"].split(",")[0])
            # kill one replica
            holders = [vs for vs in c.servers if vid in vs.store.volumes]
            assert len(holders) == 2
            victim = holders[0]
            victim.store.delete_volume(vid)
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                actions = await vc.volume_fix_replication(env)
            assert any(x.get("copy_to") for x in actions), actions
            await c.heartbeat_all()
            holders = [vs for vs in c.servers if vid in vs.store.volumes]
            assert len(holders) == 2
            # data intact on the new replica
            key = int(a["fid"].split(",")[1][:-8], 16)
            for vs in holders:
                assert vs.store.read_needle(vid, key).data == b"fragile"
    run(body())


def test_volume_balance(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            # load all volumes onto server 0 by only heartbeating it first
            for i in range(4):
                c.servers[0].store.add_volume(100 + i)
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                moves = await vc.volume_balance(env)
            assert len(moves) >= 1
            await c.heartbeat_all()
            counts = sorted(len(vs.store.volumes) for vs in c.servers)
            assert counts == [2, 2]
    run(body())
