"""Vacuum/compaction (incl. racing-write replay) + volume repair/balance."""

import asyncio
import os

import pytest

from cluster_util import Cluster, run

from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell import volume_commands as vc
from seaweedfs_tpu.storage import vacuum
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import AlreadyDeleted, Volume


def test_compact_reclaims_space(tmp_path):
    v = Volume(str(tmp_path), "", 31)
    for i in range(1, 21):
        v.write_needle(Needle(cookie=i, id=i, data=bytes([i]) * 1000))
    for i in range(1, 11):
        v.delete_needle(Needle(cookie=i, id=i))
    big = v.data_size()
    assert v.garbage_level() > 0.3
    vacuum.compact(v)
    vacuum.commit_compact(v)
    assert v.data_size() < big
    assert v.garbage_level() == 0.0
    # survivors readable, deleted still gone, revision bumped
    for i in range(11, 21):
        assert v.read_needle(i).data == bytes([i]) * 1000
    for i in range(1, 11):
        with pytest.raises(Exception):
            v.read_needle(i)
    assert v.super_block.compaction_revision == 1
    v.close()


def test_compact_with_racing_writes(tmp_path):
    """The makeupDiff path: writes and deletes that land between compact()
    and commit_compact() survive the swap (volume_vacuum_test.go pattern)."""
    v = Volume(str(tmp_path), "", 32)
    for i in range(1, 6):
        v.write_needle(Needle(cookie=i, id=i, data=b"orig-%d" % i))
    v.delete_needle(Needle(cookie=2, id=2))
    vacuum.compact(v)
    # racing traffic after the snapshot:
    v.write_needle(Needle(cookie=9, id=9, data=b"new-needle"))     # create
    v.write_needle(Needle(cookie=3, id=3, data=b"overwritten!"))   # update
    v.delete_needle(Needle(cookie=4, id=4))                        # delete
    vacuum.commit_compact(v)
    assert v.read_needle(9).data == b"new-needle"
    assert v.read_needle(3).data == b"overwritten!"
    with pytest.raises(AlreadyDeleted):
        v.read_needle(4)
    assert v.read_needle(1).data == b"orig-1"
    v.close()


def test_cluster_vacuum_command(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            a = await c.assign(replication="001")
            fids = []
            for i in range(20):
                aa = await c.assign(replication="001")
                await c.put(aa["fid"], aa["url"], b"x" * 2000)
                fids.append(aa["fid"])
            for fid in fids[:15]:
                await c.delete(fid, a["url"])
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                res = await vc.volume_vacuum(env, garbage_threshold=0.3)
            assert any(r.get("vacuumed") for r in res), res
            # surviving files still readable on both replicas
            for fid in fids[15:]:
                st, data = await c.get(fid, a["url"])
                assert st == 200 and data == b"x" * 2000
    run(body())


def test_fix_replication(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=3) as c:
            a = await c.assign(replication="001")
            await c.put(a["fid"], a["url"], b"fragile")
            await c.heartbeat_all()
            vid = int(a["fid"].split(",")[0])
            # kill one replica
            holders = [vs for vs in c.servers if vid in vs.store.volumes]
            assert len(holders) == 2
            victim = holders[0]
            victim.store.delete_volume(vid)
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                actions = await vc.volume_fix_replication(env)
            assert any(x.get("copy_to") for x in actions), actions
            await c.heartbeat_all()
            holders = [vs for vs in c.servers if vid in vs.store.volumes]
            assert len(holders) == 2
            # data intact on the new replica
            key = int(a["fid"].split(",")[1][:-8], 16)
            for vs in holders:
                assert vs.store.read_needle(vid, key).data == b"fragile"
    run(body())


def test_volume_balance(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            # load all volumes onto server 0 by only heartbeating it first
            for i in range(4):
                c.servers[0].store.add_volume(100 + i)
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                moves = await vc.volume_balance(env)
            assert len(moves) >= 1
            await c.heartbeat_all()
            counts = sorted(len(vs.store.volumes) for vs in c.servers)
            assert counts == [2, 2]
    run(body())


def _fake_node(url, dc, max_volumes, volumes):
    return {"url": url, "dataCenter": dc, "rack": "r1",
            "maxVolumes": max_volumes, "freeSlots": max_volumes - len(volumes),
            "volumes": volumes, "ecShards": []}


def _vol(vid, collection="", size=100, read_only=False):
    return {"id": vid, "collection": collection, "size": size,
            "read_only": read_only}


def test_plan_balance_reference_algorithm():
    """Planner parity with command_volume_balance.go:29-100 on a skewed
    fake topology: per-type grouping, per-collection passes, writable
    (size-ordered) vs read-only (id-ordered) phases, ceil-ideal target,
    no replica co-location."""
    limit = 1000
    # type 8: one hot node with everything, one empty
    nodes = [
        _fake_node("a:1", "dc1", 8, [
            _vol(1, "photos", size=10), _vol(2, "photos", size=900),
            _vol(3, "photos", size=50),
            _vol(4, "docs"), _vol(5, "docs"),
            _vol(6, "photos", read_only=True),
            _vol(7, "photos", size=2000),      # oversized => read-only pass
        ]),
        _fake_node("b:2", "dc1", 8, []),
        # a different TYPE (capacity 4): must be balanced separately and
        # never mixed with the capacity-8 group
        _fake_node("c:3", "dc1", 4, [_vol(20, "photos"), _vol(21, "photos")]),
        # same type but other DC (for the dataCenter filter case)
        _fake_node("d:4", "dc2", 8, [_vol(30, "photos")]),
    ]
    moves = vc.plan_balance([dict(n, volumes=list(n["volumes"]))
                             for n in nodes], limit)
    # capacity-4 group has one node in dc1 +... c:3 alone in its type
    # within the full node set? d:4 is capacity 8 => group {a,b,d}
    by_from = {}
    for m in moves:
        by_from.setdefault(m["from"], []).append(m)
    # photos writable: a holds vids 1,2,3 (sizes 10,900,50), d holds
    # vid 30 => 4 over 3 nodes -> ideal ceil(4/3)=2; one move brings a
    # down to 2 == ideal, and the SMALLEST (vid 1, size 10) moves first
    photo_moves = [m for m in moves if m["collection"] == "photos"
                   and m["volume"] in (1, 2, 3)]
    assert [m["volume"] for m in photo_moves] == [1]
    assert photo_moves[0]["to"] == "b:2"
    # docs: 2 volumes over 3 nodes -> ideal 1 -> one move
    assert len([m for m in moves if m["collection"] == "docs"]) == 1
    # read-only pass: vid 6 (flag) + vid 7 (oversized), ideal 1 -> one
    # moves, id-ordered so vid 6 first
    ro = [m for m in moves if m["volume"] in (6, 7)]
    assert len(ro) == 1 and ro[0]["volume"] == 6
    # the capacity-4 type has a single node => untouched
    assert not [m for m in moves if m["from"] == "c:3"]

    # -dataCenter filter: only dc2 nodes considered; single node => no-op
    moves_dc2 = vc.plan_balance([dict(n) for n in nodes], limit,
                                data_center="dc2")
    assert moves_dc2 == []


def test_plan_balance_never_colocates_replicas():
    limit = 1000
    # both nodes hold a copy of volume 1 (replica 001); a:1 also holds
    # two movable singles
    nodes = [
        _fake_node("a:1", "dc1", 8,
                   [_vol(1), _vol(2), _vol(3)]),
        _fake_node("b:2", "dc1", 8, [_vol(1)]),
    ]
    moves = vc.plan_balance(nodes, limit)
    assert all(m["volume"] != 1 for m in moves)
    # counts end within one of each other
    assert len(moves) == 1 and moves[0]["volume"] in (2, 3)
