"""WebDAV gateway protocol tests.

Reference behaviors: weed/server/webdav_server.go (FS ops over filer,
chunked file bodies) exercised through the DAV HTTP surface.
"""

import xml.etree.ElementTree as ET

from cluster_util import Cluster, run

from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.server.webdav_server import WebDavServer

DAV = "{DAV:}"


def _hrefs(xml_body: str) -> list[str]:
    root = ET.fromstring(xml_body)
    return [r.findtext(f"{DAV}href") for r in root.findall(f"{DAV}response")]


def test_webdav_crud_and_propfind(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            wd = WebDavServer(Filer("memory"), c.master.url, port=0,
                              chunk_size=64)  # force multi-chunk bodies
            await wd.start()
            base = f"http://{wd.url}"
            try:
                # OPTIONS advertises DAV compliance
                async with c.http.options(base + "/") as r:
                    assert r.status == 200
                    assert "PROPFIND" in r.headers["Allow"]
                    assert r.headers["DAV"].startswith("1")

                # MKCOL + nested MKCOL + missing-parent 409
                async with c.http.request("MKCOL", base + "/docs") as r:
                    assert r.status == 201
                async with c.http.request("MKCOL", base + "/docs/a") as r:
                    assert r.status == 201
                async with c.http.request("MKCOL", base + "/no/parent") as r:
                    assert r.status == 409

                # PUT a body larger than chunk_size -> multiple chunks
                payload = bytes(range(256)) * 3  # 768B over 64B chunks
                async with c.http.put(base + "/docs/a/file.bin",
                                      data=payload) as r:
                    assert r.status == 201
                entry = wd.filer.find_entry("/docs/a/file.bin")
                assert entry is not None and len(entry.chunks) > 1

                # GET full + ranged
                async with c.http.get(base + "/docs/a/file.bin") as r:
                    assert r.status == 200
                    assert await r.read() == payload
                async with c.http.get(
                        base + "/docs/a/file.bin",
                        headers={"Range": "bytes=100-199"}) as r:
                    assert r.status == 206
                    assert await r.read() == payload[100:200]

                # PROPFIND depth 1 on /docs lists the child dir
                async with c.http.request(
                        "PROPFIND", base + "/docs",
                        headers={"Depth": "1"}) as r:
                    assert r.status == 207
                    hrefs = _hrefs(await r.text())
                assert "/docs/" in hrefs and "/docs/a/" in hrefs
                # depth 0: only self
                async with c.http.request(
                        "PROPFIND", base + "/docs",
                        headers={"Depth": "0"}) as r:
                    assert len(_hrefs(await r.text())) == 1

                # getcontentlength is reported
                async with c.http.request(
                        "PROPFIND", base + "/docs/a/file.bin") as r:
                    body_txt = await r.text()
                assert f"{len(payload)}" in body_txt

                # MOVE (rename)
                async with c.http.request(
                        "MOVE", base + "/docs/a/file.bin",
                        headers={"Destination":
                                 base + "/docs/renamed.bin"}) as r:
                    assert r.status == 201
                async with c.http.get(base + "/docs/renamed.bin") as r:
                    assert await r.read() == payload
                async with c.http.get(base + "/docs/a/file.bin") as r:
                    assert r.status == 404

                # COPY makes an independent replica
                async with c.http.request(
                        "COPY", base + "/docs/renamed.bin",
                        headers={"Destination": base + "/docs/copy.bin"}
                        ) as r:
                    assert r.status == 201
                async with c.http.delete(base + "/docs/renamed.bin") as r:
                    assert r.status == 204
                async with c.http.get(base + "/docs/copy.bin") as r:
                    assert r.status == 200
                    assert await r.read() == payload

                # LOCK/UNLOCK round-trip
                async with c.http.request(
                        "LOCK", base + "/docs/copy.bin") as r:
                    assert r.status == 200
                    token = r.headers["Lock-Token"]
                    assert "opaquelocktoken" in token
                async with c.http.request(
                        "UNLOCK", base + "/docs/copy.bin",
                        headers={"Lock-Token": token}) as r:
                    assert r.status == 204

                # DELETE a directory tree
                async with c.http.delete(base + "/docs") as r:
                    assert r.status == 204
                async with c.http.request(
                        "PROPFIND", base + "/docs") as r:
                    assert r.status == 404

                # overwrite PUT returns 204 and supersedes content
                async with c.http.put(base + "/x.txt", data=b"v1") as r:
                    assert r.status == 201
                async with c.http.put(base + "/x.txt", data=b"v2!") as r:
                    assert r.status == 204
                async with c.http.get(base + "/x.txt") as r:
                    assert await r.read() == b"v2!"
            finally:
                await wd.stop()
    run(body())


def test_webdav_overwrite_false_precondition(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            wd = WebDavServer(Filer("memory"), c.master.url, port=0)
            await wd.start()
            base = f"http://{wd.url}"
            try:
                await c.http.put(base + "/a.txt", data=b"a")
                await c.http.put(base + "/b.txt", data=b"b")
                async with c.http.request(
                        "MOVE", base + "/a.txt",
                        headers={"Destination": base + "/b.txt",
                                 "Overwrite": "F"}) as r:
                    assert r.status == 412
                async with c.http.request(
                        "MOVE", base + "/a.txt",
                        headers={"Destination": base + "/b.txt"}) as r:
                    assert r.status == 204  # overwrote existing
                async with c.http.get(base + "/b.txt") as r:
                    assert await r.read() == b"a"
            finally:
                await wd.stop()
    run(body())
