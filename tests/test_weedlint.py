"""Tier-1 gates for the weedlint framework: every rule fires on its
positive fixture and stays quiet on its negative one, suppressions and
the baseline round-trip, the checked-in baseline carries no stale or
unjustified entries, and the enforced tree (seaweedfs_tpu + tools) is
clean — the acceptance bar for every future PR."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.weedlint import (ALL_RULE_CLASSES, ALL_RULE_IDS,  # noqa: E402
                            Baseline, lint, make_rules, run_file,
                            run_paths)
from tools.weedlint.baseline import DEFAULT_PATH  # noqa: E402
from tools.weedlint.cli import main as weedlint_main  # noqa: E402


def probs(tmp_path, src, name="snippet.py", select=None,
          check_unused=True):
    f = tmp_path / name
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(src))
    rules = make_rules(select=select)
    return [x for x in run_file(str(f), rules,
                                check_unused=check_unused)
            if not x.suppressed]


def rule_ids(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------
# per-rule positive / negative fixtures
# ---------------------------------------------------------------------

def test_blocking_io_fires_in_async_def(tmp_path):
    found = probs(tmp_path, """
        import os, time
        async def h(req):
            time.sleep(0.1)
            data = os.pread(3, 10, 0)
            f = open("/tmp/x")
            return data
    """, select=["blocking-io"])
    assert rule_ids(found) == ["blocking-io"] * 3


def test_blocking_io_quiet_in_sync_and_executor_thunks(tmp_path):
    found = probs(tmp_path, """
        import asyncio, os, time
        from seaweedfs_tpu.util import tracing

        def sync_helper():
            time.sleep(0.1)              # sync code: fine
            return open("/tmp/x")

        async def h(req):
            await asyncio.sleep(0.1)     # async sleep: fine
            # a thunk handed to the executor runs OFF the loop
            return await tracing.run_in_executor(
                lambda: os.pread(3, 10, 0))
    """, select=["blocking-io"])
    assert found == []


def test_blocking_io_covers_vectored_and_zero_copy_syscalls(tmp_path):
    """The unified-wire data plane's syscalls (group-commit pwritev,
    raw sendfile, vectored sendmsg) stall the loop exactly like their
    scalar siblings — flagged in async defs; the sanctioned zero-copy
    helper (`await loop.sendfile(...)`) never trips the rule."""
    found = probs(tmp_path, """
        import os
        async def h(req):
            os.pwritev(3, [b"a", b"b"], 0)
            os.sendfile(4, 3, 0, 100)
            os.sendmsg(4, [b"hdr"])
    """, select=["blocking-io"])
    assert rule_ids(found) == ["blocking-io"] * 3
    found = probs(tmp_path, """
        import asyncio
        async def h(transport, f):
            # sanctioned zero-copy: awaited loop.sendfile, not os.*
            await asyncio.get_running_loop().sendfile(
                transport, f, 0, 100)
    """, select=["blocking-io"])
    assert found == []


def test_failpoint_site_covers_pwritev_and_sendfile(tmp_path):
    found = probs(tmp_path, """
        import os
        def append_batch(self, blobs, offset):
            os.pwritev(self._fd, blobs, offset)
    """, name="seaweedfs_tpu/storage/store.py",
        select=["failpoint-site"])
    assert rule_ids(found) == ["failpoint-site"]
    found = probs(tmp_path, """
        import os
        from seaweedfs_tpu.util import failpoints
        def append_batch(self, blobs, offset):
            failpoints.sync_fail("store.write")
            os.pwritev(self._fd, blobs, offset)
    """, name="seaweedfs_tpu/storage/store.py",
        select=["failpoint-site"])
    assert found == []


def test_orphan_task_fires_on_dropped_handle(tmp_path):
    found = probs(tmp_path, """
        import asyncio
        async def go():
            asyncio.create_task(work())          # dropped
            _ = asyncio.ensure_future(work())    # throwaway name
    """, select=["orphan-task"])
    assert rule_ids(found) == ["orphan-task"] * 2


def test_orphan_task_quiet_when_retained_or_awaited(tmp_path):
    found = probs(tmp_path, """
        import asyncio
        async def go(self):
            t = asyncio.create_task(work())
            self._tasks.append(asyncio.create_task(work()))
            await asyncio.create_task(work())
            return t
    """, select=["orphan-task"])
    assert found == []


def test_orphan_task_long_lived_paced_background_loop(tmp_path):
    """The scrubber pattern (ec/scrub.py): a long-lived paced
    background task (`while True: work; await sleep(interval)`) whose
    handle is dropped is exactly the GC-cancellation class the rule
    exists for — the loop silently dies mid-flight and nothing scrubs
    again. Retaining the handle for cancel-on-stop is quiet."""
    found = probs(tmp_path, """
        import asyncio
        class Server:
            async def start(self):
                # paced background loop, handle dropped: flagged
                asyncio.create_task(self.scrubber.run())
    """, select=["orphan-task"])
    assert rule_ids(found) == ["orphan-task"]
    found = probs(tmp_path, """
        import asyncio
        class Server:
            async def start(self):
                # volume_server.py's shape: retained + cancelled in stop
                self._tasks.append(
                    asyncio.create_task(self.scrubber.run()))
            async def stop(self):
                for t in self._tasks:
                    t.cancel()
    """, select=["orphan-task"])
    assert found == []


def test_await_in_lock_fires_under_sync_lock(tmp_path):
    found = probs(tmp_path, """
        async def h(self):
            with self._lock:
                await self.client.upload(b"x")
    """, select=["await-in-lock"])
    assert rule_ids(found) == ["await-in-lock"]


def test_await_in_lock_quiet_cases(tmp_path):
    found = probs(tmp_path, """
        async def ok1(self):
            async with self._alock:
                await self.client.upload(b"x")   # async lock: fine
        async def ok2(self):
            with self._lock:
                self.counter += 1                # no await under lock
        async def ok3(self):
            with self._lock:
                async def later():
                    await work()                 # runs on its own time
                self.cb = later
    """, select=["await-in-lock"])
    assert found == []


def test_lock_acquire_fires_on_unprotected_manual_acquire(tmp_path):
    found = probs(tmp_path, """
        import asyncio
        async def h(lock):
            await lock.acquire()
            do_work()
            lock.release()
    """, select=["lock-acquire"])
    assert rule_ids(found) == ["lock-acquire"]


def test_lock_acquire_quiet_with_finally_and_async_with(tmp_path):
    found = probs(tmp_path, """
        import asyncio
        async def ok1(lock):
            await lock.acquire()
            try:
                do_work()
            finally:
                lock.release()
        async def ok2(lock):
            try:
                await lock.acquire()
                do_work()
            finally:
                lock.release()
        async def ok3(lock):
            async with lock:
                do_work()
    """, select=["lock-acquire"])
    assert found == []


def test_lock_acquire_fires_on_sync_with_over_asyncio_lock(tmp_path):
    found = probs(tmp_path, """
        import asyncio
        class S:
            def __init__(self):
                self._mu = asyncio.Lock()
            def bad(self):
                with self._mu:
                    return 1
    """, select=["lock-acquire"])
    assert rule_ids(found) == ["lock-acquire"]


def test_resource_with_fires_on_leaky_shapes(tmp_path):
    found = probs(tmp_path, """
        import aiohttp, socket
        async def leak1():
            sess = aiohttp.ClientSession()
            await sess.get("http://x")
            await sess.close()               # not exception-safe
        def leak2(p):
            return open(p).read()            # unbound chain
        def leak3():
            socket.socket()                  # discarded outright
    """, select=["resource-with"])
    assert rule_ids(found) == ["resource-with"] * 3


def test_resource_with_quiet_on_owned_shapes(tmp_path):
    found = probs(tmp_path, """
        import aiohttp, socket
        async def ok1():
            async with aiohttp.ClientSession() as sess:
                await sess.get("http://x")
        def ok2(p):
            with open(p) as f:
                return f.read()
        def ok3():
            s = socket.socket()
            try:
                s.connect(("h", 1))
            finally:
                s.close()
        def ok4(self):
            self.sock = socket.socket()      # owner closes later
        def ok5():
            s = socket.socket()
            return s                         # ownership transferred
        def ok6():
            s = socket.socket()
            register(s)                      # handed to another owner
    """, select=["resource-with"])
    assert found == []


def test_cache_invalidate_fires_on_blind_mutator(tmp_path):
    found = probs(tmp_path, """
        class Store:
            def write_needle(self, vid, n):
                return self._volume(vid).write(n)
            def read_needle(self, vid, nid):
                return self._volume(vid).read(nid)   # reads unchecked
    """, select=["cache-invalidate"])
    assert rule_ids(found) == ["cache-invalidate"]


def test_cache_invalidate_quiet_with_invalidation_or_delegation(
        tmp_path):
    found = probs(tmp_path, """
        class Store:
            def write_needle(self, vid, n):
                off = self._volume(vid).write(n)
                self.needle_cache.invalidate(vid, n.id)
                return off
        class WeedClient:
            async def upload(self, fid, data):
                self.chunk_cache.delete(fid)
                return await self._post(fid, data)
            async def upload_data(self, data):
                return await self.upload(self._fid(), data)  # delegates
    """, select=["cache-invalidate"])
    assert found == []


def test_failpoint_site_fires_in_data_plane_scope(tmp_path):
    found = probs(tmp_path, """
        async def replicate(self, url, body):
            async with self._http.post(url, data=body) as r:
                return r.status
    """, name="seaweedfs_tpu/server/newmod.py",
        select=["failpoint-site"])
    assert rule_ids(found) == ["failpoint-site"]


def test_failpoint_site_quiet_with_site_or_outside_scope(tmp_path):
    found = probs(tmp_path, """
        from seaweedfs_tpu.util import failpoints
        async def replicate(self, url, body):
            await failpoints.fail("volume.replicate")
            async with self._http.post(url, data=body) as r:
                return r.status
    """, name="seaweedfs_tpu/server/covered.py",
        select=["failpoint-site"])
    assert found == []
    found = probs(tmp_path, """
        async def fetch(self, url):
            async with self._http.get(url) as r:    # shell/: no scope
                return await r.read()
    """, name="seaweedfs_tpu/shell/helper.py",
        select=["failpoint-site"])
    assert found == []


def test_failpoint_site_covers_ec_recovery_plane(tmp_path):
    """The EC degraded-read/scrub I/O (ec_volume.py, scrub.py) is in
    failpoint scope: a raw shard pread without a site in reach is a
    recovery path the chaos soak can never break."""
    found = probs(tmp_path, """
        import os
        def _read_shard_interval(self, sid, offset, size):
            return os.pread(self.shards[sid].fileno(), size, offset)
    """, name="seaweedfs_tpu/ec/ec_volume.py",
        select=["failpoint-site"])
    assert rule_ids(found) == ["failpoint-site"]
    found = probs(tmp_path, """
        import os
        from seaweedfs_tpu.util import failpoints
        def _read_shard_interval(self, sid, offset, size):
            failpoints.sync_fail("ec.shard_read")
            return os.pread(self.shards[sid].fileno(), size, offset)
    """, name="seaweedfs_tpu/ec/scrub.py",
        select=["failpoint-site"])
    assert found == []


def test_failpoint_site_covers_master_control_plane(tmp_path):
    """The HA control plane (master/) is in failpoint scope: a raft
    RPC or follower->leader hop without a site in reach is a quorum
    path tools/chaos.py ha can never partition."""
    found = probs(tmp_path, """
        async def send(self, peer, batch):
            async with self._http.post(peer, json=batch) as r:
                return await r.json()
    """, name="seaweedfs_tpu/master/election.py",
        select=["failpoint-site"])
    assert rule_ids(found) == ["failpoint-site"]
    found = probs(tmp_path, """
        from seaweedfs_tpu.util import failpoints
        async def send(self, peer, batch):
            await failpoints.fail("master.append")
            async with self._http.post(peer, json=batch) as r:
                return await r.json()
    """, name="seaweedfs_tpu/master/server.py",
        select=["failpoint-site"])
    assert found == []


def test_failpoint_site_covers_frame_fabric(tmp_path):
    """The frame fabric (util/frame.py, util/connpool.py) is in
    failpoint scope, and a frame-channel receiver (`chan`/`channel`)
    counts as an outbound session: a multiplexed request send without
    a chaos site in reach is a hop the soak can never sever."""
    found = probs(tmp_path, """
        async def fanout(self, target, fid, body):
            chan = self.frame_hub.get(target=target)
            return await chan.request("POST", "/" + fid, body=body,
                                      timeout=30.0)
    """, name="seaweedfs_tpu/util/frame.py",
        select=["failpoint-site"])
    assert rule_ids(found) == ["failpoint-site"]
    found = probs(tmp_path, """
        from seaweedfs_tpu.util import failpoints
        async def fanout(self, target, fid, body):
            await failpoints.fail("replication.frame")
            chan = self.frame_hub.get(target=target)
            return await chan.request("POST", "/" + fid, body=body,
                                      timeout=30.0)
    """, name="seaweedfs_tpu/util/connpool.py",
        select=["failpoint-site"])
    assert found == []


def test_timeout_discipline_covers_frame_channels(tmp_path):
    """A frame-channel request with no timeout in reach is a wedged
    caller waiting on a wedged peer — `chan`/`channel` receivers are
    held to the same discipline as aiohttp sessions (phase-2 rule, so
    the fixture runs through run_paths)."""
    mod = tmp_path / "seaweedfs_tpu" / "util" / "newhop.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(textwrap.dedent("""
        async def read_one(self, fid):
            chan = self.frame_hub.get(target="x")
            return await chan.request("GET", "/" + fid)
    """))
    found = [f for f in run_paths(
        [str(mod)], make_rules(select=["timeout-discipline"]))
        if not f.suppressed]
    assert rule_ids(found) == ["timeout-discipline"]
    mod.write_text(textwrap.dedent("""
        async def read_one(self, fid):
            chan = self.frame_hub.get(target="x")
            return await chan.request("GET", "/" + fid, timeout=30.0)
    """))
    found = [f for f in run_paths(
        [str(mod)], make_rules(select=["timeout-discipline"]))
        if not f.suppressed]
    assert found == []


def test_executor_ctx_fires_on_raw_run_in_executor(tmp_path):
    found = probs(tmp_path, """
        import asyncio
        async def h(store, vid, nid):
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(
                None, lambda: store.read_needle(vid, nid))
    """, select=["executor-ctx"])
    assert rule_ids(found) == ["executor-ctx"]


def test_executor_ctx_not_fooled_by_an_argument_named_ctx(tmp_path):
    """Regression: a thunk argument that merely happens to be called
    `ctx` is not context propagation."""
    found = probs(tmp_path, """
        import asyncio
        async def h(handler, ctx):
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None, handler, ctx)
    """, select=["executor-ctx"])
    assert rule_ids(found) == ["executor-ctx"]


def test_executor_ctx_quiet_via_helper_or_explicit_copy(tmp_path):
    found = probs(tmp_path, """
        import asyncio, contextvars
        from seaweedfs_tpu.util import tracing
        async def ok1(store, vid, nid):
            return await tracing.run_in_executor(
                store.read_needle, vid, nid)
        async def ok2(fn):
            ctx = contextvars.copy_context()
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None,
                                              lambda: ctx.run(fn))
    """, select=["executor-ctx"])
    assert found == []


def test_silent_except_and_metrics_rules_still_fire(tmp_path):
    """The three legacy passes survived the port (deep coverage lives
    in test_robustness_lint.py against the shim)."""
    found = probs(tmp_path, """
        from prometheus_client import Counter
        C = Counter("wrong_ns_total", "x")
        def f(sp):
            try:
                g()
            except Exception:
                pass
            sp.finish("ok")
    """, select=["silent-except", "metric-name", "metric-help",
                 "span-finish"])
    assert rule_ids(found) == ["metric-name", "silent-except",
                               "span-finish"]


def test_syntax_error_is_a_finding(tmp_path):
    found = probs(tmp_path, "def broken(:\n")
    assert rule_ids(found) == ["syntax-error"]


# ---------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------

def test_suppression_silences_one_rule_on_one_line(tmp_path):
    f = tmp_path / "sup.py"
    f.write_text(textwrap.dedent("""
        import time
        async def h():
            time.sleep(0.1)  # weedlint: ignore[blocking-io] bench driver, loop is otherwise idle
            time.sleep(0.2)
    """))
    findings = run_file(str(f), make_rules())
    sup = [x for x in findings if x.suppressed]
    live = [x for x in findings if not x.suppressed]
    assert len(sup) == 1 and sup[0].rule == "blocking-io"
    assert sup[0].suppress_reason.startswith("bench driver")
    assert rule_ids(live) == ["blocking-io"]     # the line below


def test_suppression_on_own_line_covers_next_line(tmp_path):
    f = tmp_path / "sup2.py"
    f.write_text(textwrap.dedent("""
        def f():
            try:
                g()
            # weedlint: ignore[silent-except] probe loop, retry counter is the signal
            except Exception:
                pass
    """))
    findings = run_file(str(f), make_rules())
    assert [x.rule for x in findings if not x.suppressed] == []


def test_suppression_without_reason_is_a_finding(tmp_path):
    f = tmp_path / "noreason.py"
    f.write_text("import time\n"
                 "async def h():\n"
                 "    time.sleep(1)  # weedlint: ignore[blocking-io]\n")
    findings = run_file(str(f), make_rules())
    assert "suppress-format" in rule_ids(findings)
    # and the suppression does NOT take effect
    assert any(x.rule == "blocking-io" and not x.suppressed
               for x in findings)


def test_unused_suppression_is_a_finding(tmp_path):
    f = tmp_path / "unused.py"
    f.write_text("x = 1  # weedlint: ignore[blocking-io] leftover\n")
    findings = run_file(str(f), make_rules())
    assert rule_ids(findings) == ["unused-suppression"]
    # ...but not when a rule subset runs (--select), where the rule a
    # suppression targets may simply not be loaded
    findings = run_file(str(f), make_rules(select=["silent-except"]),
                        check_unused=False)
    assert findings == []


def test_suppression_grammar_in_docstring_is_ignored(tmp_path):
    f = tmp_path / "doc.py"
    f.write_text('"""docs: use `# weedlint: ignore[rule-id] reason`."""\n'
                 "x = 1\n")
    assert run_file(str(f), make_rules()) == []


# ---------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------

BAD_SRC = textwrap.dedent("""
    import time
    async def h():
        time.sleep(0.5)
""")


def test_baseline_round_trip_and_staleness(tmp_path):
    mod = tmp_path / "legacy.py"
    mod.write_text(BAD_SRC)
    bl_path = tmp_path / "baseline.json"

    findings = run_paths([str(mod)], make_rules())
    bl = Baseline.from_findings(findings, path=str(bl_path))
    for e in bl.entries:
        e.justification = "grandfathered: fixed in the next PR"
    bl.save()

    # round-trip: the grandfathered finding no longer gates
    result = lint([str(mod)], baseline_path=str(bl_path))
    assert result.problems == [] and result.stale == []
    assert result.ok
    assert [f.baselined for f in result.findings] == [True]

    # the offending line moves but stays identical -> still matched
    mod.write_text("\n\n" + BAD_SRC)
    result = lint([str(mod)], baseline_path=str(bl_path))
    assert result.problems == [] and result.stale == []

    # the bug gets FIXED -> the entry is stale and the tree fails
    mod.write_text("import asyncio\n"
                   "async def h():\n"
                   "    await asyncio.sleep(0.5)\n")
    result = lint([str(mod)], baseline_path=str(bl_path))
    assert result.problems == []
    assert len(result.stale) == 1
    assert not result.ok


def test_syntax_error_is_never_baselineable(tmp_path):
    """A baselined syntax-error (key code='') would mask every future
    parse failure in the file — a file no rule ever scanned would
    lint clean."""
    mod = tmp_path / "broken.py"
    mod.write_text("def broken(:\n")
    findings = run_paths([str(mod)], make_rules())
    bl = Baseline.from_findings(findings)
    assert bl.entries == []
    # even a hand-written entry is ignored at apply time
    from tools.weedlint.baseline import BaselineEntry
    forced = Baseline([BaselineEntry(findings[0].rel, "syntax-error",
                                     "", "sneaky")])
    forced.apply(findings)
    assert not findings[0].baselined


def test_write_baseline_scoped_run_preserves_other_entries(tmp_path,
                                                           capsys):
    """--write-baseline over a subset of paths/rules must not wipe
    grandfathered entries (and justifications) it never re-checked."""
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text(BAD_SRC)
    b.write_text(BAD_SRC)
    bl_path = tmp_path / "bl.json"
    assert weedlint_main([str(a), str(b), "--baseline", str(bl_path),
                          "--write-baseline"]) == 0
    capsys.readouterr()
    bl = Baseline.load(str(bl_path))
    assert len(bl.entries) == 2
    for e in bl.entries:
        e.justification = "reviewed"
    bl.save()
    # scoped rerun over a.py only: b.py's entry must survive untouched
    assert weedlint_main([str(a), "--baseline", str(bl_path),
                          "--write-baseline"]) == 0
    capsys.readouterr()
    bl2 = Baseline.load(str(bl_path))
    assert len(bl2.entries) == 2
    assert all(e.justification == "reviewed" for e in bl2.entries)


def test_await_in_lock_not_fooled_by_block_like_names(tmp_path):
    """Regression: `block`/`clock` context managers are not locks."""
    found = probs(tmp_path, """
        async def ok(self):
            with self.datablock:
                await work()
        async def bad(self, rlock):
            with rlock:
                await work()
    """, select=["await-in-lock"])
    assert [f.line for f in found] == [6]


def test_baseline_entry_requires_justification(tmp_path):
    bl_path = tmp_path / "baseline.json"
    bl_path.write_text(json.dumps({"version": 1, "entries": [
        {"path": "x.py", "rule": "blocking-io",
         "code": "time.sleep(1)", "justification": ""}]}))
    mod = tmp_path / "x.py"
    mod.write_text("x = 1\n")
    result = lint([str(mod)], baseline_path=str(bl_path))
    assert result.baseline_errors and not result.ok


@pytest.fixture(scope="module")
def tree_result():
    """One full-tree lint shared by the enforcement gates below — the
    most expensive operation in this file, computed once."""
    return lint([os.path.join(REPO, "seaweedfs_tpu"),
                 os.path.join(REPO, "tools")],
                baseline_path=DEFAULT_PATH)


def test_checked_in_baseline_has_no_stale_or_unjustified_entries(
        tree_result):
    """The real acceptance gate: the committed baseline must only
    carry entries that (a) still match a live finding and (b) say why
    they are acceptable."""
    assert tree_result.stale == [], \
        f"stale baseline entries: " \
        f"{[e.render() for e in tree_result.stale]}"
    assert tree_result.baseline_errors == []
    bl = Baseline.load(DEFAULT_PATH)
    assert all(e.justification for e in bl.entries)


# ---------------------------------------------------------------------
# the enforced tree + CLI surface
# ---------------------------------------------------------------------

def test_enforced_tree_is_clean(tree_result):
    """`python -m tools.weedlint seaweedfs_tpu tools` exits 0 — every
    rule, whole package, suppressions/baseline applied."""
    assert tree_result.problems == [], "\n".join(
        f.render() for f in tree_result.problems)
    assert tree_result.ok


def test_tests_tree_runs_in_report_only_mode():
    """tests/ is wired report-only: the lint must run to completion
    over it (exit 0 via --report-only regardless of findings)."""
    rc = weedlint_main([os.path.join(REPO, "tests"), "--report-only",
                        "--no-baseline"])
    assert rc == 0


def test_cli_json_output(tmp_path, capsys):
    mod = tmp_path / "m.py"
    mod.write_text(BAD_SRC)
    rc = weedlint_main([str(mod), "--format", "json", "--no-baseline"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["ok"] is False
    assert out["summary"] == {"blocking-io": 1}
    assert out["findings"][0]["rule"] == "blocking-io"
    assert out["findings"][0]["line"] == 4


def test_cli_rule_selection_and_unknown_rule(tmp_path, capsys):
    mod = tmp_path / "m.py"
    mod.write_text(BAD_SRC)
    rc = weedlint_main([str(mod), "--select", "silent-except",
                        "--no-baseline"])
    capsys.readouterr()
    assert rc == 0                       # blocking-io not selected
    rc = weedlint_main([str(mod), "--select", "no-such-rule"])
    capsys.readouterr()
    assert rc == 2


def test_cli_select_tests_enforced_preset(tmp_path, capsys):
    """ci.sh enforces tests/ via `--select tests-enforced`; the preset
    expands from rules.TESTS_ENFORCED_RULE_IDS so growing the constant
    (plus its test) grows CI too — no second hand-typed list."""
    from tools.weedlint.rules import TESTS_ENFORCED_RULE_IDS
    mod = tmp_path / "m.py"
    mod.write_text(textwrap.dedent("""
        import time
        async def h():
            time.sleep(0.5)          # blocking-io: NOT in the subset
        def g():
            try:
                time.sleep(0)
            except Exception:
                pass                 # silent-except: in the subset
    """))
    rc = weedlint_main([str(mod), "--select", "tests-enforced",
                        "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "silent-except" in out and "blocking-io" not in out
    assert "silent-except" in TESTS_ENFORCED_RULE_IDS


def test_cli_list_rules(capsys):
    assert weedlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ALL_RULE_IDS:
        assert rule_id in out


def test_module_entrypoint_runs():
    """`python -m tools.weedlint` is the documented invocation."""
    p = subprocess.run(
        [sys.executable, "-m", "tools.weedlint", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert p.returncode == 0
    assert "blocking-io" in p.stdout


def test_rule_catalog_is_documented():
    """STATIC_ANALYSIS.md documents every registered rule id, every
    rule carries the metadata the catalog is built from — and the
    other direction holds too: every id the catalog tables claim is a
    registered rule (a dead doc row would advertise a check that no
    longer runs)."""
    import re
    with open(os.path.join(REPO, "STATIC_ANALYSIS.md"),
              encoding="utf-8") as fh:
        doc = fh.read()
    for cls in ALL_RULE_CLASSES:
        assert cls.id and cls.title and cls.rationale and cls.fix, cls
        assert f"`{cls.id}`" in doc, \
            f"rule {cls.id} missing from STATIC_ANALYSIS.md"
    documented = set(re.findall(r"^\|\s*`([a-z][a-z0-9-]*)`", doc,
                                flags=re.M))
    assert documented, "catalog tables not found in STATIC_ANALYSIS.md"
    stale = documented - set(ALL_RULE_IDS)
    assert not stale, \
        f"STATIC_ANALYSIS.md catalogs unregistered rule ids: {stale}"


# ---------------------------------------------------------------------
# PR 9: two-phase enforcement surface
# ---------------------------------------------------------------------

def test_tests_tree_is_clean_for_enforced_subset():
    """The safe rule subset is ENFORCED over tests/ (exception/task/fd
    hygiene applies to test code too; suppress-format is always on).
    The remaining rules stay report-only — fixtures legitimately trip
    them."""
    from tools.weedlint.rules import TESTS_ENFORCED_RULE_IDS
    result = lint([os.path.join(REPO, "tests")],
                  select=list(TESTS_ENFORCED_RULE_IDS),
                  baseline_path="-")
    assert result.problems == [], "\n".join(
        f.render() for f in result.problems)


def test_phase2_rules_are_registered_and_cataloged():
    from tools.weedlint.rules import ADVISORY_RULE_IDS
    for rule_id in ("transitive-blocking", "lock-order",
                    "timeout-discipline", "transitive-orphan-span",
                    "docs-drift", "unresolved-call"):
        assert rule_id in ALL_RULE_IDS
    assert "unresolved-call" in ADVISORY_RULE_IDS


def test_changed_mode_clean_on_no_changes(tmp_path, capsys):
    """--changed vs a ref with no touched files exits 0 fast (the
    pre-commit fast path)."""
    import subprocess as sp
    repo = str(tmp_path)

    def git(*args):
        sp.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                *args], cwd=repo, check=True, capture_output=True)

    git("init", "-q")
    (tmp_path / "a.py").write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "init")
    from tools.weedlint import cli as wl_cli
    old = wl_cli.REPO
    wl_cli.REPO = repo
    try:
        rc = weedlint_main([str(tmp_path), "--changed", "HEAD",
                            "--no-baseline"])
    finally:
        wl_cli.REPO = old
    out = capsys.readouterr().out
    assert rc == 0 and "nothing changed" in out


def test_changed_mode_lints_only_touched_files(tmp_path, capsys):
    import subprocess as sp
    repo = str(tmp_path)

    def git(*args):
        sp.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                *args], cwd=repo, check=True, capture_output=True)

    git("init", "-q")
    clean = tmp_path / "clean.py"
    clean.write_text(BAD_SRC)            # bad, but NOT touched
    touched = tmp_path / "touched.py"
    touched.write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "init")
    touched.write_text(BAD_SRC)          # now it fires
    from tools.weedlint import cli as wl_cli
    old = wl_cli.REPO
    wl_cli.REPO = repo
    try:
        rc = weedlint_main([str(tmp_path), "--changed", "HEAD",
                            "--no-baseline"])
    finally:
        wl_cli.REPO = old
    out = capsys.readouterr().out
    assert rc == 1
    assert "touched.py" in out and "clean.py" not in out


def test_changed_mode_follows_renames(tmp_path, capsys):
    """An R row lints under its NEW path even when the host config
    disables rename detection (`diff.renames false`) — the old path
    must never stand in for it, and deletes are skipped, not linted."""
    import subprocess as sp
    repo = str(tmp_path)

    def git(*args):
        sp.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                "-c", "diff.renames=false", *args],
               cwd=repo, check=True, capture_output=True)

    git("init", "-q")
    old = tmp_path / "old_name.py"
    old.write_text(BAD_SRC)
    gone = tmp_path / "gone.py"
    gone.write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "init")
    git("config", "diff.renames", "false")
    git("mv", "old_name.py", "new_name.py")
    git("rm", "-q", "gone.py")
    from tools.weedlint import cli as wl_cli
    saved = wl_cli.REPO
    wl_cli.REPO = repo
    try:
        files = wl_cli.changed_files("HEAD", [repo], repo=repo)
        rc = weedlint_main([str(tmp_path), "--changed", "HEAD",
                            "--no-baseline"])
    finally:
        wl_cli.REPO = saved
    assert [os.path.basename(f) for f in files] == ["new_name.py"]
    out = capsys.readouterr().out
    assert rc == 1
    assert "new_name.py" in out
    assert "old_name.py" not in out and "gone.py" not in out


def test_jobs_parallel_output_matches_serial(tmp_path, capsys):
    """--jobs N is a pure speedup: path-sorted findings, byte-equal
    JSON to the serial run."""
    for i in range(6):
        (tmp_path / f"m{i}.py").write_text(BAD_SRC)
    rc1 = weedlint_main([str(tmp_path), "--format", "json",
                         "--no-baseline"])
    serial = capsys.readouterr().out
    rc2 = weedlint_main([str(tmp_path), "--format", "json",
                         "--no-baseline", "--jobs", "4"])
    parallel = capsys.readouterr().out
    assert (rc1, serial) == (rc2, parallel)
    assert json.loads(serial)["summary"] == {"blocking-io": 6}


def test_stats_flag_prints_resolution_metrics(tmp_path, capsys):
    (tmp_path / "a.py").write_text("def f():\n    return g()\n"
                                   "def g():\n    return 1\n")
    rc = weedlint_main([str(tmp_path), "--no-baseline", "--stats"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "call resolution:" in out and "unresolved" in out


def test_failpoint_site_covers_introspect_fanout(tmp_path):
    """stats/introspect.py is in failpoint scope: a per-node
    /debug/cluster/* pull added without a chaos site in reach is a
    cluster-view hop the soak can never sever — the degrade-to-
    missing_node contract would go unproven."""
    found = probs(tmp_path, """
        async def pull(self, http, addr, path):
            async with http.get(addr + path) as resp:
                return await resp.json()
    """, name="seaweedfs_tpu/stats/introspect.py",
        select=["failpoint-site"])
    assert rule_ids(found) == ["failpoint-site"]
    found = probs(tmp_path, """
        from seaweedfs_tpu.util import failpoints
        async def pull(self, http, addr, path):
            await failpoints.fail("introspect.fanout")
            async with http.get(addr + path) as resp:
                return await resp.json()
    """, name="seaweedfs_tpu/stats/introspect.py",
        select=["failpoint-site"])
    assert found == []
