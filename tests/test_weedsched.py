"""Tier-1 gates for weedsched, the deterministic interleaving
explorer (the dynamic half of the phase-3 cancellation gate):

* determinism — the same seed must produce the identical schedule,
  trace and violations, and the ``--json`` report must be
  byte-identical across runs (CI diffs reports; any wall-clock or
  hash-salt leak breaks that);
* replay — a recorded choice list re-executes the exact run, which is
  what makes a minimized schedule a *repro* rather than a statistic;
* detection — the two seeded known-bug fixtures (the historical
  FrameChannel pending-table leak and the pre-token cache fill) MUST
  be caught with a minimized schedule; a green fixture means the
  explorer lost its teeth;
* cores green — every real protocol core scenario holds its declared
  invariants under the quick seed corpus with injection on;
* the CLI contract ci.sh leans on (--quick exit codes, --list,
  unknown-scenario usage errors, module entrypoint).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import asyncio  # noqa: E402

from tools.weedsched import (Chooser, SCENARIOS, SchedLoop,  # noqa: E402
                             explore_scenario, run_once)
from tools.weedsched.__main__ import SEEDS_PATH, main  # noqa: E402
from tools.weedsched.fixtures import FIXTURES  # noqa: E402
from tools.weedsched.loop import Installed  # noqa: E402

QUICK_SEEDS = [2, 11]


@pytest.fixture(autouse=True)
def _quiet_glog():
    """The protocol cores log every leadership change; across hundreds
    of permuted runs that is pure noise in test output."""
    from seaweedfs_tpu.util import glog
    old = glog._to_stderr
    glog._to_stderr = False
    yield
    glog._to_stderr = old


# ---------------------------------------------------------------------
# the controlled loop
# ---------------------------------------------------------------------

def _drive_all(loop):
    while True:
        h = loop.next_handle()
        if h is None:
            return
        h._run()


def test_virtual_time_orders_timers_without_wall_clock():
    """asyncio.sleep under SchedLoop is virtual: timers fire in delay
    order instantly, and loop.time() advances to the fired deadline."""
    order = []

    async def late():
        await asyncio.sleep(5.0)
        order.append("late")

    async def soon():
        await asyncio.sleep(0.01)
        order.append("soon")

    loop = SchedLoop(Chooser(0))
    with Installed(loop):
        ts = [loop.create_task(late(), name="late"),
              loop.create_task(soon(), name="soon")]
        _drive_all(loop)
    assert all(t.done() for t in ts)
    assert order == ["soon", "late"]
    assert loop.time() >= 5.0          # virtual, not wall


def test_single_runnable_records_no_choice():
    """Forced moves (one runnable handle) must not consume the chooser
    — that is what keeps recorded schedules short and minimizable."""
    async def solo():
        for _ in range(5):
            await asyncio.sleep(0)

    ch = Chooser(7)
    loop = SchedLoop(ch)
    with Installed(loop):
        t = loop.create_task(solo(), name="solo")
        _drive_all(loop)
    assert t.done() and ch.choices == []


def test_chooser_replay_past_tail_defaults_to_fifo():
    ch = Chooser(0, replay=[1])
    assert ch.choose(3) == 1
    assert ch.choose(3) == 0            # past the tail: first runnable
    assert ch.choose(2) == 0
    assert ch.choices == [1, 0, 0]


# ---------------------------------------------------------------------
# run_once: determinism, replay, injection
# ---------------------------------------------------------------------

def test_same_seed_same_run():
    a = run_once(FIXTURES["gen-fence"], 11)
    b = run_once(FIXTURES["gen-fence"], 11)
    assert a.schedule == b.schedule
    assert a.trace == b.trace
    assert a.violations == b.violations
    assert a.resumptions == b.resumptions


def test_replay_reproduces_recorded_schedule():
    first = run_once(FIXTURES["gen-fence"], 23)
    again = run_once(FIXTURES["gen-fence"], 23,
                     replay=list(first.schedule))
    assert again.trace == first.trace
    assert again.violations == first.violations


def test_injection_cancels_victim_at_chosen_resumption():
    """inject_at=N cancels the victim immediately before its N-th
    resumption — CancelledError lands at exactly that await point, and
    the pending-leak fixture then leaks its registration."""
    res = run_once(FIXTURES["pending-leak"], 2, victim="req-1",
                   inject_at=1)
    assert "cancel!req-1" in res.trace
    assert res.trace.count("cancel!req-1") == 1
    assert any("leaked pending" in v for v in res.violations)


def test_deadlock_is_reported_not_hung():
    from tools.weedsched.scenarios import Run, Scenario

    def build():
        fut_box = {}

        async def waiter():
            fut_box["f"] = asyncio.get_running_loop().create_future()
            await fut_box["f"]          # nobody ever resolves it

        return Run(tasks=[("waiter", waiter())], check=lambda: [])

    scn = Scenario("dead", build, victims=(), kind="core",
                   expect_violation=False, description="")
    res = run_once(scn, 2)
    assert any(v.startswith("deadlock:") and "waiter" in v
               for v in res.violations)


# ---------------------------------------------------------------------
# seeded known-bug fixtures MUST be detected
# ---------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(FIXTURES))
def test_fixture_detected_with_minimized_schedule(name):
    row = explore_scenario(FIXTURES[name], QUICK_SEEDS,
                           stop_on_first=True)
    assert row["detected"] and row["ok"], row
    v = row["violations"][0]
    assert v["errors"], v
    # the minimizer only ever shrinks, and its result must replay as a
    # genuine repro of the violation (that is the whole point of
    # printing it)
    assert len(v["schedule"]) <= v["schedule_len_original"]
    replay = run_once(FIXTURES[name], v["seed"], victim=v["victim"],
                      inject_at=v["inject_at"],
                      replay=list(v["schedule"]))
    assert replay.violations, (name, v)


def test_pending_leak_needs_cancellation():
    """Schedule permutation alone never leaks the pending table — the
    bug is cancellation-shaped, which is exactly what --no-inject must
    surface as an undetected fixture."""
    row = explore_scenario(FIXTURES["pending-leak"], QUICK_SEEDS,
                           inject=False)
    assert not row["detected"] and not row["ok"]


# ---------------------------------------------------------------------
# real protocol cores hold their invariants
# ---------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_core_holds_invariants_on_quick_corpus(name):
    row = explore_scenario(SCENARIOS[name], QUICK_SEEDS,
                           stop_on_first=True)
    assert row["ok"] and not row["detected"], row["violations"]
    assert row["injections"] > 0 or not SCENARIOS[name].victims
    assert not row["truncated"]


# ---------------------------------------------------------------------
# CLI contract (what ci.sh runs)
# ---------------------------------------------------------------------

def test_cli_quick_is_green(capsys):
    assert main(["--quick"]) == 0
    out = capsys.readouterr().out
    for name in list(SCENARIOS) + list(FIXTURES):
        assert name in out


def test_cli_json_report_is_byte_identical(capsys):
    argv = ["--json", "--seed", "7",
            "--scenario", "pending-leak", "--scenario", "gen-fence"]
    assert main(argv) == 0
    first = capsys.readouterr().out
    assert main(argv) == 0
    second = capsys.readouterr().out
    assert first == second
    report = json.loads(first)
    assert report["ok"] and report["seeds"] == [7]
    assert [r["name"] for r in report["scenarios"]] \
        == sorted(["pending-leak", "gen-fence"])


def test_cli_undetected_fixture_fails(capsys):
    """A fixture that stops being detected must fail the gate — that
    is the self-test proving the explorer still has teeth."""
    assert main(["--scenario", "pending-leak", "--no-inject"]) == 1
    assert "FAIL" in capsys.readouterr().out


def test_cli_list_and_usage_errors(capsys):
    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in SCENARIOS:
        assert f"{name} [core]" in out
    for name in FIXTURES:
        assert f"{name} [fixture]" in out
    assert main(["--scenario", "no-such-scenario"]) == 2


def test_seeds_corpus_well_formed():
    with open(SEEDS_PATH) as f:
        corpus = json.load(f)
    assert corpus["version"] == 1
    assert corpus["quick"] and corpus["full"]
    assert all(isinstance(s, int) for s in
               corpus["quick"] + corpus["full"])
    assert set(corpus["quick"]) <= set(corpus["full"])


def test_module_entrypoint_runs():
    p = subprocess.run(
        [sys.executable, "-m", "tools.weedsched", "--list"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert p.returncode == 0
    assert "pending-leak" in p.stdout and "raft-sequencer" in p.stdout
