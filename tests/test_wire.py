"""Unified wire layer (server/wire.py) through BOTH listeners.

The volume public port speaks the raw fast protocol; the aiohttp app
serves the same connection after an in-place upgrade. Both now route
GET/POST/DELETE/batch through ONE shared module — these tests pin that
the semantics (Range incl. suffix/open-ended/416/mid-body resume,
batch framing, zero-copy sendfile reads, group-commit writes) are
IDENTICAL regardless of which listener answers.

A request is forced onto the aiohttp path by sending a duplicate
header: the fast parser refuses duplicate headers and upgrades the
connection, byte-for-byte semantics preserved.
"""

from __future__ import annotations

import asyncio
import json

import pytest

from cluster_util import Cluster, run
from seaweedfs_tpu.util.batchframe import parse_all


async def _raw(host: str, port: int, payload: bytes,
               expect_responses: int, timeout: float = 8.0) -> bytes:
    r, w = await asyncio.open_connection(host, port)
    w.write(payload)
    await w.drain()
    out = b""
    got = 0
    try:
        while got < expect_responses:
            chunk = await asyncio.wait_for(r.read(65536), timeout)
            if not chunk:
                break
            out += chunk
            got = out.count(b"HTTP/1.1 ")
    finally:
        w.close()
    return out


def _req(method: str, path: str, host: str, body: bytes = b"",
         extra: str = "", cold: bool = False) -> bytes:
    """cold=True adds a duplicate header so the fast parser upgrades
    the connection to aiohttp — the way to A/B the two listeners."""
    if cold:
        extra += "X-Force-Cold: 1\r\nX-Force-Cold: 2\r\n"
    head = (f"{method} {path} HTTP/1.1\r\nHost: {host}\r\n"
            + (f"Content-Length: {len(body)}\r\n" if body or
               method in ("POST", "PUT") else "")
            + extra + "\r\n")
    return head.encode() + body


def _split_one(out: bytes) -> tuple[int, dict, bytes]:
    """(status, lower-cased headers, body) of the FIRST response."""
    head, _, rest = out.partition(b"\r\n\r\n")
    lines = head.split(b"\r\n")
    status = int(lines[0].split()[1])
    headers: dict = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(b":")
        headers[k.strip().lower().decode()] = v.strip().decode()
    cl = int(headers.get("content-length", "0"))
    return status, headers, rest[:cl]


async def _get(port: int, path: str, host: str, extra: str = "",
               cold: bool = False) -> tuple[int, dict, bytes]:
    """One GET over a fresh connection, reading the FULL body (large
    sendfile responses span many TCP chunks)."""
    r, w = await asyncio.open_connection("127.0.0.1", port)
    w.write(_req("GET", path, host, extra=extra, cold=cold))
    await w.drain()
    try:
        head = await asyncio.wait_for(r.readuntil(b"\r\n\r\n"), 8)
        status, headers, _ = _split_one(head + b"")
        cl = int(headers.get("content-length", "0"))
        body = await asyncio.wait_for(r.readexactly(cl), 8) if cl \
            else b""
    finally:
        w.close()
    return status, headers, body


def test_range_semantics_identical_on_both_listeners(tmp_path):
    """The PR-2 failover contract: suffix ranges, open-ended ranges,
    invalid-range 416 (with Content-Range total), and mid-body resume
    via Range — asserted byte-identical through the raw listener and
    the aiohttp listener."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()
            vs = c.servers[0]
            host = f"127.0.0.1:{vs.port}"
            fid = a["fid"]
            payload = bytes(range(256)) * 4          # 1024 bytes
            async with c.http.post(f"http://{a['url']}/{fid}",
                                   data=payload) as resp:
                assert resp.status == 201

            cases = [
                ("bytes=5-9", 206, payload[5:10], "bytes 5-9/1024"),
                ("bytes=1000-", 206, payload[1000:],
                 "bytes 1000-1023/1024"),          # open-ended tail
                ("bytes=-24", 206, payload[-24:],
                 "bytes 1000-1023/1024"),          # suffix range
                ("bytes=0-2000", 206, payload, "bytes 0-1023/1024"),
                ("", 200, payload, None),
            ]
            for hdr, want_status, want_body, want_cr in cases:
                for cold in (False, True):
                    extra = f"Range: {hdr}\r\n" if hdr else ""
                    st, hs, got = await _get(vs.port, f"/{fid}", host,
                                             extra=extra, cold=cold)
                    assert st == want_status, (hdr, cold, st)
                    assert got == want_body, (hdr, cold)
                    if want_cr:
                        assert hs.get("content-range") == want_cr, \
                            (hdr, cold, hs)
                    assert hs.get("accept-ranges") == "bytes"

            # invalid ranges: past-the-end and malformed => 416 with
            # the total in Content-Range, through both listeners
            for bad in ("bytes=2048-", "bytes=junk-x", "bytes=9-5"):
                for cold in (False, True):
                    st, hs, _ = await _get(vs.port, f"/{fid}", host,
                                           extra=f"Range: {bad}\r\n",
                                           cold=cold)
                    assert st == 416, (bad, cold)
                    assert hs.get("content-range") == "bytes */1024", \
                        (bad, cold, hs)

            # mid-body resume: read a prefix, then resume from the
            # exact byte reached — the replica-failover shape
            st, _, first = await _get(vs.port, f"/{fid}", host,
                                      extra="Range: bytes=0-511\r\n")
            assert st == 206 and first == payload[:512]
            for cold in (False, True):
                st, _, rest = await _get(
                    vs.port, f"/{fid}", host,
                    extra=f"Range: bytes={len(first)}-\r\n", cold=cold)
                assert st == 206 and first + rest == payload, cold

            # ETag parity across listeners (sendfile path derives it
            # from the stored footer checksum)
            st, h1, _ = await _get(vs.port, f"/{fid}", host)
            st, h2, _ = await _get(vs.port, f"/{fid}", host, cold=True)
            assert h1["etag"] == h2["etag"]

    run(body())


def test_batch_get_both_listeners_and_cache_hits(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            # the in-proc cluster store has no cache by default; arm
            # one so the hot round exercises inline batch cache hits
            from seaweedfs_tpu.util.chunk_cache import NeedleCache
            vs.store.needle_cache = NeedleCache(8 << 20)
            host = f"127.0.0.1:{vs.port}"
            fids: list[str] = []
            bodies: dict[str, bytes] = {}
            for i in range(5):
                a = await c.assign()
                data = f"needle-{i}".encode() * (i + 1)
                async with c.http.post(f"http://{a['url']}/{a['fid']}",
                                       data=data) as resp:
                    assert resp.status == 201
                fids.append(a["fid"])
                bodies[a["fid"]] = data
            missing = fids[0].split(",")[0] + ",ffffffffdeadbeef"
            ask = fids[:3] + [missing] + fids[3:]

            for cold in (False, True):
                st, hs, raw = await _get(
                    vs.port, "/batch?fids=" + ",".join(ask), host,
                    cold=cold)
                assert st == 200, (cold, raw[:200])
                assert hs.get("x-batch-count") == str(len(ask))
                rows = parse_all(raw)
                assert [m["fid"] for m, _ in rows] == ask  # order kept
                for meta, got in rows:
                    if meta["fid"] == missing:
                        assert meta["status"] == 404
                    else:
                        assert meta["status"] == 200
                        assert got == bodies[meta["fid"]]
                        # etag identical to the single-GET etag
                        st2, h2, _ = await _get(
                            vs.port, f"/{meta['fid']}", host)
                        assert f'"{meta["etag"]}"' == h2["etag"]

            # POSTed JSON body form (long fid lists)
            async with c.http.post(
                    f"http://{host}/batch",
                    json={"fileIds": fids}) as resp:
                assert resp.status == 200
                rows = parse_all(await resp.read())
            assert [m["status"] for m, _ in rows] == [200] * len(fids)

            # second round is cache-hot: hits answered inline
            nc = vs.store.needle_cache
            hits_before = nc.counters.hits
            st, _, raw = await _get(
                vs.port, "/batch?fids=" + ",".join(fids), host)
            assert st == 200
            assert nc.counters.hits > hits_before

            # over -batch.max is refused, not truncated
            vs.batch_max = 3
            st, _, raw = await _get(
                vs.port, "/batch?fids=" + ",".join(ask), host)
            assert st == 413
            vs.batch_max = 256

    run(body())


def test_sendfile_cold_read_zero_copy(tmp_path):
    """A cold large needle on the raw listener goes out via the
    zero-copy ref path: identical bytes/ETag to the buffered aiohttp
    path, correct Range slicing, and the span says source=sendfile."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            vs.sendfile_min = 4096            # force the path w/o 64K+
            host = f"127.0.0.1:{vs.port}"
            a = await c.assign()
            fid = a["fid"]
            payload = bytes((i * 31 + 7) % 256 for i in range(100_000))
            async with c.http.post(f"http://{a['url']}/{fid}",
                                   data=payload) as resp:
                assert resp.status == 201
            # cold: the write invalidated any cache entry, so the read
            # below takes the ref/sendfile path on the raw listener
            from seaweedfs_tpu.util import tracing
            st, hs, got = await _get(vs.port, f"/{fid}", host)
            assert st == 200 and got == payload
            spans = [s for tr in tracing.traces_dict(
                         recent=50, slowest=0)["traces"]
                     for s in tr["spans"]
                     if s.get("attrs", {}).get("source") == "sendfile"]
            assert spans, "no sendfile-attributed span recorded"
            # aiohttp twin (cold header upgrades the connection): the
            # app now drains the same NeedleRef via StreamResponse +
            # loop.sendfile — identical bytes/ETag, and the span still
            # says source=sendfile through THIS listener too
            vs.store.drop_cached_volume(
                int(fid.split(",")[0]))
            tracing.reset()
            st2, hs2, got2 = await _get(vs.port, f"/{fid}", host,
                                        cold=True)
            assert st2 == 200 and got2 == payload
            assert hs["etag"] == hs2["etag"]
            assert hs["content-length"] == hs2["content-length"]
            spans2 = [s for tr in tracing.traces_dict(
                          recent=50, slowest=0)["traces"]
                      for s in tr["spans"]
                      if s.get("attrs", {}).get("source") == "sendfile"]
            assert spans2, "aiohttp read did not take the ref path"
            # ranged aiohttp sendfile: kernel copy sliced
            vs.store.drop_cached_volume(int(fid.split(",")[0]))
            st2r, _, got2r = await _get(
                vs.port, f"/{fid}", host,
                extra="Range: bytes=90000-\r\n", cold=True)
            assert st2r == 206 and got2r == payload[90000:]
            # ranged sendfile: slice of the data region
            vs.store.drop_cached_volume(int(fid.split(",")[0]))
            st3, hs3, got3 = await _get(
                vs.port, f"/{fid}", host,
                extra="Range: bytes=90000-\r\n")
            assert st3 == 206 and got3 == payload[90000:]

            # pipelined request AFTER a sendfile response on the same
            # connection: the kernel copy must not desync the stream
            vs.store.drop_cached_volume(int(fid.split(",")[0]))
            r, w = await asyncio.open_connection("127.0.0.1", vs.port)
            w.write(_req("GET", f"/{fid}", host)
                    + _req("GET", f"/{fid}", host,
                           extra="Range: bytes=0-9\r\n"))
            await w.drain()
            blob = b""
            want = len(payload) + 10
            while blob.count(b"HTTP/1.1 ") < 2 or \
                    len(blob) < want:
                chunk = await asyncio.wait_for(r.read(65536), 8)
                if not chunk:
                    break
                blob += chunk
            w.close()
            assert blob.count(b"HTTP/1.1 200 ") == 1
            assert blob.count(b"HTTP/1.1 206 ") == 1
            assert blob.endswith(payload[:10])

    run(body())


def test_delete_on_raw_listener(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            host = f"127.0.0.1:{vs.port}"
            a = await c.assign()
            fid = a["fid"]
            out = await _raw(
                "127.0.0.1", vs.port,
                _req("POST", f"/{fid}", host, b"to-be-deleted")
                + _req("DELETE", f"/{fid}", host)
                + _req("GET", f"/{fid}", host), 3)
            assert out.count(b"HTTP/1.1 201 ") == 1
            assert b'"size"' in out
            assert out.count(b"HTTP/1.1 404 ") == 1

    run(body())


def test_group_commit_coalesces_concurrent_writes(tmp_path):
    """Concurrent writers to one volume land as shared batches: every
    write acked AND readable, and the appender saw batches bigger than
    one (the window makes coalescing deterministic under load)."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            vs.store.group_commit_window = 0.02
            a = await c.assign(count=1)
            host = f"http://{a['url']}"
            fids = []
            for _ in range(24):
                aa = await c.assign()
                fids.append((aa["fid"], aa["url"]))

            async def put(fid: str, url: str, data: bytes):
                async with c.http.post(f"http://{url}/{fid}",
                                       data=data) as resp:
                    assert resp.status == 201, await resp.text()

            await asyncio.gather(*(
                put(fid, url, f"gc-{i}".encode() * 10)
                for i, (fid, url) in enumerate(fids)))
            stats = vs.store.group_commit_stats()
            assert stats["appended"] >= 24
            assert stats["max_batch"] > 1, stats
            # every acked write is durable + readable (cold, via raw)
            for i, (fid, url) in enumerate(fids):
                vs.store.drop_cached_volume(int(fid.split(",")[0]))
                async with c.http.get(f"http://{url}/{fid}") as resp:
                    assert resp.status == 200
                    assert await resp.read() == f"gc-{i}".encode() * 10

    run(body())


def test_group_commit_cookie_mismatch_fails_only_its_slot(tmp_path):
    """A bad write in a batch (wrong cookie on overwrite) fails alone;
    the good writes in the same group commit still land."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            vs.store.group_commit_window = 0.02
            a = await c.assign()
            fid = a["fid"]
            async with c.http.post(f"http://{a['url']}/{fid}",
                                   data=b"original") as resp:
                assert resp.status == 201
            vid = fid.split(",")[0]
            bad_fid = f"{vid},{fid.split(',')[1][:-8]}00000000"
            good = await c.assign()

            async def post(f, url, data):
                async with c.http.post(f"http://{url}/{f}",
                                       data=data) as resp:
                    return resp.status

            statuses = await asyncio.gather(
                post(bad_fid, a["url"], b"evil"),
                post(good["fid"], good["url"], b"fine"),
                post(fid, a["url"], b"overwrite-ok"))
            assert 409 in statuses        # cookie mismatch refused
            assert statuses.count(201) == 2
            async with c.http.get(
                    f"http://{a['url']}/{fid}") as resp:
                assert await resp.read() == b"overwrite-ok"

    run(body())


def test_manifest_conditional_304_and_batch_byte_budget(tmp_path):
    """A chunked-manifest GET honors If-None-Match with a 304 (the
    conditional checks run BEFORE manifest assembly, as in the
    reference), and /batch refuses to buffer past the byte budget —
    over-budget rows answer 413 so clients fall back to streamed
    single GETs."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            from seaweedfs_tpu.util.chunked import upload_in_chunks
            from seaweedfs_tpu.util.client import WeedClient
            host = f"127.0.0.1:{vs.port}"
            data = bytes((i * 13 + 5) % 256 for i in range(3_000_000))
            async with WeedClient(c.master.url) as wc:
                mfid, _ = await upload_in_chunks(wc, data, 1)
            st, hs, got = await _get(vs.port, f"/{mfid}", host)
            assert st == 200 and got == data      # assembled
            etag = hs["etag"]
            for cold in (False, True):
                st, _, got = await _get(
                    vs.port, f"/{mfid}", host,
                    extra=f"If-None-Match: {etag}\r\n", cold=cold)
                assert st == 304 and got == b"", cold
            # batch byte budget: three 1MB-ish chunk needles against a
            # 1.5MB budget -> some rows 413, none buffered past budget
            vs.batch_bytes_max = 1_500_000
            chunk_fids = []
            async with c.http.get(f"http://{host}/{mfid}?cm=false") as r:
                import json as _json
                man = _json.loads(await r.read())
                chunk_fids = [ch["fid"] for ch in man["chunks"]]
            st, _, raw = await _get(
                vs.port, "/batch?fids=" + ",".join(chunk_fids), host)
            assert st == 200
            rows = parse_all(raw)
            statuses = [m["status"] for m, _ in rows]
            assert 413 in statuses and 200 in statuses, statuses
            served = sum(len(b) for _, b in rows)
            assert served <= 1_500_000 + 1_048_576   # ≤ budget + 1 row
            vs.batch_bytes_max = 64 << 20

    run(body())


def test_conditional_and_pairs_identical_on_both_listeners(tmp_path):
    """304 (If-None-Match / If-Modified-Since) and stored-pairs
    headers behave identically through both listeners — they used to
    be two separate handler bodies."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            host = f"127.0.0.1:{vs.port}"
            a = await c.assign()
            fid = a["fid"]
            async with c.http.post(
                    f"http://{a['url']}/{fid}", data=b"cond-needle",
                    headers={"Seaweed-Color": "green"}) as resp:
                assert resp.status == 201
            st, hs, _ = await _get(vs.port, f"/{fid}", host)
            etag = hs["etag"]
            assert hs.get("seaweed-color") == "green"
            for cold in (False, True):
                st, hs2, got = await _get(
                    vs.port, f"/{fid}", host,
                    extra=f"If-None-Match: {etag}\r\n", cold=cold)
                assert st == 304 and got == b"", cold
                assert hs2.get("seaweed-color") == "green", cold
                lm = hs.get("last-modified")
                st, _, _ = await _get(
                    vs.port, f"/{fid}", host,
                    extra=f"If-Modified-Since: {lm}\r\n", cold=cold)
                assert st == 304, cold

    run(body())
