"""-workers N process-per-core data plane (server/workers.py).

Three layers of coverage:

- In-proc units: store partitioning, WorkerContext state files,
  prometheus merge, the master's seq-lease/assign-state endpoints and
  an in-proc AssignAccelerator answering off them.
- Wire-level subprocess cluster: a real `weed-tpu volume -workers 2`
  fleet behind one SO_REUSEPORT port — owned vs sibling-proxied needle
  GET/POST through the shared port, whole-host /metrics and /status
  aggregation, and worker crash -> supervisor respawn -> service
  resumes.
- Satellite regressions ride along in test_fasthttp.py /
  test_master_http.py / test_election.py.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from cluster_util import Cluster, run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# in-proc units


def test_store_partition_filters_ownership(tmp_path):
    from seaweedfs_tpu.storage.store import Store
    from seaweedfs_tpu.storage.volume import VolumeError
    d = str(tmp_path)
    full = Store([d], max_volume_counts=[8])
    for vid in (1, 2, 3, 4):
        full.add_volume(vid)
    full.close()

    s0 = Store([d], partition=(0, 2))
    s1 = Store([d], partition=(1, 2))
    try:
        assert sorted(s0.volumes) == [2, 4]
        assert sorted(s1.volumes) == [1, 3]
        assert s0.owns(6) and not s0.owns(7)
        with pytest.raises(VolumeError):
            s0.add_volume(5)          # 5 % 2 == 1: not worker 0's
        with pytest.raises(VolumeError):
            s1.mount_volume("", 2)
        # the slot budget is split so the master never sees N x capacity
        hb0 = s0.collect_heartbeat()
        hb1 = s1.collect_heartbeat()
        assert hb0.max_volume_count + hb1.max_volume_count == 8
    finally:
        s0.close()
        s1.close()


def test_worker_context_state_files(tmp_path):
    from seaweedfs_tpu.server.workers import WorkerContext
    a = WorkerContext(0, 2, 8080, str(tmp_path), token="secret")
    b = WorkerContext(1, 2, 8080, str(tmp_path), token="secret")
    a.write_state(ip="127.0.0.1", port=4001, role="volume")
    b.write_state(ip="127.0.0.1", port=4002, role="volume")
    assert a.owns(2) and not a.owns(3)
    assert b.sibling_addr(0) == "127.0.0.1:4001"
    assert a.owner_addr(3) == "127.0.0.1:4002"
    assert a.token_ok("secret") and not a.token_ok("wrong")
    assert not a.token_ok(None)
    states = a.all_states()
    assert [s["port"] for s in states] == [4001, 4002]


def test_merge_metrics_texts():
    from seaweedfs_tpu.stats.metrics import merge_metrics_texts
    t1 = (b"# HELP w writes\n# TYPE w counter\n"
          b'w_total{op="write"} 3.0\nvols 2.0\nw_created 100.0\n'
          b"w_ratio 0.25\n")
    t2 = (b"# HELP w writes\n# TYPE w counter\n"
          b'w_total{op="write"} 4.0\nvols 5.0\nw_created 90.0\n'
          b"w_ratio 0.5\n")
    merged = merge_metrics_texts([t1, t2]).decode()
    # integral sums render as plain integers (no `.0`, no exponent)
    assert 'w_total{op="write"} 7\n' in merged
    assert "vols 7\n" in merged
    assert "w_created 90\n" in merged          # min, not sum
    assert "w_ratio 0.75" in merged            # fractions keep precision
    assert merged.count("# HELP w writes") == 1


def test_master_seq_lease_and_assign_state(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            # grow one volume so the writable set is non-empty
            a = await c.assign()
            assert "fid" in a
            async with c.http.get(
                    f"http://{c.master.url}/cluster/seq_lease",
                    params={"count": "512"}) as resp:
                assert resp.status == 200
                l1 = await resp.json()
            async with c.http.get(
                    f"http://{c.master.url}/cluster/seq_lease",
                    params={"count": "512"}) as resp:
                l2 = await resp.json()
            assert l1["count"] == l2["count"] == 512
            # non-overlapping blocks
            assert l2["start"] >= l1["start"] + 512
            async with c.http.get(
                    f"http://{c.master.url}/cluster/assign_state",
                    params={"collection": "", "replication": "000",
                            "ttl": ""}) as resp:
                assert resp.status == 200
                st = await resp.json()
            assert st["entries"], st
            entry = st["entries"][0]
            assert entry["url"] == c.servers[0].url
    run(body())


def test_assign_accelerator_in_proc(tmp_path):
    """An AssignAccelerator wired to a live master answers /dir/assign
    locally (unique keys from its lease, valid volume pick) and falls
    back to None (=> proxy) for knobs it does not understand."""
    from seaweedfs_tpu.server.workers import (AssignAccelerator,
                                              WorkerContext)

    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()          # ensure a writable volume
            state_dir = str(tmp_path / "wstate")
            primary = WorkerContext(0, 2, c.master.port, state_dir,
                                    token="tok")
            primary.write_state(ip="127.0.0.1", port=c.master.port,
                                role="master")
            ctx = WorkerContext(1, 2, c.master.port, state_dir,
                                token="tok")
            acc = AssignAccelerator("127.0.0.1", 0, ctx)
            # port 0: skip the listener, drive fast_assign directly
            import aiohttp
            from seaweedfs_tpu.security import tls
            acc._http = tls.make_session(
                timeout=aiohttp.ClientTimeout(total=10))
            try:
                await acc._refill()
                await acc._refresh("", "000", "")
                assert acc._lease_end > acc._lease_next
                outs = [acc.fast_assign(b"", "127.0.0.1")
                        for _ in range(5)]
                assert all(o is not None for o in outs)
                fids = [json.loads(o.split(b"\r\n\r\n", 1)[1])["fid"]
                        for o in outs]
                keys = {f.split(",")[1][:-8] for f in fids}
                assert len(keys) == 5                  # unique file keys
                body0 = json.loads(outs[0].split(b"\r\n\r\n", 1)[1])
                assert body0["url"] == c.servers[0].url
                # a fast assign's needle is uploadable + readable
                st, _ = await c.put(fids[0], body0["url"], b"acc-needle")
                assert st == 201
                st, got = await c.get(fids[0], body0["url"])
                assert st == 200 and got == b"acc-needle"
                # unknown knob -> None (the primary must decide)
                assert acc.fast_assign(b"?dataCenter=dc9",
                                       "127.0.0.1") is None
                # count rides through and consumes count keys
                o = acc.fast_assign(b"?count=7", "127.0.0.1")
                assert json.loads(
                    o.split(b"\r\n\r\n", 1)[1])["count"] == 7
            finally:
                await acc._http.close()
    run(body())


def test_worker_route_middleware_in_proc(tmp_path):
    """Two in-proc volume servers partitioned 2 ways on one store dir:
    a needle owned by worker 1 written/read THROUGH worker 0 is proxied
    (fast path replays into aiohttp, middleware hops to the sibling)."""
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.server.workers import WorkerContext
    from seaweedfs_tpu.storage.store import Store

    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            state_dir = str(tmp_path / "wstate")
            d = str(tmp_path / "wdata")
            workers = []
            for i in range(2):
                ctx = WorkerContext(i, 2, 0, state_dir, token="tok")
                store = Store([os.path.join(d)], max_volume_counts=[8],
                              partition=(i, 2))
                vs = VolumeServer(store, c.master.url, port=0,
                                  pulse_seconds=0.2, worker_ctx=ctx)
                await vs.start()
                ctx.public_port = vs.port  # irrelevant for this test
                await vs.heartbeat_once()
                workers.append(vs)
            try:
                # volume 3 is owned by worker 1 (3 % 2)
                workers[1].store.add_volume(3)
                await workers[1].heartbeat_once()
                fid = "3,0101deadbe"
                # write through worker 0 -> proxied to worker 1
                st, out = await c.put(fid, workers[0].url, b"hop")
                assert st == 201, out
                assert 3 in workers[1].store.volumes
                n = workers[1].store.read_needle(3, 0x01)
                assert n.data == b"hop"
                # read back through worker 0 too
                st, got = await c.get(fid, workers[0].url)
                assert st == 200 and got == b"hop"
                # and directly from the owner
                st, got = await c.get(fid, workers[1].url)
                assert st == 200 and got == b"hop"
                # batch delete THROUGH the non-owner splits by owner
                async with c.http.post(
                        f"http://{workers[0].url}/admin/batch_delete",
                        json={"fileIds": [fid]}) as resp:
                    rows = (await resp.json())["results"]
                assert rows[0]["status"] == 202, rows
            finally:
                for vs in workers:
                    await vs.stop()
    run(body())


# ---------------------------------------------------------------------------
# wire-level subprocess fleet


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def _post(url: str, data: bytes, timeout: float = 10.0) -> bytes:
    req = urllib.request.Request(url, data=data, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read()


def _wait(fn, tries: int = 50, delay: float = 0.3):
    last = None
    for _ in range(tries):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — startup polling
            last = e
            time.sleep(delay)
    raise AssertionError(f"never became ready: {last}")


class _Fleet:
    """master + `volume -workers N` as real CLI subprocesses."""

    def __init__(self, tmp: str, port0: int, workers: int = 2):
        self.tmp = tmp
        self.mport = port0
        self.vport = port0 + 1
        self.workers = workers
        self.procs: list[subprocess.Popen] = []
        self.env = dict(os.environ, JAX_PLATFORMS="cpu",
                        PYTHONPATH=REPO)

    def __enter__(self) -> "_Fleet":
        def spawn(*args):
            log = open(os.path.join(
                self.tmp, f"proc{len(self.procs)}.log"), "w")
            p = subprocess.Popen(
                [sys.executable, "-m", "seaweedfs_tpu.cli", *args],
                stdout=log, stderr=subprocess.STDOUT, env=self.env,
                cwd=self.tmp)
            self.procs.append(p)
            return p

        spawn("master", "-port", str(self.mport),
              "-mdir", os.path.join(self.tmp, "m"),
              "-volumeSizeLimitMB", "8", "-pulseSeconds", "1")
        spawn("volume", "-port", str(self.vport),
              "-dir", os.path.join(self.tmp, "v"), "-max", "20",
              "-master", f"127.0.0.1:{self.mport}",
              "-pulseSeconds", "1", "-workers", str(self.workers))
        try:
            # generous budget: under a full-suite run on a throttled
            # container, 4 subprocesses each importing jax can take a
            # long time to come up
            _wait(lambda: json.loads(_get(
                f"http://127.0.0.1:{self.mport}/dir/assign"))["fid"],
                tries=150)
            # both workers registered (state files + live pids)
            _wait(lambda: self.worker_rows() and all(
                w["alive"] for w in self.worker_rows()), tries=100)
        except BaseException:
            # __exit__ never runs when __enter__ raises: a leaked fleet
            # would squat the SO_REUSEPORT port and poison every later
            # test that reuses it (the kernel balances onto zombies)
            self.__exit__()
            raise
        return self

    def __exit__(self, *exc) -> None:
        # worker pids come from the ON-DISK state files, captured
        # before the fleet dies — asking the (dying) HTTP surface used
        # to silently return [] and skip the wait, leaving orphan
        # workers heartbeating the port for a second or two and
        # poisoning the NEXT fleet's master topology with zombie
        # nodes (its /vol/grow then 500s against a dead private url)
        pids: list[int] = []
        state_dir = os.path.join(self.tmp, "v", ".workers")
        try:
            for fn in os.listdir(state_dir):
                if not fn.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(state_dir, fn)) as f:
                        pid = json.load(f).get("pid")
                except (OSError, ValueError):
                    continue
                if pid:
                    pids.append(int(pid))
        except OSError:
            pass
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in self.procs:
            p.wait(timeout=10)
        # SIGKILL the orphaned workers too (they would exit on their
        # own after noticing the dead supervisor, but not before
        # heartbeating a reused port), then wait until they are gone
        for pid in pids:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        for pid in pids:
            for _ in range(40):
                try:
                    os.kill(pid, 0)
                except OSError:
                    break
                time.sleep(0.1)

    def worker_rows(self) -> list[dict]:
        try:
            return json.loads(_get(
                f"http://127.0.0.1:{self.vport}/stats/workers",
                timeout=3))["workers"]
        except Exception:  # noqa: BLE001
            return []

    def assign(self, **params) -> dict:
        q = "&".join(f"{k}={v}" for k, v in params.items())
        return json.loads(_get(
            f"http://127.0.0.1:{self.mport}/dir/assign"
            + (f"?{q}" if q else "")))


def test_volume_workers_wire(tmp_path):
    """The acceptance scenario: -workers 2 serves the shared port —
    owned and sibling-owned needles both round-trip through it, stats
    stay whole-host, and a killed worker is respawned and serves
    again."""
    with _Fleet(str(tmp_path), 22300) as f:
        shared = f"http://127.0.0.1:{f.vport}"
        # grow several volumes so BOTH partitions (vid % 2) own some
        _get(f"http://127.0.0.1:{f.mport}/vol/grow?count=3")
        payloads: dict[str, bytes] = {}
        for i in range(24):
            a = f.assign()
            data = f"needle-{i}".encode() * (i % 5 + 1)
            _post(f"http://{a['url']}/{a['fid']}", data)
            payloads[a["fid"]] = data
        vids = {int(fid.split(",")[0]) for fid in payloads}
        rows = _wait(lambda: [r for r in f.worker_rows()
                              if r.get("volumes")] and f.worker_rows())
        # every needle reads back through the SHARED port, whichever
        # worker accepts the connection (sibling proxy covers the rest)
        for fid, want in payloads.items():
            assert _get(f"{shared}/{fid}") == want
        # raw keep-alive pipelining through the shared port: one
        # connection (= one worker), POST + 2 GETs, with the needle
        # owned by EITHER partition — responses must stay in sequence
        # whether served locally or via the sibling proxy
        async def pipelined(fid: str, data: bytes) -> bytes:
            r, w = await asyncio.open_connection("127.0.0.1", f.vport)
            host = f"127.0.0.1:{f.vport}"
            blob = (
                f"POST /{fid} HTTP/1.1\r\nHost: {host}\r\n"
                f"Content-Length: {len(data)}\r\n\r\n".encode() + data
                + f"GET /{fid} HTTP/1.1\r\nHost: {host}\r\n\r\n".encode()
                + f"GET /{fid} HTTP/1.1\r\nHost: {host}\r\n\r\n".encode())
            w.write(blob)
            await w.drain()
            out = b""
            # proxied responses stream headers and body in separate
            # writes: read until both GET bodies fully arrived, not
            # just until the third status line shows up
            while out.count(b"HTTP/1.1 ") < 3 or out.count(data) < 2:
                try:
                    chunk = await asyncio.wait_for(r.read(65536), 10)
                except asyncio.TimeoutError:
                    break
                if not chunk:
                    break
                out += chunk
            w.close()
            return out

        for parity in (0, 1):
            a = _wait(lambda p=parity: [x for x in (f.assign(),)
                      if int(x["fid"].split(",")[0]) % 2 == p][0],
                      tries=60, delay=0.1)
            data = f"pipelined-{parity}".encode()
            out = asyncio.run(pipelined(a["fid"], data))
            assert out.count(b"HTTP/1.1 201 ") == 1, out[:200]
            assert out.count(b"HTTP/1.1 200 ") == 2
            assert out.count(data) == 2
            payloads[a["fid"]] = data

        # whole-host status: all vids visible via one worker
        st = json.loads(_get(f"{shared}/status"))
        assert st.get("workers") == 2
        assert {m["id"] for m in st["volumes"]} >= vids
        # aggregated metrics count every write, not one worker's share
        metrics = _get(f"{shared}/metrics").decode()
        wrote = [ln for ln in metrics.splitlines()
                 if ln.startswith("SeaweedFS_volumeServer_request_total")
                 and 'type="write"' in ln and 'status="ok"' in ln]
        assert wrote and float(wrote[0].rsplit(" ", 1)[1]) >= \
            len(payloads)

        # ---- crash -> respawn -> serve again ----
        victim = [r for r in f.worker_rows() if r["index"] == 1][0]
        os.kill(victim["pid"], signal.SIGKILL)
        _wait(lambda: [r for r in f.worker_rows()
                       if r["index"] == 1 and r["alive"]
                       and r["pid"] != victim["pid"]][0], tries=80)
        # a needle owned by the killed worker serves again (retry
        # through the respawn window)
        odd = [fid for fid in payloads
               if int(fid.split(",")[0]) % 2 == 1]
        for fid in odd or list(payloads):
            _wait(lambda: _get(f"{shared}/{fid}") == payloads[fid]
                  or (_ for _ in ()).throw(AssertionError("stale")),
                  tries=40)

        # the respawn is journaled where /debug/events can see it: the
        # supervisor serves no HTTP, so the respawned worker records
        # the event in its OWN ring at boot (regression: it used to
        # land only in the supervisor's unserved journal)
        def respawn_journaled():
            body = json.loads(_get(f"{shared}/debug/events?n=200"))
            row = [e for e in body["events"]
                   if e["type"] == "worker_respawn"][0]
            assert row["index"] == 1 and row["respawns"] >= 1
            return True
        _wait(respawn_journaled, tries=40)


def test_master_workers_wire(tmp_path):
    """`master -workers 2`: assigns through the shared port stay unique
    (accelerator lease blocks), cold master routes answer via the
    transparent proxy, and heartbeats landing on the accelerator still
    register with the primary."""
    tmp = str(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = []

    def spawn(*args):
        log = open(os.path.join(tmp, f"proc{len(procs)}.log"), "w")
        p = subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", *args],
            stdout=log, stderr=subprocess.STDOUT, env=env, cwd=tmp)
        procs.append(p)
        return p

    mport, vport = 22320, 22321
    try:
        spawn("master", "-port", str(mport),
              "-mdir", os.path.join(tmp, "m"), "-pulseSeconds", "1",
              "-workers", "2")
        time.sleep(2)
        spawn("volume", "-port", str(vport),
              "-dir", os.path.join(tmp, "v"), "-max", "20",
              "-master", f"127.0.0.1:{mport}", "-pulseSeconds", "1")
        _wait(lambda: json.loads(_get(
            f"http://127.0.0.1:{mport}/dir/assign"))["fid"])
        keys = set()
        payload = None
        for i in range(30):
            # _wait: a 503 during the primary's election / the
            # accelerator's lease-refill window is a transient a real
            # client retries; key uniqueness below still catches any
            # actual assign regression
            a = _wait(lambda: json.loads(_get(
                f"http://127.0.0.1:{mport}/dir/assign")),
                tries=20, delay=0.2)
            key = a["fid"].split(",")[1][:-8]
            assert key not in keys, f"duplicate file key {a['fid']}"
            keys.add(key)
            if payload is None:
                payload = (a["fid"], b"via-master-workers")
                _post(f"http://{a['url']}/{a['fid']}", payload[1])
        assert _get(f"http://127.0.0.1:{vport}/{payload[0]}") \
            == payload[1]
        # cold routes through the shared port (proxy on the accelerator)
        for _ in range(6):
            st = json.loads(_get(
                f"http://127.0.0.1:{mport}/dir/status"))
            assert "topology" in st
            cs = json.loads(_get(
                f"http://127.0.0.1:{mport}/cluster/status"))
            assert cs["leader"] == f"127.0.0.1:{mport}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in procs:
            p.wait(timeout=10)
        # collect orphaned workers (parent-watch exit)
        time.sleep(0)
        deadline = time.time() + 10
        while time.time() < deadline:
            if not [1 for line in os.popen(
                    "ps -eo pid,args").read().splitlines()
                    if "-workerIndex" in line and f"{mport}" in line]:
                break
            time.sleep(0.3)
