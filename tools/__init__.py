# Makes `python -m tools.weedlint` resolvable from the repo root.
