"""Repair-bandwidth + stripe-batch benchmark for the EC tier.

Measures the costs ROADMAP items 2+3 target, before/after style:

* **degraded reads** — drive N needle reads whose stripes touch lost
  shards through (a) the pre-PR *all-survivor gather* baseline (fixed
  sid-order row set, locality-blind — reimplemented here so the
  shipped path carries no dead code) and (b) the shipped minimal-fetch
  plan (`EcVolume.read_needle`). Reports bytes-moved-per-byte-repaired,
  repair GB/s and p50/p99 latency for both; the plan must move
  STRICTLY fewer bytes (arxiv 2306.10528's selection win).
* **whole-volume rebuild** — rebuild M lost shards sequentially (one
  full survivor pass per shard, the pre-batching shape) vs batched
  (one coefficient-matrix dispatch per window block), byte-verifying
  both against the originals; reports GB/s and the speedup.
* **backend bake-off** (`--mode bakeoff`) — every available encoder
  backend (cpu-numpy / cpu-native / jax) runs batched encode, verify
  and reconstruct over the same (B, 10, L) window blocks: encode GB/s
  (wall-clock, informational), dispatches-per-GB batched vs
  per-window (deterministic), and a repair-bandwidth reconstruct row
  — gated on byte-identity against the per-window numpy oracle. Also
  measures the host-vs-device recover crossover that keeps the
  `-ec.smallrecover` default honest.
* **engine accounting** (`--mode engine`) — the stripe-batch engine's
  deterministic dispatch/pread contract on a REAL volume:
  `encode_volume`, `EcVolume.verify_parity` and `rebuild_ec_files`
  at batch 1 vs batch B must be byte-identical with <= ceil(W/B)
  transform dispatches and strictly fewer preads at B >= 8.

Topology model (degraded mode): parity shards are local, lost shards
are gone, the remaining data shards live on --holders emulated remote
holders; every remote interval fetched is counted (bytes + per-holder
round trips) by the same fetch hooks the volume server injects.

    python tools/bench_ec.py                    # full run (32 MB)
    python tools/bench_ec.py --smoke            # ci.sh gate (~4 MB):
                                                # plan < naive bytes,
                                                # batched dispatch/pread
                                                # counts, byte-identity
                                                # across backends
    python tools/bench_ec.py --json out.json

Documented in PERF.md rounds 10+13 / BENCH_EC.md / EC.md.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from seaweedfs_tpu.ec import gf  # noqa: E402
from seaweedfs_tpu.ec import pipeline as pl  # noqa: E402
from seaweedfs_tpu.ec.ec_volume import EcVolume  # noqa: E402
from seaweedfs_tpu.ec.locate import locate_data  # noqa: E402
from seaweedfs_tpu.storage import types as t  # noqa: E402
from seaweedfs_tpu.storage.needle import Needle  # noqa: E402
from seaweedfs_tpu.storage.volume import Volume  # noqa: E402
from seaweedfs_tpu.util.chunk_cache import EcRecoverCache  # noqa: E402

LB = 256 * 1024      # large block — small enough that a bench volume
SB = 16 * 1024       # small block   exercises both areas quickly
VID = 7


def build_volume(d: str, size_mb: float, rng: random.Random) -> dict:
    """Random needles totalling ~size_mb; returns {nid: (cookie, data)}."""
    v = Volume(d, "", VID)
    contents: dict = {}
    nid = 0
    target = int(size_mb * (1 << 20))
    while v.data_size() < target:
        nid += 1
        data = rng.randbytes(rng.randint(2048, 24576))
        cookie = rng.getrandbits(32)
        v.write_needle(Needle(cookie=cookie, id=nid, data=data))
        contents[nid] = (cookie, data)
    v.close()
    base = os.path.join(d, str(VID))
    enc = pl.get_encoder("cpu")
    pl.write_ec_files(base, encoder=enc, large_block=LB, small_block=SB,
                      buffer_size=SB)
    pl.write_sorted_file_from_idx(base)
    return contents


class RemoteCounter:
    """Emulated remote holders: serves shard intervals from files moved
    to a side directory, counting every byte and round trip — the same
    accounting shape as the volume server's per-holder batch gather."""

    def __init__(self, remote_dir: str, base: str, sids: list[int],
                 holders: int):
        self.dir = remote_dir
        self.base = base
        self.holder_of = {sid: f"holder{i % holders}"
                          for i, sid in enumerate(sorted(sids))}
        self.bytes_fetched = 0
        self.round_trips = 0
        self.intervals = 0
        self.max_batch_rows = 0

    def _path(self, sid: int) -> str:
        return os.path.join(self.dir,
                            os.path.basename(self.base) + pl.to_ext(sid))

    def fetch(self, sid: int, off: int, size: int) -> bytes | None:
        p = self._path(sid)
        if not os.path.exists(p):
            return None
        self.round_trips += 1
        self.intervals += 1
        self.bytes_fetched += size
        with open(p, "rb") as f:
            f.seek(off)
            raw = f.read(size)
        return raw + b"\x00" * (size - len(raw))

    def fetch_batch(self, reads) -> dict:
        out = {}
        holders = set()
        self.max_batch_rows = max(self.max_batch_rows, len(reads))
        for sid, off, size in reads:
            p = self._path(sid)
            if not os.path.exists(p):
                continue
            holders.add(self.holder_of.get(sid, "holder?"))
            self.intervals += 1
            self.bytes_fetched += size
            with open(p, "rb") as f:
                f.seek(off)
                raw = f.read(size)
            out[sid] = raw + b"\x00" * (size - len(raw))
        self.round_trips += len(holders)
        return out


def split_layout(src: str, work: str, local_sids: list[int],
                 lost_sids: list[int]) -> tuple[str, str]:
    """Lay out holder-local vs remote shard files from the encoded
    volume in `src`: local shards + .ecx/.ecj in work/local, surviving
    non-local shards in work/remote, lost shards nowhere."""
    local_d = os.path.join(work, "local")
    remote_d = os.path.join(work, "remote")
    os.makedirs(local_d, exist_ok=True)
    os.makedirs(remote_d, exist_ok=True)
    name = str(VID)
    for ext in (".ecx", ".ecj"):
        if os.path.exists(os.path.join(src, name + ext)):
            shutil.copy(os.path.join(src, name + ext),
                        os.path.join(local_d, name + ext))
    for sid in range(gf.TOTAL_SHARDS):
        if sid in lost_sids:
            continue
        dst = local_d if sid in local_sids else remote_d
        shutil.copy(os.path.join(src, name + pl.to_ext(sid)),
                    os.path.join(dst, name + pl.to_ext(sid)))
    return local_d, remote_d


def needles_on_lost(base: str, contents: dict, lost: list[int],
                    dat_size: int, n: int, rng: random.Random) -> list:
    """Sample n needles whose stripe intervals touch a lost shard —
    the reads that actually pay repair bandwidth."""
    from seaweedfs_tpu.storage.needle_map import SortedFileNeedleMap
    ecx = SortedFileNeedleMap(base + ".ecx")
    touching = []
    for nid, (cookie, data) in contents.items():
        raw = ecx.get_raw(nid)
        if raw is None or raw[1] == t.TOMBSTONE_FILE_SIZE:
            continue
        off, size = raw
        for iv in locate_data(LB, SB, dat_size, off,
                              t.actual_size(size, t.CURRENT_VERSION)):
            sid, _ = iv.to_shard_and_offset(LB, SB)
            if sid in lost:
                touching.append(nid)
                break
    ecx.close()
    rng.shuffle(touching)
    return touching[:n]


def naive_read(ev: EcVolume, counter: RemoteCounter, nid: int,
               cookie: int) -> bytes:
    """The pre-PR all-survivor gather: every interval of the needle is
    served row-by-row; an interval on a lost shard is reconstructed
    from the FIRST k survivors in sid order, locality-blind (each
    remote row its own round trip — the pre-batching shape)."""
    offset, size = ev.find_needle(nid)
    record_len = t.actual_size(size, ev.version)
    parts = []
    for iv in locate_data(ev.large_block, ev.small_block, ev.dat_size,
                          offset, record_len):
        sid, soff = iv.to_shard_and_offset(ev.large_block, ev.small_block)
        f = ev.shards.get(sid)
        if f is not None:
            raw = os.pread(f.fileno(), iv.size, soff)
            parts.append(raw + b"\x00" * (iv.size - len(raw)))
            continue
        raw = counter.fetch(sid, soff, iv.size)
        if raw is not None:   # surviving remote shard: plain fetch
            parts.append(raw)
            continue
        rows, bufs = [], []
        for s in range(gf.TOTAL_SHARDS):
            if s == sid or len(rows) == gf.DATA_SHARDS:
                continue
            fh = ev.shards.get(s)
            if fh is not None:
                raw = os.pread(fh.fileno(), iv.size, soff)
                raw += b"\x00" * (iv.size - len(raw))
            else:
                raw = counter.fetch(s, soff, iv.size)
                if raw is None:
                    continue
            rows.append(s)
            bufs.append(np.frombuffer(raw, np.uint8))
        assert len(rows) == gf.DATA_SHARDS, rows
        coeff = gf.shard_rows([sid], rows)
        out = pl._transform_buffers(ev.encoder(iv.size), coeff, bufs)
        parts.append(np.asarray(out[0], np.uint8).tobytes())
    n = Needle.from_bytes(b"".join(parts), ev.version)
    assert n.cookie == cookie
    return n.data


def _pct(sorted_ms: list[float], p: float) -> float:
    if not sorted_ms:
        return 0.0
    return sorted_ms[min(len(sorted_ms) - 1,
                         int(p / 100.0 * len(sorted_ms)))]


def bench_degraded(src: str, contents: dict, args, report: dict) -> None:
    rng = random.Random(args.seed + 1)
    lost = list(range(args.missing))                 # data shards die
    local = list(range(gf.DATA_SHARDS, gf.TOTAL_SHARDS))  # parity local
    base = os.path.join(src, str(VID))
    dat_size = pl.find_dat_file_size(base)
    nids = needles_on_lost(base, contents, lost, dat_size,
                           args.reads, rng)
    if not nids:
        raise SystemExit("no needles touch the lost shards; grow --size-mb")
    results = {}
    for mode in ("naive", "plan"):
        with tempfile.TemporaryDirectory(dir=src) as work:
            local_d, remote_d = split_layout(src, work, local, lost)
            counter = RemoteCounter(
                remote_d, base,
                [s for s in range(gf.TOTAL_SHARDS)
                 if s not in local and s not in lost],
                args.holders)
            ev = EcVolume(
                local_d, "", VID, large_block=LB, small_block=SB,
                encoder=pl.get_encoder("cpu"),
                fetch_remote=counter.fetch,
                fetch_remote_batch=(counter.fetch_batch
                                    if mode == "plan" else None),
                recover_cache=(EcRecoverCache(16 << 20)
                               if mode == "plan" else None),
                holder_peek=(lambda c=counter: dict(c.holder_of))
                if mode == "plan" else None)
            lat_ms: list[float] = []
            repaired = 0
            t0 = time.perf_counter()
            try:
                for nid in nids:
                    cookie, data = contents[nid]
                    t1 = time.perf_counter()
                    if mode == "naive":
                        got = naive_read(ev, counter, nid, cookie)
                    else:
                        got = ev.read_needle(nid, cookie).data
                    lat_ms.append((time.perf_counter() - t1) * 1e3)
                    assert got == data, f"byte mismatch nid={nid} {mode}"
                    repaired += len(data)
            finally:
                ev.close()
            dur = time.perf_counter() - t0
            lat_ms.sort()
            results[mode] = {
                "reads": len(nids),
                "bytes_repaired": repaired,
                "bytes_fetched": counter.bytes_fetched,
                "round_trips": counter.round_trips,
                "intervals_fetched": counter.intervals,
                "max_batch_rows": counter.max_batch_rows,
                "bytes_moved_per_byte_repaired": round(
                    counter.bytes_fetched / max(1, repaired), 3),
                "repair_MBps": round(repaired / (1 << 20) / dur, 2),
                "p50_ms": round(_pct(lat_ms, 50), 3),
                "p99_ms": round(_pct(lat_ms, 99), 3),
            }
    report["degraded"] = {
        "lost_shards": lost, "local_shards": local,
        "holders": args.holders, **{k: v for k, v in results.items()}}
    n, p = results["naive"], results["plan"]
    print(f"degraded reads ({len(nids)} needles, lost={lost}, "
          f"local={local}):")
    for mode, r in results.items():
        print(f"  {mode:6s} bytes-moved/byte-repaired="
              f"{r['bytes_moved_per_byte_repaired']:<6} "
              f"fetched={r['bytes_fetched'] / (1 << 20):.1f}MB "
              f"round-trips={r['round_trips']:<5} "
              f"repair={r['repair_MBps']}MB/s "
              f"p50={r['p50_ms']}ms p99={r['p99_ms']}ms")
    if args.smoke:
        assert p["bytes_fetched"] < n["bytes_fetched"], \
            (p["bytes_fetched"], n["bytes_fetched"])
        assert p["max_batch_rows"] <= gf.DATA_SHARDS, p["max_batch_rows"]
        assert p["round_trips"] < n["round_trips"]
        print("  smoke OK: plan moves strictly fewer bytes over fewer "
              "round trips, fetches <= k rows")


REBUILD_BUF = 128 * 1024   # rebuild window: small enough that a bench
#                            volume holds several per shard, so the
#                            ceil(W/B) dispatch contract is exercised


def bench_rebuild(src: str, args, report: dict) -> None:
    base = os.path.join(src, str(VID))
    lost = [0, 1, gf.DATA_SHARDS, gf.DATA_SHARDS + 1][:max(2, args.missing)]
    originals = {}
    for sid in lost:
        with open(base + pl.to_ext(sid), "rb") as f:
            originals[sid] = f.read()
    shard_size = len(originals[lost[0]])
    n_windows = -(-shard_size // REBUILD_BUF)
    results = {}
    for mode in ("sequential", "batched"):
        for sid in lost:
            if os.path.exists(base + pl.to_ext(sid)):
                os.remove(base + pl.to_ext(sid))
        stats: dict = {}
        rebuilt = pl.rebuild_ec_files(base, encoder=pl.get_encoder("cpu"),
                                      sequential=(mode == "sequential"),
                                      buffer_size=REBUILD_BUF,
                                      batch_windows=args.batch,
                                      stats=stats)
        assert sorted(rebuilt) == sorted(lost), (rebuilt, lost)
        for sid in lost:
            with open(base + pl.to_ext(sid), "rb") as f:
                assert f.read() == originals[sid], \
                    f"rebuild {mode} shard {sid} differs"
        results[mode] = {
            "lost": lost,
            "seconds": round(stats["seconds"], 4),
            "bytes_read": stats["bytes_read"],
            "bytes_rebuilt": stats["bytes_rebuilt"],
            "launches": stats["launches"],
            "bytes_moved_per_byte_repaired": round(
                stats["bytes_read"] / stats["bytes_rebuilt"], 3),
            "rebuild_MBps": round(
                stats["bytes_rebuilt"] / (1 << 20) / stats["seconds"], 2),
        }
    speedup = results["sequential"]["seconds"] / \
        max(1e-9, results["batched"]["seconds"])
    report["rebuild"] = {**results, "speedup": round(speedup, 2)}
    print(f"whole-volume rebuild ({len(lost)} lost shards {lost}):")
    for mode, r in results.items():
        print(f"  {mode:10s} {r['seconds']}s "
              f"{r['rebuild_MBps']}MB/s "
              f"read/rebuilt={r['bytes_moved_per_byte_repaired']} "
              f"launches={r['launches']}")
    print(f"  batched speedup: {speedup:.2f}x")
    if args.smoke:
        # the gate is DETERMINISTIC byte accounting (plus the
        # byte-identity check above): batched reads the survivors once,
        # sequential once per lost shard. Wall-clock speedup at smoke
        # sizes is scheduler-noise territory, so it is reported, not
        # asserted — the full-size run documents it in PERF.md.
        assert results["batched"]["bytes_read"] < \
            results["sequential"]["bytes_read"]
        assert results["batched"]["launches"] < \
            results["sequential"]["launches"]
        # the stripe-batch engine's dispatch contract: ceil(W/B)
        # transform dispatches for a W-window volume (vs W per lost
        # shard in the sequential shape), at the engine's EFFECTIVE
        # width (the byte budget may clamp a huge requested --batch)
        from seaweedfs_tpu.ec.batch import clamp_batch_windows
        eff = clamp_batch_windows(args.batch, REBUILD_BUF,
                                  gf.DATA_SHARDS + len(lost))
        want = -(-n_windows // eff)
        assert results["batched"]["launches"] <= want, \
            (results["batched"]["launches"], want, n_windows, eff)
        if speedup <= 1.0:
            print(f"  note: wall-clock speedup {speedup:.2f}x <= 1 at "
                  f"smoke size (noise); byte accounting still proves "
                  f"the batching win")
        print(f"  smoke OK: batched reads survivors once in "
              f"{results['batched']['launches']} dispatches "
              f"(<= ceil({n_windows}/{args.batch})), byte-identical "
              f"rebuilds")


def backends() -> list:
    """Every encoder backend available in this container, numpy oracle
    first (it is the byte-identity reference for the others)."""
    from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
    out = [("cpu-numpy", CpuEncoder(use_native=False))]
    from seaweedfs_tpu.native import gf256 as _native
    if _native.available():
        out.append(("cpu-native", CpuEncoder(use_native=True)))
    try:
        from seaweedfs_tpu.ec.encoder_jax import JaxEncoder
        out.append(("jax", JaxEncoder(use_pallas=False)))
    except Exception as e:  # noqa: BLE001 — jax-less host: CPU rows only
        print(f"  (jax backend unavailable: {type(e).__name__}: {e})")
    return out


def bench_bakeoff(args, report: dict) -> None:
    """Backend bake-off over identical (B, 10, L) window blocks:
    encode GB/s + dispatches-per-GB + repair reconstruct row per
    backend, gated on byte-identity against the per-window numpy
    oracle (wall-clock informational, accounting deterministic)."""
    from seaweedfs_tpu.ec import batch as ecb
    from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder

    B = args.batch
    L = (64 if args.smoke else 512) * 1024      # window bytes
    reps = 2 if args.smoke else 5
    rng = np.random.default_rng(args.seed)
    block = rng.integers(0, 256, (B, gf.DATA_SHARDS, L)).astype(np.uint8)
    bytes_in = block.nbytes
    # the oracle IS cpu-numpy by definition — no need to probe every
    # backend (and double-initialize jax) just to fetch it
    oracle = CpuEncoder(use_native=False)
    rows_backends = backends()
    # per-window numpy oracle: THE byte-identity gate
    want_parity = np.stack([
        np.stack(oracle.encode(list(block[b]))[gf.DATA_SHARDS:])
        for b in range(B)])
    full = np.concatenate([block, want_parity], axis=1)
    present = [0, 2, 3, 4, 5, 6, 7, 8, 10, 12]
    lost = [1, 9, 11, 13]
    rows = {}
    for name, enc in rows_backends:
        # encode: batched (ONE dispatch per block) vs per-window
        stats: dict = {}
        par = ecb.transform_block(enc, gf.parity_matrix(), block, stats)
        assert np.array_equal(par, want_parity), \
            f"{name}: batched encode differs from numpy oracle"
        t0 = time.perf_counter()
        for _ in range(reps):
            np.asarray(enc.transform_batch(gf.parity_matrix(), block))
        dt = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for b in range(B):
            np.asarray(enc.transform_batch(
                gf.parity_matrix(), block[b:b + 1]))
        dt_pw = time.perf_counter() - t0
        # verify: per-window verdicts out of one dispatch
        bad = full.copy()
        bad[B // 2, gf.DATA_SHARDS + 1, 7] ^= 0x40
        verdicts = ecb.verify_block(enc, bad)
        assert verdicts == [i != B // 2 for i in range(B)], \
            (name, verdicts)
        assert ecb.verify_block(enc, full) == [True] * B, name
        # reconstruct (repair-bandwidth row): all 4 lost rows of every
        # window from one coefficient dispatch
        t0 = time.perf_counter()
        rec = np.asarray(enc.reconstruct_batch(present, lost,
                                               full[:, present, :]))
        dt_rec = time.perf_counter() - t0
        assert np.array_equal(rec, full[:, lost, :]), \
            f"{name}: batched reconstruct differs from numpy oracle"
        rows[name] = {
            "encode_GBps": round(bytes_in / (1 << 30) / dt, 3),
            "encode_perwindow_GBps": round(
                bytes_in / (1 << 30) / dt_pw, 3),
            "dispatches_per_GB_batched": round((1 << 30) / bytes_in, 1),
            "dispatches_per_GB_perwindow": round(
                B * (1 << 30) / bytes_in, 1),
            "repair_GBps": round(
                len(lost) * B * L / (1 << 30) / dt_rec, 3),
            "byte_identical": True,
        }
    report["bakeoff"] = {"batch": B, "window_bytes": L, "rows": rows}
    print(f"backend bake-off (B={B} windows x 10 x {L >> 10}KB, "
          f"{reps} reps; wall-clock informational, byte-identity "
          f"gated):")
    print(f"  {'backend':10s} {'enc GB/s':>9} {'1-win GB/s':>10} "
          f"{'disp/GB B={}'.format(B):>12} {'disp/GB B=1':>12} "
          f"{'repair GB/s':>12}")
    for name, r in rows.items():
        print(f"  {name:10s} {r['encode_GBps']:>9} "
              f"{r['encode_perwindow_GBps']:>10} "
              f"{r['dispatches_per_GB_batched']:>12} "
              f"{r['dispatches_per_GB_perwindow']:>12} "
              f"{r['repair_GBps']:>12}")
    bench_crossover(args, report)
    if args.smoke:
        print("  smoke OK: every backend byte-identical to the "
              "numpy oracle on encode/verify/reconstruct")


def bench_crossover(args, report: dict) -> None:
    """Measure the host-vs-device single-recover crossover that
    `-ec.smallrecover` (default 1 MB) encodes: the smallest interval
    at which dispatching the recover transform to the device backend
    beats the host encoder. Informational — prints the measured value
    next to the default so the flag stays honest."""
    from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
    try:
        from seaweedfs_tpu.ec.encoder_jax import JaxEncoder
        dev = JaxEncoder(use_pallas=False)
    except Exception as e:  # noqa: BLE001 — no device backend: nothing
        # to cross over to
        print(f"  crossover: skipped (jax unavailable: {e})")
        return
    host = CpuEncoder()
    coeff = gf.cached_shard_rows((0,), tuple(range(1, 11)))
    sizes = [1 << s for s in range(16, 20 if args.smoke else 23)]
    rng = np.random.default_rng(args.seed + 2)
    rows = {}
    crossover = None
    for size in sizes:
        blk = rng.integers(0, 256, (1, gf.DATA_SHARDS, size)
                           ).astype(np.uint8)
        times = {}
        for name, enc in (("cpu", host), ("jax", dev)):
            np.asarray(enc.transform_batch(coeff, blk))   # warm/compile
            best = min(
                _timed(lambda: np.asarray(enc.transform_batch(coeff, blk)))
                for _ in range(3))
            times[name] = best
        rows[size] = {k: round(v * 1e3, 3) for k, v in times.items()}
        if crossover is None and times["jax"] < times["cpu"]:
            crossover = size
    report["crossover"] = {"sizes_ms": rows,
                           "measured_bytes": crossover,
                           "default_bytes": 1 << 20}
    got = f"{crossover} bytes" if crossover else \
        f"none up to {sizes[-1]} bytes (host wins throughout)"
    print(f"  -ec.smallrecover crossover: measured {got} "
          f"(shipped default {1 << 20}); per-size ms {rows}")


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def bench_engine(src: str, args, report: dict) -> None:
    """Deterministic stripe-batch accounting on a REAL volume: the
    three bulk paths at batch 1 vs batch B must be byte-identical
    with <= ceil(W/B) transform dispatches and strictly fewer
    preads (rebuild is covered by bench_rebuild's asserts)."""
    import hashlib

    from seaweedfs_tpu.ec.batch import clamp_batch_windows

    base = os.path.join(src, str(VID))
    out: dict = {}
    # --- encode_volume ---------------------------------------------------
    enc_rows = {}
    sums = {}
    for bw in (1, args.batch):
        stats: dict = {}
        with tempfile.TemporaryDirectory(dir=src) as d:
            nb = os.path.join(d, str(VID))
            shutil.copy(base + ".dat", nb + ".dat")
            pl.encode_volume(nb, encoder=pl.get_encoder("cpu"),
                             large_block=LB, small_block=SB,
                             buffer_size=SB, batch_windows=bw,
                             stats=stats)
            h = hashlib.sha256()
            for sid in range(gf.TOTAL_SHARDS):
                with open(nb + pl.to_ext(sid), "rb") as f:
                    h.update(f.read())
            sums[bw] = h.hexdigest()
        enc_rows[bw] = stats
    windows = enc_rows[1]["windows"]
    out["encode"] = enc_rows
    assert sums[1] == sums[args.batch], "batched encode not byte-identical"
    # ceilings computed at the engine's EFFECTIVE width — the byte
    # budget may clamp a huge requested --batch
    eff = clamp_batch_windows(args.batch, SB, gf.TOTAL_SHARDS)
    want = -(-windows // eff)
    assert enc_rows[args.batch]["dispatches"] <= want, \
        (enc_rows[args.batch]["dispatches"], want, eff)
    assert enc_rows[args.batch]["preads"] < enc_rows[1]["preads"]
    # --- verify_parity (the scrub transform) -----------------------------
    window = 128 * 1024
    ev = EcVolume(src, "", VID, large_block=LB, small_block=SB,
                  encoder=pl.get_encoder("cpu"))
    try:
        # plant one flipped byte in a parity shard so the verdict set
        # is non-trivial, then restore it
        p12 = base + pl.to_ext(12)
        with open(p12, "r+b") as f:
            f.seek(window + 11)
            orig = f.read(1)
            f.seek(window + 11)
            f.write(bytes([orig[0] ^ 0xFF]))
        try:
            reps = {bw: ev.verify_parity(window, batch_windows=bw)
                    for bw in (1, args.batch)}
        finally:
            with open(p12, "r+b") as f:
                f.seek(window + 11)
                f.write(orig)
    finally:
        ev.close()
    out["scrub"] = reps
    r1, rb = reps[1], reps[args.batch]
    assert r1["bad_windows"] == rb["bad_windows"] == [window], \
        (r1["bad_windows"], rb["bad_windows"])
    eff = clamp_batch_windows(args.batch, window, gf.TOTAL_SHARDS)
    want = -(-r1["windows"] // eff)
    assert rb["dispatches"] <= want, (rb["dispatches"], want, eff)
    assert rb["preads"] < r1["preads"]
    report["engine"] = out
    print(f"stripe-batch engine accounting (B={args.batch}):")
    print(f"  encode {windows} windows: dispatches "
          f"{enc_rows[1]['dispatches']} -> "
          f"{enc_rows[args.batch]['dispatches']} "
          f"(<= ceil = {-(-windows // args.batch)}), preads "
          f"{enc_rows[1]['preads']} -> {enc_rows[args.batch]['preads']}")
    print(f"  scrub  {r1['windows']} windows: dispatches "
          f"{r1['dispatches']} -> {rb['dispatches']}, preads "
          f"{r1['preads']} -> {rb['preads']}, same corrupt verdicts "
          f"{rb['bad_windows']}")
    if args.smoke:
        print("  smoke OK: batched encode+scrub byte-identical, "
              "<= ceil(W/B) dispatches, strictly fewer preads")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--size-mb", type=float, default=32.0)
    ap.add_argument("--reads", type=int, default=200)
    ap.add_argument("--missing", type=int, default=2,
                    help="lost shards (1..4)")
    ap.add_argument("--holders", type=int, default=3,
                    help="emulated remote holder count")
    ap.add_argument("--mode", default="all",
                    choices=["all", "degraded", "rebuild", "bakeoff",
                             "engine"])
    ap.add_argument("--batch", type=int, default=8,
                    help="stripe windows per transform dispatch "
                         "(the engine's B)")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--json", default="")
    ap.add_argument("--smoke", action="store_true",
                    help="small sizes + hard assertions (ci.sh gate)")
    args = ap.parse_args()
    if not 1 <= args.missing <= gf.PARITY_SHARDS:
        raise SystemExit("--missing must be 1..4")
    if args.smoke:
        args.size_mb = min(args.size_mb, 4.0)
        args.reads = min(args.reads, 60)
    rng = random.Random(args.seed)
    report: dict = {"size_mb": args.size_mb, "missing": args.missing,
                    "batch": args.batch}
    if args.mode == "bakeoff":
        bench_bakeoff(args, report)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=2)
            print(f"report written to {args.json}")
        return 0
    with tempfile.TemporaryDirectory() as src:
        contents = build_volume(src, args.size_mb, rng)
        report["needles"] = len(contents)
        if args.mode in ("all", "degraded"):
            bench_degraded(src, contents, args, report)
        if args.mode in ("all", "engine"):
            bench_engine(src, args, report)
        if args.mode in ("all", "rebuild"):
            bench_rebuild(src, args, report)
        if args.mode == "all":
            bench_bakeoff(args, report)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
        print(f"report written to {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
