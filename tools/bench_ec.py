"""Repair-bandwidth benchmark for the EC tier.

Measures the three costs ROADMAP item 3 targets, before/after style:

* **degraded reads** — drive N needle reads whose stripes touch lost
  shards through (a) the pre-PR *all-survivor gather* baseline (fixed
  sid-order row set, locality-blind — reimplemented here so the
  shipped path carries no dead code) and (b) the shipped minimal-fetch
  plan (`EcVolume.read_needle`). Reports bytes-moved-per-byte-repaired,
  repair GB/s and p50/p99 latency for both; the plan must move
  STRICTLY fewer bytes (arxiv 2306.10528's selection win).
* **whole-volume rebuild** — rebuild M lost shards sequentially (one
  full survivor pass per shard, the pre-batching shape) vs batched
  (one coefficient-matrix multiply per window), byte-verifying both
  against the originals; reports GB/s and the speedup.

Topology model: parity shards are local, lost shards are gone, the
remaining data shards live on --holders emulated remote holders; every
remote interval fetched is counted (bytes + per-holder round trips)
by the same fetch hooks the volume server injects.

    python tools/bench_ec.py                    # full run (32 MB)
    python tools/bench_ec.py --smoke            # ci.sh gate (~4 MB):
                                                # asserts plan < naive
                                                # bytes, batched >=
                                                # sequential, byte-
                                                # identical rebuilds
    python tools/bench_ec.py --json out.json

Documented in PERF.md round 10 / EC.md.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from seaweedfs_tpu.ec import gf  # noqa: E402
from seaweedfs_tpu.ec import pipeline as pl  # noqa: E402
from seaweedfs_tpu.ec.ec_volume import EcVolume  # noqa: E402
from seaweedfs_tpu.ec.locate import locate_data  # noqa: E402
from seaweedfs_tpu.storage import types as t  # noqa: E402
from seaweedfs_tpu.storage.needle import Needle  # noqa: E402
from seaweedfs_tpu.storage.volume import Volume  # noqa: E402
from seaweedfs_tpu.util.chunk_cache import EcRecoverCache  # noqa: E402

LB = 256 * 1024      # large block — small enough that a bench volume
SB = 16 * 1024       # small block   exercises both areas quickly
VID = 7


def build_volume(d: str, size_mb: float, rng: random.Random) -> dict:
    """Random needles totalling ~size_mb; returns {nid: (cookie, data)}."""
    v = Volume(d, "", VID)
    contents: dict = {}
    nid = 0
    target = int(size_mb * (1 << 20))
    while v.data_size() < target:
        nid += 1
        data = rng.randbytes(rng.randint(2048, 24576))
        cookie = rng.getrandbits(32)
        v.write_needle(Needle(cookie=cookie, id=nid, data=data))
        contents[nid] = (cookie, data)
    v.close()
    base = os.path.join(d, str(VID))
    enc = pl.get_encoder("cpu")
    pl.write_ec_files(base, encoder=enc, large_block=LB, small_block=SB,
                      buffer_size=SB)
    pl.write_sorted_file_from_idx(base)
    return contents


class RemoteCounter:
    """Emulated remote holders: serves shard intervals from files moved
    to a side directory, counting every byte and round trip — the same
    accounting shape as the volume server's per-holder batch gather."""

    def __init__(self, remote_dir: str, base: str, sids: list[int],
                 holders: int):
        self.dir = remote_dir
        self.base = base
        self.holder_of = {sid: f"holder{i % holders}"
                          for i, sid in enumerate(sorted(sids))}
        self.bytes_fetched = 0
        self.round_trips = 0
        self.intervals = 0
        self.max_batch_rows = 0

    def _path(self, sid: int) -> str:
        return os.path.join(self.dir,
                            os.path.basename(self.base) + pl.to_ext(sid))

    def fetch(self, sid: int, off: int, size: int) -> bytes | None:
        p = self._path(sid)
        if not os.path.exists(p):
            return None
        self.round_trips += 1
        self.intervals += 1
        self.bytes_fetched += size
        with open(p, "rb") as f:
            f.seek(off)
            raw = f.read(size)
        return raw + b"\x00" * (size - len(raw))

    def fetch_batch(self, reads) -> dict:
        out = {}
        holders = set()
        self.max_batch_rows = max(self.max_batch_rows, len(reads))
        for sid, off, size in reads:
            p = self._path(sid)
            if not os.path.exists(p):
                continue
            holders.add(self.holder_of.get(sid, "holder?"))
            self.intervals += 1
            self.bytes_fetched += size
            with open(p, "rb") as f:
                f.seek(off)
                raw = f.read(size)
            out[sid] = raw + b"\x00" * (size - len(raw))
        self.round_trips += len(holders)
        return out


def split_layout(src: str, work: str, local_sids: list[int],
                 lost_sids: list[int]) -> tuple[str, str]:
    """Lay out holder-local vs remote shard files from the encoded
    volume in `src`: local shards + .ecx/.ecj in work/local, surviving
    non-local shards in work/remote, lost shards nowhere."""
    local_d = os.path.join(work, "local")
    remote_d = os.path.join(work, "remote")
    os.makedirs(local_d, exist_ok=True)
    os.makedirs(remote_d, exist_ok=True)
    name = str(VID)
    for ext in (".ecx", ".ecj"):
        if os.path.exists(os.path.join(src, name + ext)):
            shutil.copy(os.path.join(src, name + ext),
                        os.path.join(local_d, name + ext))
    for sid in range(gf.TOTAL_SHARDS):
        if sid in lost_sids:
            continue
        dst = local_d if sid in local_sids else remote_d
        shutil.copy(os.path.join(src, name + pl.to_ext(sid)),
                    os.path.join(dst, name + pl.to_ext(sid)))
    return local_d, remote_d


def needles_on_lost(base: str, contents: dict, lost: list[int],
                    dat_size: int, n: int, rng: random.Random) -> list:
    """Sample n needles whose stripe intervals touch a lost shard —
    the reads that actually pay repair bandwidth."""
    from seaweedfs_tpu.storage.needle_map import SortedFileNeedleMap
    ecx = SortedFileNeedleMap(base + ".ecx")
    touching = []
    for nid, (cookie, data) in contents.items():
        raw = ecx.get_raw(nid)
        if raw is None or raw[1] == t.TOMBSTONE_FILE_SIZE:
            continue
        off, size = raw
        for iv in locate_data(LB, SB, dat_size, off,
                              t.actual_size(size, t.CURRENT_VERSION)):
            sid, _ = iv.to_shard_and_offset(LB, SB)
            if sid in lost:
                touching.append(nid)
                break
    ecx.close()
    rng.shuffle(touching)
    return touching[:n]


def naive_read(ev: EcVolume, counter: RemoteCounter, nid: int,
               cookie: int) -> bytes:
    """The pre-PR all-survivor gather: every interval of the needle is
    served row-by-row; an interval on a lost shard is reconstructed
    from the FIRST k survivors in sid order, locality-blind (each
    remote row its own round trip — the pre-batching shape)."""
    offset, size = ev.find_needle(nid)
    record_len = t.actual_size(size, ev.version)
    parts = []
    for iv in locate_data(ev.large_block, ev.small_block, ev.dat_size,
                          offset, record_len):
        sid, soff = iv.to_shard_and_offset(ev.large_block, ev.small_block)
        f = ev.shards.get(sid)
        if f is not None:
            raw = os.pread(f.fileno(), iv.size, soff)
            parts.append(raw + b"\x00" * (iv.size - len(raw)))
            continue
        raw = counter.fetch(sid, soff, iv.size)
        if raw is not None:   # surviving remote shard: plain fetch
            parts.append(raw)
            continue
        rows, bufs = [], []
        for s in range(gf.TOTAL_SHARDS):
            if s == sid or len(rows) == gf.DATA_SHARDS:
                continue
            fh = ev.shards.get(s)
            if fh is not None:
                raw = os.pread(fh.fileno(), iv.size, soff)
                raw += b"\x00" * (iv.size - len(raw))
            else:
                raw = counter.fetch(s, soff, iv.size)
                if raw is None:
                    continue
            rows.append(s)
            bufs.append(np.frombuffer(raw, np.uint8))
        assert len(rows) == gf.DATA_SHARDS, rows
        coeff = gf.shard_rows([sid], rows)
        out = pl._transform_buffers(ev.encoder(iv.size), coeff, bufs)
        parts.append(np.asarray(out[0], np.uint8).tobytes())
    n = Needle.from_bytes(b"".join(parts), ev.version)
    assert n.cookie == cookie
    return n.data


def _pct(sorted_ms: list[float], p: float) -> float:
    if not sorted_ms:
        return 0.0
    return sorted_ms[min(len(sorted_ms) - 1,
                         int(p / 100.0 * len(sorted_ms)))]


def bench_degraded(src: str, contents: dict, args, report: dict) -> None:
    rng = random.Random(args.seed + 1)
    lost = list(range(args.missing))                 # data shards die
    local = list(range(gf.DATA_SHARDS, gf.TOTAL_SHARDS))  # parity local
    base = os.path.join(src, str(VID))
    dat_size = pl.find_dat_file_size(base)
    nids = needles_on_lost(base, contents, lost, dat_size,
                           args.reads, rng)
    if not nids:
        raise SystemExit("no needles touch the lost shards; grow --size-mb")
    results = {}
    for mode in ("naive", "plan"):
        with tempfile.TemporaryDirectory(dir=src) as work:
            local_d, remote_d = split_layout(src, work, local, lost)
            counter = RemoteCounter(
                remote_d, base,
                [s for s in range(gf.TOTAL_SHARDS)
                 if s not in local and s not in lost],
                args.holders)
            ev = EcVolume(
                local_d, "", VID, large_block=LB, small_block=SB,
                encoder=pl.get_encoder("cpu"),
                fetch_remote=counter.fetch,
                fetch_remote_batch=(counter.fetch_batch
                                    if mode == "plan" else None),
                recover_cache=(EcRecoverCache(16 << 20)
                               if mode == "plan" else None),
                holder_peek=(lambda c=counter: dict(c.holder_of))
                if mode == "plan" else None)
            lat_ms: list[float] = []
            repaired = 0
            t0 = time.perf_counter()
            try:
                for nid in nids:
                    cookie, data = contents[nid]
                    t1 = time.perf_counter()
                    if mode == "naive":
                        got = naive_read(ev, counter, nid, cookie)
                    else:
                        got = ev.read_needle(nid, cookie).data
                    lat_ms.append((time.perf_counter() - t1) * 1e3)
                    assert got == data, f"byte mismatch nid={nid} {mode}"
                    repaired += len(data)
            finally:
                ev.close()
            dur = time.perf_counter() - t0
            lat_ms.sort()
            results[mode] = {
                "reads": len(nids),
                "bytes_repaired": repaired,
                "bytes_fetched": counter.bytes_fetched,
                "round_trips": counter.round_trips,
                "intervals_fetched": counter.intervals,
                "max_batch_rows": counter.max_batch_rows,
                "bytes_moved_per_byte_repaired": round(
                    counter.bytes_fetched / max(1, repaired), 3),
                "repair_MBps": round(repaired / (1 << 20) / dur, 2),
                "p50_ms": round(_pct(lat_ms, 50), 3),
                "p99_ms": round(_pct(lat_ms, 99), 3),
            }
    report["degraded"] = {
        "lost_shards": lost, "local_shards": local,
        "holders": args.holders, **{k: v for k, v in results.items()}}
    n, p = results["naive"], results["plan"]
    print(f"degraded reads ({len(nids)} needles, lost={lost}, "
          f"local={local}):")
    for mode, r in results.items():
        print(f"  {mode:6s} bytes-moved/byte-repaired="
              f"{r['bytes_moved_per_byte_repaired']:<6} "
              f"fetched={r['bytes_fetched'] / (1 << 20):.1f}MB "
              f"round-trips={r['round_trips']:<5} "
              f"repair={r['repair_MBps']}MB/s "
              f"p50={r['p50_ms']}ms p99={r['p99_ms']}ms")
    if args.smoke:
        assert p["bytes_fetched"] < n["bytes_fetched"], \
            (p["bytes_fetched"], n["bytes_fetched"])
        assert p["max_batch_rows"] <= gf.DATA_SHARDS, p["max_batch_rows"]
        assert p["round_trips"] < n["round_trips"]
        print("  smoke OK: plan moves strictly fewer bytes over fewer "
              "round trips, fetches <= k rows")


def bench_rebuild(src: str, args, report: dict) -> None:
    base = os.path.join(src, str(VID))
    lost = [0, 1, gf.DATA_SHARDS, gf.DATA_SHARDS + 1][:max(2, args.missing)]
    originals = {}
    for sid in lost:
        with open(base + pl.to_ext(sid), "rb") as f:
            originals[sid] = f.read()
    results = {}
    for mode in ("sequential", "batched"):
        for sid in lost:
            if os.path.exists(base + pl.to_ext(sid)):
                os.remove(base + pl.to_ext(sid))
        stats: dict = {}
        rebuilt = pl.rebuild_ec_files(base, encoder=pl.get_encoder("cpu"),
                                      sequential=(mode == "sequential"),
                                      stats=stats)
        assert sorted(rebuilt) == sorted(lost), (rebuilt, lost)
        for sid in lost:
            with open(base + pl.to_ext(sid), "rb") as f:
                assert f.read() == originals[sid], \
                    f"rebuild {mode} shard {sid} differs"
        results[mode] = {
            "lost": lost,
            "seconds": round(stats["seconds"], 4),
            "bytes_read": stats["bytes_read"],
            "bytes_rebuilt": stats["bytes_rebuilt"],
            "launches": stats["launches"],
            "bytes_moved_per_byte_repaired": round(
                stats["bytes_read"] / stats["bytes_rebuilt"], 3),
            "rebuild_MBps": round(
                stats["bytes_rebuilt"] / (1 << 20) / stats["seconds"], 2),
        }
    speedup = results["sequential"]["seconds"] / \
        max(1e-9, results["batched"]["seconds"])
    report["rebuild"] = {**results, "speedup": round(speedup, 2)}
    print(f"whole-volume rebuild ({len(lost)} lost shards {lost}):")
    for mode, r in results.items():
        print(f"  {mode:10s} {r['seconds']}s "
              f"{r['rebuild_MBps']}MB/s "
              f"read/rebuilt={r['bytes_moved_per_byte_repaired']} "
              f"launches={r['launches']}")
    print(f"  batched speedup: {speedup:.2f}x")
    if args.smoke:
        # the gate is DETERMINISTIC byte accounting (plus the
        # byte-identity check above): batched reads the survivors once,
        # sequential once per lost shard. Wall-clock speedup at smoke
        # sizes is scheduler-noise territory, so it is reported, not
        # asserted — the full-size run documents it in PERF.md.
        assert results["batched"]["bytes_read"] < \
            results["sequential"]["bytes_read"]
        assert results["batched"]["launches"] < \
            results["sequential"]["launches"]
        if speedup <= 1.0:
            print(f"  note: wall-clock speedup {speedup:.2f}x <= 1 at "
                  f"smoke size (noise); byte accounting still proves "
                  f"the batching win")
        print("  smoke OK: batched reads survivors once, "
              "byte-identical rebuilds")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--size-mb", type=float, default=32.0)
    ap.add_argument("--reads", type=int, default=200)
    ap.add_argument("--missing", type=int, default=2,
                    help="lost shards (1..4)")
    ap.add_argument("--holders", type=int, default=3,
                    help="emulated remote holder count")
    ap.add_argument("--mode", default="all",
                    choices=["all", "degraded", "rebuild"])
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--json", default="")
    ap.add_argument("--smoke", action="store_true",
                    help="small sizes + hard assertions (ci.sh gate)")
    args = ap.parse_args()
    if not 1 <= args.missing <= gf.PARITY_SHARDS:
        raise SystemExit("--missing must be 1..4")
    if args.smoke:
        args.size_mb = min(args.size_mb, 4.0)
        args.reads = min(args.reads, 60)
    rng = random.Random(args.seed)
    report: dict = {"size_mb": args.size_mb, "missing": args.missing}
    with tempfile.TemporaryDirectory() as src:
        contents = build_volume(src, args.size_mb, rng)
        report["needles"] = len(contents)
        if args.mode in ("all", "degraded"):
            bench_degraded(src, contents, args, report)
        if args.mode in ("all", "rebuild"):
            bench_rebuild(src, args, report)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
        print(f"report written to {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
