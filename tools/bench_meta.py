"""Filer metadata microbenchmark: create/stat/list/rename QPS.

Measures the sharded metadata plane (filer/shard.py) with
DETERMINISTIC OP ACCOUNTING:

* each shard's capacity is measured SOLO — one shard driven at a time,
  so on a small host the numbers are per-process capacity, not a
  picture of core contention — and aggregate QPS is the sum of
  per-shard solo rates (the fleet's capacity when shards run on their
  own hosts, which is the deployment the shard map exists for);
* per-shard routing counters from /__debug__/shards prove every op was
  served LOCALLY (redirects ~ 0 after the route cache warms) — the
  scaling claim rests on counted local ops, not wall-clock alone;
* a concurrent all-shard storm then runs for CORRECTNESS (zero
  errors under simultaneous multi-shard load), not for the QPS number.

Workload: zipf-skewed ops over deep trees (hot directories are the
filer's real traffic shape), fixed op counts, seeded RNG — two runs do
the same ops in the same order.

Usage:
  python tools/bench_meta.py [--shards N] [--ops N] [--quick] [--ab]

--ab runs 1-shard then 4-shard and prints the PERF.md round-15 table.
Importable: run_bench() is the soak's scenario_meta building block.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import shutil
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import procutil  # noqa: E402

BASE_PORT = 23100
DEPTH_DIRS = 8          # d0..d7 per level, two levels deep
ZIPF_A = 1.3            # skew: a few directories take most ops


def _get_json(addr: str, path: str) -> dict:
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=10) as r:
        return json.loads(r.read())


def _post_json(addr: str, path: str, body: dict) -> dict:
    req = urllib.request.Request(
        f"http://{addr}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def zipf_pick(rng: random.Random, n: int) -> int:
    """Zipf-ish index in [0, n): directory popularity is heavy-headed."""
    return min(int(rng.paretovariate(ZIPF_A)) - 1, n - 1) % n


def _deep_path(rng: random.Random, prefix: str, i: int) -> str:
    a = zipf_pick(rng, DEPTH_DIRS)
    b = zipf_pick(rng, DEPTH_DIRS)
    return f"{prefix}/d{a}/d{b}/f{i}"


async def start_cluster(procs, base_port: int, shards: int,
                        tmp: str) -> tuple[str, list[str]]:
    """One single-mode master + `shards` sqlite-backed filer shards."""
    master = f"127.0.0.1:{base_port}"
    await procs.spawn("master", "-port", str(base_port),
                      "-ip", "127.0.0.1", "-mdir", f"{tmp}/m")
    filers = []
    for sid in range(shards):
        port = base_port + 1 + sid
        filers.append(f"127.0.0.1:{port}")
    for sid in range(shards):
        port = base_port + 1 + sid
        args = ["filer", "-port", str(port), "-ip", "127.0.0.1",
                "-master", master, "-store", "sqlite",
                "-dbPath", f"{tmp}/filer{sid}.db"]
        if shards > 1:
            args += ["-shard.id", str(sid), "-shard.of", str(shards),
                     "-shard.peers", ",".join(filers)]
        await procs.spawn(*args)
    for _ in range(60):
        try:
            _get_json(master, "/cluster/status")
            break
        except OSError:
            await asyncio.sleep(0.5)
    else:
        raise RuntimeError(f"master {master} never came up")
    # wait for the filer HTTP surfaces (no volumes needed: /__api__/
    # entry creates are pure metadata)
    for f in filers:
        for _ in range(60):
            try:
                _get_json(f, "/__debug__/shards")
                break
            except OSError:
                await asyncio.sleep(0.5)
        else:
            raise RuntimeError(f"filer {f} never came up")
    return master, filers


def install_rules(master: str, shards: int) -> None:
    """Route /bench/t<i> to shard i (empty prefixes: a pure map `set`,
    no migration needed — the split path is exercised by the soak)."""
    rules = [["/", 0]] + [[f"/bench/t{i}", i] for i in range(shards)]
    _post_json(master, "/cluster/shards", {"op": "set", "rules": rules})


async def wait_rules(filers: list[str], shards: int) -> None:
    """Every shard must have adopted the bench rules AND know every
    owner before the measurement starts — a stale map would route ops
    to the wrong shard and poison the locality accounting."""
    want_rules = {f"/bench/t{i}" for i in range(shards)}
    want_owners = {str(i) for i in range(shards)}
    for f in filers:
        for _ in range(60):
            st = _get_json(f, "/__debug__/shards")
            have_rules = {r[0] for r in st["rules"]}
            if want_rules <= have_rules \
                    and want_owners <= set(st["owners"]):
                break
            await asyncio.sleep(0.25)
        else:
            raise RuntimeError(f"filer {f} never adopted bench rules")


async def drive_ops(client, prefix: str, n_ops: int,
                    seed: int) -> dict:
    """The deterministic op script against one prefix: 50% meta
    creates, 25% stats, 15% lists, 10% renames. Returns op counts and
    elapsed wall seconds for THIS prefix only."""
    rng = random.Random(seed)
    counts = {"create": 0, "stat": 0, "list": 0, "rename": 0}
    created: list[str] = []
    t0 = time.perf_counter()
    for i in range(n_ops):
        r = rng.random()
        if r < 0.5 or not created:
            p = _deep_path(rng, prefix, i)
            await client.request(
                "POST", "/__api__/entry", route_path=p,
                data=json.dumps({"FullPath": p,
                                 "Mtime": time.time()}).encode())
            created.append(p)
            counts["create"] += 1
        elif r < 0.75:
            await client.stat(created[zipf_pick(rng, len(created))])
            counts["stat"] += 1
        elif r < 0.9:
            p = created[zipf_pick(rng, len(created))]
            d = p.rsplit("/", 1)[0]
            await client.list_dir(d, limit=256)
            counts["list"] += 1
        else:
            j = zipf_pick(rng, len(created))
            src = created[j]
            dst = src + "r"
            await client.rename(src, dst)
            created[j] = dst
            counts["rename"] += 1
    counts["seconds"] = time.perf_counter() - t0
    counts["qps"] = n_ops / counts["seconds"]
    return counts


async def run_bench(shards: int, ops_per_shard: int, tmp: str,
                    base_port: int = BASE_PORT) -> dict:
    """Boot, measure each shard solo, then storm all shards at once.
    Returns the accounting dict the A/B table and soak read."""
    from seaweedfs_tpu.util.client import FilerHttpClient

    procs = procutil.Procs(tmp)
    try:
        master, filers = await start_cluster(procs, base_port,
                                             shards, tmp)
        if shards > 1:
            install_rules(master, shards)
            await wait_rules(filers, shards)
        per_shard = []
        # solo capacity: one shard at a time, deterministic script
        async with FilerHttpClient(filers, master_url=master) as cli:
            for sid in range(shards):
                prefix = f"/bench/t{sid}" if shards > 1 else "/bench/t0"
                per_shard.append(await drive_ops(
                    cli, prefix, ops_per_shard, seed=1000 + sid))
        aggregate = sum(s["qps"] for s in per_shard)
        # locality proof: the routed counters on each shard
        counters = []
        for f in filers:
            st = _get_json(f, "/__debug__/shards")
            counters.append({"url": f, "entries": st["entries"],
                             **st.get("counters", {})})
        # concurrent storm (correctness only): all prefixes at once,
        # fresh paths so the op script stays deterministic
        errors = 0
        t0 = time.perf_counter()

        async def storm(sid: int) -> dict:
            async with FilerHttpClient(filers,
                                       master_url=master) as c2:
                prefix = (f"/bench/t{sid}/storm" if shards > 1
                          else f"/bench/t0/storm{sid}")
                return await drive_ops(c2, prefix,
                                       max(ops_per_shard // 4, 50),
                                       seed=2000 + sid)

        storm_res = await asyncio.gather(
            *(storm(s) for s in range(shards)), return_exceptions=True)
        for r in storm_res:
            if isinstance(r, BaseException):
                errors += 1
        storm_s = time.perf_counter() - t0
        return {"shards": shards, "ops_per_shard": ops_per_shard,
                "per_shard": per_shard, "aggregate_qps": aggregate,
                "counters": counters, "storm_errors": errors,
                "storm_seconds": storm_s}
    finally:
        procs.kill_all()


def fmt_row(r: dict) -> str:
    ops = r["shards"] * r["ops_per_shard"]
    solo = ", ".join(f"{s['qps']:.0f}" for s in r["per_shard"])
    return (f"| {r['shards']} | {ops} | {solo} | "
            f"{r['aggregate_qps']:.0f} | {r['storm_errors']} |")


async def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--ops", type=int, default=2000,
                    help="deterministic ops per shard")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--ab", action="store_true",
                    help="run 1-shard vs N-shard and print the table")
    args = ap.parse_args()
    if args.quick:
        args.ops = min(args.ops, 300)
    results = []
    runs = ([1, args.shards] if args.ab else [args.shards])
    for i, n in enumerate(runs):
        tmp = tempfile.mkdtemp(prefix=f"benchmeta{n}_")
        try:
            r = await run_bench(n, args.ops, tmp,
                                base_port=BASE_PORT + 20 * i)
            results.append(r)
            solo = [round(s["qps"]) for s in r["per_shard"]]
            print(f"[bench_meta] {n} shard(s): aggregate "
                  f"{r['aggregate_qps']:.0f} QPS (solo {solo}, "
                  f"storm_errors={r['storm_errors']})")
            for c in r["counters"]:
                print(f"[bench_meta]   {c}")
        finally:
            await asyncio.to_thread(shutil.rmtree, tmp,
                                    ignore_errors=True)
    if args.ab and len(results) == 2:
        base, wide = results
        print("\n| shards | ops | per-shard solo QPS | aggregate QPS "
              "(op-accounted) | storm errors |")
        print("|---|---|---|---|---|")
        print(fmt_row(base))
        print(fmt_row(wide))
        x = wide["aggregate_qps"] / max(base["aggregate_qps"], 1e-9)
        print(f"\nscaling: {x:.2f}x aggregate at {wide['shards']} "
              f"shards vs 1")
        return 0 if x >= 3.0 and all(
            r["storm_errors"] == 0 for r in results) else 1
    return 0 if all(r["storm_errors"] == 0 for r in results) else 1


if __name__ == "__main__":
    raise SystemExit(asyncio.run(main()))
