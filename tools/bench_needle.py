"""Needle data-plane benchmark driver, multi-worker aware.

Automates the BENCH_NEEDLE.md measurement: starts a master + volume
server as real CLI processes, runs `weed-tpu benchmark` against them
over real sockets, and repeats for each requested `-workers` value so
single-core regressions and multi-core scaling are one command:

    python tools/bench_needle.py                 # workers 1 and 2
    python tools/bench_needle.py 1 2 4           # explicit sweep
    SWTPU_BENCH_N=20000 python tools/bench_needle.py 1 4
    python tools/bench_needle.py zipf 1          # Zipfian hot-read mix,
                                                 # cache on vs off, with
                                                 # needle-cache hit rate
    python tools/bench_needle.py batch 1         # multi-needle /batch
                                                 # vs single-GET A/B,
                                                 # zipf + uniform orders
                                                 # (round-9 measurement)
    python tools/bench_needle.py trace 2         # after each run, pull
                                                 # /debug/traces (merged
                                                 # across workers) and
                                                 # print the per-tier
                                                 # latency breakdown

Prints one JSON line per configuration:
    {"workers": 1, "write_rps": ..., "read_rps": ...}
zipf mode adds {"cache": "on"|"off", "reads": ..., "hit_rate": ...}
(hit rate scraped from the volume server's /metrics, summed across
workers).

Scaling expectation (PERF.md): each worker runs the full single-core
fast path independently behind SO_REUSEPORT, so throughput scales with
PHYSICAL cores; on a one-core host extra workers only add scheduling
overhead (~10% measured round 6) — run the sweep on the target host.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
BASE_PORT = 21700

_RPS = re.compile(r"^(write|read):\s+([0-9.]+) req/s", re.M)
_NEEDLES = re.compile(r"needles/s: ([0-9.]+) \(batch=(\d+)")


def _wait_assign(master: str, tries: int = 60) -> None:
    for _ in range(tries):
        try:
            with urllib.request.urlopen(
                    f"http://{master}/dir/assign", timeout=3) as r:
                if b"fid" in r.read():
                    return
        except OSError:
            pass
        time.sleep(0.5)
    raise RuntimeError("cluster never became assignable")


def _needle_cache_hit_rate(vol: str) -> "tuple[float, float] | None":
    """(hits, misses) of the needle cache from /metrics (any worker
    answers for the whole host; counters are summed server-side)."""
    try:
        with urllib.request.urlopen(f"http://{vol}/metrics",
                                    timeout=10) as r:
            body = r.read().decode()
    except OSError:
        return None
    hits = misses = 0.0
    for line in body.splitlines():
        if 'cache="needle"' not in line:
            continue
        if line.startswith("SeaweedFS_cache_hits_total"):
            hits += float(line.rsplit(" ", 1)[1])
        elif line.startswith("SeaweedFS_cache_misses_total"):
            misses += float(line.rsplit(" ", 1)[1])
    return hits, misses


def bench_one(workers: int, n: int, size: int, conc: int,
              cache_mb: "int | None" = None,
              read_mode: str = "", read_n: int = 0,
              batch_size: int = 0,
              trace: bool = False) -> dict:
    tmp = tempfile.mkdtemp(prefix=f"swtpu_bn_w{workers}_")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs: list[subprocess.Popen] = []
    master = f"127.0.0.1:{BASE_PORT}"
    vol_addr = f"127.0.0.1:{BASE_PORT + 1}"

    def spawn(*args: str) -> None:
        log = open(os.path.join(tmp, f"proc{len(procs)}.log"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", *args],
            stdout=log, stderr=subprocess.STDOUT, env=env, cwd=tmp))

    try:
        spawn("master", "-port", str(BASE_PORT),
              "-mdir", os.path.join(tmp, "m"), "-pulseSeconds", "2")
        time.sleep(2)
        vol = ["volume", "-port", str(BASE_PORT + 1),
               "-dir", os.path.join(tmp, "v"), "-max", "50",
               "-master", master, "-pulseSeconds", "2"]
        if workers > 1:
            vol += ["-workers", str(workers)]
        if cache_mb is not None:
            vol += ["-cache.mem", str(cache_mb)]
        # extra volume-server flags, e.g. the tracing-overhead A/B:
        #   SWTPU_BENCH_VOLFLAGS="-trace.sample 0" python tools/bench_needle.py zipf 1
        vol += os.environ.get("SWTPU_BENCH_VOLFLAGS", "").split()
        spawn(*vol)
        _wait_assign(master)
        bench = [sys.executable, "-m", "seaweedfs_tpu.cli", "benchmark",
                 "-master", master, "-n", str(n), "-size", str(size),
                 "-c", str(conc)]
        if read_mode:
            bench += ["-readMode", read_mode]
        if read_n:
            bench += ["-readN", str(read_n)]
        if batch_size:
            bench += ["-batchSize", str(batch_size)]
        out = subprocess.run(bench, capture_output=True, text=True,
                             env=env, cwd=tmp, timeout=1800).stdout
        rates = dict(_RPS.findall(out))
        row = {"workers": workers,
               "write_rps": float(rates.get("write", 0.0)),
               "read_rps": float(rates.get("read", 0.0)),
               "n": n, "size": size, "concurrency": conc}
        if batch_size:
            m = _NEEDLES.search(out)
            if m:
                # the A/B headline: needles served per second — for
                # batch rows read_rps counts WIRE requests, not needles
                row["needles_rps"] = float(m.group(1))
                row["batch"] = int(m.group(2))
        if read_mode:
            row["read_mode"] = read_mode
            row["reads"] = read_n or n
        if cache_mb is not None:
            row["cache"] = "off" if cache_mb == 0 else "on"
        hm = _needle_cache_hit_rate(vol_addr)
        if hm is not None and sum(hm) > 0:
            row["hit_rate"] = round(hm[0] / (hm[0] + hm[1]), 4)
        if trace:
            # per-tier latency breakdown from the volume fleet's span
            # ring (/debug/traces is whole-host: any worker merges its
            # siblings' rings before answering)
            import trace_table
            print(f"--- per-tier trace breakdown (workers={workers}) "
                  f"---", file=sys.stderr)
            print(trace_table.breakdown([vol_addr]), file=sys.stderr)
            # flight-recorder pull: force one timeline window covering
            # the run and report the health verdict (the whole-host
            # merged /debug surfaces, same fan-out as /metrics)
            try:
                req = urllib.request.Request(
                    f"http://{vol_addr}/debug/timeline?snap=1",
                    method="POST")
                with urllib.request.urlopen(req, timeout=10) as r:
                    tl = json.load(r)
                with urllib.request.urlopen(
                        f"http://{vol_addr}/debug/health",
                        timeout=10) as r:
                    health = json.load(r)
                # only report a verdict when an objective is armed
                # (SWTPU_BENCH_VOLFLAGS="-slo ..."): the empty-engine
                # stub says "ok" no matter what happened, and a bench
                # row must not launder that into a health claim
                if health.get("objectives"):
                    row["health"] = health.get("status", "?")
                win = (tl.get("windows") or [{}])[-1]
                for base, q in win.get("quantiles", {}).items():
                    if "request_duration" in base and "read" in base:
                        row.setdefault("p99_s", {})[base] = q.get("p99")
            except (OSError, ValueError) as e:
                print(f"(flight recorder pull failed: {e})",
                      file=sys.stderr)
        return row
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in procs:
            p.wait(timeout=10)
        time.sleep(1)   # workers notice the dead supervisor and exit


def main() -> None:
    args = sys.argv[1:]
    zipf = "zipf" in args
    batch = "batch" in args
    trace = "trace" in args
    sweep = [int(a) for a in args if a.isdigit()] or (
        [1] if zipf or batch else [1, 2])
    n = int(os.environ.get("SWTPU_BENCH_N", "10000"))
    size = int(os.environ.get("SWTPU_BENCH_SIZE", "1024"))
    conc = int(os.environ.get("SWTPU_BENCH_C", "64"))
    if batch:
        # round-9 A/B: multi-needle /batch vs single GET, zipf +
        # uniform read orders, cache on (the production shape)
        read_n = int(os.environ.get("SWTPU_BENCH_READN", str(3 * n)))
        bs = int(os.environ.get("SWTPU_BENCH_BATCH", "32"))
        for w in sweep:
            for mode in ("zipf", "shuffle"):
                for bsz in (bs, 0):
                    print(json.dumps(bench_one(
                        w, n, size, conc, cache_mb=32,
                        read_mode=mode, read_n=read_n,
                        batch_size=bsz, trace=trace)), flush=True)
        return
    if zipf:
        # Zipfian hot-read mix, 3 reads per written needle: the cache-on
        # vs cache-off rows are the BENCH_NEEDLE.md comparison
        read_n = int(os.environ.get("SWTPU_BENCH_READN", str(3 * n)))
        for w in sweep:
            for cache_mb in (32, 0):
                print(json.dumps(bench_one(
                    w, n, size, conc, cache_mb=cache_mb,
                    read_mode="zipf", read_n=read_n,
                    trace=trace)), flush=True)
        return
    for w in sweep:
        print(json.dumps(bench_one(w, n, size, conc, trace=trace)),
              flush=True)


if __name__ == "__main__":
    main()
