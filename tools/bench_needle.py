"""Needle data-plane benchmark driver, multi-worker aware.

Automates the BENCH_NEEDLE.md measurement: starts a master + volume
server as real CLI processes, runs `weed-tpu benchmark` against them
over real sockets, and repeats for each requested `-workers` value so
single-core regressions and multi-core scaling are one command:

    python tools/bench_needle.py                 # workers 1 and 2
    python tools/bench_needle.py 1 2 4           # explicit sweep
    SWTPU_BENCH_N=20000 python tools/bench_needle.py 1 4
    python tools/bench_needle.py zipf 1          # Zipfian hot-read mix,
                                                 # cache on vs off, with
                                                 # needle-cache hit rate
    python tools/bench_needle.py batch 1         # multi-needle /batch
                                                 # vs single-GET A/B,
                                                 # zipf + uniform orders
                                                 # (round-9 measurement)
    python tools/bench_needle.py trace 2         # after each run, pull
                                                 # /debug/traces (merged
                                                 # across workers) and
                                                 # print the per-tier
                                                 # latency breakdown
    python tools/bench_needle.py pipeline 1      # depth-8 multiplexed
                                                 # frame reads vs single
                                                 # GETs (round-12 A/B)
    python tools/bench_needle.py hop             # deterministic sibling-
                                                 # hop accounting, HTTP
                                                 # vs frame: overhead
                                                 # bytes + round trips on
                                                 # the zipf batch mix
                                                 # (wall-clock stays
                                                 # informational)

Prints one JSON line per configuration:
    {"workers": 1, "write_rps": ..., "read_rps": ...}
zipf mode adds {"cache": "on"|"off", "reads": ..., "hit_rate": ...}
(hit rate scraped from the volume server's /metrics, summed across
workers).

Scaling expectation (PERF.md): each worker runs the full single-core
fast path independently behind SO_REUSEPORT, so throughput scales with
PHYSICAL cores; on a one-core host extra workers only add scheduling
overhead (~10% measured round 6) — run the sweep on the target host.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
BASE_PORT = 21700

_RPS = re.compile(r"^(write|read):\s+([0-9.]+) req/s", re.M)
_NEEDLES = re.compile(r"needles/s: ([0-9.]+) \(batch=(\d+)")
_PIPE = re.compile(r"needles/s: ([0-9.]+) \(pipeline=(\d+) over "
                   r"frames, (\d+) HTTP fallbacks\)")


def _wait_assign(master: str, tries: int = 60) -> None:
    for _ in range(tries):
        try:
            with urllib.request.urlopen(
                    f"http://{master}/dir/assign", timeout=3) as r:
                if b"fid" in r.read():
                    return
        except OSError:
            pass
        time.sleep(0.5)
    raise RuntimeError("cluster never became assignable")


def _needle_cache_hit_rate(vol: str) -> "tuple[float, float] | None":
    """(hits, misses) of the needle cache from /metrics (any worker
    answers for the whole host; counters are summed server-side)."""
    try:
        with urllib.request.urlopen(f"http://{vol}/metrics",
                                    timeout=10) as r:
            body = r.read().decode()
    except OSError:
        return None
    hits = misses = 0.0
    for line in body.splitlines():
        if 'cache="needle"' not in line:
            continue
        if line.startswith("SeaweedFS_cache_hits_total"):
            hits += float(line.rsplit(" ", 1)[1])
        elif line.startswith("SeaweedFS_cache_misses_total"):
            misses += float(line.rsplit(" ", 1)[1])
    return hits, misses


def bench_one(workers: int, n: int, size: int, conc: int,
              cache_mb: "int | None" = None,
              read_mode: str = "", read_n: int = 0,
              batch_size: int = 0, pipeline: int = 0,
              trace: bool = False,
              scrape_frames: bool = False) -> dict:
    tmp = tempfile.mkdtemp(prefix=f"swtpu_bn_w{workers}_")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs: list[subprocess.Popen] = []
    master = f"127.0.0.1:{BASE_PORT}"
    vol_addr = f"127.0.0.1:{BASE_PORT + 1}"

    def spawn(*args: str) -> None:
        log = open(os.path.join(tmp, f"proc{len(procs)}.log"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", *args],
            stdout=log, stderr=subprocess.STDOUT, env=env, cwd=tmp))

    try:
        spawn("master", "-port", str(BASE_PORT),
              "-mdir", os.path.join(tmp, "m"), "-pulseSeconds", "2")
        time.sleep(2)
        vol = ["volume", "-port", str(BASE_PORT + 1),
               "-dir", os.path.join(tmp, "v"), "-max", "50",
               "-master", master, "-pulseSeconds", "2"]
        if workers > 1:
            vol += ["-workers", str(workers)]
        if cache_mb is not None:
            vol += ["-cache.mem", str(cache_mb)]
        # extra volume-server flags, e.g. the tracing-overhead A/B:
        #   SWTPU_BENCH_VOLFLAGS="-trace.sample 0" python tools/bench_needle.py zipf 1
        vol += os.environ.get("SWTPU_BENCH_VOLFLAGS", "").split()
        spawn(*vol)
        _wait_assign(master)
        bench = [sys.executable, "-m", "seaweedfs_tpu.cli", "benchmark",
                 "-master", master, "-n", str(n), "-size", str(size),
                 "-c", str(conc)]
        if read_mode:
            bench += ["-readMode", read_mode]
        if read_n:
            bench += ["-readN", str(read_n)]
        if batch_size:
            bench += ["-batchSize", str(batch_size)]
        if pipeline:
            bench += ["-pipeline", str(pipeline)]
        out = subprocess.run(bench, capture_output=True, text=True,
                             env=env, cwd=tmp, timeout=1800).stdout
        rates = dict(_RPS.findall(out))
        row = {"workers": workers,
               "write_rps": float(rates.get("write", 0.0)),
               "read_rps": float(rates.get("read", 0.0)),
               "n": n, "size": size, "concurrency": conc}
        if batch_size:
            m = _NEEDLES.search(out)
            if m:
                # the A/B headline: needles served per second — for
                # batch rows read_rps counts WIRE requests, not needles
                row["needles_rps"] = float(m.group(1))
                row["batch"] = int(m.group(2))
        if pipeline:
            m = _PIPE.search(out)
            if m:
                row["needles_rps"] = float(m.group(1))
                row["pipeline"] = int(m.group(2))
                row["frame_fallbacks"] = int(m.group(3))
        if read_mode:
            row["read_mode"] = read_mode
            row["reads"] = read_n or n
        if cache_mb is not None:
            row["cache"] = "off" if cache_mb == 0 else "on"
        if scrape_frames:
            # live sibling frame channel counters (whole-host /status
            # merge): every number is a plain event/byte count
            try:
                with urllib.request.urlopen(
                        f"http://{vol_addr}/status", timeout=10) as r:
                    frames = json.load(r).get("frames", {})
                agg: dict = {}
                for per_w in frames.values():
                    for chs in per_w.values():
                        for k, v in chs.items():
                            agg[k] = agg.get(k, 0) + v
                if agg:
                    row["sibling_frames"] = agg
            except (OSError, ValueError):
                pass
        hm = _needle_cache_hit_rate(vol_addr)
        if hm is not None and sum(hm) > 0:
            row["hit_rate"] = round(hm[0] / (hm[0] + hm[1]), 4)
        if trace:
            # per-tier latency breakdown from the volume fleet's span
            # ring (/debug/traces is whole-host: any worker merges its
            # siblings' rings before answering)
            import trace_table
            print(f"--- per-tier trace breakdown (workers={workers}) "
                  f"---", file=sys.stderr)
            print(trace_table.breakdown([vol_addr]), file=sys.stderr)
            # flight-recorder pull: force one timeline window covering
            # the run and report the health verdict (the whole-host
            # merged /debug surfaces, same fan-out as /metrics)
            try:
                req = urllib.request.Request(
                    f"http://{vol_addr}/debug/timeline?snap=1",
                    method="POST")
                with urllib.request.urlopen(req, timeout=10) as r:
                    tl = json.load(r)
                with urllib.request.urlopen(
                        f"http://{vol_addr}/debug/health",
                        timeout=10) as r:
                    health = json.load(r)
                # only report a verdict when an objective is armed
                # (SWTPU_BENCH_VOLFLAGS="-slo ..."): the empty-engine
                # stub says "ok" no matter what happened, and a bench
                # row must not launder that into a health claim
                if health.get("objectives"):
                    row["health"] = health.get("status", "?")
                win = (tl.get("windows") or [{}])[-1]
                for base, q in win.get("quantiles", {}).items():
                    if "request_duration" in base and "read" in base:
                        row.setdefault("p99_s", {})[base] = q.get("p99")
                # exemplar link: the window's worst read trace id,
                # chased through the leader's cluster assembly for a
                # per-host/per-tier self-time table of THAT request
                worst = None
                for key, ex in (win.get("exemplars") or {}).items():
                    if "read" in key or "get" in key:
                        if worst is None or ex.get("dur_ms", 0) > \
                                worst.get("dur_ms", 0):
                            worst = ex
                if worst and worst.get("trace"):
                    print(f"--- cluster trace of worst read "
                          f"({worst['dur_ms']}ms) ---", file=sys.stderr)
                    print(trace_table.cluster_breakdown(
                        master, worst["trace"]), file=sys.stderr)
            except (OSError, ValueError) as e:
                print(f"(flight recorder pull failed: {e})",
                      file=sys.stderr)
        return row
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in procs:
            p.wait(timeout=10)
        time.sleep(1)   # workers notice the dead supervisor and exit


def hop_accounting(n_files: int = 2000, reads: int = 6000,
                   batch: int = 32, depth: int = 8,
                   seed: int = 9) -> dict:
    """Deterministic sibling-hop accounting, HTTP vs frame, on the
    zipf batch mix — every number is computed from the REAL codecs
    over a seeded workload, so two runs produce identical output (no
    wall-clock anywhere).

    Workload: `reads` zipf-ordered needle reads over `n_files` fids
    spread across both vid-parity partitions, grouped into /batch
    requests of `batch`, entering at worker 0 — each batch's odd-vid
    rows cross the sibling hop as ONE sub-request.

    Accounting per sub-request:
      * frame overhead  = the REAL encoded frame bytes minus payload
        (util/frame.encode_frame, via overhead_model);
      * HTTP overhead   = the request line + headers the HTTP hop
        sends (worker token, traceparent, aiohttp's standard headers)
        plus the raw listener's response head — same fids string on
        both sides, so the delta is pure protocol framing.

    Round trips count serialized response-waits for the single-GET
    shape of the same zipf mix: HTTP/1.1 keep-alive blocks its
    connection per request (one wait per needle); a depth-N frame
    channel overlaps N (one wait per window) — the client-pipelining
    half of the PR."""
    import random
    sys.path.insert(0, REPO)
    from seaweedfs_tpu.util import batchframe
    from seaweedfs_tpu.util.frame import overhead_model
    from seaweedfs_tpu.server.workers import WORKER_HEADER

    rng = random.Random(seed)
    fids = [f"{(i % 10) + 1},{i:016x}35c2" for i in range(n_files)]
    ranked = list(fids)
    rng.shuffle(ranked)
    weights = [1.0 / (r + 1) ** 1.1 for r in range(len(ranked))]
    order = rng.choices(ranked, weights=weights, k=reads)
    token = "ab" * 16                 # launch tokens are 32 hex chars
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"

    http_over = frame_over = sub_requests = sib_needles = 0
    spec_bytes = 0                    # the fids string, same both sides
    for lo in range(0, len(order), batch):
        group = order[lo:lo + batch]
        sib = [f for f in group if int(f.split(",")[0]) % 2 == 1]
        if not sib:
            continue
        sub_requests += 1
        sib_needles += len(sib)
        q = ",".join(sib)
        spec_bytes += len(q)
        frame_over += overhead_model(
            "GET", "/batch", query={"fids": q},
            headers={"traceparent": tp},
            resp_headers={}, resp_ct=batchframe.CONTENT_TYPE)
        req_head = (f"GET /batch?fids={q} HTTP/1.1\r\n"
                    f"Host: 127.0.0.1:20000\r\n"
                    f"{WORKER_HEADER}: {token}\r\n"
                    f"traceparent: {tp}\r\n"
                    f"Accept: */*\r\n"
                    f"Accept-Encoding: gzip, deflate\r\n"
                    f"User-Agent: Python/3.10 aiohttp/3.8\r\n\r\n")
        resp_head = (f"HTTP/1.1 200 OK\r\n"
                     f"Content-Type: {batchframe.CONTENT_TYPE}\r\n"
                     f"Content-Length: 1048576\r\n\r\n")
        http_over += len(req_head) + len(resp_head)

    http_rts = reads                  # one blocking wait per needle
    frame_rts = -(-reads // depth)    # one wait per depth-N window
    return {
        "mode": "hop", "hop": "sibling", "reads": reads, "batch": batch,
        "sibling_sub_requests": sub_requests,
        "sibling_needles": sib_needles,
        # the fids spec rides both transports identically; protocol_*
        # rows subtract it so the framing cost itself is visible
        "fids_spec_bytes": spec_bytes,
        "http": {"overhead_bytes": http_over,
                 "per_needle": round(http_over / sib_needles, 2),
                 "protocol_per_needle": round(
                     (http_over - spec_bytes) / sib_needles, 2),
                 "single_get_round_trips": http_rts},
        "frame": {"overhead_bytes": frame_over,
                  "per_needle": round(frame_over / sib_needles, 2),
                  "protocol_per_needle": round(
                      (frame_over - spec_bytes) / sib_needles, 2),
                  "pipelined_round_trips": frame_rts,
                  "pipeline_depth": depth},
    }


def interhost_accounting(n_needles: int = 2000, depth: int = 8,
                         seed: int = 9) -> list:
    """Deterministic INTER-HOST hop accounting for the frame fabric:
    for each of the three cluster hop types the fabric carries —
    replication fan-out, client->volume single-needle reads, and
    cross-host EC shard gather — compute the per-request protocol
    bytes and the serialized round-trip waits for a seeded workload,
    frame vs HTTP, from the REAL codecs (util/frame.encode_frame on
    the frame side, the literal request/response heads aiohttp and the
    listeners emit on the HTTP side). No wall-clock anywhere: two runs
    print identical JSON.

    Also proves payload byte-identity through the frame codec: every
    needle body in the seeded corpus is encoded with encode_frame and
    re-decoded with FrameDecoder, and must come back bit-exact — the
    fabric's transport-equivalence invariant (the integration tests
    assert the same thing against live servers)."""
    import random
    sys.path.insert(0, REPO)
    from seaweedfs_tpu.util.frame import (FrameDecoder, REQ,
                                          encode_frame, overhead_model)

    rng = random.Random(seed)
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    fids = [f"{(i % 10) + 1},{i:016x}35c2" for i in range(n_needles)]
    sizes = [rng.randint(300, 8000) for _ in fids]

    # codec byte-identity over the corpus: encode -> decode -> compare
    dec = FrameDecoder()
    checked = 0
    for fid, size in zip(fids[:256], sizes[:256]):
        body = bytes((i * 31 + size) % 256 for i in range(size))
        wire = encode_frame(REQ, checked + 1,
                            {"m": "POST", "p": f"/{fid}"}, body)
        frames = list(dec.feed(wire))
        assert len(frames) == 1 and frames[0].payload == body, \
            f"frame codec tore payload for {fid}"
        checked += 1

    std_http_req = ("Accept: */*\r\n"
                    "Accept-Encoding: gzip, deflate\r\n"
                    "User-Agent: Python/3.10 aiohttp/3.8\r\n")
    resp_head_json = ("HTTP/1.1 200 OK\r\n"
                      "Content-Type: application/json\r\n"
                      "Content-Length: 64\r\n\r\n")

    def http_req_head(method: str, path_q: str,
                      extra: str = "", blen: int = 0) -> int:
        head = (f"{method} {path_q} HTTP/1.1\r\n"
                f"Host: 127.0.0.1:20000\r\n"
                f"traceparent: {tp}\r\n" + extra + std_http_req)
        if blen:
            head += (f"Content-Length: {blen}\r\n"
                     f"Content-Type: application/octet-stream\r\n")
        return len(head + "\r\n")

    rows = []

    def account(hop_type: str, frame_over: int, http_over: int,
                n_reqs: int) -> None:
        # HTTP/1.1 keep-alive serializes one response wait per
        # request; a depth-N frame channel overlaps N
        rows.append({
            "mode": "hop", "hop": "interhost", "type": hop_type,
            "requests": n_reqs,
            "http": {"overhead_bytes": http_over,
                     "per_needle": round(http_over / n_reqs, 2),
                     "round_trips": n_reqs},
            "frame": {"overhead_bytes": frame_over,
                      "per_needle": round(frame_over / n_reqs, 2),
                      "round_trips": -(-n_reqs // depth),
                      "pipeline_depth": depth},
        })

    # 1. replication fan-out: POST /<fid>?type=replicate, raw needle
    #    body, X-Raw-Needle marker (server/volume_server._replicate)
    f_over = h_over = 0
    for fid, size in zip(fids, sizes):
        f_over += overhead_model(
            "POST", f"/{fid}", query={"type": "replicate"},
            headers={"x-raw-needle": "1", "traceparent": tp},
            resp_headers={}, resp_ct="application/json")
        h_over += http_req_head(
            "POST", f"/{fid}?type=replicate",
            extra="X-Raw-Needle: 1\r\n", blen=size)
        h_over += len(resp_head_json)
    account("replication_fanout", f_over, h_over, len(fids))

    # 2. client->volume whole-needle read: GET /<fid>
    #    (util/client._read_stream_net's frame fast path)
    f_over = h_over = 0
    for fid in fids:
        f_over += overhead_model(
            "GET", f"/{fid}", headers={"traceparent": tp},
            resp_headers={"Etag": "35c2"},
            resp_ct="application/octet-stream")
        h_over += http_req_head("GET", f"/{fid}")
        h_over += len("HTTP/1.1 200 OK\r\n"
                      "Content-Type: application/octet-stream\r\n"
                      "Etag: \"35c2\"\r\n"
                      "Accept-Ranges: bytes\r\n"
                      "Content-Length: 4096\r\n\r\n")
    account("client_read", f_over, h_over, len(fids))

    # 3. cross-host EC shard gather: GET /admin/ec/shard_read with
    #    volume/shard/offset/size (server/volume_server._sync_shard_fetch)
    f_over = h_over = 0
    gathers = [(rng.randint(1, 10), rng.randint(0, 13),
                rng.randrange(0, 1 << 30, 4096),
                rng.choice((4096, 65536)))
               for _ in range(n_needles)]
    for vid, shard, off, size in gathers:
        q = {"volume": str(vid), "shard": str(shard),
             "offset": str(off), "size": str(size)}
        f_over += overhead_model(
            "GET", "/admin/ec/shard_read", query=q,
            headers={"traceparent": tp}, resp_headers={},
            resp_ct="application/octet-stream")
        qs = "&".join(f"{k}={v}" for k, v in q.items())
        h_over += http_req_head("GET", f"/admin/ec/shard_read?{qs}")
        h_over += len("HTTP/1.1 200 OK\r\n"
                      "Content-Type: application/octet-stream\r\n"
                      "Content-Length: 65536\r\n\r\n")
    account("ec_shard_gather", f_over, h_over, len(gathers))

    for row in rows:
        row["codec_payloads_checked"] = checked
    return rows


def main() -> None:
    args = sys.argv[1:]
    zipf = "zipf" in args
    batch = "batch" in args
    pipeline = "pipeline" in args
    hop = "hop" in args
    trace = "trace" in args
    sweep = [int(a) for a in args if a.isdigit()] or (
        [1] if zipf or batch else [1, 2])
    n = int(os.environ.get("SWTPU_BENCH_N", "10000"))
    size = int(os.environ.get("SWTPU_BENCH_SIZE", "1024"))
    conc = int(os.environ.get("SWTPU_BENCH_C", "64"))
    if hop:
        # deterministic sibling-hop accounting (the acceptance gate:
        # frames strictly cheaper per needle, fewer round trips) ...
        acct = hop_accounting()
        print(json.dumps(acct), flush=True)
        assert acct["frame"]["overhead_bytes"] < \
            acct["http"]["overhead_bytes"], "frame overhead not lower"
        assert acct["frame"]["pipelined_round_trips"] < \
            acct["http"]["single_get_round_trips"], \
            "frame round trips not fewer"
        # ... the INTER-HOST fabric hops under the same gate: on every
        # hop type the frame wire must be strictly cheaper per needle
        # AND serialize strictly fewer round-trip waits at depth 8,
        # with payload byte-identity proven through the real codec
        for row in interhost_accounting():
            print(json.dumps(row), flush=True)
            assert row["frame"]["overhead_bytes"] < \
                row["http"]["overhead_bytes"], \
                f"{row['type']}: frame overhead not lower"
            assert row["frame"]["round_trips"] < \
                row["http"]["round_trips"], \
                f"{row['type']}: frame round trips not fewer"
            assert row["codec_payloads_checked"] > 0
        # ... plus one LIVE -workers 2 zipf batch run: wall-clock
        # informational (±2x container band, PERF.md round 8), the
        # scraped sibling frame channel counters are the real-wire
        # confirmation of the model
        read_n = int(os.environ.get("SWTPU_BENCH_READN", str(2 * n)))
        print(json.dumps(bench_one(
            2, n, size, conc, cache_mb=32, read_mode="zipf",
            read_n=read_n, batch_size=32, trace=trace,
            scrape_frames=True)), flush=True)
        return
    if pipeline:
        # round-12 A/B: depth-8 multiplexed frame reads vs single
        # GETs over the same zipf order, cache on
        read_n = int(os.environ.get("SWTPU_BENCH_READN", str(3 * n)))
        depth = int(os.environ.get("SWTPU_BENCH_PIPELINE", "8"))
        for w in sweep:
            for d in (depth, 0):
                print(json.dumps(bench_one(
                    w, n, size, conc, cache_mb=32,
                    read_mode="zipf", read_n=read_n,
                    pipeline=d, trace=trace)), flush=True)
        return
    if batch:
        # round-9 A/B: multi-needle /batch vs single GET, zipf +
        # uniform read orders, cache on (the production shape)
        read_n = int(os.environ.get("SWTPU_BENCH_READN", str(3 * n)))
        bs = int(os.environ.get("SWTPU_BENCH_BATCH", "32"))
        for w in sweep:
            for mode in ("zipf", "shuffle"):
                for bsz in (bs, 0):
                    print(json.dumps(bench_one(
                        w, n, size, conc, cache_mb=32,
                        read_mode=mode, read_n=read_n,
                        batch_size=bsz, trace=trace)), flush=True)
        return
    if zipf:
        # Zipfian hot-read mix, 3 reads per written needle: the cache-on
        # vs cache-off rows are the BENCH_NEEDLE.md comparison
        read_n = int(os.environ.get("SWTPU_BENCH_READN", str(3 * n)))
        for w in sweep:
            for cache_mb in (32, 0):
                print(json.dumps(bench_one(
                    w, n, size, conc, cache_mb=cache_mb,
                    read_mode="zipf", read_n=read_n,
                    trace=trace)), flush=True)
        return
    for w in sweep:
        print(json.dumps(bench_one(w, n, size, conc, trace=trace)),
              flush=True)


if __name__ == "__main__":
    main()
