"""End-to-end EC pipeline ratio benchmark (BASELINE.json config 1).

Measures, on the SAME run so the arithmetic is checkable:
  1. host->device transfer GB/s through this tunnel/PJRT path,
  2. the raw kernel GB/s at the pipeline's buffer size,
  3. `write_ec_files` GB/s on a real .dat volume file (the reference's
     256KB streaming loop is ec_encoder.go:114-186; ours overlaps file
     reads, device transforms, and shard writes — ec/pipeline.py),
and prints one JSON line: pipeline vs min(kernel, transfer) bound.

Usage:  python tools/bench_pipeline.py [size_mb] [buffer_mb]
Env:    JAX_PLATFORMS=cpu for a harness self-test on the CPU backend.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    size_mb = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    buffer_mb = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    out: dict = {"metric": "ec_pipeline_GBps", "volume_mb": size_mb,
                 "buffer_mb": buffer_mb}

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # the axon sitecustomize force-registers the TPU tunnel no
        # matter what JAX_PLATFORMS says; jax.config wins at init time
        jax.config.update("jax_platforms", "cpu")
    out["backend"] = jax.default_backend()
    # tiny probe first: a wedged tunnel should fail here, not mid-run
    jax.device_put(np.ones(256, np.uint8)).block_until_ready()

    from seaweedfs_tpu.ec import gf
    from seaweedfs_tpu.ec import pipeline as ecpl
    from seaweedfs_tpu.ec.encoder_jax import JaxEncoder

    # 1. host->device GB/s (the tunnel bound the round-4 verdict asked
    # to publish): one buffer-sized device_put, repeated
    buf = np.random.default_rng(0).integers(
        0, 256, buffer_mb << 20).astype(np.uint8)
    jax.device_put(buf).block_until_ready()      # warm path
    t0 = time.perf_counter()
    iters = 4
    for _ in range(iters):
        jax.device_put(buf).block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    out["host_to_device_GBps"] = round(len(buf) / dt / 1e9, 3)

    # 2. kernel GB/s at the pipeline's working shape (one buffer split
    # into 10 data shards => buffer_mb/10 per shard)
    enc = JaxEncoder()
    shard = np.ascontiguousarray(
        buf[:(len(buf) // gf.DATA_SHARDS // 512 * 512) * gf.DATA_SHARDS]
        .reshape(gf.DATA_SHARDS, -1))
    dev = jax.device_put(shard)
    r = enc.encode(dev)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(iters):
        r = enc.encode(dev)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / iters
    out["kernel_GBps"] = round(shard.size / dt / 1e9, 3)

    # 3. the real file pipeline on a .dat volume
    tmp = tempfile.mkdtemp(prefix="swtpu_benchpipe_")
    base = os.path.join(tmp, "1")
    try:
        rng = np.random.default_rng(1)
        with open(base + ".dat", "wb") as f:
            left = size_mb << 20
            chunk = 64 << 20
            while left > 0:
                f.write(rng.integers(0, 256, min(chunk, left))
                        .astype(np.uint8).tobytes())
                left -= min(chunk, left)
        t0 = time.perf_counter()
        ecpl.write_ec_files(base, encoder=enc,
                            buffer_size=buffer_mb << 20)
        dt = time.perf_counter() - t0
        out["pipeline_GBps"] = round((size_mb << 20) / dt / 1e9, 3)
        out["pipeline_seconds"] = round(dt, 2)
        bound = min(out["kernel_GBps"], out["host_to_device_GBps"])
        out["bound_GBps"] = round(bound, 3)
        out["pipeline_vs_bound"] = round(
            out["pipeline_GBps"] / bound, 3) if bound else 0.0
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
