"""Failpoint-driven chaos soak: prove the resilience layer end to end.

Two scenarios:

``soak`` (default) boots a real CLI cluster (master + volume fleet on
private ports), arms failpoints over the live /debug/failpoints admin
endpoint (5% injected read/write errors, latency spikes, mid-body
truncations, replication fan-out faults), runs a mixed
write/read/delete workload, SIGKILLs one volume server mid-run, and
then asserts the two invariants that define user-visible durability
and availability:

  1. ZERO acknowledged-write loss — every fid whose upload was ACKed
     (and not deliberately deleted) reads back byte-identical at the
     end, through location failover past the killed server.
  2. BOUNDED client-observed error rate — retries + breakers must
     absorb the injected 5% fault rate; the workload's post-retry
     error rate must stay under --error-bound.

``ha`` is the multi-master quorum proof: 3 masters (raft ``-peers``,
fast election timings) + 2 volume servers under sustained
assign+write load; the LEADER is SIGKILLed mid-assign (twice in the
full run, with a respawn between), and a 5-way partition window is
armed through the raft failpoints (``master.vote`` / ``master.append``
/ ``master.snapshot`` = drop on the leader, flaky drops on the other
masters and the volume heartbeats) — processes alive, network lying.
Asserted across the ENTIRE run:

  1. ZERO lost acked writes (final byte-identical read-back);
  2. ZERO duplicate fids — every assign ever answered, including those
     whose upload then failed, is parsed to (vid, key) and checked
     globally unique (a deposed leader may drain its committed
     reservation window; a successor can never re-issue from it);
  3. failover completes within 2 election timeouts of each kill,
     cross-checked against the ``raft_leader_change`` journal rows on
     the survivors (`/debug/events`) and `/debug/health` reachability;
  4. the autopilot stays PARKED on every follower (no action ever
     executed from a non-leader).

    python tools/chaos.py               # full soak (~60s of load)
    python tools/chaos.py --quick       # CI smoke (~10s of load)
    python tools/chaos.py ha            # full quorum chaos (~50s)
    python tools/chaos.py ha --quick    # CI smoke: one leader kill

Exit code 0 only when every invariant holds.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import signal
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import procutil  # noqa: E402

Procs = procutil.Procs
wait_assign = procutil.wait_assign

BASE_PORT = 23400

# what gets armed on every volume server (spec grammar:
# action[=arg][:count][@probability] — util/failpoints.py)
VOLUME_FAILPOINTS = {
    "store.read": "error@0.04",
    "store.write": "error@0.04",
    "volume.read.http": "truncate=0.5@0.25",
    "volume.replicate": "error@0.03",
}
VOLUME_LATENCY = {"store.read": "latency=80@0.05"}  # alternate arming
MASTER_FAILPOINTS = {"master.assign": "latency=50@0.05"}


def http_json(url: str, method: str = "GET",
              timeout: float = 5.0) -> dict:
    req = urllib.request.Request(url, method=method)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.load(r)


def arm(addr: str, specs: dict[str, str], path="/debug/failpoints") -> None:
    for site, spec in specs.items():
        out = http_json(f"http://{addr}{path}?site={site}&spec={spec}",
                        method="POST")
        assert any(a["site"] == site for a in out.get("armed", [])), out


class Stats:
    def __init__(self):
        self.writes_ok = 0
        self.writes_err = 0
        self.reads_ok = 0
        self.reads_err = 0
        self.deletes = 0

    @property
    def ops(self) -> int:
        return self.writes_ok + self.writes_err + \
            self.reads_ok + self.reads_err

    @property
    def errors(self) -> int:
        return self.writes_err + self.reads_err

    def to_dict(self) -> dict:
        rate = self.errors / self.ops if self.ops else 0.0
        return {"writes_ok": self.writes_ok, "writes_err": self.writes_err,
                "reads_ok": self.reads_ok, "reads_err": self.reads_err,
                "deletes": self.deletes,
                "client_error_rate": round(rate, 4)}


async def workload(master: str, duration: float, concurrency: int,
                   stats: Stats, acked: dict, deleted: set,
                   rng: random.Random, kill_at: float,
                   kill_fn) -> None:
    from seaweedfs_tpu.util.client import OperationError, WeedClient
    stop_at = time.monotonic() + duration
    killed = False
    lock = asyncio.Lock()

    async with WeedClient(master) as c:
        async def worker(wid: int) -> None:
            nonlocal killed
            while time.monotonic() < stop_at:
                roll = rng.random()
                try:
                    if roll < 0.45 or not acked:
                        data = rng.randbytes(rng.randint(400, 24000))
                        fid = await c.upload_data(data,
                                                  replication="001")
                        async with lock:
                            acked[fid] = data
                            stats.writes_ok += 1
                    elif roll < 0.9:
                        fid = rng.choice(list(acked))
                        want = acked.get(fid)
                        try:
                            got = await c.read(fid)
                        except OperationError:
                            if fid in deleted:
                                continue   # raced a deleter: benign
                            raise
                        # re-check: a deleter may have tombstoned it
                        # between our pick and the read completing
                        if fid in deleted:
                            continue
                        if want is not None and got != want:
                            raise OperationError(
                                f"payload mismatch {fid}: "
                                f"{len(got)} vs {len(want)}")
                        stats.reads_ok += 1
                    else:
                        fid = rng.choice(list(acked))
                        async with lock:
                            if fid not in acked:
                                continue
                            del acked[fid]
                            deleted.add(fid)
                        await c.delete_fids([fid])
                        stats.deletes += 1
                except Exception as e:  # noqa: BLE001 — every failure counts
                    if roll < 0.45:
                        stats.writes_err += 1
                    else:
                        stats.reads_err += 1
                    if stats.errors <= 5:
                        print(f"  [w{wid}] op error: "
                              f"{type(e).__name__} {str(e)[:120]}")
                await asyncio.sleep(0)

        async def killer() -> None:
            nonlocal killed
            await asyncio.sleep(kill_at)
            kill_fn()
            killed = True

        await asyncio.gather(killer(),
                             *(worker(i) for i in range(concurrency)))


async def final_verify(master: str, acked: dict) -> list[str]:
    """Every acknowledged, undeleted write must read back byte-identical
    — through failover, with a patient fresh client."""
    from seaweedfs_tpu.util.client import WeedClient
    from seaweedfs_tpu.util.resilience import RetryPolicy
    lost: list[str] = []
    sem = asyncio.Semaphore(16)
    async with WeedClient(master, retry=RetryPolicy(
            max_attempts=6, base_delay=0.2, total_timeout=60)) as c:

        async def check(fid: str, want: bytes) -> None:
            async with sem:
                for attempt in range(4):
                    try:
                        got = await c.read(fid)
                        if got == want:
                            return
                        lost.append(f"{fid}: MISMATCH {len(got)} vs "
                                    f"{len(want)}")
                        return
                    except Exception as e:  # noqa: BLE001
                        if attempt == 3:
                            lost.append(f"{fid}: {type(e).__name__} "
                                        f"{str(e)[:100]}")
                            return
                        await asyncio.sleep(0.5 * (attempt + 1))

        await asyncio.gather(*(check(f, w) for f, w in acked.items()))
    return lost


async def run(args) -> int:
    tmp = tempfile.mkdtemp(prefix="chaos_")
    procs = Procs(tmp)
    n_servers = 3
    rng = random.Random(args.seed)
    report: dict = {"mode": "quick" if args.quick else "soak"}
    try:
        master = f"127.0.0.1:{BASE_PORT}"
        await procs.spawn("master", "-port", str(BASE_PORT),
                    "-mdir", os.path.join(tmp, "m"),
                    "-volumeSizeLimitMB", "8", "-pulseSeconds", "1",
                    "-defaultReplication", "001")
        await asyncio.sleep(2)
        for i in range(n_servers):
            # --slo arms a real objective on every server: without one
            # the engine is empty and /debug/health answers a
            # structurally-ok stub no matter how much damage the
            # failpoints do, which would make the recorder report a lie
            slo_flags = (("-slo", "volume.read:p99<250ms@99")
                         if args.slo else ())
            await procs.spawn("volume", "-port", str(BASE_PORT + 1 + i),
                        "-dir", os.path.join(tmp, f"v{i}"),
                        "-max", "20", "-master", master,
                        "-pulseSeconds", "1", *slo_flags)
        await wait_assign(master, "replication=001", tries=45)

        # runtime arming over the live admin endpoint (this also IS the
        # endpoint's integration test)
        arm(master, MASTER_FAILPOINTS)
        for i in range(n_servers):
            addr = f"127.0.0.1:{BASE_PORT + 1 + i}"
            arm(addr, VOLUME_FAILPOINTS)
        # one server additionally gets latency spikes
        arm(f"127.0.0.1:{BASE_PORT + 1}", VOLUME_LATENCY)
        print(f"armed failpoints on master + {n_servers} volume servers")

        stats = Stats()
        acked: dict = {}
        deleted: set = set()
        duration = 10.0 if args.quick else 60.0
        kill_at = duration * 0.5
        victim = procs.procs[1 + n_servers - 1]   # last volume server

        def kill_victim() -> None:
            print(f"  SIGKILL volume server pid {victim.pid} "
                  f"(port {BASE_PORT + n_servers})")
            victim.send_signal(signal.SIGKILL)

        t0 = time.monotonic()
        await workload(master, duration, args.concurrency, stats,
                       acked, deleted, rng, kill_at, kill_victim)
        elapsed = time.monotonic() - t0

        report["stats"] = stats.to_dict()
        report["acked"] = len(acked)
        report["deleted"] = len(deleted)
        report["elapsed_s"] = round(elapsed, 1)
        print(f"workload done in {elapsed:.1f}s: {report['stats']}")
        if stats.writes_ok < (10 if args.quick else 100):
            print("FAIL: workload acked too few writes to prove anything")
            report["verdict"] = "FAIL(too few writes)"
            return 1

        # collect fired-failpoint + breaker evidence from survivors
        fired = {}
        for i in range(n_servers - 1):
            addr = f"127.0.0.1:{BASE_PORT + 1 + i}"
            try:
                for a in http_json(
                        f"http://{addr}/debug/failpoints")["failpoints"]:
                    fired[a["site"]] = fired.get(a["site"], 0) + a["hits"]
            except OSError:
                pass
        report["failpoint_hits"] = fired
        print(f"failpoint hits (surviving servers): {fired}")

        if args.trace:
            # per-tier latency breakdown from the survivors' span
            # rings: under injected faults this is where the retry /
            # failover time shows up as client-tier self time. ONE
            # fetch round — table and JSON report must describe the
            # same ring snapshot
            import trace_table
            addrs = [f"127.0.0.1:{BASE_PORT + 1 + i}"
                     for i in range(n_servers - 1)]
            rows = trace_table.rows_from_payloads(
                [p for p in (trace_table.fetch(a) for a in addrs) if p])
            print("--- per-tier trace breakdown (survivors) ---")
            print(trace_table.render(rows))
            report["trace_breakdown"] = rows
        if args.slo:
            # flight-recorder pull from the survivors: one forced
            # timeline window covering the run, the merged journal, and
            # the health verdict — the chaos report carries what the
            # cluster SAW, not just what the driver measured
            recorder = {}
            for i in range(n_servers - 1):
                addr = f"127.0.0.1:{BASE_PORT + 1 + i}"
                try:
                    http_json(f"http://{addr}/debug/timeline?snap=1",
                              method="POST")
                    h = http_json(f"http://{addr}/debug/health")
                    ev = http_json(f"http://{addr}/debug/events?n=50")
                    recorder[addr] = {
                        "health": h["status"],
                        "objectives": h.get("objectives", []),
                        "event_types": sorted(
                            {e["type"] for e in ev["events"]})}
                except (OSError, ValueError, KeyError):
                    continue
            report["recorder"] = recorder
            for addr, rec in recorder.items():
                print(f"recorder {addr}: health={rec['health']} "
                      f"events={rec['event_types']}")
        if not args.quick and not any(fired.values()):
            print("FAIL: no failpoint ever fired — the chaos run "
                  "tested nothing")
            report["verdict"] = "FAIL(no faults injected)"
            return 1

        # invariant 1: zero acknowledged-write loss
        lost = await final_verify(master, acked)
        report["lost"] = len(lost)
        for line in lost[:10]:
            print("  LOST:", line)

        # invariant 2: bounded client-observed error rate
        rate = report["stats"]["client_error_rate"]
        ok = not lost and rate <= args.error_bound
        report["verdict"] = "PASS" if ok else "FAIL"
        print(f"acked={len(acked)} lost={len(lost)} "
              f"err_rate={rate:.3f} (bound {args.error_bound}) "
              f"-> {report['verdict']}")
        return 0 if ok else 1
    finally:
        procs.kill_all()

        def teardown() -> None:
            if args.json:
                with open(args.json, "w") as f:
                    json.dump(report, f, indent=2)
            if not args.keep:
                import shutil
                shutil.rmtree(tmp, ignore_errors=True)

        # teardown I/O off the loop: pending client tasks may still be
        # draining their cancellations on it
        from seaweedfs_tpu.util import tracing
        await tracing.run_in_executor(teardown)
        if args.keep:
            print("logs under", tmp)


# ---------------------------------------------------------------------------
# ha: multi-master quorum chaos (leader SIGKILLs + partition window)
# ---------------------------------------------------------------------------

HA_PORT = 23500
HA_TIMEOUT = (0.5, 1.0)          # -raft.timeout armed on every master
HA_PULSE = 0.1                   # -raft.pulse


def cluster_status(addr: str) -> dict:
    return http_json(f"http://{addr}/cluster/status", timeout=3)


async def _wait_ha_leader(masters: list[str], exclude: str = "",
                          timeout: float = 30.0) -> tuple[str, float]:
    """Poll the fleet until one live master claims leadership (not
    `exclude`); returns (leader, seconds waited)."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        for m in masters:
            try:
                st = await asyncio.to_thread(cluster_status, m)
            except OSError:
                continue
            if st.get("isLeader") and st["leader"] != exclude:
                return st["leader"], time.monotonic() - t0
        await asyncio.sleep(0.05)
    raise RuntimeError(f"no leader elected within {timeout}s")


async def run_ha(args) -> int:
    from seaweedfs_tpu.storage.types import FileId
    from seaweedfs_tpu.util.client import WeedClient
    from seaweedfs_tpu.util.resilience import RetryPolicy

    tmp = tempfile.mkdtemp(prefix="chaos_ha_")
    procs = Procs(tmp)
    rng = random.Random(args.seed)
    masters = [f"127.0.0.1:{HA_PORT + i}" for i in range(3)]
    vols = [f"127.0.0.1:{HA_PORT + 10 + i}" for i in range(2)]
    report: dict = {"mode": "ha-quick" if args.quick else "ha",
                    "failovers": [], "kills": 0}
    margin = 0.5                  # poll granularity + heartbeat slack
    bound = 2 * HA_TIMEOUT[1] + margin

    def master_args(i: int) -> tuple:
        return ("master", "-port", str(HA_PORT + i),
                "-mdir", os.path.join(tmp, f"m{i}"),
                "-peers", ",".join(masters),
                "-raft.timeout", f"{HA_TIMEOUT[0]},{HA_TIMEOUT[1]}",
                "-raft.pulse", str(HA_PULSE),
                "-volumeSizeLimitMB", "8", "-pulseSeconds", "0.5",
                "-defaultReplication", "001",
                # dry-run autopilot on every master: the run asserts it
                # only ever cycles on the leader
                "-autopilot.interval", "1", "-autopilot.dryrun")

    try:
        mprocs = {}
        for i in range(3):
            mprocs[i] = await procs.spawn(*master_args(i))
        await asyncio.sleep(2.5)       # first election
        for i, v in enumerate(vols):
            await procs.spawn("volume", "-port", str(HA_PORT + 10 + i),
                              "-dir", os.path.join(tmp, f"v{i}"),
                              "-max", "20",
                              "-master", ",".join(masters),
                              "-pulseSeconds", "0.5")
        await wait_assign(masters[0], "replication=001", tries=45)

        stats = Stats()
        issued: list[str] = []         # EVERY fid any assign answered
        acked: dict = {}
        stop = asyncio.Event()
        lock = asyncio.Lock()

        async with WeedClient(",".join(masters)) as c:
            async def writer(wid: int) -> None:
                while not stop.is_set():
                    data = rng.randbytes(rng.randint(400, 16000))
                    try:
                        a = await c.assign(replication="001")
                        async with lock:
                            issued.append(a["fid"])
                        await c.upload(a["fid"], a["url"], data,
                                       auth=a.get("auth", ""))
                        async with lock:
                            acked[a["fid"]] = data
                            stats.writes_ok += 1
                    except Exception as e:  # noqa: BLE001 — counted
                        stats.writes_err += 1
                        if stats.writes_err <= 5:
                            print(f"  [w{wid}] write error: "
                                  f"{type(e).__name__} {str(e)[:100]}")
                        await asyncio.sleep(0.1)
                    await asyncio.sleep(0)

            writers = [asyncio.create_task(writer(i))
                       for i in range(args.concurrency)]

            async def kill_leader(round_no: int) -> str:
                leader, _ = await _wait_ha_leader(
                    [m for i, m in enumerate(masters)
                     if mprocs[i].poll() is None])
                li = masters.index(leader)
                print(f"  SIGKILL leader #{round_no} master{li} "
                      f"({leader}) mid-assign")
                mprocs[li].send_signal(signal.SIGKILL)
                report["kills"] += 1
                new_leader, waited = await _wait_ha_leader(
                    [m for m in masters if m != leader],
                    exclude=leader)
                print(f"  new leader {new_leader} after {waited:.2f}s "
                      f"(bound {bound:.1f}s)")
                report["failovers"].append(
                    {"killed": leader, "leader": new_leader,
                     "seconds": round(waited, 2)})
                return new_leader

            await asyncio.sleep(3)                 # load before chaos
            new_leader = await kill_leader(1)

            if not args.quick:
                # respawn the victim (same -mdir: durable raft state)
                # so the quorum is back to 3/3 before the second kill
                dead = masters.index(report["failovers"][0]["killed"])
                mprocs[dead] = await procs.spawn(*master_args(dead))
                await asyncio.sleep(3)

                # ---- 5-way partition window: every process keeps
                # running, the network starts lying. The leader drops
                # ALL outbound raft RPCs (lease expiry forces a step
                # down + re-election); the other masters and both
                # volume heartbeats get flaky drops.
                leader = new_leader
                part = {"master.vote": "drop:*", "master.append":
                        "drop:*", "master.snapshot": "drop:*"}
                arm(leader, part)
                for m in masters:
                    if m != leader and \
                            mprocs[masters.index(m)].poll() is None:
                        arm(m, {"master.append": "drop@0.3"})
                for v in vols:
                    arm(v, {"volume.heartbeat": "drop@0.5"})
                print(f"  5-way partition window armed "
                      f"(leader {leader} fully cut outbound)")
                t_cut = time.time()
                successor, waited = await _wait_ha_leader(
                    [m for m in masters if m != leader],
                    exclude=leader, timeout=20)
                print(f"  partition: successor {successor} after "
                      f"{waited:.2f}s")
                await asyncio.sleep(2)
                for node in masters + vols:
                    try:
                        http_json(f"http://{node}/debug/failpoints",
                                  method="DELETE")
                    except OSError:
                        pass
                report["partition"] = {
                    "cut_leader": leader, "successor": successor,
                    "window_s": round(time.time() - t_cut, 1),
                    "elected_in_s": round(waited, 2)}
                await asyncio.sleep(2)             # heal + re-home

                await kill_leader(2)

            await asyncio.sleep(3)                 # post-chaos load
            stop.set()
            await asyncio.gather(*writers, return_exceptions=True)

        alive = [m for i, m in enumerate(masters)
                 if mprocs[i].poll() is None]
        final_leader, _ = await _wait_ha_leader(alive)

        # ---- invariant 3: failover bound + journal/health evidence
        ok = True
        for f in report["failovers"]:
            if f["seconds"] > bound:
                print(f"  FAIL: failover after killing {f['killed']} "
                      f"took {f['seconds']}s > {bound:.1f}s")
                ok = False
        changes, step_downs = [], []
        for m in alive:
            try:
                ev = http_json(f"http://{m}/debug/events?n=500"
                               f"&type=raft_leader_change,raft_step_down")
                for row in ev["events"]:
                    (changes if row["type"] == "raft_leader_change"
                     else step_downs).append(row)
            except OSError:
                pass
        leaders_seen = {r.get("leader") for r in changes}
        report["journal"] = {
            "leader_changes": len(changes),
            "step_downs": len(step_downs),
            "leaders_seen": sorted(x for x in leaders_seen if x)}
        print(f"  journal: {len(changes)} raft_leader_change rows "
              f"({len(leaders_seen)} leaders), "
              f"{len(step_downs)} raft_step_down")
        for f in report["failovers"]:
            if f["leader"] not in leaders_seen:
                print(f"  FAIL: no raft_leader_change journal row for "
                      f"elected leader {f['leader']}")
                ok = False
        if not args.quick and not step_downs:
            print("  FAIL: partition window never journaled a "
                  "raft_step_down on the cut leader")
            ok = False
        health = http_json(f"http://{final_leader}/debug/health")
        report["final_leader"] = {"url": final_leader,
                                  "health": health.get("status", "?")}

        # ---- invariant 4: autopilot parked on every follower
        for m in alive:
            try:
                ap = http_json(
                    f"http://{m}/debug/autopilot")["autopilot"]
            except OSError:
                continue
            if m != final_leader:
                executed = ap["actions_ok"] + ap["actions_failed"]
                if ap["leader"] or ap["in_flight"] or executed:
                    print(f"  FAIL: follower {m} autopilot not parked: "
                          f"leader={ap['leader']} "
                          f"in_flight={ap['in_flight']} "
                          f"executed={executed}")
                    ok = False
        print(f"  autopilot parked on "
              f"{len(alive) - 1} followers (leader {final_leader})")

        # ---- invariant 2: ZERO duplicate fids across the whole run
        keys: dict = {}
        dups = []
        for fid in issued:
            k = None
            try:
                f = FileId.parse(fid)
                k = (f.volume_id, f.key)
            except ValueError:
                dups.append(f"unparseable fid {fid!r}")
                continue
            if k in keys:
                dups.append(f"duplicate (vid,key) {k}: "
                            f"{keys[k]!r} vs {fid!r}")
            keys[k] = fid
        report["issued"] = len(issued)
        report["duplicates"] = len(dups)
        for line in dups[:10]:
            print("  DUP:", line)

        # ---- invariant 1: ZERO lost acked writes
        async def patient_verify() -> list[str]:
            lost: list[str] = []
            sem = asyncio.Semaphore(16)
            async with WeedClient(",".join(alive),
                                  retry=RetryPolicy(
                                      max_attempts=6, base_delay=0.2,
                                      total_timeout=60)) as vc:
                async def check(fid: str, want: bytes) -> None:
                    async with sem:
                        for attempt in range(4):
                            try:
                                got = await vc.read(fid)
                                if got != want:
                                    lost.append(
                                        f"{fid}: MISMATCH {len(got)} "
                                        f"vs {len(want)}")
                                return
                            except Exception as e:  # noqa: BLE001
                                if attempt == 3:
                                    lost.append(
                                        f"{fid}: {type(e).__name__} "
                                        f"{str(e)[:80]}")
                                    return
                                await asyncio.sleep(0.5 * (attempt + 1))
                await asyncio.gather(*(check(f, w)
                                       for f, w in acked.items()))
            return lost
        lost = await patient_verify()
        report["stats"] = stats.to_dict()
        report["acked"] = len(acked)
        report["lost"] = len(lost)
        for line in lost[:10]:
            print("  LOST:", line)

        min_writes = 20 if args.quick else 100
        if stats.writes_ok < min_writes:
            print(f"FAIL: only {stats.writes_ok} acked writes — too "
                  f"few to prove anything")
            ok = False
        ok = ok and not lost and not dups
        report["verdict"] = "PASS" if ok else "FAIL"
        print(f"ha: issued={len(issued)} acked={len(acked)} "
              f"lost={len(lost)} dups={len(dups)} kills="
              f"{report['kills']} failovers="
              f"{[f['seconds'] for f in report['failovers']]}s "
              f"-> {report['verdict']}")
        return 0 if ok else 1
    finally:
        procs.kill_all()

        def teardown() -> None:
            if args.json:
                with open(args.json, "w") as f:
                    json.dump(report, f, indent=2)
            if not args.keep:
                import shutil
                shutil.rmtree(tmp, ignore_errors=True)
        from seaweedfs_tpu.util import tracing
        await tracing.run_in_executor(teardown)
        if args.keep:
            print("logs under", tmp)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("scenario", nargs="?", default="soak",
                    choices=("soak", "ha"),
                    help="soak = data-plane chaos (default); "
                         "ha = multi-master quorum chaos")
    ap.add_argument("--quick", action="store_true",
                    help="~10s CI smoke instead of the full soak")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--error-bound", type=float, default=0.20,
                    help="max post-retry client error rate")
    ap.add_argument("--seed", type=int, default=1337)
    ap.add_argument("--trace", action="store_true",
                    help="pull /debug/traces from the surviving volume "
                         "servers and print the per-tier latency "
                         "breakdown table")
    ap.add_argument("--slo", action="store_true",
                    help="pull the flight recorder (/debug/timeline + "
                         "/debug/events + /debug/health) from the "
                         "surviving volume servers into the report")
    ap.add_argument("--json", help="write the report to this path")
    ap.add_argument("--keep", action="store_true",
                    help="keep tmpdir + server logs")
    args = ap.parse_args()
    if args.scenario == "ha":
        return asyncio.run(run_ha(args))
    return asyncio.run(run(args))


if __name__ == "__main__":
    sys.exit(main())
