#!/usr/bin/env bash
# One-command gate: weedlint (enforced tree, JSON-consumed) +
# weedlint over tests/ (report-only) + the tier-1 test suite.
# Usage: tools/ci.sh [extra pytest args]
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== weedlint: enforced tree (seaweedfs_tpu tools, two-phase) =="
WL_JSON=$(mktemp)
wl_start=$(date +%s)
python -m tools.weedlint seaweedfs_tpu tools --jobs auto \
    --format json > "$WL_JSON"
wl_rc=$?
wl_secs=$(( $(date +%s) - wl_start ))
python - "$WL_JSON" <<'PY'
import json, sys
r = json.load(open(sys.argv[1]))
gating = [f for f in r["findings"]
          if not f["suppressed"] and not f["baselined"]]
for f in gating:
    print(f"  {f['path']}:{f['line']}: [{f['rule']}] {f['message']}")
for e in r["stale_baseline"]:
    print(f"  stale baseline entry: {e['path']} [{e['rule']}] "
          f"{e['code']!r}")
for msg in r["baseline_errors"]:
    print(f"  {msg}")
if r["summary"]:
    counts = " ".join(f"{k}={v}" for k, v in r["summary"].items())
    print(f"  {len(gating)} new finding(s): {counts}")
else:
    print("  clean")
PY
rm -f "$WL_JSON"
if [ "$wl_rc" -ne 0 ]; then
    echo "weedlint: FAILED (new findings — fix, suppress with a"
    echo "reason, or baseline with a justification; see"
    echo "STATIC_ANALYSIS.md)"
    exit "$wl_rc"
fi
# wall-clock budget: the whole-tree two-phase run (symbol table +
# call graph included) must stay a sub-minute gate, or people stop
# running it pre-commit. Override for slow CI hosts with WL_BUDGET_S.
WL_BUDGET_S=${WL_BUDGET_S:-30}
echo "  whole-tree run: ${wl_secs}s (budget ${WL_BUDGET_S}s)"
if [ "$wl_secs" -gt "$WL_BUDGET_S" ]; then
    echo "weedlint: FAILED (${wl_secs}s exceeds the ${WL_BUDGET_S}s"
    echo "wall-clock budget — profile the new pass, or raise"
    echo "WL_BUDGET_S with a justification in the PR)"
    exit 1
fi

echo "== weedlint: tests/ (enforced safe subset + advisory rest) =="
# exception/task/fd hygiene applies to test code too; the remaining
# rules stay report-only over tests/ (fixtures legitimately trip them)
# no `tail` here: an enforced gate must show the file:line findings
if ! python -m tools.weedlint tests \
        --select tests-enforced \
        --no-baseline; then
    echo "weedlint: FAILED (tests/ violate the enforced subset —"
    echo "see TESTS_ENFORCED_RULE_IDS in tools/weedlint/rules; fix"
    echo "or suppress with a reason)"
    exit 1
fi
python -m tools.weedlint tests --report-only --no-baseline | tail -n 1

echo "== weedsched --quick (interleaving explorer: cores green, seeded bugs caught) =="
# the dynamic half of the phase-3 cancellation gate: real protocol
# cores must hold their invariants under permuted schedules + injected
# cancellation, and the two seeded known-bug fixtures MUST be detected
# (a green fixture means the explorer lost its teeth). WS_BUDGET_S
# bounds the quick corpus the same way WL_BUDGET_S bounds weedlint.
WS_BUDGET_S=${WS_BUDGET_S:-60}
if ! timeout -k 10 $((WS_BUDGET_S + 30)) env JAX_PLATFORMS=cpu \
        WS_BUDGET_S="$WS_BUDGET_S" python -m tools.weedsched --quick; then
    echo "weedsched: FAILED (a protocol core broke an invariant under"
    echo "some schedule/cancellation — the minimized trace above is a"
    echo "deterministic repro — or a seeded fixture went undetected,"
    echo "or the quick corpus blew WS_BUDGET_S; see STATIC_ANALYSIS.md)"
    exit 1
fi

echo "== wire smoke (batch + group commit + sendfile + frame hop) =="
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu python tools/wire_smoke.py; then
    echo "wire smoke: FAILED (data-plane regression — see output above)"
    exit 1
fi

echo "== flight-recorder smoke (timeline + events + health, -workers 2) =="
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu python tools/recorder_smoke.py; then
    echo "recorder smoke: FAILED (schema drift on /debug/timeline,"
    echo "/debug/events or /debug/health — soaks and operator tooling"
    echo "assert these shapes; see output above)"
    exit 1
fi

echo "== frame fabric smoke (inter-host frames + HTTP fallback + HELLO auth) =="
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu python tools/fabric_smoke.py; then
    echo "fabric smoke: FAILED (inter-host frame fabric regression —"
    echo "replica fan-out must ride frames byte-identically, survive a"
    echo "severed frame leg over HTTP, and a jwt-secured master must"
    echo "refuse unauthenticated HELLOs; see output above)"
    exit 1
fi

echo "== ec smoke (repair bandwidth + stripe-batch engine + bake-off) =="
if ! timeout -k 10 240 env JAX_PLATFORMS=cpu python tools/bench_ec.py --smoke; then
    echo "bench_ec smoke: FAILED (EC regression — minimal-fetch must"
    echo "move strictly fewer bytes than the all-survivor gather, the"
    echo "stripe-batch engine must stay byte-identical within"
    echo "<= ceil(W/B) dispatches + fewer preads on encode/scrub/"
    echo "rebuild, and every backend must match the numpy oracle)"
    exit 1
fi

echo "== autopilot heal smoke (soak heal --quick: rot + holder kill -> converge) =="
if ! timeout -k 10 480 env JAX_PLATFORMS=cpu python tools/soak.py heal --quick; then
    echo "heal smoke: FAILED (the autopilot did not converge the fleet"
    echo "back to full redundancy — planted rot must be scrub-localized"
    echo "and rebuilt, the killed holder's shards re-hosted, foreground"
    echo "reads untouched, and the dry-run ledger must match executed"
    echo "actions; see output above)"
    exit 1
fi

echo "== ha quorum smoke (chaos ha --quick: leader SIGKILL -> zero lost/dup fids) =="
if ! timeout -k 10 240 env JAX_PLATFORMS=cpu python tools/chaos.py ha --quick; then
    echo "ha smoke: FAILED (the master quorum lost or duplicated a fid"
    echo "across a leader kill, failover blew the 2-election-timeout"
    echo "bound, or the autopilot ran on a follower; see output above)"
    exit 1
fi

echo "== qos smoke (soak qos --quick: abuser shed, paying SLO holds, arbiter budget) =="
if ! timeout -k 10 240 env JAX_PLATFORMS=cpu python tools/soak.py qos --quick; then
    echo "qos smoke: FAILED (multi-tenant admission regression — the"
    echo "paying tenant's objective must hold while a flooding abuser"
    echo "is throttled/shed on its own class, the qos.admit failpoint"
    echo "must answer an honest 503 + Retry-After, the abuser must be"
    echo "readmitted after the flood stops, and every acked write must"
    echo "read back byte-identical; see output above)"
    exit 1
fi

echo "== meta smoke (soak meta --quick: sharded filer QPS + split under chaos) =="
if ! timeout -k 10 480 env JAX_PLATFORMS=cpu python tools/soak.py meta --quick; then
    echo "meta smoke: FAILED (sharded filer metadata plane regression —"
    echo "op-accounted aggregate QPS must scale >= 3x at 4 shards with"
    echo "local-serve counters proving routing, an online split must"
    echo "survive armed filer.shard.* failpoints plus a SIGKILL of the"
    echo "source filer by replaying the raft-committed move journal, and"
    echo "the final paged enumeration must hold every entry exactly"
    echo "once; see output above)"
    exit 1
fi

echo "== tier-1 tests =="
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly "$@"
