"""~20s inter-host frame-fabric smoke for tools/ci.sh.

Boots a REAL master (-defaultReplication 001) + two volume servers as
CLI processes and proves the cluster fabric end to end:

  1. replicated writes enter over HTTP; the volume->volume fan-out hop
     rides the frame fabric, and BOTH holders serve byte-identical
     bodies;
  2. the live /metrics confirm hop-labeled inter-host frame traffic
     (SeaweedFS_frame_requests_total{hop="interhost",...} > 0) — the
     heartbeat, lookup and fan-out hops really used the wire;
  3. with `replication.frame` armed (error) on every server the frame
     leg is severed: writes still replicate byte-identically over the
     HTTP fallback, and the armed site's hit counter proves the frame
     leg was actually cut (not silently skipped);
  4. a jwt-secured master refuses an identity-less AND a wrong-key
     frame HELLO at the handshake — before any request payload — while
     the correct key is served.

Fabric regressions fail here in seconds, before tier-1 runs.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
PORT = int(os.environ.get("SWTPU_SMOKE_PORT", "22150"))


def get_json(addr: str, path: str, method: str = "GET") -> dict:
    req = urllib.request.Request(f"http://{addr}{path}", method=method)
    with urllib.request.urlopen(req, timeout=15) as r:
        return json.load(r)


def wait_assign(master: str, tries: int = 60) -> None:
    for _ in range(tries):
        try:
            with urllib.request.urlopen(
                    f"http://{master}/dir/assign", timeout=3) as r:
                if b"fid" in r.read():
                    return
        except OSError:
            pass
        time.sleep(0.5)
    raise RuntimeError("cluster never became assignable")


def check(cond: bool, what: str) -> None:
    if not cond:
        raise AssertionError(f"fabric smoke: {what}")


def write_replicated(master: str, body: bytes) -> str:
    a = get_json(master, "/dir/assign?replication=001")
    check("fid" in a, f"assign failed: {a}")
    req = urllib.request.Request(
        f"http://{a['url']}/{a['fid']}", data=body, method="POST",
        headers={"X-Raw-Needle": "0"})
    with urllib.request.urlopen(req, timeout=10) as r:
        check(r.status in (200, 201), f"write {r.status}")
    return a["fid"]


def read_from(vol: str, fid: str) -> bytes:
    with urllib.request.urlopen(f"http://{vol}/{fid}", timeout=10) as r:
        check(r.status == 200, f"read {fid} from {vol}: {r.status}")
        return r.read()


def frame_counters(addr: str) -> dict:
    """hop-labeled SeaweedFS_frame_requests_total rows from /metrics."""
    with urllib.request.urlopen(f"http://{addr}/metrics",
                                timeout=10) as r:
        body = r.read().decode()
    out: dict = {}
    for line in body.splitlines():
        if line.startswith("SeaweedFS_frame_requests_total"):
            key, _, val = line.rpartition(" ")
            out[key] = out.get(key, 0.0) + float(val)
    return out


def hello_refusal_check(tmp: str, env: dict) -> None:
    """A jwt-secured master must refuse identity-less / wrong-key frame
    HELLOs at the handshake and serve the correct key."""
    from seaweedfs_tpu.util.frame import FrameChannel, FrameChannelError

    port = PORT + 10
    log = open(os.path.join(tmp, "jwtmaster.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu.cli", "master",
         "-port", str(port), "-mdir", os.path.join(tmp, "mjwt"),
         "-pulseSeconds", "1", "-jwtKey", "fabric-smoke-secret"],
        stdout=log, stderr=subprocess.STDOUT, env=env, cwd=tmp)
    target = f"127.0.0.1:{port}"
    try:
        # a bare master (no volumes) can't assign: probe /cluster/status
        for _ in range(60):
            try:
                if "leader" in get_json(target, "/cluster/status"):
                    break
            except OSError:
                pass
            time.sleep(0.5)
        else:
            raise RuntimeError("jwt master never came up")

        async def drive():
            for key, want_refused in (("", True),
                                      ("wrong-secret", True),
                                      ("fabric-smoke-secret", False)):
                chan = FrameChannel(target=target, jwt_key=key)
                try:
                    status, _, _ = await chan.request(
                        "GET", "/dir/lookup",
                        query={"volumeId": "1"}, timeout=5.0)
                    refused = False
                except FrameChannelError as e:
                    refused = "handshake refused" in str(e)
                    check(refused, f"unexpected channel error: {e}")
                finally:
                    await chan.close()
                check(refused == want_refused,
                      f"jwt key {key!r}: refused={refused}, "
                      f"wanted {want_refused}")

        asyncio.run(drive())
        print("  hello: identity-less + wrong-key HELLOs refused at "
              "handshake, correct key served")
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="swtpu_fabric_smoke_")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    master = f"127.0.0.1:{PORT}"
    vols = [f"127.0.0.1:{PORT + 1}", f"127.0.0.1:{PORT + 2}"]
    procs: list[subprocess.Popen] = []

    def spawn(*args: str) -> None:
        log = open(os.path.join(tmp, f"proc{len(procs)}.log"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", *args],
            stdout=log, stderr=subprocess.STDOUT, env=env, cwd=tmp))

    try:
        spawn("master", "-port", str(PORT), "-mdir",
              os.path.join(tmp, "m"), "-pulseSeconds", "1",
              "-defaultReplication", "001")
        time.sleep(1.5)
        for i, vol in enumerate(vols):
            spawn("volume", "-port", vol.rsplit(":", 1)[1], "-dir",
                  os.path.join(tmp, f"v{i}"), "-max", "10",
                  "-master", master, "-pulseSeconds", "1")
        wait_assign(master)

        # -- 1. replicated writes: fan-out rides frames ----------------
        blobs = {}
        for i in range(6):
            body = f"fabric-{i}-".encode() * (64 + i)
            blobs[write_replicated(master, body)] = body
        for fid, body in blobs.items():
            got = [read_from(v, fid) for v in vols]
            check(got[0] == got[1] == body,
                  f"replica bodies diverge for {fid}")
        print(f"  fanout: {len(blobs)} replicated writes, both holders "
              f"byte-identical")

        # -- 2. live wire evidence: hop-labeled frame counters ---------
        rows: dict = {}
        for v in vols:
            for k, n in frame_counters(v).items():
                rows[k] = rows.get(k, 0.0) + n
        inter_client = sum(n for k, n in rows.items()
                           if 'hop="interhost"' in k
                           and 'side="client"' in k)
        inter_server = sum(n for k, n in rows.items()
                           if 'hop="interhost"' in k
                           and 'side="server"' in k)
        check(inter_client > 0,
              f"no client-side interhost frame traffic (saw {rows})")
        check(inter_server > 0,
              f"no server-side interhost frame traffic (saw {rows})")
        print(f"  wire: interhost frames client={int(inter_client)} "
              f"server={int(inter_server)}")

        # -- 3. sever the frame leg: HTTP fallback, still identical ----
        for v in vols:
            out = get_json(v, "/debug/failpoints?site=replication.frame"
                              "&spec=error:*", method="POST")
            check(any(a["site"] == "replication.frame"
                      for a in out.get("armed", [])), f"arm failed: {out}")
        blobs2 = {}
        for i in range(4):
            body = f"fallback-{i}-".encode() * (64 + i)
            blobs2[write_replicated(master, body)] = body
        for fid, body in blobs2.items():
            got = [read_from(v, fid) for v in vols]
            check(got[0] == got[1] == body,
                  f"HTTP-fallback replica bodies diverge for {fid}")
        hits = 0
        for v in vols:
            for a in get_json(v, "/debug/failpoints")["failpoints"]:
                if a["site"] == "replication.frame":
                    hits += a["hits"]
            get_json(v, "/debug/failpoints?site=replication.frame",
                     method="DELETE")
        check(hits >= len(blobs2),
              f"armed replication.frame fired {hits} < {len(blobs2)} — "
              f"the frame leg was not actually severed")
        print(f"  fallback: {len(blobs2)} writes with the frame leg cut "
              f"({hits} fires), replicas still byte-identical over HTTP")

        # -- 4. HELLO auth on a jwt-secured master ---------------------
        hello_refusal_check(tmp, env)
        print("fabric smoke: OK")
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        time.sleep(1)


if __name__ == "__main__":
    sys.exit(main())
