"""Back-compat shim over tools/weedlint/ (the original 3-pass lint
grew into the multi-pass framework there — see STATIC_ANALYSIS.md).

Kept because CHANGES.md, ROBUSTNESS.md and muscle memory reference
this path. It runs exactly the original three passes (silent broad
exceptions, metrics hygiene, span-finish-in-finally) via the shared
weedlint driver and keeps the original string-list API:

    python tools/lint_robustness.py [path ...]

For everything else — the asyncio/resource/cache rules, suppressions,
baseline, JSON — use ``python -m tools.weedlint``.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:
    from tools.weedlint import LEGACY_RULE_IDS, make_rules, run_paths
except ImportError:                      # run as a script from tools/
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from weedlint import LEGACY_RULE_IDS, make_rules, run_paths

DEFAULT_PATHS = [os.path.join(REPO, "seaweedfs_tpu", "server"),
                 os.path.join(REPO, "seaweedfs_tpu", "stats")]


def _findings(paths: list[str]):
    rules = make_rules(select=LEGACY_RULE_IDS)
    return [f for f in run_paths(paths, rules, check_unused=False)
            if not f.suppressed]


def lint_file(path: str) -> list[str]:
    return [f"{f.path}:{f.line}: {f.message}" for f in _findings([path])]


def lint_paths(paths: list[str]) -> list[str]:
    return [f"{f.path}:{f.line}: {f.message}" for f in _findings(paths)]


def main(argv: list[str]) -> int:
    findings = _findings(argv or DEFAULT_PATHS)
    for f in findings:
        print(f"{f.path}:{f.line}: {f.message}")
    if findings:
        # summary counts per rule — the old single-line summary called
        # every finding a "silent broad exception handler" even when it
        # was a metric/span problem
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        parts = " ".join(f"{r}={n}" for r, n in sorted(counts.items()))
        print(f"{len(findings)} finding(s): {parts}")
        return 1
    print("robustness lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
