"""AST lint: forbid silently-swallowed broad exceptions.

Flags any ``except`` handler that (a) catches ``Exception`` /
``BaseException`` or is a bare ``except:``, AND (b) whose body is only
``pass`` / ``continue`` — the shape that turns real faults invisible.
Narrow handlers may still swallow (that is often correct: idempotent
deletes, probe loops); broad ones must at least log.

Run as a tier-1 test (tests/test_robustness_lint.py) over
``seaweedfs_tpu/server/`` so the data plane can never regress, or by
hand over any path:

    python tools/lint_robustness.py [path ...]
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATHS = [os.path.join(REPO, "seaweedfs_tpu", "server")]

BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True                          # bare except:
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in BROAD:
            return True
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(s, (ast.Pass, ast.Continue))
               for s in handler.body)


def lint_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    problems = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                and _is_silent(node):
            what = "bare except" if node.type is None \
                else "except Exception"
            problems.append(
                f"{path}:{node.lineno}: silent {what}: pass — narrow "
                f"the exception type and/or glog the fault")
    return problems


def lint_paths(paths: list[str]) -> list[str]:
    problems: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            problems += lint_file(p)
            continue
        for root, _dirs, files in os.walk(p):
            for name in sorted(files):
                if name.endswith(".py"):
                    problems += lint_file(os.path.join(root, name))
    return problems


def main(argv: list[str]) -> int:
    paths = argv or DEFAULT_PATHS
    problems = lint_paths(paths)
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} silent broad exception handler(s)")
        return 1
    print("robustness lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
