"""AST lint: robustness + observability hygiene.

Three passes:

1. Silent broad exceptions — any ``except`` handler that (a) catches
   ``Exception`` / ``BaseException`` or is a bare ``except:``, AND (b)
   whose body is only ``pass`` / ``continue`` — the shape that turns
   real faults invisible. Narrow handlers may still swallow (often
   correct: idempotent deletes, probe loops); broad ones must log.
2. Metrics hygiene — every ``Counter``/``Gauge``/``Histogram``
   construction must use a ``SeaweedFS_``-prefixed lowercase-starting
   name (the registry's one namespace) and carry non-empty help text.
3. Span hygiene — every explicit tracing ``<span>.finish(...)`` call
   (on a name that looks like a span: ``sp``/``rsp``/``span``/
   ``*_span``/``*_sp``) must sit inside a ``finally`` block, so an
   exception on any path can never leak an unfinished span out of the
   in-flight table. ``with tracing.start(...)`` needs no finish and
   is exempt by construction.

Run as a tier-1 test (tests/test_robustness_lint.py) over
``seaweedfs_tpu/server/`` (+ util, master, stats) so the data plane
can never regress, or by hand over any path:

    python tools/lint_robustness.py [path ...]
"""

from __future__ import annotations

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATHS = [os.path.join(REPO, "seaweedfs_tpu", "server"),
                 os.path.join(REPO, "seaweedfs_tpu", "stats")]

BROAD = {"Exception", "BaseException"}

METRIC_CTORS = {"Counter", "Gauge", "Histogram", "Summary"}
# SeaweedFS_ prefix then a lowercase-led snake-ish name; interior
# camelCase segments are allowed (the reference's own idiom:
# SeaweedFS_volumeServer_request_total)
METRIC_NAME_RE = re.compile(r"^SeaweedFS_[a-z][A-Za-z0-9_]*$")
SPAN_NAME_RE = re.compile(r"^(sp|rsp|span|.*_span|.*_sp)$")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True                          # bare except:
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in BROAD:
            return True
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(s, (ast.Pass, ast.Continue))
               for s in handler.body)


def _metric_problems(path: str, node: ast.Call) -> list[str]:
    """Pass 2: metrics hygiene on Counter/Gauge/Histogram calls."""
    func = node.func
    name = func.id if isinstance(func, ast.Name) else (
        func.attr if isinstance(func, ast.Attribute) else "")
    if name not in METRIC_CTORS or len(node.args) < 1:
        return []
    problems = []
    first = node.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        if not METRIC_NAME_RE.match(first.value):
            problems.append(
                f"{path}:{node.lineno}: metric name {first.value!r} "
                f"must match SeaweedFS_[a-z]... (one registry "
                f"namespace, lowercase-led)")
    help_arg = node.args[1] if len(node.args) > 1 else None
    if help_arg is None or (isinstance(help_arg, ast.Constant)
                            and not str(help_arg.value or "").strip()):
        problems.append(
            f"{path}:{node.lineno}: metric {name} needs non-empty "
            f"help text")
    return problems


def _finally_calls(tree: ast.AST) -> set[int]:
    """ids of every Call node located inside some `finally` block."""
    inside: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Try) and node.finalbody:
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call):
                        inside.add(id(sub))
    return inside


def _span_finish_problem(path: str, node: ast.Call,
                         in_finally: set[int]) -> list[str]:
    """Pass 3: span.finish() must be exception-safe (in a finally)."""
    func = node.func
    if not (isinstance(func, ast.Attribute) and func.attr == "finish"
            and isinstance(func.value, ast.Name)
            and SPAN_NAME_RE.match(func.value.id)):
        return []
    if id(node) in in_finally:
        return []
    return [f"{path}:{node.lineno}: span {func.value.id}.finish() "
            f"outside a finally — an exception path would leak the "
            f"span (use `with` or move the finish into finally)"]


def lint_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    problems = []
    in_finally = _finally_calls(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                and _is_silent(node):
            what = "bare except" if node.type is None \
                else "except Exception"
            problems.append(
                f"{path}:{node.lineno}: silent {what}: pass — narrow "
                f"the exception type and/or glog the fault")
        elif isinstance(node, ast.Call):
            problems += _metric_problems(path, node)
            problems += _span_finish_problem(path, node, in_finally)
    return problems


def lint_paths(paths: list[str]) -> list[str]:
    problems: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            problems += lint_file(p)
            continue
        for root, _dirs, files in os.walk(p):
            for name in sorted(files):
                if name.endswith(".py"):
                    problems += lint_file(os.path.join(root, name))
    return problems


def main(argv: list[str]) -> int:
    paths = argv or DEFAULT_PATHS
    problems = lint_paths(paths)
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} silent broad exception handler(s)")
        return 1
    print("robustness lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
