"""Shared process harness for the soak/chaos drivers: spawn
`seaweedfs_tpu.cli` daemons with per-process log files (fork + file
open happen off the event loop) and wait for a cluster to become
assignable. One copy — a fix to spawning applies everywhere."""

from __future__ import annotations

import asyncio
import os
import signal
import subprocess
import sys
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Procs:
    def __init__(self, tmp: str):
        self.tmp = tmp
        self.procs: list[subprocess.Popen] = []
        self.env = dict(os.environ, JAX_PLATFORMS="cpu",
                        PYTHONPATH=REPO)

    def _spawn_sync(self, *args: str) -> subprocess.Popen:
        log = open(os.path.join(
            self.tmp, f"proc{len(self.procs)}.log"), "w")
        p = subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", *args],
            stdout=log, stderr=subprocess.STDOUT, env=self.env,
            cwd=REPO)
        self.procs.append(p)
        return p

    async def spawn(self, *args: str) -> subprocess.Popen:
        # log-file open + fork happen off the loop: drivers spawn
        # servers while foreground load is already in flight
        return await asyncio.to_thread(self._spawn_sync, *args)

    def kill_all(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in self.procs:
            p.wait(timeout=10)


async def wait_assign(master: str, params: str = "",
                      tries: int = 30) -> None:
    def probe() -> bool:
        try:
            with urllib.request.urlopen(
                    f"http://{master}/dir/assign?{params}",
                    timeout=3) as r:
                return b"fid" in r.read()
        except OSError:
            return False

    for _ in range(tries):
        if await asyncio.to_thread(probe):
            return
        await asyncio.sleep(1)
    raise RuntimeError("cluster never became assignable")
