"""~20s flight-recorder smoke for tools/ci.sh.

Boots a REAL master + `-workers 2` volume fleet as CLI processes,
writes and reads a handful of needles through the shared public port,
forces a whole-host timeline snapshot, and then SCHEMA-CHECKS the
three recorder surfaces:

  /debug/timeline  — merged windows with rates/gauges/hist/quantiles,
                     build_info + process_start_time gauges present
                     (restart detection), request histograms recorded;
  /debug/events    — merged journal rows with type/wall_ms/mono/trace,
                     at least one volume_mount from the write path;
  /debug/health    — ok with a configured -slo objective evaluated
                     (fast/slow burn rows present);
  /debug/scrub     — merged scrubber status with the machine-readable
                     `reported_windows` list and a forced cycle's
                     `corrupt_windows` rows (the autopilot observer's
                     input schema);
  /debug/autopilot — maintenance-plane status + a forced dry-run
                     cycle's planned/deferred/executed ledger.

Any key drift in these payloads fails CI before a soak or operator
tooling trips over it.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
PORT = int(os.environ.get("SWTPU_SMOKE_PORT", "22050"))


def wait_assign(master: str, tries: int = 60) -> None:
    for _ in range(tries):
        try:
            with urllib.request.urlopen(
                    f"http://{master}/dir/assign", timeout=3) as r:
                if b"fid" in r.read():
                    return
        except OSError:
            pass
        time.sleep(0.5)
    raise RuntimeError("cluster never became assignable")


def get_json(addr: str, path: str, method: str = "GET") -> dict:
    req = urllib.request.Request(f"http://{addr}{path}", method=method)
    with urllib.request.urlopen(req, timeout=15) as r:
        return json.load(r)


def check(cond: bool, what: str) -> None:
    if not cond:
        raise AssertionError(f"schema drift: {what}")


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="swtpu_rec_smoke_")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    master = f"127.0.0.1:{PORT}"
    vol = f"127.0.0.1:{PORT + 1}"
    procs: list[subprocess.Popen] = []

    def spawn(*args: str) -> None:
        log = open(os.path.join(tmp, f"proc{len(procs)}.log"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", *args],
            stdout=log, stderr=subprocess.STDOUT, env=env, cwd=tmp))

    try:
        spawn("master", "-port", str(PORT), "-mdir",
              os.path.join(tmp, "m"), "-pulseSeconds", "1",
              "-autopilot.dryrun", "-timeline.interval", "2")
        time.sleep(1.5)
        spawn("volume", "-port", str(PORT + 1), "-dir",
              os.path.join(tmp, "v"), "-max", "10", "-master", master,
              "-pulseSeconds", "1", "-workers", "2",
              "-timeline.interval", "2",
              "-slo", "volume.read:p99<250ms@99",
              "-qos.tenant", "smoke:4:100",
              "-qos.mbps", "50")
        wait_assign(master)

        # traffic across both workers' vid partitions
        fids = []
        for i in range(8):
            a = get_json(master, "/dir/assign")
            body = f"recorder-{i}-".encode() * 64
            req = urllib.request.Request(
                f"http://{a['url']}/{a['fid']}", data=body,
                method="POST", headers={"X-Raw-Needle": "0"})
            with urllib.request.urlopen(req, timeout=10) as r:
                check(r.status in (200, 201), f"write {r.status}")
            fids.append((a["fid"], a["url"]))
        for fid, url in fids:
            with urllib.request.urlopen(f"http://{vol}/{fid}",
                                        timeout=10) as r:
                check(r.status == 200, f"read {r.status}")

        # -- /debug/timeline (forced whole-host snapshot) ---------------
        tl = get_json(vol, "/debug/timeline?snap=1", method="POST")
        for key in ("interval_s", "ring", "windows"):
            check(key in tl, f"/debug/timeline missing {key!r}")
        check(tl["windows"], "/debug/timeline has no windows")
        win = tl["windows"][-1]
        for key in ("wall_ms", "dt_s", "rates", "gauges", "hist",
                    "quantiles"):
            check(key in win, f"timeline window missing {key!r}")
        gk = list(win["gauges"])
        check(any(k.startswith("SeaweedFS_build_info") for k in gk),
              "build_info gauge absent")
        check("SeaweedFS_process_start_time_seconds" in win["gauges"],
              "process_start_time gauge absent")
        hists = [k for w in tl["windows"]
                 for k in w["quantiles"]
                 if k.startswith("SeaweedFS_request_duration_seconds")
                 and 'tier="volume"' in k]
        check(hists, "no volume request histograms in any window")
        qrow = None
        for w in tl["windows"]:
            for k, q in w["quantiles"].items():
                if k in hists:
                    qrow = q
        for key in ("p50", "p95", "p99", "count", "rate"):
            check(key in qrow, f"quantile row missing {key!r}")
        print(f"  timeline: {len(tl['windows'])} merged windows, "
              f"volume read p99={qrow['p99'] * 1000:.1f}ms over "
              f"{int(qrow['count'])} requests")

        # -- /debug/events ---------------------------------------------
        ev = get_json(vol, "/debug/events?n=200")
        for key in ("events", "recorded"):
            check(key in ev, f"/debug/events missing {key!r}")
        check(ev["events"], "journal is empty after allocate traffic")
        row = ev["events"][0]
        for key in ("seq", "type", "wall_ms", "mono", "trace"):
            check(key in row, f"event row missing {key!r}")
        types = {e["type"] for e in ev["events"]}
        check("volume_mount" in types,
              f"no volume_mount in journal (saw {sorted(types)})")
        check(any("worker" in e for e in ev["events"]),
              "merged events carry no worker tags")
        print(f"  events: {len(ev['events'])} rows "
              f"({', '.join(sorted(types))})")

        # -- /debug/health ---------------------------------------------
        h = get_json(vol, "/debug/health")
        for key in ("status", "objectives", "now_ms"):
            check(key in h, f"/debug/health missing {key!r}")
        check(h["status"] == "ok",
              f"healthy fleet reports {h['status']!r}")
        check(len(h["objectives"]) == 1, "configured -slo not evaluated")
        obj = h["objectives"][0]
        for key in ("spec", "status", "fast", "slow", "threshold_ms",
                    "objective"):
            check(key in obj, f"objective row missing {key!r}")
        for key in ("horizon_s", "count", "frac_over", "burn"):
            check(key in obj["fast"], f"burn window missing {key!r}")
        print(f"  health: {h['status']} ({obj['spec']}, fast burn "
              f"{obj['fast']['burn']})")

        # -- /debug/scrub (autopilot observer input schema) -------------
        sc = get_json(vol, "/debug/scrub")
        check("workers" in sc, "/debug/scrub not worker-merged")
        st = next(iter(sc["workers"].values()))
        for key in ("state", "cycles", "corruptions",
                    "reported_windows", "last_cycle"):
            check(key in st, f"scrub status missing {key!r}")
        forced = get_json(vol, "/debug/scrub?run=1", method="POST")
        cyc = next(iter(forced["workers"].values()))["cycle"]
        for key in ("volumes", "windows", "corrupt", "corrupt_windows",
                    "bytes", "skipped", "errors", "seconds"):
            check(key in cyc, f"scrub cycle missing {key!r}")
        print(f"  scrub: {len(sc['workers'])} workers merged, cycle "
              f"keys OK")

        # -- /debug/qos (admission + arbiter schema, -workers merged) ---
        qd = get_json(vol, "/debug/qos")
        check(qd.get("workers") == 2, "/debug/qos not worker-merged")
        q = qd["qos"]
        for key in ("tenants", "inflight", "inflight_limit", "queued",
                    "shed_level", "ladder", "thresholds", "probes",
                    "arbiter"):
            check(key in q, f"/debug/qos missing {key!r}")
        check("smoke" in q["tenants"],
              f"-qos.tenant class absent (saw {sorted(q['tenants'])})")
        trow = q["tenants"]["smoke"]
        for key in ("admitted", "throttled", "shed", "queued", "cls",
                    "weight", "rps", "burst", "tokens", "queue_depth"):
            check(key in trow, f"qos tenant row missing {key!r}")
        arb = q["arbiter"]
        for key in ("budget_mbps", "floor", "foreground_bps",
                    "consumers", "grants"):
            check(key in arb, f"qos arbiter missing {key!r}")
        check("scrub" in arb["consumers"],
              f"scrub bucket not adopted by the arbiter "
              f"(consumers: {sorted(arb['consumers'])})")
        print(f"  qos: {len(q['tenants'])} tenant classes, arbiter "
              f"budget {arb['budget_mbps']} MiB/s, "
              f"{len(arb['consumers'])} adopted consumer(s)")

        # -- raft surfaces on the master (HA control plane schema) ------
        mtl = get_json(master, "/debug/timeline?snap=1", method="POST")
        mg = mtl["windows"][-1]["gauges"]
        for key in ("SeaweedFS_raft_term", "SeaweedFS_raft_commit_index",
                    "SeaweedFS_raft_is_leader"):
            check(key in mg, f"master timeline missing {key!r} gauge")
        check(mg["SeaweedFS_raft_is_leader"] == 1,
              "single-mode master not reporting raft_is_leader=1")
        mev = get_json(master, "/debug/events?n=200"
                       "&type=raft_leader_change")
        check(mev["events"], "no raft_leader_change journal row on a "
                             "booted master")
        lead = mev["events"][0]
        for key in ("leader", "term", "me"):
            check(key in lead, f"raft_leader_change row missing {key!r}")
        print(f"  raft: is_leader gauge + leader_change journal OK "
              f"(term {int(mg['SeaweedFS_raft_term'])})")

        # -- frame fabric (hop-labeled wire accounting schema) ----------
        vfr = [k for w in tl["windows"] for k in w["rates"]
               if k.startswith("SeaweedFS_frame_requests_total")]
        check(vfr, "no frame_requests_total counters on the volume")
        check(any('hop="interhost"' in k and 'side="client"' in k
                  for k in vfr),
              f"volume->master heartbeat not counted as a "
              f"client/interhost frame hop (saw {sorted(set(vfr))})")
        vgauges: dict = {}
        for w in tl["windows"]:
            vgauges.update(w["gauges"])
        open_ch = [k for k in vgauges
                   if k.startswith("SeaweedFS_frame_open_channels")]
        check(any(f'peer="{master}"' in k for k in open_ch),
              f"no per-peer open-channel gauge for the master "
              f"(saw {open_ch})")
        mfr = [k for w in mtl["windows"] for k in w["rates"]
               if k.startswith("SeaweedFS_frame_requests_total")]
        check(any('side="server"' in k for k in mfr),
              f"master served no frame requests (saw {sorted(set(mfr))})")
        print(f"  frames: hop-labeled request counters + "
              f"{len(open_ch)} open-channel gauge(s) OK")

        # -- /debug/autopilot (forced dry-run cycle) --------------------
        ap = get_json(master, "/debug/autopilot")["autopilot"]
        for key in ("enabled", "leader", "dryrun", "state", "cycles",
                    "budget_mbps", "actions_ok", "actions_failed",
                    "bytes_paid", "paced_sleep_s", "in_flight",
                    "history", "last_cycle"):
            check(key in ap, f"/debug/autopilot missing {key!r}")
        check(ap["dryrun"] is True, "autopilot -autopilot.dryrun lost")
        forced = get_json(master, "/debug/autopilot?run=1",
                          method="POST")
        for key in ("wall_ms", "seconds", "dryrun", "observed",
                    "planned", "deferred", "executed"):
            check(key in forced["cycle"],
                  f"autopilot cycle missing {key!r}")
        obs = forced["cycle"]["observed"]
        for key in ("nodes", "volumes", "ec_volumes", "corruptions",
                    "paging", "errors"):
            check(key in obs, f"autopilot observed missing {key!r}")
        check(obs["nodes"] >= 1, "autopilot observed no nodes")
        print(f"  autopilot: dry-run cycle over {obs['nodes']} nodes, "
              f"{len(forced['cycle']['planned'])} planned")
        # -- sharded filer plane (map, redirect hints, debug, events) ---
        import http.client
        f0, f1 = f"127.0.0.1:{PORT + 10}", f"127.0.0.1:{PORT + 11}"
        for sid, fp in ((0, PORT + 10), (1, PORT + 11)):
            spawn("filer", "-port", str(fp), "-ip", "127.0.0.1",
                  "-master", master, "-store", "sqlite",
                  "-dbPath", os.path.join(tmp, f"f{sid}.db"),
                  "-shard.id", str(sid), "-shard.of", "2",
                  "-shard.peers", f"{f0},{f1}")
        for _ in range(60):
            try:
                if {"0", "1"} <= set(get_json(
                        master, "/cluster/shards").get("owners", {})):
                    break
            except OSError:
                pass
            time.sleep(0.5)
        else:
            raise AssertionError("filer shards never registered")
        req = urllib.request.Request(
            f"http://{master}/cluster/shards",
            data=json.dumps({"op": "set", "rules":
                             [["/", 0], ["/shard/t", 1]]}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            check(r.status == 200, f"shard map set -> {r.status}")
        # the foreign-path answer must carry the learnable hint trio
        # (poll: shard 0 adopts the new rule on its ~2s map refresh)
        rr = None
        for _ in range(40):
            c = http.client.HTTPConnection("127.0.0.1", PORT + 10,
                                           timeout=10)
            c.request("GET", "/__api__/lookup?path=/shard/t/x")
            rr = c.getresponse()
            rr.read()
            if rr.status == 307:
                break
            time.sleep(0.5)
        check(rr is not None and rr.status == 307,
              f"foreign path not redirected "
              f"(got {rr.status if rr else '?'})")
        for h in ("X-Shard-Owner", "X-Shard-Prefix", "X-Shard-Epoch"):
            check(rr.getheader(h), f"307 missing {h} hint header")
        check(rr.getheader("X-Shard-Owner") == f1,
              f"wrong owner hint {rr.getheader('X-Shard-Owner')!r}")
        # a tiny real split: seed /shard/u on 0, move it to 1 — the
        # journal must record the flip and the done phases
        for i in range(3):
            body = json.dumps({"FullPath": f"/shard/u/e{i}",
                               "Mtime": 1.0 + i}).encode()
            req = urllib.request.Request(
                f"http://{f0}/__api__/entry", data=body,
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                check(r.status == 200, f"seed entry -> {r.status}")
        req = urllib.request.Request(
            f"http://{master}/cluster/shards",
            data=json.dumps({"op": "split_intent",
                             "prefix": "/shard/u", "to": 1}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            check(r.status == 200, f"split_intent -> {r.status}")
        for _ in range(60):
            if not get_json(master, "/cluster/shards").get("moves"):
                break
            time.sleep(0.5)
        else:
            raise AssertionError("split never drained")
        ds = get_json(f0, "/__debug__/shards")
        for key in ("shard", "of", "url", "epoch", "entries", "rules",
                    "owners", "moves", "counters", "singleflight"):
            check(key in ds, f"/__debug__/shards missing {key!r}")
        for key in ("local", "redirect", "forward", "merge", "ingest",
                    "moved", "replayed"):
            check(key in ds["counters"],
                  f"shard counters missing {key!r}")
        check(ds["counters"]["moved"] >= 3,
              f"split moved {ds['counters']['moved']} < 3 entries")
        md = get_json(master, "/debug/shards")
        for key in ("epoch", "leader", "map", "shards"):
            check(key in md, f"master /debug/shards missing {key!r}")
        check(len(md["shards"]) == 2,
              f"master fan-out saw {len(md['shards'])} shards")
        sev = get_json(f0, "/__debug__/events?type=shard_split")
        check(sev["events"], "no shard_split journal rows after the "
                             "split")
        for key in ("id", "phase", "shard", "seconds"):
            check(key in sev["events"][0],
                  f"shard_split row missing {key!r}")
        phases = {e["phase"] for e in sev["events"]}
        check({"flip", "done"} <= phases,
              f"split phases incomplete (saw {sorted(phases)})")
        with urllib.request.urlopen(f"http://{f0}/__metrics__",
                                    timeout=10) as r:
            mtext = r.read().decode()
        for name in ("SeaweedFS_filer_shard_requests_total",
                     "SeaweedFS_filer_shard_map_epoch",
                     "SeaweedFS_filer_shard_moved_entries_total"):
            check(name in mtext, f"{name} absent from filer metrics")
        print(f"  shards: 307 hints + split journal (flip/done) + "
              f"map epoch {ds['epoch']} + moved="
              f"{ds['counters']['moved']} OK")

        # -- /debug/profile (continuous profiler, -workers merged) ------
        pr = get_json(vol, "/debug/profile?seconds=0.5")
        for key in ("hz", "running", "window_s", "samples", "folded"):
            check(key in pr, f"/debug/profile missing {key!r}")
        check(pr["samples"] >= 10,
              f"0.5s on-demand window took {pr['samples']} samples "
              f"(expected ~99Hz x 0.5s x 2 workers)")
        check(pr["folded"], "profiler window folded no stacks")
        check(all(";" in k or k == "(other)" for k in pr["folded"]),
              "folded keys not tier-prefixed stack;frames")
        print(f"  profile: {pr['samples']} samples, "
              f"{len(pr['folded'])} folded stacks in 0.5s window")

        # -- /debug/cluster/trace/<id> (cross-host assembly) ------------
        tid = "c0ffee" + "0" * 26
        req = urllib.request.Request(
            f"http://{vol}/{fids[0][0]}",
            headers={"traceparent": f"00-{tid}-00000000000000ab-01"})
        with urllib.request.urlopen(req, timeout=10) as r:
            check(r.status == 200, f"traced read {r.status}")
        ct = get_json(master, f"/debug/cluster/trace/{tid}")
        for key in ("trace_id", "spans", "start_ms", "dur_ms", "tiers",
                    "hosts", "complete", "missing_nodes", "tree"):
            check(key in ct, f"/debug/cluster/trace missing {key!r}")
        check(ct["trace_id"] == tid, "assembled wrong trace id")
        check(ct["spans"] >= 1 and ct["tree"],
              f"traced volume read not assembled (spans={ct['spans']})")
        check(ct["complete"] and not ct["missing_nodes"],
              f"healthy fleet reported missing nodes: "
              f"{ct['missing_nodes']}")
        check("volume" in ct["tiers"],
              f"no volume tier in assembled trace ({ct['tiers']})")
        check(any(s.get("host") for s in ct["tree"]),
              "assembled spans carry no host attribution")
        print(f"  cluster trace: {ct['spans']} span(s) across "
              f"{len(ct['hosts'])} host(s), tiers="
              f"{','.join(ct['tiers'])}")

        # -- /debug/cluster/health (cluster-merged SLO verdict) ---------
        ch = get_json(master, "/debug/cluster/health")
        for key in ("status", "objectives", "now_ms", "nodes",
                    "missing_nodes"):
            check(key in ch, f"/debug/cluster/health missing {key!r}")
        check(ch["nodes"] >= 3,
              f"cluster health merged only {ch['nodes']} nodes "
              f"(want master + volume + 2 filers)")
        check(not ch["missing_nodes"],
              f"healthy fleet missing {ch['missing_nodes']}")
        # exemplar link: the traced read must surface a worst-trace
        # pointer in the volume's timeline window
        tl2 = get_json(vol, "/debug/timeline?snap=1", method="POST")
        exs = {}
        for w in tl2.get("windows", ()):
            exs.update(w.get("exemplars") or {})
        check(exs, "no timeline exemplars after traced traffic")
        check(all("trace" in e and "dur_ms" in e for e in exs.values()),
              "exemplar rows missing trace/dur_ms")
        print(f"  cluster health: {ch['status']} over {ch['nodes']} "
              f"nodes; {len(exs)} exemplar key(s) in the timeline")
        print("recorder smoke: OK")
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        time.sleep(1)   # workers notice the dead supervisor and exit


if __name__ == "__main__":
    sys.exit(main())
