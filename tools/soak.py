"""Cross-feature chaos soaks against a real CLI cluster.

These are the round-4 scenarios that found the post-ec.encode
stale-registry bug (ROUND4.md "Session-2 soak results") — kept runnable
so regressions in the distributed plane surface again. Each scenario
starts its own master/volume processes on private ports, drives load
over real sockets, and byte-verifies every surviving file at the end.

    python tools/soak.py ec            # write/delete/vacuum/ec.encode/verify
    python tools/soak.py vacuum-race   # writers+deletes racing vacuum rounds
    python tools/soak.py rebuild       # encode, SIGKILL a shard holder, rebuild
    python tools/soak.py failover      # SIGKILL the leader master under load
    python tools/soak.py all

Exit code 0 only when every read verifies.
"""

from __future__ import annotations

import asyncio
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASE_PORT = 21500


class Procs:
    def __init__(self, tmp: str):
        self.tmp = tmp
        self.procs: list[subprocess.Popen] = []
        self.env = dict(os.environ, JAX_PLATFORMS="cpu",
                        PYTHONPATH=REPO)

    def spawn(self, *args: str) -> subprocess.Popen:
        log = open(os.path.join(
            self.tmp, f"proc{len(self.procs)}.log"), "w")
        p = subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", *args],
            stdout=log, stderr=subprocess.STDOUT, env=self.env, cwd=REPO)
        self.procs.append(p)
        return p

    def shell(self, master: str, cmd: str) -> str:
        # timeout: a shell command wedged on a dead server must fail
        # the scenario, not hang the soak forever
        out = subprocess.run(
            [sys.executable, "-m", "seaweedfs_tpu.cli", "shell",
             "-master", master, "-c", cmd],
            capture_output=True, text=True, env=self.env, cwd=REPO,
            timeout=180)
        return out.stdout + out.stderr

    def kill_all(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in self.procs:
            p.wait(timeout=10)


def wait_assign(master: str, params: str = "", tries: int = 30) -> None:
    for _ in range(tries):
        try:
            with urllib.request.urlopen(
                    f"http://{master}/dir/assign?{params}",
                    timeout=3) as r:
                if b"fid" in r.read():
                    return
        except OSError:
            pass
        time.sleep(1)
    raise RuntimeError("cluster never became assignable")


async def fill(client, payloads: dict, n: int, rng,
               replication: str = "001") -> None:
    sem = asyncio.Semaphore(24)

    async def put(i):
        data = rng.randbytes(rng.randint(500, 30000))
        async with sem:
            fid = await client.upload_data(data, replication=replication)
        payloads[fid] = data

    await asyncio.gather(*(put(i) for i in range(n)))


async def verify(client, payloads: dict, tag: str) -> int:
    sem = asyncio.Semaphore(24)
    bad = []

    async def check(fid, want):
        async with sem:
            try:
                got = await client.read(fid)
            except Exception as e:  # noqa: BLE001 — every failure counts
                bad.append((fid, f"ERR {type(e).__name__} "
                                 f"{str(e)[:80]}"))
                return
        if got != want:
            bad.append((fid, f"MISMATCH {len(got)} vs {len(want)}"))

    await asyncio.gather(*(check(f, w) for f, w in payloads.items()))
    print(f"  {tag}: bad={len(bad)}/{len(payloads)}")
    for fid, why in bad[:5]:
        print("   ", fid, why)
    return len(bad)


def cluster(procs: Procs, port0: int, n_servers: int,
            master_args: tuple[str, ...] = ()) -> str:
    master = f"127.0.0.1:{port0}"
    procs.spawn("master", "-port", str(port0),
                "-mdir", os.path.join(procs.tmp, "m"),
                "-volumeSizeLimitMB", "8", "-pulseSeconds", "1",
                *master_args)
    time.sleep(2)
    for i in range(n_servers):
        procs.spawn("volume", "-port", str(port0 + 1 + i),
                    "-dir", os.path.join(procs.tmp, f"v{i}"),
                    "-max", "20", "-master", master,
                    "-pulseSeconds", "1")
    return master


async def scenario_ec(tmp: str) -> int:
    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    try:
        master = cluster(procs, BASE_PORT, 3)
        wait_assign(master, "replication=001")
        rng = random.Random(42)
        payloads: dict = {}
        async with WeedClient(master) as c:
            await fill(c, payloads, 1500, rng)
            dead = rng.sample(sorted(payloads), 450)
            await c.delete_fids(dead)
            for f in dead:
                del payloads[f]
            await asyncio.to_thread(
                procs.shell, master,
                "volume.vacuum -garbageThreshold 0.05")
            await asyncio.to_thread(
                procs.shell, master, "ec.encode -fullPercent 1")
            # NO settling sleep: reads must verify IMMEDIATELY
            return await verify(c, payloads, "after ec.encode")
    finally:
        procs.kill_all()


async def scenario_vacuum_race(tmp: str) -> int:
    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    try:
        master = cluster(procs, BASE_PORT + 10, 2)
        wait_assign(master)
        rng = random.Random(9)
        payloads: dict = {}
        stop = asyncio.Event()
        async with WeedClient(master) as c:
            async def writer():
                i = 0
                while not stop.is_set():
                    i += 1
                    data = rng.randbytes(rng.randint(500, 20000))
                    try:
                        fid = await c.upload_data(data,
                                                  replication="001")
                    except Exception:  # noqa: BLE001
                        await asyncio.sleep(0.05)
                        continue
                    payloads[fid] = data
                    if i % 4 == 0 and payloads:
                        victim = rng.choice(list(payloads))
                        try:
                            await c.delete_fids([victim])
                            del payloads[victim]
                        except Exception:  # noqa: BLE001
                            pass

            writers = [asyncio.create_task(writer()) for _ in range(8)]
            for round_ in range(4):
                await asyncio.sleep(4)
                # to_thread: a blocking subprocess.run would suspend the
                # writers and erase the very race being tested
                await asyncio.to_thread(
                    procs.shell, master,
                    "volume.vacuum -garbageThreshold 0.01")
                print(f"  vacuum round {round_ + 1} "
                      f"({len(payloads)} live)")
            stop.set()
            await asyncio.gather(*writers, return_exceptions=True)
            return await verify(c, payloads, "after vacuum races")
    finally:
        procs.kill_all()


async def scenario_rebuild(tmp: str) -> int:
    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    try:
        master = cluster(procs, BASE_PORT + 20, 4)
        wait_assign(master)
        rng = random.Random(12)
        payloads: dict = {}
        async with WeedClient(master) as c:
            await fill(c, payloads, 900, rng, replication="000")
            await asyncio.sleep(2)
            # fullPercent 1: EVERY volume (incl. small tails) must be
            # EC-protected, or the killed server takes replication-000
            # files with it and the scenario fails on placement luck
            await asyncio.to_thread(
                procs.shell, master, "ec.encode -fullPercent 1")
            bad = await verify(c, payloads, "after encode")
            # SIGKILL one shard-holding volume server (procs[2])
            procs.procs[2].send_signal(signal.SIGKILL)
            await asyncio.sleep(4)
            bad += await verify(c, payloads, "degraded (server killed)")
            await asyncio.to_thread(
                procs.shell, master, "ec.rebuild -force")
            await asyncio.sleep(2)
            bad += await verify(c, payloads, "after ec.rebuild")
            return bad
    finally:
        procs.kill_all()


async def scenario_failover(tmp: str) -> int:
    import json

    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    try:
        port0 = BASE_PORT + 30
        peers = ",".join(f"127.0.0.1:{port0 + i}" for i in range(3))
        for i in range(3):
            procs.spawn("master", "-port", str(port0 + i),
                        "-mdir", os.path.join(procs.tmp, f"m{i}"),
                        "-peers", peers, "-pulseSeconds", "1",
                        "-sequencer",
                        f"file:{os.path.join(procs.tmp, f'seq{i}')}")
        time.sleep(4)
        for i in range(2):
            procs.spawn("volume", "-port", str(port0 + 10 + i),
                        "-dir", os.path.join(procs.tmp, f"v{i}"),
                        "-max", "16", "-master", peers,
                        "-pulseSeconds", "1")
        first = f"127.0.0.1:{port0}"
        wait_assign(first, "replication=001")
        with urllib.request.urlopen(
                f"http://{first}/cluster/status", timeout=5) as r:
            leader = json.load(r)["leader"]
        leader_proc = procs.procs[int(leader.split(":")[1]) - port0]

        rng = random.Random(3)
        payloads: dict = {}
        errors = []
        stop = asyncio.Event()
        # the client gets the FULL seed list: whichever master dies —
        # including the one a single-seed client would be pointed at —
        # the seed rotation must carry it through the failover
        async with WeedClient(peers) as c:
            async def writer():
                while not stop.is_set():
                    data = rng.randbytes(rng.randint(500, 8000))
                    try:
                        fid = await c.upload_data(data,
                                                  replication="001")
                        payloads[fid] = data
                    except Exception as e:  # noqa: BLE001
                        errors.append(str(e)[:60])
                        await asyncio.sleep(0.2)

            writers = [asyncio.create_task(writer()) for _ in range(6)]
            await asyncio.sleep(5)
            pre = len(payloads)
            leader_proc.send_signal(signal.SIGKILL)
            t_kill = time.time()
            while len(payloads) <= pre and time.time() - t_kill < 60:
                await asyncio.sleep(0.5)
            recovery = time.time() - t_kill
            print(f"  first post-kill write after {recovery:.1f}s "
                  f"({len(errors)} transient errors)")
            await asyncio.sleep(8)
            stop.set()
            await asyncio.gather(*writers, return_exceptions=True)
            bad = await verify(c, payloads, "after leader failover")
            if recovery >= 60:
                print("  FAIL: no write succeeded within 60s of the kill")
                bad += 1
            return bad
    finally:
        procs.kill_all()


SCENARIOS = {
    "ec": scenario_ec,
    "vacuum-race": scenario_vacuum_race,
    "rebuild": scenario_rebuild,
    "failover": scenario_failover,
}


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which != "all" and which not in SCENARIOS:
        raise SystemExit(f"unknown scenario {which!r}; "
                         f"choose from: all, {', '.join(SCENARIOS)}")
    names = list(SCENARIOS) if which == "all" else [which]
    total_bad = 0
    for name in names:
        print(f"== soak: {name}")
        tmp = tempfile.mkdtemp(prefix=f"swtpu_soak_{name}_")
        try:
            total_bad += asyncio.run(SCENARIOS[name](tmp))
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    print("PASS" if total_bad == 0 else f"FAIL ({total_bad} bad reads)")
    sys.exit(0 if total_bad == 0 else 1)


if __name__ == "__main__":
    main()
