"""Cross-feature chaos soaks against a real CLI cluster.

These are the round-4 scenarios that found the post-ec.encode
stale-registry bug (ROUND4.md "Session-2 soak results") — kept runnable
so regressions in the distributed plane surface again. Each scenario
starts its own master/volume processes on private ports, drives load
over real sockets, and byte-verifies every surviving file at the end.

    python tools/soak.py ec            # write/delete/vacuum/ec.encode/verify
    python tools/soak.py vacuum-race   # writers+deletes racing vacuum rounds
    python tools/soak.py rebuild       # encode, SIGKILL a shard holder, rebuild
    python tools/soak.py failover      # SIGKILL the leader master under load
    python tools/soak.py partition     # cut the leader's raft links (alive)
    python tools/soak.py workers       # -workers 2 fleet: writes under worker
                                       # SIGKILLs, byte-verify via shared port
    python tools/soak.py cache-churn   # read-your-writes under cache churn:
                                       # zipf reads racing overwrites/deletes
                                       # with failpoints armed, every read
                                       # byte-verified (zero stale tolerated)
    python tools/soak.py scrub         # paced parity scrubber vs planted
                                       # bit-rot (real on-disk + scrub.read
                                       # flip failpoint): every corruption
                                       # reported, zero foreground read
                                       # errors, byte budget held
    python tools/soak.py heal          # autopilot acceptance: rot
                                       # planted in two EC volumes + one
                                       # shard holder SIGKILLed mid-soak
                                       # must converge back to full
                                       # declared redundancy (scrub
                                       # clean, shards re-hosted) with
                                       # ZERO operator intervention,
                                       # zero foreground read errors,
                                       # and the -autopilot.mbps repair
                                       # budget held (--quick: smaller
                                       # fill, the ci.sh smoke)
    python tools/soak.py slo           # flight-recorder acceptance: a
                                       # latency failpoint drives
                                       # /debug/health ok -> page with the
                                       # violating timeline slice + a
                                       # correlated journal event in the
                                       # evidence; disarmed phase stays ok
    python tools/soak.py qos           # multi-tenant QoS acceptance: an
                                       # abusive S3 tenant at full
                                       # throttle vs a paying tenant with
                                       # an armed per-tenant -slo — the
                                       # paying objective must hold, all
                                       # throttle/shed decisions must land
                                       # on the abuser's class, and every
                                       # acked write must read back
                                       # byte-identical (--quick: the
                                       # ci.sh smoke)
    python tools/soak.py meta          # sharded filer metadata plane:
                                       # >=3x op-accounted QPS at 4
                                       # shards, an online split under
                                       # armed filer.shard.* failpoints
                                       # + a SIGKILL of the source filer
                                       # (the journaled move must
                                       # replay), a cross-shard rename
                                       # storm with kills — the final
                                       # paged enumeration must hold
                                       # every entry exactly once
                                       # (--quick: the ci.sh smoke)
    python tools/soak.py all

Exit code 0 only when every read verifies.
"""

from __future__ import annotations

import asyncio
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import procutil  # noqa: E402

BASE_PORT = 21500


class Procs(procutil.Procs):
    def shell(self, master: str, cmd: str) -> str:
        # timeout: a shell command wedged on a dead server must fail
        # the scenario, not hang the soak forever
        out = subprocess.run(
            [sys.executable, "-m", "seaweedfs_tpu.cli", "shell",
             "-master", master, "-c", cmd],
            capture_output=True, text=True, env=self.env, cwd=REPO,
            timeout=180)
        return out.stdout + out.stderr


wait_assign = procutil.wait_assign


async def fill(client, payloads: dict, n: int, rng,
               replication: str = "001") -> None:
    sem = asyncio.Semaphore(24)

    async def put(i):
        data = rng.randbytes(rng.randint(500, 30000))
        async with sem:
            fid = await client.upload_data(data, replication=replication)
        payloads[fid] = data

    await asyncio.gather(*(put(i) for i in range(n)))


async def verify(client, payloads: dict, tag: str) -> int:
    sem = asyncio.Semaphore(24)
    bad = []

    async def check(fid, want):
        async with sem:
            try:
                got = await client.read(fid)
            except Exception as e:  # noqa: BLE001 — every failure counts
                bad.append((fid, f"ERR {type(e).__name__} "
                                 f"{str(e)[:80]}"))
                return
        if got != want:
            bad.append((fid, f"MISMATCH {len(got)} vs {len(want)}"))

    await asyncio.gather(*(check(f, w) for f, w in payloads.items()))
    print(f"  {tag}: bad={len(bad)}/{len(payloads)}")
    for fid, why in bad[:5]:
        print("   ", fid, why)
    return len(bad)


async def cluster(procs: Procs, port0: int, n_servers: int,
            master_args: tuple[str, ...] = ()) -> str:
    master = f"127.0.0.1:{port0}"
    await procs.spawn("master", "-port", str(port0),
                "-mdir", os.path.join(procs.tmp, "m"),
                "-volumeSizeLimitMB", "8", "-pulseSeconds", "1",
                *master_args)
    await asyncio.sleep(2)
    for i in range(n_servers):
        await procs.spawn("volume", "-port", str(port0 + 1 + i),
                    "-dir", os.path.join(procs.tmp, f"v{i}"),
                    "-max", "20", "-master", master,
                    "-pulseSeconds", "1")
    return master


async def scenario_ec(tmp: str) -> int:
    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    try:
        master = await cluster(procs, BASE_PORT, 3)
        await wait_assign(master, "replication=001")
        rng = random.Random(42)
        payloads: dict = {}
        async with WeedClient(master) as c:
            await fill(c, payloads, 1500, rng)
            dead = rng.sample(sorted(payloads), 450)
            await c.delete_fids(dead)
            for f in dead:
                del payloads[f]
            await asyncio.to_thread(
                procs.shell, master,
                "volume.vacuum -garbageThreshold 0.05")
            await asyncio.to_thread(
                procs.shell, master, "ec.encode -fullPercent 1")
            # NO settling sleep: reads must verify IMMEDIATELY
            return await verify(c, payloads, "after ec.encode")
    finally:
        procs.kill_all()


async def scenario_vacuum_race(tmp: str) -> int:
    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    try:
        master = await cluster(procs, BASE_PORT + 10, 2)
        await wait_assign(master)
        rng = random.Random(9)
        payloads: dict = {}
        stop = asyncio.Event()
        async with WeedClient(master) as c:
            async def writer():
                i = 0
                while not stop.is_set():
                    i += 1
                    data = rng.randbytes(rng.randint(500, 20000))
                    try:
                        fid = await c.upload_data(data,
                                                  replication="001")
                    except Exception:  # noqa: BLE001
                        await asyncio.sleep(0.05)
                        continue
                    payloads[fid] = data
                    if i % 4 == 0 and payloads:
                        victim = rng.choice(list(payloads))
                        try:
                            await c.delete_fids([victim])
                            del payloads[victim]
                        # weedlint: ignore[silent-except] churn driver: armed failpoints make deletes fail by design; the byte-verify pass catches real loss
                        except Exception:  # noqa: BLE001
                            pass

            writers = [asyncio.create_task(writer()) for _ in range(8)]
            for round_ in range(4):
                await asyncio.sleep(4)
                # to_thread: a blocking subprocess.run would suspend the
                # writers and erase the very race being tested
                await asyncio.to_thread(
                    procs.shell, master,
                    "volume.vacuum -garbageThreshold 0.01")
                print(f"  vacuum round {round_ + 1} "
                      f"({len(payloads)} live)")
            stop.set()
            await asyncio.gather(*writers, return_exceptions=True)
            return await verify(c, payloads, "after vacuum races")
    finally:
        procs.kill_all()


async def scenario_rebuild(tmp: str) -> int:
    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    try:
        master = await cluster(procs, BASE_PORT + 20, 4)
        await wait_assign(master)
        rng = random.Random(12)
        payloads: dict = {}
        async with WeedClient(master) as c:
            await fill(c, payloads, 900, rng, replication="000")
            await asyncio.sleep(2)
            # fullPercent 1: EVERY volume (incl. small tails) must be
            # EC-protected, or the killed server takes replication-000
            # files with it and the scenario fails on placement luck
            await asyncio.to_thread(
                procs.shell, master, "ec.encode -fullPercent 1")
            bad = await verify(c, payloads, "after encode")
            # SIGKILL one shard-holding volume server (procs[2])
            procs.procs[2].send_signal(signal.SIGKILL)
            await asyncio.sleep(4)
            bad += await verify(c, payloads, "degraded (server killed)")
            await asyncio.to_thread(
                procs.shell, master, "ec.rebuild -force")
            await asyncio.sleep(2)
            bad += await verify(c, payloads, "after ec.rebuild")
            return bad
    finally:
        procs.kill_all()


async def scenario_failover(tmp: str) -> int:
    import json

    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    try:
        port0 = BASE_PORT + 30
        peers = ",".join(f"127.0.0.1:{port0 + i}" for i in range(3))
        for i in range(3):
            await procs.spawn("master", "-port", str(port0 + i),
                        "-mdir", os.path.join(procs.tmp, f"m{i}"),
                        "-peers", peers, "-pulseSeconds", "1",
                        "-sequencer",
                        f"file:{os.path.join(procs.tmp, f'seq{i}')}")
        await asyncio.sleep(4)
        for i in range(2):
            await procs.spawn("volume", "-port", str(port0 + 10 + i),
                        "-dir", os.path.join(procs.tmp, f"v{i}"),
                        "-max", "16", "-master", peers,
                        "-pulseSeconds", "1")
        first = f"127.0.0.1:{port0}"
        await wait_assign(first, "replication=001")
        with urllib.request.urlopen(
                f"http://{first}/cluster/status", timeout=5) as r:
            leader = json.load(r)["leader"]
        leader_proc = procs.procs[int(leader.split(":")[1]) - port0]

        rng = random.Random(3)
        payloads: dict = {}
        errors = []
        stop = asyncio.Event()
        # the client gets the FULL seed list: whichever master dies —
        # including the one a single-seed client would be pointed at —
        # the seed rotation must carry it through the failover
        async with WeedClient(peers) as c:
            async def writer():
                while not stop.is_set():
                    data = rng.randbytes(rng.randint(500, 8000))
                    try:
                        fid = await c.upload_data(data,
                                                  replication="001")
                        payloads[fid] = data
                    except Exception as e:  # noqa: BLE001
                        errors.append(str(e)[:60])
                        await asyncio.sleep(0.2)

            writers = [asyncio.create_task(writer()) for _ in range(6)]
            await asyncio.sleep(5)
            pre = len(payloads)
            leader_proc.send_signal(signal.SIGKILL)
            t_kill = time.time()
            while len(payloads) <= pre and time.time() - t_kill < 60:
                await asyncio.sleep(0.5)
            recovery = time.time() - t_kill
            print(f"  first post-kill write after {recovery:.1f}s "
                  f"({len(errors)} transient errors)")
            await asyncio.sleep(8)
            stop.set()
            await asyncio.gather(*writers, return_exceptions=True)
            bad = await verify(c, payloads, "after leader failover")
            if recovery >= 60:
                print("  FAIL: no write succeeded within 60s of the kill")
                bad += 1
            return bad
    finally:
        procs.kill_all()


class PairProxy:
    """Userspace TCP link for ONE direction of a master pair; the soak
    cuts it to simulate a network partition (both processes stay alive —
    the failure class SIGKILL soaks can't produce)."""

    def __init__(self, name: str, listen_port: int, target_port: int,
                 cut: set):
        self.name = name
        self.listen_port = listen_port
        self.target_port = target_port
        self.cut = cut              # shared: {name} membership = severed
        self.conns: set = set()
        self.server = None

    async def start(self) -> None:
        self.server = await asyncio.start_server(
            self._handle, "127.0.0.1", self.listen_port)

    def sever(self) -> None:
        for w in list(self.conns):
            w.close()

    async def _handle(self, r, w) -> None:
        if self.name in self.cut:
            w.close()
            return
        try:
            tr, tw = await asyncio.open_connection(
                "127.0.0.1", self.target_port)
        except OSError:
            w.close()
            return
        self.conns.update((w, tw))

        async def pipe(a, b):
            try:
                while True:
                    d = await a.read(65536)
                    if not d or self.name in self.cut:
                        break
                    b.write(d)
                    await b.drain()
            # weedlint: ignore[silent-except] chaos TCP proxy: severed/reset pipes are this tool's purpose, any stream error just ends the pipe
            except Exception:  # noqa: BLE001
                pass
            finally:
                try:
                    b.close()
                except OSError:
                    pass  # peer already gone

        await asyncio.gather(pipe(r, tw), pipe(tr, w),
                             return_exceptions=True)
        self.conns.difference_update((w, tw))


async def scenario_partition(tmp: str) -> int:
    """Cut the LEADER's raft links (both directions, processes alive):
    the minority master must stop assigning within its lease, the
    majority must elect a successor, writes must keep flowing, and the
    heal must leave ONE leader and ZERO duplicate fids. Reference
    behavior contract: raft_server.go:28-97 (chrislusf/raft leader
    lease + election under partition)."""
    import json

    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    port0 = BASE_PORT + 40
    real = [f"127.0.0.1:{port0 + i}" for i in range(3)]
    # directed-pair proxies: master i dials master j via Q[i][j]; raft
    # traffic (and only raft traffic) rides these links
    qport = {(i, j): port0 + 50 + i * 3 + j
             for i in range(3) for j in range(3) if i != j}
    cut: set = set()
    proxies = {}
    for (i, j), qp in qport.items():
        proxies[(i, j)] = PairProxy(f"{i}->{j}", qp, port0 + j, cut)
    for p in proxies.values():
        await p.start()
    try:
        for i in range(3):
            peer_list = ",".join(
                [real[i]] + [f"127.0.0.1:{qport[(i, j)]}"
                             for j in range(3) if j != i])
            await procs.spawn("master", "-port", str(port0 + i),
                        "-mdir", os.path.join(procs.tmp, f"m{i}"),
                        "-peers", peer_list, "-pulseSeconds", "1",
                        "-sequencer",
                        f"file:{os.path.join(procs.tmp, f'seq{i}')}")
        # asyncio.sleep / to_thread, NOT time.sleep: the pair proxies run
        # on THIS loop — blocking it severs every raft link at once
        await asyncio.sleep(4)
        for i in range(2):
            await procs.spawn("volume", "-port", str(port0 + 10 + i),
                        "-dir", os.path.join(procs.tmp, f"v{i}"),
                        "-max", "16", "-master", ",".join(real),
                        "-pulseSeconds", "1")
        await wait_assign(real[0], "replication=001")

        def status(url):
            with urllib.request.urlopen(
                    f"http://{url}/cluster/status", timeout=3) as r:
                return json.load(r)

        leader = (await asyncio.to_thread(status, real[0]))["leader"]
        li = real.index(leader)
        others = [i for i in range(3) if i != li]
        print(f"  leader is master{li} ({leader})")

        rng = random.Random(17)
        payloads: dict = {}
        errors: list = []
        stop = asyncio.Event()
        async with WeedClient(",".join(real)) as c:
            async def writer():
                while not stop.is_set():
                    data = rng.randbytes(rng.randint(500, 8000))
                    try:
                        fid = await c.upload_data(data,
                                                  replication="001")
                        payloads[fid] = data
                    except Exception as e:  # noqa: BLE001
                        errors.append(str(e)[:60])
                        await asyncio.sleep(0.2)

            writers = [asyncio.create_task(writer()) for _ in range(6)]
            await asyncio.sleep(4)
            pre = len(payloads)

            # ---- CUT: isolate the leader from both peers ----
            for j in others:
                cut.add(f"{li}->{j}")
                cut.add(f"{j}->{li}")
            for p in proxies.values():
                if p.name in cut:
                    p.sever()
            t_cut = time.time()
            print(f"  partition: master{li} isolated "
                  f"({len(payloads)} files so far)")

            # majority elects a successor; old leader must step down
            new_leader = None
            while time.time() - t_cut < 30:
                await asyncio.sleep(0.5)
                try:
                    st = await asyncio.to_thread(status, real[others[0]])
                    if st["leader"] and st["leader"] != leader:
                        new_leader = st["leader"]
                        break
                except OSError:
                    pass
            bad = 0
            if not new_leader:
                print("  FAIL: majority elected no successor in 30s")
                bad += 1
            else:
                print(f"  new leader {new_leader} after "
                      f"{time.time() - t_cut:.1f}s")
            # the isolated minority master must NOT be assigning: its
            # lease expired and it has no quorum
            await asyncio.sleep(1.5)
            try:
                st = await asyncio.to_thread(status, real[li])
                if st.get("isLeader"):
                    print("  FAIL: isolated master still claims "
                          "leadership past its lease")
                    bad += 1
                with urllib.request.urlopen(
                        f"http://{real[li]}/dir/assign?replication=001",
                        timeout=3) as r:
                    body = json.load(r)
                    if "fid" in body:
                        print(f"  FAIL: isolated master still assigns: "
                              f"{body}")
                        bad += 1
            except (OSError, ValueError):
                pass  # refusing/erroring is the correct behavior

            # writes must keep flowing through the majority
            t0 = time.time()
            while len(payloads) <= pre and time.time() - t0 < 30:
                await asyncio.sleep(0.5)
            if len(payloads) <= pre:
                print("  FAIL: no write succeeded during the partition")
                bad += 1
            await asyncio.sleep(4)

            # ---- HEAL ----
            cut.clear()
            print(f"  healed ({len(payloads)} files, "
                  f"{len(errors)} transient errors)")
            t_heal = time.time()
            converged = False
            while time.time() - t_heal < 30:
                await asyncio.sleep(0.5)
                try:
                    sts = [await asyncio.to_thread(status, u)
                           for u in real]
                except OSError:
                    continue
                leaders = {st["leader"] for st in sts}
                claiming = [st for st in sts if st.get("isLeader")]
                if len(leaders) == 1 and "" not in leaders \
                        and len(claiming) == 1:
                    converged = True
                    break
            if not converged:
                print("  FAIL: masters did not converge on one leader "
                      "after heal")
                bad += 1
            await asyncio.sleep(3)
            stop.set()
            await asyncio.gather(*writers, return_exceptions=True)

            # ZERO duplicate fids across the whole run: upload_data
            # would have overwritten payloads[fid] silently, so count
            # via a second write-log? payloads keys ARE the issued fids;
            # a duplicate issue to two writers would byte-mismatch one
            # of them in verify below. Also verify every byte.
            bad += await verify(c, payloads, "after partition heal")
            return bad
    finally:
        for p in proxies.values():
            if p.server:
                p.server.close()
        procs.kill_all()


async def scenario_workers(tmp: str) -> int:
    """-workers 2 volume fleet (SO_REUSEPORT, vid % 2 partitioning):
    continuous writes while each worker is SIGKILLed in turn (the
    supervisor respawns them), then every surviving byte is verified
    through the SHARED port, exercising the sibling proxy path for the
    ~half of reads the kernel routes to the non-owner."""
    import json
    import urllib.request as urq

    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    try:
        port0 = BASE_PORT + 60
        master = f"127.0.0.1:{port0}"
        await procs.spawn("master", "-port", str(port0),
                    "-mdir", os.path.join(procs.tmp, "m"),
                    "-volumeSizeLimitMB", "8", "-pulseSeconds", "1")
        await asyncio.sleep(2)
        vport = port0 + 1
        await procs.spawn("volume", "-port", str(vport),
                    "-dir", os.path.join(procs.tmp, "v0"),
                    "-max", "20", "-master", master,
                    "-pulseSeconds", "1", "-workers", "2")
        await wait_assign(master)

        def worker_rows():
            with urq.urlopen(f"http://127.0.0.1:{vport}/stats/workers",
                             timeout=3) as r:
                return json.load(r)["workers"]

        rng = random.Random(77)
        payloads: dict = {}
        errors: list = []
        stop = asyncio.Event()
        async with WeedClient(master) as c:
            async def writer():
                while not stop.is_set():
                    data = rng.randbytes(rng.randint(500, 20000))
                    try:
                        fid = await c.upload_data(data)
                        payloads[fid] = data
                    except Exception as e:  # noqa: BLE001
                        errors.append(str(e)[:60])
                        await asyncio.sleep(0.1)

            writers = [asyncio.create_task(writer()) for _ in range(6)]
            await asyncio.sleep(4)
            bad = 0
            for victim_idx in (1, 0):
                rows = await asyncio.to_thread(worker_rows)
                victim = [w for w in rows
                          if w["index"] == victim_idx][0]
                os.kill(victim["pid"], signal.SIGKILL)
                print(f"  killed worker {victim_idx} "
                      f"(pid {victim['pid']}, {len(payloads)} files)")
                t0 = time.time()
                while time.time() - t0 < 30:
                    await asyncio.sleep(0.5)
                    rows = await asyncio.to_thread(worker_rows)
                    me = [w for w in rows if w["index"] == victim_idx]
                    if me and me[0]["alive"] \
                            and me[0]["pid"] != victim["pid"]:
                        break
                else:
                    print(f"  FAIL: worker {victim_idx} not respawned "
                          f"within 30s")
                    bad += 1
                await asyncio.sleep(3)
            stop.set()
            await asyncio.gather(*writers, return_exceptions=True)
            print(f"  {len(payloads)} files written "
                  f"({len(errors)} transient errors)")

            # byte-verify through the SHARED port only: whichever
            # worker accepts each connection must serve or proxy
            async def shared_read(fid: str) -> bytes:
                path = f"http://127.0.0.1:{vport}/{fid}"
                return await asyncio.to_thread(
                    lambda: urq.urlopen(path, timeout=10).read())

            sem = asyncio.Semaphore(16)
            failures = []

            async def check(fid, want):
                async with sem:
                    try:
                        got = await shared_read(fid)
                    except Exception as e:  # noqa: BLE001
                        failures.append((fid, str(e)[:60]))
                        return
                if got != want:
                    failures.append((fid, "MISMATCH"))

            await asyncio.gather(*(check(f, w)
                                   for f, w in payloads.items()))
            print(f"  shared-port verify: bad={len(failures)}"
                  f"/{len(payloads)}")
            for fid, why in failures[:5]:
                print("   ", fid, why)
            return bad + len(failures)
    finally:
        procs.kill_all()


def _failpoints(vport: int, method: str, query: str = "") -> None:
    req = urllib.request.Request(
        f"http://127.0.0.1:{vport}/debug/failpoints{query}",
        method=method)
    with urllib.request.urlopen(req, timeout=10) as r:
        r.read()


async def scenario_cache_churn(tmp: str) -> int:
    """Read-your-writes through the new cache tiers: a -workers 2
    volume fleet with the hot-needle cache on, a client with the chunk
    cache on, Zipfian hot reads racing same-fid overwrites and deletes
    while failpoints inject read errors/latency. EVERY read is
    byte-verified against the current truth under a per-fid lock: a
    single stale byte (old bytes after overwrite, success after
    delete) fails the scenario. Injected-fault read errors are counted
    as transient, not stale."""
    from seaweedfs_tpu.util.chunk_cache import TieredChunkCache
    from seaweedfs_tpu.util.client import OperationError, WeedClient
    procs = Procs(tmp)
    duration = float(os.environ.get("SWTPU_CHURN_SECONDS", "20"))
    n_files = int(os.environ.get("SWTPU_CHURN_FILES", "250"))
    try:
        port0 = BASE_PORT + 70
        master = f"127.0.0.1:{port0}"
        await procs.spawn("master", "-port", str(port0),
                    "-mdir", os.path.join(procs.tmp, "m"),
                    "-volumeSizeLimitMB", "8", "-pulseSeconds", "1")
        await asyncio.sleep(2)
        vport = port0 + 1
        await procs.spawn("volume", "-port", str(vport),
                    "-dir", os.path.join(procs.tmp, "v"),
                    "-max", "20", "-master", master,
                    "-pulseSeconds", "1", "-workers", "2",
                    "-cache.mem", "16")
        await wait_assign(master)

        rng = random.Random(5)
        payloads: dict = {}
        locks: dict = {}
        deleted: set = set()
        stats = {"reads": 0, "stale": 0, "transient": 0,
                 "overwrites": 0, "deletes": 0, "batched": 0,
                 "pipelined": 0}
        async with WeedClient(
                master, chunk_cache=await asyncio.to_thread(TieredChunkCache, 8 << 20)) as c:
            await fill(c, payloads, n_files, rng, replication="000")
            fid_list = sorted(payloads)
            for f in fid_list:
                locks[f] = asyncio.Lock()

            def pick() -> str:
                # zipf-ish hot head: most traffic lands on a few fids,
                # so the caches actually heat up before churn hits them
                i = min(len(fid_list) - 1,
                        int(rng.paretovariate(1.2)) - 1)
                return fid_list[i]

            # armed for the WHOLE churn window: cache-hot reads must
            # stay byte-exact while the miss path throws errors and
            # stalls (the volume fans the arming out to both workers)
            await asyncio.to_thread(
                _failpoints, vport, "POST",
                "?site=store.read&spec=error@0.02")
            await asyncio.to_thread(
                _failpoints, vport, "POST",
                "?site=volume.read.http&spec=latency=10@0.05")
            # sever a slice of the binary frame hop too: pipelined
            # reads and the sibling frame proxy must fall back to
            # HTTP without a single stale/lost byte. Armed BOTH on the
            # servers (the worker-to-worker frame forward) and in this
            # process (the client's own channels).
            await asyncio.to_thread(
                _failpoints, vport, "POST",
                "?site=worker.frame&spec=error@0.05")
            from seaweedfs_tpu.util import failpoints as _fp
            _fp.arm("worker.frame", "error@0.05")
            stop_at = time.time() + duration

            async def reader() -> None:
                while time.time() < stop_at:
                    fid = pick()
                    async with locks[fid]:
                        want = payloads.get(fid)
                        try:
                            got = await c.read(fid)
                        except OperationError:
                            # correct for a deleted fid; otherwise an
                            # injected-fault miss that exhausted its
                            # holders — transient, not stale
                            if fid not in deleted:
                                stats["transient"] += 1
                            continue
                        stats["reads"] += 1
                        if want is None:
                            print(f"  STALE: read of deleted {fid} "
                                  f"returned {len(got)} bytes")
                            stats["stale"] += 1
                        elif got != want:
                            print(f"  STALE: {fid} returned "
                                  f"{len(got)}B != expected "
                                  f"{len(want)}B after overwrite")
                            stats["stale"] += 1

            async def batch_reader() -> None:
                # the /batch multi-needle wire path must hold the same
                # read-your-writes bar as single GETs, under the same
                # armed failpoints; locks taken in sorted order so the
                # group acquisition can't deadlock against reader()
                import contextlib
                while time.time() < stop_at:
                    group = sorted({pick() for _ in range(4)})
                    async with contextlib.AsyncExitStack() as held:
                        for f in group:
                            await held.enter_async_context(locks[f])
                        want = {f: payloads.get(f) for f in group}
                        got = await c.batch_read(group)
                        for f in group:
                            g = got.get(f)
                            if g is None:
                                # deleted fid: correct; live fid: an
                                # injected-fault miss — transient
                                if f not in deleted:
                                    stats["transient"] += 1
                                continue
                            stats["reads"] += 1
                            stats["batched"] += 1
                            if want[f] is None:
                                print(f"  STALE: batch read of "
                                      f"deleted {f} returned "
                                      f"{len(g)} bytes")
                                stats["stale"] += 1
                            elif g != want[f]:
                                print(f"  STALE: batch {f} returned "
                                      f"{len(g)}B != expected "
                                      f"{len(want[f])}B")
                                stats["stale"] += 1

            async def pipeline_reader() -> None:
                # a fraction of traffic rides the binary frame wire
                # (multiplexed pipelined reads) with worker.frame
                # faults armed: every severed request must downgrade
                # to HTTP and still return current bytes
                import contextlib
                while time.time() < stop_at:
                    group = sorted({pick() for _ in range(4)})
                    async with contextlib.AsyncExitStack() as held:
                        for f in group:
                            await held.enter_async_context(locks[f])
                        want = {f: payloads.get(f) for f in group}
                        got = await c.pipelined_read(group, depth=4)
                        for f in group:
                            g = got.get(f)
                            if g is None:
                                if f not in deleted:
                                    stats["transient"] += 1
                                continue
                            stats["reads"] += 1
                            stats["pipelined"] += 1
                            if want[f] is None:
                                print(f"  STALE: pipelined read of "
                                      f"deleted {f} returned "
                                      f"{len(g)} bytes")
                                stats["stale"] += 1
                            elif g != want[f]:
                                print(f"  STALE: pipelined {f} "
                                      f"returned {len(g)}B != "
                                      f"expected {len(want[f])}B")
                                stats["stale"] += 1

            async def overwriter() -> None:
                while time.time() < stop_at:
                    fid = pick()
                    if fid in deleted:
                        continue
                    new = rng.randbytes(rng.randint(200, 8000))
                    async with locks[fid]:
                        if fid in deleted:
                            continue
                        try:
                            locs = await c.lookup(fid.split(",")[0])
                            await c.upload(fid, locs[0]["url"], new)
                        except OperationError:
                            stats["transient"] += 1
                            continue
                        payloads[fid] = new
                        stats["overwrites"] += 1
                    await asyncio.sleep(0.005)

            async def deleter() -> None:
                while time.time() < stop_at:
                    await asyncio.sleep(max(0.2, duration / 25))
                    fid = rng.choice(fid_list)
                    if fid in deleted:
                        continue
                    async with locks[fid]:
                        try:
                            await c.delete_fids([fid])
                        except OperationError:
                            continue
                        deleted.add(fid)
                        payloads.pop(fid, None)
                        stats["deletes"] += 1

            await asyncio.gather(*[reader() for _ in range(4)],
                                 *[batch_reader() for _ in range(2)],
                                 *[pipeline_reader() for _ in range(2)],
                                 *[overwriter() for _ in range(2)],
                                 deleter())
            await asyncio.to_thread(_failpoints, vport, "DELETE")
            print(f"  churn: {stats['reads']} verified reads "
                  f"({stats['batched']} via /batch, "
                  f"{stats['pipelined']} pipelined over frames), "
                  f"{stats['overwrites']} overwrites, "
                  f"{stats['deletes']} deletes, "
                  f"{stats['transient']} transient errors, "
                  f"{stats['stale']} stale")
            _fp.reset()
            # quiescent final sweep: every live file byte-exact, every
            # deleted fid a clean 404 (lost/stale both count as bad)
            bad = await verify(c, payloads, "after cache churn")
            for fid in deleted:
                try:
                    await c.read(fid)
                except OperationError:
                    continue
                print(f"  STALE: deleted {fid} still readable")
                bad += 1
            return bad + stats["stale"]
    finally:
        procs.kill_all()


def _http_json(port: int, path: str, method: str = "GET") -> dict:
    import json as _json
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method)
    with urllib.request.urlopen(req, timeout=300) as r:
        return _json.loads(r.read())


async def scenario_scrub(tmp: str) -> int:
    """Silent-corruption hunt under pacing: one volume server holds
    every EC shard, real bit-rot is planted on disk in parity shards
    (bytes no foreground needle read ever visits) AND the scrub.read
    failpoint is armed with `flip`, while foreground reads hammer the
    same volumes. The paced scrubber must report EVERY planted
    corruption, cause ZERO foreground read errors, and hold its token-
    bucket byte budget (the pacing floor is asserted from the cycle
    report)."""
    import glob as _glob

    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    failures = 0
    mbps = 8.0
    try:
        port0 = BASE_PORT + 80
        master = f"127.0.0.1:{port0}"
        await procs.spawn("master", "-port", str(port0),
                    "-mdir", os.path.join(procs.tmp, "m"),
                    "-volumeSizeLimitMB", "8", "-pulseSeconds", "1")
        await asyncio.sleep(2)
        vport = port0 + 1
        vdir = os.path.join(procs.tmp, "v")
        await procs.spawn("volume", "-port", str(vport), "-dir", vdir,
                    "-max", "20", "-master", master,
                    "-pulseSeconds", "1",
                    "-scrub.mbps", str(mbps),
                    "-scrub.interval", "3600",   # loop alive, cycles
                    "-scrub.pausems", "500")     # driven via ?run=1
        await wait_assign(master)
        rng = random.Random(77)
        payloads: dict = {}
        async with WeedClient(master) as c:
            await fill(c, payloads, 900, rng, replication="000")
            await asyncio.to_thread(
                procs.shell, master, "ec.encode -fullPercent 1")
            bad = await verify(c, payloads, "after ec.encode")

            vids = sorted(int(os.path.basename(p)[:-4])
                          for p in _glob.glob(os.path.join(vdir,
                                                           "*.ecx")))
            if len(vids) < 2:
                print(f"  want >=2 EC volumes, got {vids}")
                return bad + 1
            # real on-disk bit rot in a PARITY shard of every volume
            # but the first (shard files < 4MB => scrub window 0)
            def flip_byte(path: str, off: int) -> None:
                with open(path, "r+b") as f:
                    f.seek(off)
                    b = f.read(1)
                    f.seek(off)
                    f.write(bytes([b[0] ^ 0xFF]))

            planted = []
            for vid in vids[1:]:
                await asyncio.to_thread(
                    flip_byte, os.path.join(vdir, f"{vid}.ec12"), 4321)
                planted.append(vid)
            # failpoint-injected corruption lands in the FIRST
            # scrubbed volume's first window (2 row reads flipped)
            await asyncio.to_thread(
                _failpoints, vport, "POST",
                "?site=scrub.read&spec=flip:2")
            expected = {(vids[0], 0)} | {(v, 0) for v in planted}

            # foreground reads run THROUGH the scrub cycle: zero
            # errors tolerated (the scrubber must never disturb them)
            stop = asyncio.Event()
            fg = {"reads": 0, "errors": 0}
            sample = dict(rng.sample(sorted(payloads.items()), 200))

            async def forever_reads() -> None:
                while not stop.is_set():
                    for fid, want in sample.items():
                        if stop.is_set():
                            break
                        try:
                            got = await c.read(fid)
                        except Exception as e:  # noqa: BLE001
                            print(f"  FG ERROR {fid}: "
                                  f"{type(e).__name__} {e}")
                            fg["errors"] += 1
                            continue
                        fg["reads"] += 1
                        if got != want:
                            print(f"  FG STALE {fid}")
                            fg["errors"] += 1

            readers = [asyncio.create_task(forever_reads())
                       for _ in range(2)]
            body = await asyncio.to_thread(
                _http_json, vport, "/debug/scrub?run=1", "POST")
            stop.set()
            await asyncio.gather(*readers)
            cycle, status = body["cycle"], body["status"]
            reported = {(r["volume"], r["offset"])
                        for r in status["corruptions"]}
            print(f"  cycle 1: {cycle['volumes']} volumes, "
                  f"{cycle['windows']} windows, "
                  f"{cycle['bytes'] / (1 << 20):.1f}MB in "
                  f"{cycle['seconds']}s, corrupt={cycle['corrupt']}, "
                  f"paced_sleep={status['paced_sleep_s']}s; "
                  f"foreground: {fg['reads']} reads "
                  f"{fg['errors']} errors")
            if reported != expected:
                print(f"  MISSED/extra corruption: reported="
                      f"{sorted(reported)} expected={sorted(expected)}")
                failures += 1
            if cycle["skipped"]:
                print(f"  unexpected skips: {cycle['skipped']}")
                failures += 1
            # pacing floor: every byte past the burst was paid for at
            # -scrub.mbps; a cycle faster than that broke the budget
            rate = mbps * (1 << 20)
            floor = max(0.0, (cycle["bytes"] - rate) / rate)
            if cycle["seconds"] < floor * 0.95:
                print(f"  BUDGET BROKEN: {cycle['bytes']}B in "
                      f"{cycle['seconds']}s < floor {floor:.2f}s")
                failures += 1
            if floor > 0 and status["paced_sleep_s"] <= 0:
                print("  pacing never engaged (paced_sleep_s == 0)")
                failures += 1
            failures += fg["errors"]

            # cycle 2: the failpoint is spent, the REAL bit rot
            # persists and must be re-detected every pass
            body = await asyncio.to_thread(
                _http_json, vport, "/debug/scrub?run=1", "POST")
            c2 = body["cycle"]
            print(f"  cycle 2: corrupt={c2['corrupt']} "
                  f"(want {len(planted)}: real rot persists, "
                  f"failpoint spent)")
            if c2["corrupt"] != len(planted):
                failures += 1
            bad += await verify(c, payloads, "after scrub cycles")
            return bad + failures
    finally:
        procs.kill_all()


async def scenario_slo(tmp: str) -> int:
    """SLO flight-recorder acceptance: a `-workers 2` fleet armed with
    `-slo volume.read:p99<40ms@99` serves a healthy read phase —
    /debug/health must stay ok end-to-end — then a latency failpoint
    (store.read latency > threshold) plus sibling-proxy faults (to trip
    a server-side breaker) drive the SAME objective from ok to PAGE.
    The page's evidence must carry the violating timeline slice and at
    least one correlated journal event (breaker/retry/scrub family),
    proving the three recorder surfaces actually cross-link."""
    from seaweedfs_tpu.util.client import WeedClient
    procs = Procs(tmp)
    failures = 0
    try:
        port0 = BASE_PORT + 120
        master = f"127.0.0.1:{port0}"
        await procs.spawn("master", "-port", str(port0),
                    "-mdir", os.path.join(procs.tmp, "m"),
                    "-volumeSizeLimitMB", "8", "-pulseSeconds", "1")
        await asyncio.sleep(2)
        vport = port0 + 1
        await procs.spawn("volume", "-port", str(vport),
                    "-dir", os.path.join(procs.tmp, "v"),
                    "-max", "20", "-master", master,
                    "-pulseSeconds", "1", "-workers", "2",
                    "-timeline.interval", "1",
                    # threshold sized for this container class (~20x
                    # slower than a production host, PERF.md): healthy
                    # server-side reads sit well under it, the armed
                    # latency failpoint far over it
                    "-slo", "volume.read:p99<150ms@99")
        await wait_assign(master)
        rng = random.Random(99)
        payloads: dict = {}

        async with WeedClient(master) as c:
            await fill(c, payloads, 60, rng, replication="000")
            sample = sorted(payloads)

            stats = {"reads": 0, "errors": 0, "first_error": None}
            stop = asyncio.Event()

            async def reader() -> None:
                while not stop.is_set():
                    fid = rng.choice(sample)
                    try:
                        await c.read(fid)
                        stats["reads"] += 1
                    except Exception as e:  # noqa: BLE001 — injected
                        # faults are expected once armed; counted not
                        # raised
                        stats["errors"] += 1
                        if stats["first_error"] is None:
                            stats["first_error"] = repr(e)[:120]

            def health() -> dict:
                # force a merged snapshot so the newest window covers
                # the traffic just driven, then read the verdict
                _http_json(vport, "/debug/timeline?snap=1", "POST")
                return _http_json(vport, "/debug/health")

            readers = [asyncio.create_task(reader()) for _ in range(8)]
            try:
                # -- phase 1: disarmed must stay ok end-to-end --------
                ok_polls = 0
                for _ in range(4):
                    await asyncio.sleep(3)
                    h = await asyncio.to_thread(health)
                    print(f"  healthy phase: status={h['status']} "
                          f"reads={stats['reads']} "
                          f"errors={stats['errors']}"
                          + (f" first_error={stats['first_error']}"
                             if stats["errors"] else ""))
                    if h["status"] == "ok":
                        ok_polls += 1
                if ok_polls < 4:
                    print("  FAIL: healthy fleet left ok")
                    failures += 1

                # -- phase 2: arm latency + sibling faults ------------
                # store.read latency puts every read far over the
                # threshold; a BOUNDED worker.proxy error burst trips
                # the entry worker's sibling breaker => breaker_open
                # lands in the server-side journal as correlated
                # evidence, then the spent failpoint lets the breaker
                # recover so fast 503 rows stop diluting the latency
                # histogram the objective is computed from.  250ms
                # (1.7x the threshold) rather than something larger:
                # the slow 600s window is diluted by every fast
                # healthy-phase row, so paging needs slow-row VOLUME —
                # 8 readers at 250ms feed ~32 violating rows/s vs ~20
                # at 400ms, which on a slow container is the margin
                # between paging inside the budget and timing out
                await asyncio.to_thread(
                    _failpoints, vport, "POST",
                    "?site=store.read&spec=latency=250:*")
                await asyncio.to_thread(
                    _failpoints, vport, "POST",
                    "?site=worker.proxy&spec=error:12")
                paged = None
                t0 = time.monotonic()
                while time.monotonic() - t0 < 300:
                    await asyncio.sleep(5)
                    h = await asyncio.to_thread(health)
                    obj = h["objectives"][0]
                    print(f"  armed phase: status={h['status']} "
                          f"fast_burn={obj['fast']['burn']} "
                          f"slow_burn={obj['slow']['burn']} "
                          f"reads={stats['reads']} "
                          f"errors={stats['errors']}")
                    if h["status"] == "page":
                        paged = h
                        break
                if paged is None:
                    print("  FAIL: never paged under armed latency")
                    return failures + 1
                ev = paged["objectives"][0].get("evidence", {})
                if not ev.get("violating_windows"):
                    print("  FAIL: page without violating timeline "
                          "slice")
                    failures += 1
                etypes = {e["type"] for e in ev.get("events", ())}
                want = {"breaker_open", "breaker_close",
                        "retry_budget_exhausted", "scrub_corruption"}
                if not etypes & want:
                    print(f"  FAIL: no correlated journal event "
                          f"(saw {sorted(etypes)})")
                    failures += 1
                else:
                    print(f"  page evidence: "
                          f"{len(ev['violating_windows'])} violating "
                          f"windows, events={sorted(etypes)}, "
                          f"worst_trace="
                          f"{ev.get('worst_trace', {}).get('trace', '-')}")

                # -- phase 3: disarm, recorder must recover -----------
                # warn/page need the FAST (60s) window burning too, so
                # once the armed latency stops feeding it the verdict
                # must drain back to ok within ~2 fast horizons even
                # though the slow (600s) window still remembers the
                # damage (regression guard: an engine that latches
                # page forever would otherwise pass this scenario)
                await asyncio.to_thread(_failpoints, vport, "DELETE")
                recovered = None
                for _ in range(30):
                    await asyncio.sleep(5)
                    h = await asyncio.to_thread(health)
                    if h["status"] == "ok":
                        recovered = h
                        break
                if recovered is None:
                    print(f"  FAIL: health never drained back to ok "
                          f"after disarm (last={h['status']})")
                    failures += 1
                else:
                    print(f"  disarmed phase: status=ok "
                          f"(fast window drained)")
            finally:
                stop.set()
                await asyncio.gather(*readers, return_exceptions=True)
            return failures
    finally:
        procs.kill_all()


def _sign_s3(method: str, host: str, path: str,
             access_key: str, secret: str) -> dict:
    """Client-side SigV4 (UNSIGNED-PAYLOAD), the way an SDK signs —
    the soak's S3 traffic must carry REAL verified identities so the
    gateway's tenant classification keys on the access key."""
    import hashlib
    import hmac
    from seaweedfs_tpu.s3.auth import ALGORITHM, UNSIGNED, signing_key
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    headers = {"host": host, "x-amz-date": amz_date,
               "x-amz-content-sha256": UNSIGNED}
    signed = sorted(headers)
    canon = "\n".join([
        method, path, "",
        "".join(f"{h}:{headers[h]}\n" for h in signed),
        ";".join(signed), UNSIGNED])
    scope = f"{date}/us-east-1/s3/aws4_request"
    sts = "\n".join([ALGORITHM, amz_date, scope,
                     hashlib.sha256(canon.encode()).hexdigest()])
    sig = hmac.new(signing_key(secret, date, "us-east-1"), sts.encode(),
                   hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"{ALGORITHM} Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return headers


async def scenario_qos(tmp: str) -> int:
    """Multi-tenant QoS acceptance: an S3 gateway with two REAL SigV4
    identities — `PAYKEY` (weight 8, effectively unlimited rps, an
    armed per-tenant -slo objective) and `ABUSEKEY` (weight 1, tight
    rps) — serves a paying workload while the abuser floods zipf GETs
    at full throttle. The gate: /__debug__/health keeps the paying
    objective ok THROUGH the flood, every throttle/shed decision lands
    on the abuser's class only (paying sheds stay exactly 0), the
    `qos.admit` failpoint proves the chaos path end to end, the abuser
    is readmitted within a couple of bucket horizons once it stops,
    and every byte the gateway ever acked reads back identical."""
    import aiohttp
    procs = Procs(tmp)
    quick = "--quick" in sys.argv
    failures = 0
    try:
        port0 = BASE_PORT + 140
        master = f"127.0.0.1:{port0}"
        await procs.spawn("master", "-port", str(port0),
                    "-mdir", os.path.join(procs.tmp, "m"),
                    "-volumeSizeLimitMB", "8", "-pulseSeconds", "1",
                    "-qos.mbps", "64")
        await asyncio.sleep(2)
        await procs.spawn("volume", "-port", str(port0 + 1),
                    "-dir", os.path.join(procs.tmp, "v"),
                    "-max", "20", "-master", master,
                    "-pulseSeconds", "1", "-qos.mbps", "64")
        s3port = port0 + 2
        s3host = f"127.0.0.1:{s3port}"
        await procs.spawn(
            "s3", "-port", str(s3port), "-master", master,
            "-store", "memory",
            "-accessKey", "PAYKEY", "-secretKey", "PAYSECRET",
            "-accessKey", "ABUSEKEY", "-secretKey", "ABUSESECRET",
            "-qos.tenant", "PAYKEY:8:1000:2000",
            "-qos.tenant", "ABUSEKEY:1:25:25",
            # armed thresholds sized far above this light load: the
            # ladder plumbing runs live, but the scenario's shed
            # evidence stays the deterministic rate-limit path
            "-qos.shed.lagms", "2000", "-qos.shed.waitms", "2000",
            "-timeline.interval", "1",
            "-slo", "s3.get/PAYKEY:p99<1500ms@99")
        await wait_assign(master)
        for _ in range(60):     # gateway readiness
            try:
                _http_json(s3port, "/__debug__/health")
                break
            except OSError:
                await asyncio.sleep(0.5)

        rng = random.Random(17)
        n_objects = 30 if quick else 120
        abuse_s = 8 if quick else 40
        payloads: dict[str, bytes] = {}
        stats = {"pay_reads": 0, "pay_errors": 0, "abuse_200": 0,
                 "abuse_429": 0, "abuse_503": 0, "abuse_other": 0,
                 "pay_stale": 0}

        def qos_snapshot() -> dict:
            return _http_json(s3port, "/__debug__/qos")["qos"]

        def health() -> dict:
            _http_json(s3port, "/__debug__/timeline?snap=1", "POST")
            return _http_json(s3port, "/__debug__/health")

        async with aiohttp.ClientSession() as http:
            async def s3req(method: str, path: str, key: str,
                            secret: str, data: bytes | None = None):
                h = _sign_s3(method, s3host, path, key, secret)
                return await http.request(
                    method, f"http://{s3host}{path}", headers=h,
                    data=data)

            # -- phase 1: the paying tenant fills (every ack recorded)
            async with await s3req("PUT", "/qosbkt", "PAYKEY",
                                   "PAYSECRET") as r:
                assert r.status == 200, await r.text()
            sem = asyncio.Semaphore(16)

            async def put(i: int) -> None:
                body = rng.randbytes(rng.randint(2000, 20000))
                path = f"/qosbkt/obj-{i}"
                async with sem:
                    async with await s3req("PUT", path, "PAYKEY",
                                           "PAYSECRET", body) as r:
                        if r.status == 200:
                            payloads[path] = body
                        else:
                            stats["pay_errors"] += 1

            await asyncio.gather(*(put(i) for i in range(n_objects)))
            print(f"  fill: {len(payloads)}/{n_objects} acked writes")
            if len(payloads) != n_objects:
                print("  FAIL: paying writes rejected during fill")
                failures += 1
            paths = sorted(payloads)

            # -- phase 2: abuser floods, paying keeps reading ---------
            stop = asyncio.Event()

            async def abuser() -> None:
                while not stop.is_set():
                    path = rng.choice(paths)
                    try:
                        async with await s3req("GET", path, "ABUSEKEY",
                                               "ABUSESECRET") as r:
                            await r.read()
                            if r.status == 200:
                                stats["abuse_200"] += 1
                            elif r.status == 429:
                                stats["abuse_429"] += 1
                            elif r.status == 503:
                                stats["abuse_503"] += 1
                            else:
                                stats["abuse_other"] += 1
                    except aiohttp.ClientError:
                        stats["abuse_other"] += 1

            async def paying() -> None:
                while not stop.is_set():
                    path = rng.choice(paths)
                    try:
                        async with await s3req("GET", path, "PAYKEY",
                                               "PAYSECRET") as r:
                            body = await r.read()
                            if r.status != 200:
                                stats["pay_errors"] += 1
                            elif body != payloads[path]:
                                stats["pay_stale"] += 1
                            else:
                                stats["pay_reads"] += 1
                    except aiohttp.ClientError:
                        stats["pay_errors"] += 1
                    await asyncio.sleep(0.02)

            tasks = [asyncio.create_task(abuser()) for _ in range(6)]
            tasks += [asyncio.create_task(paying()) for _ in range(2)]
            t0 = time.monotonic()
            ok_polls = polls = 0
            while time.monotonic() - t0 < abuse_s:
                await asyncio.sleep(3)
                h = await asyncio.to_thread(health)
                polls += 1
                if h["status"] == "ok":
                    ok_polls += 1
                print(f"  flood: health={h['status']} "
                      f"pay={stats['pay_reads']} "
                      f"abuse 200/429/503="
                      f"{stats['abuse_200']}/{stats['abuse_429']}"
                      f"/{stats['abuse_503']}")
            stop.set()
            await asyncio.gather(*tasks, return_exceptions=True)

            if ok_polls < polls:
                print(f"  FAIL: paying objective left ok during the "
                      f"flood ({ok_polls}/{polls} ok)")
                failures += 1
            if stats["pay_errors"] or stats["pay_stale"]:
                print(f"  FAIL: paying tenant saw "
                      f"{stats['pay_errors']} errors / "
                      f"{stats['pay_stale']} stale reads in the flood")
                failures += 1
            if not (stats["abuse_429"] + stats["abuse_503"]):
                print("  FAIL: abuser at full throttle was never "
                      "throttled")
                failures += 1
            q = await asyncio.to_thread(qos_snapshot)
            pay = q["tenants"]["PAYKEY"]
            abu = q["tenants"]["ABUSEKEY"]
            if pay["throttled"] or pay["shed"]:
                print(f"  FAIL: sheds landed on the paying class "
                      f"(throttled={pay['throttled']} "
                      f"shed={pay['shed']})")
                failures += 1
            if not (abu["throttled"] + abu["shed"]):
                print("  FAIL: no decision attributed to the abuser's "
                      "class")
                failures += 1
            print(f"  qos: abuser throttled={abu['throttled']} "
                  f"shed={abu['shed']}; paying admitted="
                  f"{pay['admitted']} throttled=0 shed=0")

            # -- phase 3: the qos.admit chaos path --------------------
            await asyncio.to_thread(
                _http_json, s3port,
                "/__debug__/failpoints?site=qos.admit&spec=error:2",
                "POST")
            async with await s3req("GET", paths[0], "PAYKEY",
                                   "PAYSECRET") as r:
                if r.status != 503 or "Retry-After" not in r.headers:
                    print(f"  FAIL: armed qos.admit answered "
                          f"{r.status} without Retry-After")
                    failures += 1
            await asyncio.to_thread(
                _http_json, s3port, "/__debug__/failpoints", "DELETE")

            # -- phase 4: abuser recovery after the flood stops -------
            readmitted = False
            for _ in range(20):
                await asyncio.sleep(0.5)
                async with await s3req("GET", paths[0], "ABUSEKEY",
                                       "ABUSESECRET") as r:
                    await r.read()
                    if r.status == 200:
                        readmitted = True
                        break
            if not readmitted:
                print("  FAIL: abuser never readmitted after backing "
                      "off")
                failures += 1
            h = await asyncio.to_thread(health)
            if h["status"] != "ok":
                print(f"  FAIL: health {h['status']} after the flood "
                      f"ended")
                failures += 1

            # -- phase 5: zero lost acked writes ----------------------
            bad = 0
            for path in paths:
                async with await s3req("GET", path, "PAYKEY",
                                       "PAYSECRET") as r:
                    body = await r.read()
                    if r.status != 200 or body != payloads[path]:
                        bad += 1
            print(f"  verify: bad={bad}/{len(paths)} acked objects, "
                  f"readmitted={readmitted}, health={h['status']}")
            return failures + bad
    finally:
        procs.kill_all()


async def scenario_heal(tmp: str) -> int:
    """Autopilot acceptance (ISSUE 12): a fleet with the scrubber and
    the autopilot BOTH running autonomously. Real bit-rot is planted
    on disk in a parity shard of two EC volumes, then one shard-
    holding server is SIGKILLed mid-soak. With zero operator
    intervention the fleet must converge back to full declared
    redundancy — every EC volume's 14 shards hosted on live holders,
    a fresh scrub cycle reporting zero corruptions — while foreground
    reads stay error-free and the repair token bucket provably never
    exceeds -autopilot.mbps (pacing floor asserted from the ledger)."""
    import glob as _glob
    import json as _json

    from seaweedfs_tpu.ec import gf as _gf
    from seaweedfs_tpu.util.client import WeedClient
    quick = "--quick" in sys.argv
    procs = Procs(tmp)
    failures = 0
    mbps = 4.0
    try:
        port0 = BASE_PORT + 160
        master = f"127.0.0.1:{port0}"
        await procs.spawn("master", "-port", str(port0),
                    "-mdir", os.path.join(procs.tmp, "m"),
                    "-volumeSizeLimitMB", "4", "-pulseSeconds", "1",
                    "-autopilot.interval", "2",
                    "-autopilot.mbps", str(mbps))
        await asyncio.sleep(2)
        n_servers = 4
        vdirs = []
        for i in range(n_servers):
            d = os.path.join(procs.tmp, f"v{i}")
            vdirs.append(d)
            await procs.spawn("volume", "-port", str(port0 + 1 + i),
                        "-dir", d, "-max", "20", "-master", master,
                        "-pulseSeconds", "1",
                        "-rack", f"r{i % 2}",
                        "-scrub.interval", "4",
                        "-scrub.mbps", "50",
                        "-scrub.pausems", "500")
        await wait_assign(master)

        # pre-grow a second volume: on a fast container the fill below
        # outruns the heartbeat-fed size accounting (every write lands
        # before vid 1 ever reports size >= limit, so the layout never
        # rolls), and the scenario NEEDS >= 2 EC volumes to plant rot
        # in — offer two up front and let pick_for_write spread data
        def pregrow() -> None:
            req = urllib.request.Request(
                f"http://{master}/vol/grow?count=1&replication=000",
                method="POST")
            with urllib.request.urlopen(req, timeout=15) as r:
                r.read()
        await asyncio.to_thread(pregrow)

        rng = random.Random(2026)
        payloads: dict = {}
        async with WeedClient(master) as c:
            # enough bytes to roll past -volumeSizeLimitMB at least once
            await fill(c, payloads, 500 if quick else 900, rng,
                       replication="000")
            await asyncio.to_thread(
                procs.shell, master, "ec.encode -fullPercent 1")
            bad = await verify(c, payloads, "after ec.encode")

            # locate the EC volumes and plant REAL on-disk rot in a
            # parity shard of two of them (window 0 — shard files
            # are < 1 MB here), wherever those shards landed
            vids = sorted({int(os.path.basename(p).split(".")[0])
                           for d in vdirs
                           for p in _glob.glob(
                               os.path.join(d, "*.ecx"))})
            if len(vids) < 2:
                print(f"  want >=2 EC volumes, got {vids}")
                return bad + 1

            def flip_byte(path: str, off: int) -> None:
                with open(path, "r+b") as f:
                    f.seek(off)
                    b = f.read(1)
                    f.seek(off)
                    f.write(bytes([b[0] ^ 0xFF]))

            rotten = []
            for vid in vids[:2]:
                for d in vdirs:
                    p = os.path.join(d, f"{vid}.ec12")
                    if os.path.exists(p):
                        await asyncio.to_thread(flip_byte, p, 4321)
                        rotten.append(vid)
                        break
            print(f"  planted parity rot in volumes {rotten} "
                  f"(shard 12); autopilot + scrub are autonomous")
            if len(rotten) < 2:
                return bad + 1

            # foreground readers run through the WHOLE soak: repair
            # traffic and holder death must never surface to them
            stop = asyncio.Event()
            fg = {"reads": 0, "errors": 0}
            sample = dict(rng.sample(sorted(payloads.items()),
                                     min(150, len(payloads))))

            async def forever_reads() -> None:
                while not stop.is_set():
                    for fid, want in sample.items():
                        if stop.is_set():
                            break
                        try:
                            got = await c.read(fid)
                        except Exception as e:  # noqa: BLE001
                            print(f"  FG ERROR {fid}: "
                                  f"{type(e).__name__} {e}")
                            fg["errors"] += 1
                            continue
                        fg["reads"] += 1
                        if got != want:
                            print(f"  FG STALE {fid}")
                            fg["errors"] += 1

            readers = [asyncio.create_task(forever_reads())
                       for _ in range(2)]

            # let scrub find the rot and the autopilot start repairing,
            # then SIGKILL one shard-holding volume server mid-soak
            await asyncio.sleep(10)
            victim = procs.procs[2]        # volume server index 1
            victim.send_signal(signal.SIGKILL)
            victim_port = port0 + 2
            print(f"  SIGKILLed volume server :{victim_port} mid-soak")

            def shard_map() -> dict:
                body = _http_json(port0, "/vol/volumes")
                out: dict = {}
                for node in body["nodes"]:
                    for m in node["ecShards"]:
                        e = out.setdefault(m["id"], {})
                        for sid in range(32):
                            if m["ec_index_bits"] & (1 << sid):
                                e.setdefault(sid, []).append(
                                    node["url"])
                return out

            # convergence: every EC volume back to 14 hosted shards on
            # LIVE nodes, with zero operator intervention
            t0 = time.monotonic()
            deadline = t0 + (240 if quick else 420)
            converged = False
            while time.monotonic() < deadline:
                await asyncio.sleep(5)
                smap = await asyncio.to_thread(shard_map)
                whole = all(
                    len(smap.get(vid, {})) == _gf.TOTAL_SHARDS
                    and all(f":{victim_port}" not in u
                            for us in smap.get(vid, {}).values()
                            for u in us)
                    for vid in vids)
                ap = (await asyncio.to_thread(
                    _http_json, port0, "/debug/autopilot"))["autopilot"]
                print(f"  t+{int(time.monotonic() - t0)}s"
                      f" cycles={ap['cycles']} ok={ap['actions_ok']}"
                      f" failed={ap['actions_failed']}"
                      f" paid={ap['bytes_paid'] >> 20}MB"
                      f" paced={ap['paced_sleep_s']}s whole={whole}")
                if whole and ap["actions_ok"] > 0:
                    converged = True
                    break
            if not converged:
                print("  FAIL: never converged to full redundancy")
                failures += 1
            # snapshot the ledger NOW: the 16-cycle /debug/autopilot
            # history keeps rolling during the verification scrubs
            # below and would evict the executed cycles this report's
            # pacing-floor math needs
            ap_conv = (await asyncio.to_thread(
                _http_json, port0, "/debug/autopilot"))["autopilot"]

            # a FRESH scrub cycle on every live server must be clean
            # for the healed volumes (this is verification, not
            # repair: the autopilot did all the healing). Retried for
            # a bit: each server's EC location cache keeps an ~11s
            # freshness tier, so a scrub fired the instant after
            # convergence can still chase the dead holder for a
            # remote row and report the volume as degraded.
            if converged:
                clean = False
                for attempt in range(10):
                    clean = True
                    rows_seen = []
                    for i in range(n_servers):
                        if port0 + 1 + i == victim_port:
                            continue
                        body = await asyncio.to_thread(
                            _http_json, port0 + 1 + i,
                            "/debug/scrub?run=1", "POST")
                        cyc = body["cycle"]
                        for row in cyc.get("corrupt_windows", ()):
                            rows_seen.append(("CORRUPT", row))
                            clean = False
                        for sk in cyc.get("skipped", ()):
                            if sk.get("missing_shards"):
                                rows_seen.append(("DEGRADED", sk))
                                clean = False
                    if clean:
                        print(f"  verification scrub clean "
                              f"(attempt {attempt + 1})")
                        break
                    await asyncio.sleep(6)
                if not clean:
                    for tag, row in rows_seen:
                        print(f"  STILL {tag}: {row}")
                    failures += 1

            stop.set()
            await asyncio.gather(*readers)
            failures += fg["errors"]
            print(f"  foreground: {fg['reads']} reads, "
                  f"{fg['errors']} errors")

            # repair budget provably held: every byte past the burst
            # was paid for at -autopilot.mbps — reconstruct the repair
            # wall-clock span from the executed ledger (snapshotted at
            # convergence) and compare against the pacing floor
            ap = ap_conv
            stamps = []
            for cyc in ap["history"]:
                if cyc["executed"]:
                    stamps.append(cyc["wall_ms"])
                    stamps.extend(r["wall_ms"]
                                  for r in cyc["executed"])
                # dry-run-equivalence witness: executed rides the
                # planned ledger verbatim, in order
                if [r["action"] for r in cyc["executed"]] \
                        != cyc["planned"]:
                    print("  LEDGER MISMATCH in cycle")
                    failures += 1
            rate = mbps * (1 << 20)
            floor = max(0.0, (ap["bytes_paid"] - rate) / rate)
            span = (max(stamps) - min(stamps)) / 1000.0 if stamps \
                else 0.0
            print(f"  budget: paid={ap['bytes_paid']}B floor="
                  f"{floor:.1f}s span={span:.1f}s "
                  f"paced_sleep={ap['paced_sleep_s']}s")
            if span < floor * 0.9:
                print("  BUDGET BROKEN: repairs finished faster than "
                      "the token bucket allows")
                failures += 1
            if floor > 1.0 and ap["paced_sleep_s"] <= 0:
                print("  pacing never engaged")
                failures += 1

            bad += await verify(c, payloads, "after convergence")
            return bad + failures
    finally:
        procs.kill_all()


def _filer_failpoints(fport: int, method: str, query: str = "") -> None:
    # the filer's path-shadowed admin surface lives under /__debug__/
    req = urllib.request.Request(
        f"http://127.0.0.1:{fport}/__debug__/failpoints{query}",
        method=method)
    with urllib.request.urlopen(req, timeout=10) as r:
        r.read()


def _shards_body(port: int, path: str, body: dict) -> dict:
    import json as _json
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=_json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=15) as r:
        return _json.loads(r.read())


async def scenario_meta(tmp: str) -> int:
    """Sharded filer metadata plane acceptance (ISSUE 18). Three
    phases:

    1. QPS A/B — bench_meta.run_bench at 1 shard then 4; the
       op-accounted aggregate (sum of per-shard SOLO rates, locality
       proven by the routed counters) must scale >= 3x.
    2. Online split under chaos — populate /soak/hot on shard 0, arm
       filer.shard.* failpoints, commit a split_intent to shard 1,
       SIGKILL the SOURCE filer while the journaled move is pending,
       restart it and let the raft-committed intent replay to
       completion. Foreground creates/stats/lists run the whole time
       (retrying transient faults) and must end with ZERO given-up
       ops; the final paged enumeration must match the expected
       namespace exactly — no lost entry, no duplicate, mtimes
       byte-identical — and the source shard's local copy of the
       moved prefix must be fully tombstoned.
    3. Cross-shard rename storm — journaled two-phase moves from
       shard 0 into the split prefix on shard 1, with the source
       filer SIGKILLed mid-storm; every dst must exist exactly once
       with the src's mtime, every src must be gone.
    """
    import json as _json

    import aiohttp

    import bench_meta
    from seaweedfs_tpu.util import failpoints as _fp
    from seaweedfs_tpu.util.client import FilerHttpClient, OperationError
    quick = "--quick" in sys.argv
    failures = 0
    port0 = BASE_PORT + 180

    # -- phase 1: the >=3x op-accounted scaling gate -------------------
    ab_ops = 100 if quick else 600
    r1 = r4 = None
    for n, off in ((1, 0), (4, 20)):
        d = os.path.join(tmp, f"ab{n}")
        await asyncio.to_thread(os.makedirs, d, exist_ok=True)
        r = await bench_meta.run_bench(n, ab_ops, d,
                                       base_port=port0 + off)
        if n == 1:
            r1 = r
        else:
            r4 = r
    x = r4["aggregate_qps"] / max(r1["aggregate_qps"], 1e-9)
    print(f"  A/B: 1-shard {r1['aggregate_qps']:.0f} QPS, 4-shard "
          f"{r4['aggregate_qps']:.0f} QPS -> {x:.2f}x "
          f"(storm_errors={r1['storm_errors']}+{r4['storm_errors']})")
    if x < 3.0:
        print("  FAIL: aggregate metadata QPS did not scale >= 3x")
        failures += 1
    if r1["storm_errors"] or r4["storm_errors"]:
        print("  FAIL: errors under the concurrent storm")
        failures += 1
    for c in r4["counters"]:
        # locality is the accounting's foundation: a shard serving
        # redirects instead of local ops would inflate nothing
        if c["local"] <= 0 or c["redirect"] * 20 > c["local"]:
            print(f"  FAIL: shard {c['url']} not serving locally: {c}")
            failures += 1

    # -- phase 2: online split under failpoints + SIGKILL --------------
    procs = Procs(tmp)
    try:
        port = port0 + 40
        master = f"127.0.0.1:{port}"
        fports = [port + 1, port + 2]
        filers = [f"127.0.0.1:{p}" for p in fports]

        async def spawn_filer(sid: int):
            return await procs.spawn(
                "filer", "-port", str(fports[sid]), "-ip", "127.0.0.1",
                "-master", master, "-store", "sqlite",
                "-dbPath", os.path.join(tmp, f"filer{sid}.db"),
                "-shard.id", str(sid), "-shard.of", "2",
                "-shard.peers", ",".join(filers),
                "-shard.splitMbps", "0.05" if quick else "0.02")

        await procs.spawn("master", "-port", str(port),
                          "-ip", "127.0.0.1",
                          "-mdir", os.path.join(tmp, "m"))
        for sid in range(2):
            await spawn_filer(sid)
        for _ in range(120):
            try:
                m = _http_json(port, "/cluster/shards")
                if {"0", "1"} <= set(m.get("owners", {})):
                    break
            except OSError:
                pass
            await asyncio.sleep(0.5)
        else:
            raise RuntimeError("filer shards never registered")

        n_seed = 300 if quick else 800
        expect: dict[str, float] = {}
        async with FilerHttpClient(filers, master_url=master) as cli:
            sem = asyncio.Semaphore(16)

            async def seed(i: int) -> None:
                p = f"/soak/hot/d{i % 10}/f{i:04d}"
                mt = 1_700_000_000.0 + i
                async with sem:
                    await cli.request(
                        "POST", "/__api__/entry", route_path=p,
                        data=_json.dumps({"FullPath": p,
                                          "Mtime": mt}).encode())
                expect[p] = mt

            await asyncio.gather(*(seed(i) for i in range(n_seed)))
            print(f"  seeded {len(expect)} entries under /soak/hot "
                  f"(shard 0)")

            # chaos: migration batches on the source always fail (the
            # move is guaranteed pending when the SIGKILL lands), the
            # routed gate throws/stalls a slice of foreground hops,
            # and the client's own hop site stalls in-process
            _filer_failpoints(fports[0], "POST",
                              "?site=filer.shard.split&spec=error")
            for fp in fports:
                _filer_failpoints(
                    fp, "POST",
                    "?site=filer.shard.route&spec=error@0.03")
            _fp.arm("filer.shard.route", "latency=5@0.05")

            # foreground load for the WHOLE split window: transient
            # faults (injected 5xx, the dead-filer gap) are retried,
            # an op that never lands within its deadline is a failure
            stop = asyncio.Event()
            fg = {"ok": 0, "retries": 0, "gaveup": 0, "seq": 0}

            async def fg_op(kind: str, path: str, mt: float) -> bool:
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    try:
                        if kind == "create":
                            await cli.request(
                                "POST", "/__api__/entry",
                                route_path=path,
                                data=_json.dumps(
                                    {"FullPath": path,
                                     "Mtime": mt}).encode())
                        elif kind == "stat":
                            await cli.stat(path)
                        else:
                            await cli.list_dir(path, limit=64)
                        fg["ok"] += 1
                        return True
                    except (OperationError, aiohttp.ClientError,
                            asyncio.TimeoutError, OSError):
                        fg["retries"] += 1
                        await asyncio.sleep(0.3)
                fg["gaveup"] += 1
                return False

            async def fg_loop() -> None:
                rng = random.Random(77)
                while not stop.is_set():
                    r = rng.random()
                    if r < 0.5:
                        fg["seq"] += 1
                        p = f"/soak/hot/live/f{fg['seq']:05d}"
                        mt = 1_750_000_000.0 + fg["seq"]
                        if await fg_op("create", p, mt):
                            expect[p] = mt
                    elif r < 0.8 and expect:
                        await fg_op("stat", rng.choice(
                            sorted(expect)[:50]), 0)
                    else:
                        await fg_op("list",
                                    f"/soak/hot/d{rng.randrange(10)}",
                                    0)
                    await asyncio.sleep(0.02)

            fg_tasks = [asyncio.create_task(fg_loop())
                        for _ in range(3)]

            _shards_body(port, "/cluster/shards",
                         {"op": "split_intent", "prefix": "/soak/hot",
                          "to": 1})
            await asyncio.sleep(2.0 if quick else 3.0)
            m = _http_json(port, "/cluster/shards")
            mv = [v for v in m.get("moves", ())
                  if v["id"] == "split:/soak/hot"]
            if not mv:
                print("  FAIL: split intent not pending in the map")
                failures += 1
            print(f"  split committed, state="
                  f"{mv[0]['state'] if mv else '?'}; SIGKILLing the "
                  f"source filer with the move journaled")
            procs.procs[1].send_signal(signal.SIGKILL)
            await asyncio.sleep(1.0)
            await spawn_filer(0)
            # the restarted source replays the raft-committed intent;
            # re-arm a moderate batch-failure rate so the replay
            # itself retries through injected faults
            for _ in range(60):
                try:
                    _filer_failpoints(
                        fports[0], "POST",
                        "?site=filer.shard.split&spec=error@0.2")
                    break
                except OSError:
                    await asyncio.sleep(0.5)

            deadline = time.monotonic() + (180 if quick else 300)
            done = False
            while time.monotonic() < deadline:
                await asyncio.sleep(2)
                try:
                    m = _http_json(port, "/cluster/shards")
                except OSError:
                    continue
                rules = {tuple(r) for r in m.get("rules", ())}
                if not m.get("moves") and ("/soak/hot", 1) in rules:
                    done = True
                    break
            if not done:
                print("  FAIL: split never drained after the replay")
                failures += 1
            else:
                print("  split replayed to completion: /soak/hot -> "
                      "shard 1, moves empty")

            stop.set()
            await asyncio.gather(*fg_tasks)
            print(f"  foreground: ok={fg['ok']} retries={fg['retries']}"
                  f" gaveup={fg['gaveup']}")
            failures += fg["gaveup"]

            # -- phase 3: cross-shard rename storm with a kill ---------
            n_ren = 12 if quick else 30
            for i in range(n_ren):
                p = f"/soak/ren/r{i:03d}"
                await cli.request(
                    "POST", "/__api__/entry", route_path=p,
                    data=_json.dumps({"FullPath": p,
                                      "Mtime": 1_800_000_000.0 + i
                                      }).encode())

            async def ren(i: int) -> bool:
                src = f"/soak/ren/r{i:03d}"
                dst = f"/soak/hot/m{i:03d}"
                deadline = time.monotonic() + 90
                while time.monotonic() < deadline:
                    try:
                        await cli.rename(src, dst)
                        return True
                    except (OperationError, aiohttp.ClientError,
                            asyncio.TimeoutError, OSError):
                        try:
                            await cli.stat(dst)
                            try:
                                await cli.stat(src)
                            except OperationError:
                                return True      # move replayed through
                        except (OperationError, aiohttp.ClientError,
                                asyncio.TimeoutError, OSError):
                            pass  # dst not there yet: retry until the
                            # deadline; a stuck move fails below
                        await asyncio.sleep(0.5)
                return False

            async def ren_batch(lo: int, hi: int) -> int:
                res = await asyncio.gather(*(ren(i)
                                             for i in range(lo, hi)))
                return sum(0 if ok else 1 for ok in res)

            bad_ren = await ren_batch(0, n_ren // 3)
            procs.procs[-1].send_signal(signal.SIGKILL)
            print("  SIGKILLed the rename source filer mid-storm")
            await asyncio.sleep(1.0)
            await spawn_filer(0)
            bad_ren += await ren_batch(n_ren // 3, n_ren)
            if bad_ren:
                print(f"  FAIL: {bad_ren} renames never completed")
                failures += bad_ren
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                try:
                    if not _http_json(port,
                                      "/cluster/shards").get("moves"):
                        break
                except OSError:
                    pass
                await asyncio.sleep(1)
            else:
                print("  FAIL: rename moves never drained")
                failures += 1
            for i in range(n_ren):
                expect[f"/soak/hot/m{i:03d}"] = 1_800_000_000.0 + i

            # -- byte-verify: exactly-once, in-order enumeration -------
            for fp in fports:
                _filer_failpoints(fp, "DELETE")
            _fp.reset()

            async def list_all(d: str, limit: int = 7) -> dict:
                out: dict[str, dict] = {}
                start, inc = "", False
                while True:
                    page = await cli.list_dir(d, start_file=start,
                                              limit=limit,
                                              inclusive=inc)
                    names = [e["FullPath"].rsplit("/", 1)[1]
                             for e in page]
                    if names != sorted(names):
                        print(f"  FAIL: {d} page out of order")
                        nonlocal_fail[0] += 1
                    for e, nm in zip(page, names):
                        if e["FullPath"] in out:
                            print(f"  FAIL: duplicate "
                                  f"{e['FullPath']} across pages")
                            nonlocal_fail[0] += 1
                        out[e["FullPath"]] = e
                    if len(page) < limit:
                        return out
                    start = names[-1]

            nonlocal_fail = [0]
            got: dict[str, dict] = {}
            dirs = ([f"/soak/hot/d{i}" for i in range(10)]
                    + ["/soak/hot/live", "/soak/hot", "/soak/ren"])
            for d in dirs:
                for p, e in (await list_all(d)).items():
                    if not e.get("IsDirectory"):
                        got[p] = e
            failures += nonlocal_fail[0]
            missing = sorted(set(expect) - set(got))
            extra = sorted(set(got) - set(expect))
            stale = [p for p in expect
                     if p in got and got[p]["Mtime"] != expect[p]]
            if missing or extra or stale:
                print(f"  FAIL: lost={len(missing)} dup/extra="
                      f"{len(extra)} stale-mtime={len(stale)}")
                for p in (missing[:3] + extra[:3] + stale[:3]):
                    print(f"    {p}")
                failures += len(missing) + len(extra) + len(stale)
            else:
                print(f"  byte-verify: {len(got)} entries exactly "
                      f"once, every mtime intact")

            # tombstone completeness: the SOURCE shard must hold no
            # local copy of the moved prefix (peer-internal local=1
            # listing bypasses routing)
            left = _http_json(
                fports[0],
                "/__api__/list?path=/soak/hot&local=1").get(
                "entries", [])
            if left:
                print(f"  FAIL: {len(left)} entries still on the "
                      f"source shard after tombstone")
                failures += 1
            st = [_http_json(p, "/__debug__/shards") for p in fports]
            print(f"  shard entries: {[s['entries'] for s in st]}, "
                  f"replayed={st[0]['counters']['replayed']}, "
                  f"ingested={st[1]['counters']['ingest']}")
        return failures
    finally:
        _fp.reset()
        procs.kill_all()


SCENARIOS = {
    "ec": scenario_ec,
    "vacuum-race": scenario_vacuum_race,
    "rebuild": scenario_rebuild,
    "failover": scenario_failover,
    "partition": scenario_partition,
    "workers": scenario_workers,
    "cache-churn": scenario_cache_churn,
    "scrub": scenario_scrub,
    "heal": scenario_heal,
    "slo": scenario_slo,
    "qos": scenario_qos,
    "meta": scenario_meta,
}


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which != "all" and which not in SCENARIOS:
        raise SystemExit(f"unknown scenario {which!r}; "
                         f"choose from: all, {', '.join(SCENARIOS)}")
    names = list(SCENARIOS) if which == "all" else [which]
    total_bad = 0
    for name in names:
        print(f"== soak: {name}")
        tmp = tempfile.mkdtemp(prefix=f"swtpu_soak_{name}_")
        try:
            total_bad += asyncio.run(SCENARIOS[name](tmp))
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    print("PASS" if total_bad == 0 else f"FAIL ({total_bad} bad reads)")
    sys.exit(0 if total_bad == 0 else 1)


if __name__ == "__main__":
    main()
