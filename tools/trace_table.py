"""Per-tier latency breakdown from /debug/traces payloads.

Shared by tools/bench_needle.py (`trace` mode) and tools/chaos.py
(`--trace`): pulls the trace rings of one or more daemons, folds the
spans into per-(tier, op) rows of self-time — the non-overlapping
"which tier ate the time" attribution computed by util/tracing.py —
and renders an aligned text table:

    tier      op       spans   p50ms   p95ms   avg_self  total_self
    volume    read      1820     0.8     2.1        0.6      1092.0
    store     read      1820     0.4     1.2        0.4       728.0

Usage as a script:

    python tools/trace_table.py host:port [host:port ...]
    python tools/trace_table.py --cluster master:port <trace_id>

`--cluster` renders ONE assembled cross-host trace from the leader's
/debug/cluster/trace/<id> — per-host/per-tier self-time, one row per
(host, tier, op), plus the missing_nodes rows when members were down.
"""

from __future__ import annotations

import json
import sys
import urllib.request


def fetch(addr: str, path: str = "/debug/traces",
          n: int = 200, timeout: float = 10.0) -> dict | None:
    """One daemon's trace payload, or None when unreachable."""
    url = f"http://{addr}{path}?n={n}&slowest=50"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.load(r)
    except (OSError, ValueError):
        return None


def rows_from_payloads(payloads: list[dict]) -> list[dict]:
    """Fold trace payloads into per-(tier, op) rows, deduping spans
    repeated between the recent and slowest lists."""
    seen: set[tuple[str, str]] = set()
    per: dict[tuple[str, str], list[tuple[float, float]]] = {}
    for p in payloads:
        if not p:
            continue
        for g in list(p.get("traces", ())) + list(p.get("slowest", ())):
            for s in g.get("spans", ()):
                key = (s.get("trace", ""), s.get("span", ""))
                if key in seen:
                    continue
                seen.add(key)
                per.setdefault((s.get("tier", "?"), s.get("op", "?")),
                               []).append((s.get("dur_ms", 0.0),
                                           s.get("self_ms",
                                                 s.get("dur_ms", 0.0))))
    rows = []
    for (tier, op), vals in per.items():
        durs = sorted(d for d, _ in vals)
        selfs = [sf for _, sf in vals]

        def pct(p: float) -> float:
            return durs[min(len(durs) - 1, int(p / 100 * len(durs)))]

        rows.append({
            "tier": tier, "op": op, "spans": len(vals),
            "p50_ms": round(pct(50), 2), "p95_ms": round(pct(95), 2),
            "p99_ms": round(pct(99), 2),
            "avg_self_ms": round(sum(selfs) / len(selfs), 3),
            "total_self_ms": round(sum(selfs), 1),
        })
    rows.sort(key=lambda r: -r["total_self_ms"])
    return rows


def render(rows: list[dict]) -> str:
    if not rows:
        return "(no traced spans — is -trace.sample > 0?)"
    cols = ["tier", "op", "spans", "p50_ms", "p95_ms", "p99_ms",
            "avg_self_ms", "total_self_ms"]
    table = [cols] + [[str(r[c]) for c in cols] for r in rows]
    widths = [max(len(line[i]) for line in table)
              for i in range(len(cols))]
    out = []
    for line in table:
        out.append("  ".join(v.ljust(w) for v, w in zip(line, widths)))
    return "\n".join(out)


def breakdown(addrs: list[str], paths: dict[str, str] | None = None
              ) -> str:
    """Fetch + fold + render in one call. `paths` overrides the debug
    path per address (the filer/S3 gateways use /__debug__/traces)."""
    payloads = []
    for addr in addrs:
        path = (paths or {}).get(addr, "/debug/traces")
        payloads.append(fetch(addr, path))
    return render(rows_from_payloads([p for p in payloads if p]))


# ---------------------------------------------------------------------------
# --cluster: one assembled cross-host trace


def fetch_cluster(master_addr: str, trace_id: str,
                  extra: str = "", timeout: float = 30.0) -> dict | None:
    """One assembled trace from the leader's /debug/cluster/trace/<id>
    (extra= forwards unregistered members, e.g. 's3:host:port')."""
    url = f"http://{master_addr}/debug/cluster/trace/{trace_id}"
    if extra:
        url += f"?extra={extra}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.load(r)
    except (OSError, ValueError):
        return None


def _flatten_tree(tree: list[dict]) -> list[dict]:
    out: list[dict] = []
    stack = list(tree)
    while stack:
        d = stack.pop()
        out.append(d)
        stack.extend(d.get("children", ()))
    return out


def cluster_rows(assembled: dict) -> list[dict]:
    """Per-(host, tier, op) self-time rows from one assembled cluster
    trace — "which host's which tier ate this request's time"."""
    per: dict[tuple[str, str, str], list[float]] = {}
    for s in _flatten_tree(assembled.get("tree", ())):
        per.setdefault((s.get("host", "?"), s.get("tier", "?"),
                        s.get("op", "?")),
                       []).append(s.get("self_ms",
                                        s.get("dur_ms", 0.0)))
    rows = []
    for (host, tier, op), selfs in per.items():
        rows.append({
            "host": host, "tier": tier, "op": op, "spans": len(selfs),
            "avg_self_ms": round(sum(selfs) / len(selfs), 3),
            "total_self_ms": round(sum(selfs), 1),
        })
    rows.sort(key=lambda r: -r["total_self_ms"])
    return rows


def render_cluster(assembled: dict | None) -> str:
    if not assembled or not assembled.get("tree"):
        return "(no assembled spans — bad trace id, or rings rotated?)"
    rows = cluster_rows(assembled)
    cols = ["host", "tier", "op", "spans", "avg_self_ms",
            "total_self_ms"]
    table = [cols] + [[str(r[c]) for c in cols] for r in rows]
    widths = [max(len(line[i]) for line in table)
              for i in range(len(cols))]
    out = [f"trace {assembled.get('trace_id', '?')}: "
           f"{assembled.get('spans', 0)} spans, "
           f"{assembled.get('dur_ms', 0)}ms, hosts="
           f"{','.join(assembled.get('hosts', {}) or ['?'])}"]
    for line in table:
        out.append("  ".join(v.ljust(w) for v, w in zip(line, widths)))
    for m in assembled.get("missing_nodes", ()):
        out.append(f"missing: {m.get('node')} ({m.get('kind')}): "
                   f"{m.get('error')}")
    return "\n".join(out)


def cluster_breakdown(master_addr: str, trace_id: str,
                      extra: str = "") -> str:
    return render_cluster(fetch_cluster(master_addr, trace_id,
                                        extra=extra))


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--cluster":
        if len(sys.argv) < 4:
            print(__doc__)
            sys.exit(2)
        print(cluster_breakdown(sys.argv[2], sys.argv[3],
                                extra=(sys.argv[4]
                                       if len(sys.argv) > 4 else "")))
        sys.exit(0)
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(2)
    print(breakdown(sys.argv[1:]))
