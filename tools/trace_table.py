"""Per-tier latency breakdown from /debug/traces payloads.

Shared by tools/bench_needle.py (`trace` mode) and tools/chaos.py
(`--trace`): pulls the trace rings of one or more daemons, folds the
spans into per-(tier, op) rows of self-time — the non-overlapping
"which tier ate the time" attribution computed by util/tracing.py —
and renders an aligned text table:

    tier      op       spans   p50ms   p95ms   avg_self  total_self
    volume    read      1820     0.8     2.1        0.6      1092.0
    store     read      1820     0.4     1.2        0.4       728.0

Usage as a script:

    python tools/trace_table.py host:port [host:port ...]
"""

from __future__ import annotations

import json
import sys
import urllib.request


def fetch(addr: str, path: str = "/debug/traces",
          n: int = 200, timeout: float = 10.0) -> dict | None:
    """One daemon's trace payload, or None when unreachable."""
    url = f"http://{addr}{path}?n={n}&slowest=50"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.load(r)
    except (OSError, ValueError):
        return None


def rows_from_payloads(payloads: list[dict]) -> list[dict]:
    """Fold trace payloads into per-(tier, op) rows, deduping spans
    repeated between the recent and slowest lists."""
    seen: set[tuple[str, str]] = set()
    per: dict[tuple[str, str], list[tuple[float, float]]] = {}
    for p in payloads:
        if not p:
            continue
        for g in list(p.get("traces", ())) + list(p.get("slowest", ())):
            for s in g.get("spans", ()):
                key = (s.get("trace", ""), s.get("span", ""))
                if key in seen:
                    continue
                seen.add(key)
                per.setdefault((s.get("tier", "?"), s.get("op", "?")),
                               []).append((s.get("dur_ms", 0.0),
                                           s.get("self_ms",
                                                 s.get("dur_ms", 0.0))))
    rows = []
    for (tier, op), vals in per.items():
        durs = sorted(d for d, _ in vals)
        selfs = [sf for _, sf in vals]

        def pct(p: float) -> float:
            return durs[min(len(durs) - 1, int(p / 100 * len(durs)))]

        rows.append({
            "tier": tier, "op": op, "spans": len(vals),
            "p50_ms": round(pct(50), 2), "p95_ms": round(pct(95), 2),
            "p99_ms": round(pct(99), 2),
            "avg_self_ms": round(sum(selfs) / len(selfs), 3),
            "total_self_ms": round(sum(selfs), 1),
        })
    rows.sort(key=lambda r: -r["total_self_ms"])
    return rows


def render(rows: list[dict]) -> str:
    if not rows:
        return "(no traced spans — is -trace.sample > 0?)"
    cols = ["tier", "op", "spans", "p50_ms", "p95_ms", "p99_ms",
            "avg_self_ms", "total_self_ms"]
    table = [cols] + [[str(r[c]) for c in cols] for r in rows]
    widths = [max(len(line[i]) for line in table)
              for i in range(len(cols))]
    out = []
    for line in table:
        out.append("  ".join(v.ljust(w) for v, w in zip(line, widths)))
    return "\n".join(out)


def breakdown(addrs: list[str], paths: dict[str, str] | None = None
              ) -> str:
    """Fetch + fold + render in one call. `paths` overrides the debug
    path per address (the filer/S3 gateways use /__debug__/traces)."""
    payloads = []
    for addr in addrs:
        path = (paths or {}).get(addr, "/debug/traces")
        payloads.append(fetch(addr, path))
    return render(rows_from_payloads([p for p in payloads if p]))


if __name__ == "__main__":
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(2)
    print(breakdown(sys.argv[1:]))
