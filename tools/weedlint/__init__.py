"""weedlint — whole-tree static analysis for asyncio correctness,
resource safety, observability hygiene, and cache/failpoint
discipline.

Grown from tools/lint_robustness.py (PR-2's 3-pass, 167-line lint)
into a framework: shared single-walk visitor driver, per-line
suppression comments with mandatory reasons, a checked-in baseline
for grandfathered findings, rule selection, and JSON output. See
STATIC_ANALYSIS.md for the rule catalog and how to add a pass.

    python -m tools.weedlint seaweedfs_tpu tools
    python -m tools.weedlint --list-rules
    python -m tools.weedlint tests --report-only
"""

from __future__ import annotations

from .baseline import Baseline, BaselineEntry
from .core import Finding, Rule, run_file, run_paths
from .rules import (ALL_RULE_CLASSES, ALL_RULE_IDS, LEGACY_RULE_IDS,
                    META_RULE_IDS, make_rules)

__all__ = [
    "Baseline", "BaselineEntry", "Finding", "Rule", "run_file",
    "run_paths", "ALL_RULE_CLASSES", "ALL_RULE_IDS",
    "LEGACY_RULE_IDS", "META_RULE_IDS", "make_rules", "lint",
]


def lint(paths, *, select=None, ignore=None, baseline_path=None,
         check_unused=None):
    """One-call API used by tests, the CI gate and the back-compat
    shim: lint `paths`, apply the baseline, return a LintResult."""
    from .cli import LintResult, apply_baseline
    rules = make_rules(select, ignore)
    if check_unused is None:
        check_unused = not select and not ignore
    findings = run_paths(list(paths), rules, check_unused=check_unused)
    baseline, stale, format_errors = apply_baseline(
        findings, baseline_path)
    return LintResult(findings=findings, stale=stale,
                      baseline_errors=format_errors)
