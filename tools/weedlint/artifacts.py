"""Cross-artifact extraction for the docs-drift pass.

The last four PRs each grew a hand-maintained catalog — CLI flags in
README/OBSERVABILITY, metric names and `/debug` surfaces in
OBSERVABILITY, journal event types and failpoint sites in ROBUSTNESS,
scrub knobs in EC.md — and none of them has ever been machine-checked
against the code. This module pulls the five artifact families out of
the AST (never by running anything) and out of the markdown (never by
guessing prose), so rules/drift.py can diff the two:

- **flags**       — every `add_argument("-x", ...)` in
  seaweedfs_tpu/cli.py;
- **metrics**     — the first argument of every
  Counter/Gauge/Histogram/Summary construction;
- **events**      — the first argument of every `events.record(...)`;
- **failpoints**  — the first argument of every
  `failpoints.fail/sync_fail/corrupt(...)` planted in the package
  (tools/ *arms* sites, it doesn't plant them);
- **routes**      — every `/debug/<name>` / `/__debug__/<name>`
  string constant (registration and dispatch comparisons are both the
  live surface; names are normalized to the tail segment so the
  gateway twins don't double-count), plus the tiered-storage admin
  surface `/admin/tier/<name>` (normalized to `tier/<name>`) — the
  route family README's tier docs claim.

Doc side, two strictnesses:

- a *mention* is any match inside the scanned catalogs (README.md,
  OBSERVABILITY.md, ROBUSTNESS.md, EC.md) — code with no mention
  anywhere is **undocumented**;
- a *claim* is an entry in a designated catalog table (flag tables,
  the ROBUSTNESS `| site |` / `| type |` tables) or, for the families
  with an unambiguous lexical shape (metrics, routes), any token in
  any scanned doc — a claim naming nothing in the code is **dead**.

Metric tokens understand the docs' two compression idioms:
`SeaweedFS_disk_{free,used}_bytes` expands mid-token braces, and a
token ending in `_` or `*` is a family prefix that must match at
least one live metric.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from .core import REPO
from .symbols import SymbolTable, chain_of

#: the catalogs docs-drift diffs against (repo-relative)
DOC_FILES = ("README.md", "OBSERVABILITY.md", "ROBUSTNESS.md", "EC.md")

_METRIC_CTORS = frozenset({"Counter", "Gauge", "Histogram", "Summary"})
# fail/sync_fail/corrupt raise at the site; take/pending are the
# response-phase form (wire.py's volume.read.http) — all five plant
_FAILPOINT_FNS = frozenset({"fail", "sync_fail", "corrupt", "take",
                            "pending"})
_ROUTE_RE = re.compile(r"^/(?:debug|__debug__)/([a-z_]+)$")
_TIER_ROUTE_RE = re.compile(r"^/admin/(tier/[a-z_]+)$")

_FLAG_TOKEN_RE = re.compile(r"(?<![\w-])-([a-zA-Z][a-zA-Z0-9.]*)")
_METRIC_TOKEN_RE = re.compile(r"SeaweedFS_[A-Za-z0-9_{},*]*")
_ROUTE_TOKEN_RE = re.compile(r"/(?:debug|__debug__)/([a-z_]+)")
_TIER_ROUTE_TOKEN_RE = re.compile(r"/admin/(tier/[a-z_]+)")
_BACKTICK_RE = re.compile(r"`([^`]+)`")


@dataclass
class Artifact:
    """One name the code defines, with every site it appears at."""

    name: str
    rel: str
    line: int


@dataclass
class DocClaim:
    """One name a catalog table (or unambiguous doc token) asserts."""

    name: str
    rel: str
    line: int


@dataclass
class CodeArtifacts:
    flags: dict[str, Artifact] = field(default_factory=dict)
    metrics: dict[str, Artifact] = field(default_factory=dict)
    events: dict[str, Artifact] = field(default_factory=dict)
    failpoints: dict[str, Artifact] = field(default_factory=dict)
    routes: dict[str, Artifact] = field(default_factory=dict)


@dataclass
class DocArtifacts:
    """mentions: name -> True (any reference counts as documentation);
    claims per family: entries that must name live code."""

    flag_mentions: set[str] = field(default_factory=set)
    metric_mentions: list[str] = field(default_factory=list)
    event_mentions: set[str] = field(default_factory=set)
    failpoint_mentions: set[str] = field(default_factory=set)
    route_mentions: set[str] = field(default_factory=set)

    flag_claims: list[DocClaim] = field(default_factory=list)
    metric_claims: list[DocClaim] = field(default_factory=list)
    event_claims: list[DocClaim] = field(default_factory=list)
    failpoint_claims: list[DocClaim] = field(default_factory=list)
    route_claims: list[DocClaim] = field(default_factory=list)


# -- code side -----------------------------------------------------------

def _first_str_arg(node: ast.Call) -> str | None:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _add(family: dict[str, Artifact], name: str, rel: str,
         line: int) -> None:
    family.setdefault(name, Artifact(name, rel, line))


def extract_code(table: SymbolTable) -> CodeArtifacts:
    out = CodeArtifacts()
    for mod in table.modules.values():
        # segment match, not prefix: fixture trees under a tmp dir
        # carry absolute rels but the same package layout
        in_pkg = "seaweedfs_tpu/" in mod.rel
        is_cli = mod.rel.endswith("seaweedfs_tpu/cli.py")
        if not in_pkg:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                m = _ROUTE_RE.match(node.value)
                if m:
                    _add(out.routes, m.group(1), mod.rel, node.lineno)
                m = _TIER_ROUTE_RE.match(node.value)
                if m:
                    _add(out.routes, m.group(1), mod.rel, node.lineno)
            if not isinstance(node, ast.Call):
                continue
            chain = chain_of(node.func)
            if not chain:
                continue
            tail = chain[-1]
            arg = _first_str_arg(node)
            if is_cli and tail == "add_argument" and arg \
                    and arg.startswith("-") and not arg.startswith("--"):
                _add(out.flags, arg.lstrip("-"), mod.rel, node.lineno)
            elif tail in _METRIC_CTORS and arg \
                    and arg.startswith("SeaweedFS_"):
                _add(out.metrics, arg, mod.rel, node.lineno)
            elif tail == "record" and len(chain) >= 2 \
                    and chain[-2] == "events" and arg:
                _add(out.events, arg, mod.rel, node.lineno)
            elif tail in _FAILPOINT_FNS and len(chain) >= 2 \
                    and chain[-2] == "failpoints" and arg:
                _add(out.failpoints, arg, mod.rel, node.lineno)
    return out


# -- doc side ------------------------------------------------------------

def _expand_metric_token(tok: str) -> list[str]:
    """'SeaweedFS_disk_{free,used}_bytes{path}' ->
    ['SeaweedFS_disk_free_bytes', 'SeaweedFS_disk_used_bytes'].
    A trailing brace group is a label set, not alternatives — strip it.
    Returns [] for tokens that carry no name (pure 'SeaweedFS_')."""
    tok = re.sub(r"\{[^}]*\}$", "", tok)
    head, sep, tail = tok.rpartition("{")
    if sep and "}" not in tail:
        # the source regex stops at '=' / '"', so a labeled example
        # like SeaweedFS_x_total{volume="1"} arrives with an UNCLOSED
        # brace — that trailing fragment is a label set, not part of
        # the name
        tok = head
    m = re.match(r"^([A-Za-z0-9_]*)\{([^}]*)\}([A-Za-z0-9_*]*)$", tok)
    if m:
        head, alts, rest = m.groups()
        return [v for a in alts.split(",")
                for v in _expand_metric_token(head + a.strip() + rest)]
    if "{" in tok or "}" in tok or "," in tok:
        return []
    return [tok] if tok != "SeaweedFS_" else []


def _is_prefix_token(tok: str) -> bool:
    return tok.endswith("*") or tok.endswith("_")


def _table_cell_claims(lines: list[str], header_key: str,
                       rel: str) -> list[DocClaim]:
    """First-column backtick tokens of every markdown table whose
    header's first cell is `header_key` (e.g. 'site', 'type'). Cells
    like `` `volume_mount` / `volume_unmount` `` claim both names."""
    claims: list[DocClaim] = []
    in_table = False
    for i, line in enumerate(lines, 1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        first = cells[0].strip("* ").lower()
        if first == header_key:
            in_table = True
            continue
        if set(cells[0]) <= {"-", ":", " "}:
            continue
        if in_table:
            for tok in _BACKTICK_RE.findall(cells[0]):
                for part in re.split(r"[\s/]+", tok):
                    if part:
                        claims.append(DocClaim(part, rel, i))
    return claims


def _flag_table_claims(lines: list[str], rel: str) -> list[DocClaim]:
    """Backticked `-flag` first cells of any markdown table row — the
    designated flag catalogs (README flag reference, OBSERVABILITY
    flags table). Prose mentions of a flag are free; a table row is a
    claim that the flag exists."""
    claims: list[DocClaim] = []
    for i, line in enumerate(lines, 1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            continue
        first = stripped.strip("|").split("|", 1)[0].strip()
        for tok in _BACKTICK_RE.findall(first):
            m = _FLAG_TOKEN_RE.match(tok)
            if m and tok.startswith("-"):
                claims.append(DocClaim(m.group(1), rel, i))
    return claims


def extract_docs(repo: str = REPO,
                 doc_files=DOC_FILES) -> DocArtifacts:
    out = DocArtifacts()
    for rel in doc_files:
        path = os.path.join(repo, rel)
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for i, line in enumerate(lines, 1):
            for span in _BACKTICK_RE.findall(line):
                for m in _FLAG_TOKEN_RE.finditer(span):
                    out.flag_mentions.add(m.group(1))
            for tok in _METRIC_TOKEN_RE.findall(line):
                for name in _expand_metric_token(tok):
                    out.metric_mentions.append(name)
                    out.metric_claims.append(DocClaim(name, rel, i))
            for m in _ROUTE_TOKEN_RE.finditer(line):
                out.route_mentions.add(m.group(1))
                out.route_claims.append(DocClaim(m.group(1), rel, i))
            for m in _TIER_ROUTE_TOKEN_RE.finditer(line):
                out.route_mentions.add(m.group(1))
                out.route_claims.append(DocClaim(m.group(1), rel, i))
            for span in _BACKTICK_RE.findall(line):
                out.event_mentions.add(span.strip())
                out.failpoint_mentions.add(span.strip())
        out.flag_claims += _flag_table_claims(lines, rel)
        out.event_claims += _table_cell_claims(lines, "type", rel)
        out.failpoint_claims += _table_cell_claims(lines, "site", rel)
    return out


def metric_documented(name: str, mentions: list[str]) -> bool:
    for tok in mentions:
        if _is_prefix_token(tok):
            if name.startswith(tok.rstrip("*")):
                return True
        elif name == tok:
            return True
    return False


def metric_claim_live(tok: str, code: dict[str, Artifact]) -> bool:
    if _is_prefix_token(tok):
        prefix = tok.rstrip("*")
        return any(n.startswith(prefix) for n in code)
    return tok in code
