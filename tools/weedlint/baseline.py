"""Checked-in baseline of grandfathered findings.

The baseline lets a new rule land with enforcement ON while old,
reviewed findings are carried explicitly instead of silently: each
entry names the repo-relative path, the rule id, the exact source
line it excuses (so line-number drift doesn't rot it, but any edit to
the offending line re-opens the finding), and a mandatory written
justification.

Staleness is an error by design: an entry whose finding no longer
exists means somebody fixed the bug — the entry must be deleted in
the same PR, and tests/test_weedlint.py enforces that the checked-in
file never carries dead weight.
"""

from __future__ import annotations

import json
import os

DEFAULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "baseline.json")


class BaselineEntry:
    __slots__ = ("path", "rule", "code", "justification", "hits")

    def __init__(self, path: str, rule: str, code: str,
                 justification: str):
        self.path = path
        self.rule = rule
        self.code = code
        self.justification = justification
        self.hits = 0

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.path, self.rule, self.code)

    def to_dict(self) -> dict:
        return {"path": self.path, "rule": self.rule, "code": self.code,
                "justification": self.justification}

    def render(self) -> str:
        return f"{self.path} [{self.rule}] {self.code!r}"


class Baseline:
    def __init__(self, entries: list[BaselineEntry] | None = None,
                 path: str | None = None):
        self.path = path
        self.entries = entries or []
        self.format_errors: list[str] = []

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        bl = cls(path=path)
        for i, e in enumerate(data.get("entries", [])):
            entry = BaselineEntry(e.get("path", ""), e.get("rule", ""),
                                  e.get("code", ""),
                                  str(e.get("justification", "")).strip())
            if not entry.justification:
                bl.format_errors.append(
                    f"baseline entry #{i} ({entry.render()}) has no "
                    f"justification — every grandfathered finding "
                    f"must say why it is acceptable")
            bl.entries.append(entry)
        return bl

    def save(self, path: str | None = None) -> None:
        path = path or self.path or DEFAULT_PATH
        data = {"version": 1,
                "entries": [e.to_dict() for e in sorted(
                    self.entries, key=lambda e: e.key)]}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")

    # findings the baseline must never absorb: the meta-rules, and
    # syntax-error (its code key is always '' — one baselined entry
    # would mask every future syntax error in the file, i.e. a file no
    # rule ever scanned would lint clean)
    UNBASELINEABLE = ("suppress-format", "unused-suppression",
                      "syntax-error")

    def apply(self, findings) -> None:
        """Mark findings covered by an entry. One entry absorbs every
        finding with its (path, rule, code) key — a grandfathered
        shape repeated on N lines of one file is one reviewed fact."""
        index: dict[tuple[str, str, str], BaselineEntry] = {
            e.key: e for e in self.entries}
        for f in findings:
            if f.suppressed or f.rule in self.UNBASELINEABLE:
                continue
            e = index.get((f.rel, f.rule, f.code))
            if e is not None:
                f.baselined = True
                e.hits += 1

    def stale(self) -> list[BaselineEntry]:
        return [e for e in self.entries if e.hits == 0]

    @classmethod
    def from_findings(cls, findings, *, old: "Baseline | None" = None,
                      path: str | None = None) -> "Baseline":
        """Build a baseline from current unsuppressed findings,
        carrying justifications over from `old` where keys match; new
        entries get a TODO the format check will reject until a human
        writes the reason."""
        carried = {e.key: e.justification for e in old.entries} if old \
            else {}
        seen: dict[tuple[str, str, str], BaselineEntry] = {}
        for f in findings:
            if f.suppressed or f.rule in cls.UNBASELINEABLE:
                continue
            key = (f.rel, f.rule, f.code)
            if key not in seen:
                seen[key] = BaselineEntry(
                    f.rel, f.rule, f.code,
                    carried.get(key, ""))
        return cls(list(seen.values()), path=path)
