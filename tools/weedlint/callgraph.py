"""Phase-2 call graph: per-function call sites, bounded resolution,
and the property-propagation substrate the interprocedural rules run
on.

Every function body is walked once (its OWN body — nested defs and
lambdas are separate schedulable units and are never inlined into
their parent, matching phase 1's executor-thunk exemption). Each call
is classified:

- ``blocking``   — a known loop-stalling primitive (the same table
  phase 1's blocking-io rule uses: time.sleep, os.pread, open, ...);
- ``resolved``   — we can name the in-tree FunctionInfo it lands on
  (module functions, `self.`/`cls.` methods through the bounded MRO,
  `self.attr.method` through the attr-type heuristic, local
  `x = Ctor(); x.method()`, imports and from-imports);
- ``external``   — provably out of tree (stdlib/third-party modules,
  builtin container/str methods);
- ``unresolved`` — everything else. Resolution is deliberately
  bounded; the rate of unresolved candidates is the precision metric
  the `unresolved-call` diagnostic reports and
  tests/test_callgraph.py ceilings.

Executor boundaries: a call THROUGH ``run_in_executor`` /
``to_thread`` / ``tracing.run_in_executor`` is an edge to the event
loop's thread pool, not to the thunk — the thunk's blocking I/O is
sanctioned. Only direct (inline) calls create propagation edges, so
``transitive-blocking`` stops exactly where the loop stops executing.
"""

from __future__ import annotations

import ast
import builtins

from .rules.asynchrony import _BLOCKING_ATTRS, _BLOCKING_NAMES
from .symbols import (EXTERNAL_MODULES, FunctionInfo, SymbolTable,
                      chain_of)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_BUILTIN_NAMES = frozenset(dir(builtins))

# receiver-agnostic methods of builtin containers/str/bytes/int:
# chains ending in these are classified external (no resolution
# attempt) so dict.get()/list.append() noise doesn't drown the
# unresolved-call precision metric
BUILTIN_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "reverse",
    "sort", "clear", "copy", "pop", "popleft", "popitem", "keys",
    "values", "items", "get", "setdefault", "update", "add",
    "discard", "union", "intersection", "difference", "isdisjoint",
    "issubset", "issuperset", "startswith", "endswith", "split",
    "rsplit", "splitlines", "strip", "lstrip", "rstrip", "lower",
    "upper", "title", "capitalize", "casefold", "swapcase", "encode",
    "decode", "format", "format_map", "join", "partition",
    "rpartition", "replace", "find", "rfind", "index", "rindex",
    "count", "zfill", "ljust", "rjust", "center", "expandtabs",
    "translate", "maketrans", "isdigit", "isalpha", "isalnum",
    "isspace", "isidentifier", "isupper", "islower", "istitle",
    "hex", "to_bytes", "from_bytes", "bit_length", "as_integer_ratio",
    "hexdigest", "digest", "total_seconds", "timestamp", "isoformat",
    "strftime", "strptime", "group", "groups", "groupdict", "match",
    "search", "fullmatch", "finditer", "findall", "sub", "subn",
})

# calling THROUGH these runs the referenced thunk off the event loop
EXECUTOR_TAILS = frozenset({"run_in_executor", "to_thread", "submit"})

# Sanctioned sinks: functions whose blocking is accepted BY DESIGN and
# documented in STATIC_ANALYSIS.md. The bar for an entry is high — it
# must be bounded, rare-or-amortized I/O whose async alternative would
# cost more than it saves. Today that is exactly one function: glog's
# emitter (small line writes; the file open amortizes over a 64MB
# rotation; making logging async would reorder crash-time evidence and
# every production asyncio stack logs synchronously for the same
# reason). transitive-blocking stops its walk here the same way it
# stops at an executor boundary.
SANCTIONED_SINKS = frozenset({
    "seaweedfs_tpu.util.glog._emit",
})


class CallSite:
    __slots__ = ("node", "lineno", "chain", "kind", "target", "what")

    def __init__(self, node: ast.Call, chain, kind: str,
                 target: FunctionInfo | None = None, what: str = ""):
        self.node = node
        self.lineno = node.lineno
        self.chain = chain
        self.kind = kind            # blocking|resolved|external|unresolved
        self.target = target
        self.what = what            # blocking primitive / unresolved head

    def __repr__(self) -> str:  # pragma: no cover
        t = self.target.qual if self.target else self.what
        return f"<call {self.kind}:{t} @{self.lineno}>"


def iter_own_nodes(fn_node: ast.AST):
    """Every node of `fn_node`'s own body, never descending into
    nested defs/lambdas (they run on their own schedule)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FUNC_NODES):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _normalize(table: SymbolTable, fi: FunctionInfo, chain):
    """Rewrite a from-import alias head to its (module, symbol) form
    so `from time import sleep; sleep()` matches the blocking table."""
    if not chain:
        return chain
    fs = fi.module.from_symbols.get(chain[0])
    if fs and fs[0] and fs[0].split(".")[0] in EXTERNAL_MODULES:
        return (fs[0].split(".")[-1], fs[1]) + chain[1:]
    return chain


def classify_blocking(table: SymbolTable, fi: FunctionInfo,
                      chain) -> str:
    """'' or the blocking primitive name ('os.pread', 'open')."""
    chain = _normalize(table, fi, chain)
    if not chain:
        return ""
    if len(chain) == 1 and chain[0] in _BLOCKING_NAMES:
        return chain[0]
    if len(chain) == 2 and chain[1] in _BLOCKING_ATTRS.get(chain[0],
                                                           ()):
        return f"{chain[0]}.{chain[1]}"
    return ""


def _annotation_chain(ann) -> tuple[str, ...] | None:
    """A parameter annotation as a resolvable class chain: plain
    names/attributes, string forms ('VolumeServer'), and the X of
    `X | None`. Subscripts (list[dict], Optional[...]) are containers,
    not receiver types — skipped."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        parts = ann.value.strip().split(".")
        if all(p.isidentifier() for p in parts):
            return tuple(parts)
        return None
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        left = _annotation_chain(ann.left)
        if left is not None:
            return left
        return _annotation_chain(ann.right)
    if isinstance(ann, (ast.Name, ast.Attribute)):
        return chain_of(ann)
    return None


class Program:
    """Call sites for every function in the table + memoized
    propagation passes."""

    def __init__(self, table: SymbolTable):
        self.table = table
        self.calls: dict[str, list[CallSite]] = {}
        self.stats = {"resolved": 0, "unresolved": 0, "external": 0,
                      "blocking": 0}
        self._blocking_memo: dict[str, list | None] = {}
        self._cycle_cut = False
        for fi in table.functions.values():
            self.calls[fi.qual] = self._extract(fi)

    # -- extraction -----------------------------------------------------
    def _extract(self, fi: FunctionInfo) -> list[CallSite]:
        self._harvest_var_types(fi)
        sites = []
        for node in iter_own_nodes(fi.node):
            if isinstance(node, ast.Call):
                sites.append(self._classify(fi, node))
        sites.sort(key=lambda s: s.lineno)
        for s in sites:
            self.stats[s.kind] += 1
        return sites

    def _harvest_var_types(self, fi: FunctionInfo) -> None:
        args = fi.node.args
        if not isinstance(fi.node, ast.Lambda):
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                chain = _annotation_chain(a.annotation)
                if chain is None:
                    continue
                ci = self.table.resolve_class_chain(fi, chain)
                if ci is not None:
                    fi.var_types[a.arg] = ci.qual
        for node in iter_own_nodes(fi.node):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            if isinstance(node.value, ast.Call):
                value_chain = chain_of(node.value.func)
                if value_chain and value_chain[-1] == "partial" \
                        and node.value.args:
                    # f = functools.partial(self.method, x): calling
                    # f() lands on the wrapped callable
                    target = self._resolve_callable_ref(
                        fi, chain_of(node.value.args[0]))
                    if target is not None:
                        fi.var_funcs[name] = target
                        continue
                ci = self.table.resolve_class_chain(fi, value_chain)
                if ci is not None:
                    fi.var_types[name] = ci.qual
            elif isinstance(node.value, (ast.Attribute, ast.Name)):
                # f = self.method / f = helper: a bound-method or
                # function alias — calling f() lands on the target
                target = self._resolve_callable_ref(
                    fi, chain_of(node.value))
                if target is not None:
                    fi.var_funcs[name] = target

    def _resolve_callable_ref(self, fi: FunctionInfo,
                              chain) -> FunctionInfo | None:
        """A callable REFERENCE (no call parens): the FunctionInfo a
        later `ref()` would land on, or None when unresolvable. Class
        references are excluded — aliasing a class then calling it is
        construction, which var_types already models."""
        if not chain:
            return None
        if self.table.resolve_class_chain(fi, chain) is not None:
            return None
        kind, target = self._resolve(fi, chain)
        return target if kind == "resolved" else None

    def _classify(self, fi: FunctionInfo, node: ast.Call) -> CallSite:
        chain = chain_of(node.func)
        what = classify_blocking(self.table, fi, chain)
        if what:
            return CallSite(node, chain, "blocking", what=what)
        if chain and chain[-1] in EXECUTOR_TAILS:
            # the thunk argument runs off-loop; the dispatching call
            # itself is event-loop machinery
            return CallSite(node, chain, "external")
        kind, target = self._resolve(fi, chain)
        return CallSite(node, chain, kind, target=target,
                        what="" if target else
                        ".".join(chain) if chain else "<dynamic>")

    # -- resolution -----------------------------------------------------
    def _resolve(self, fi: FunctionInfo, chain):
        table = self.table
        if not chain:
            return "unresolved", None
        head = chain[0]
        if head == "<const>":
            return "external", None     # literal receivers are builtin
        if head == "<call>":
            # get_running_loop().x / self._volume(vid).write(n): the
            # receiver is a call result we do not type. Known-external
            # tails stay external; the rest is honestly unresolved.
            if chain[-1] in BUILTIN_METHODS:
                return "external", None
            return "unresolved", None
        if chain[-1] in BUILTIN_METHODS and len(chain) > 1:
            return "external", None
        if len(chain) == 1 and head in fi.var_funcs:
            return "resolved", fi.var_funcs[head]
        if head in ("self", "cls") and fi.cls is not None:
            if len(chain) == 2:
                m = table.lookup_method(fi.cls, chain[1])
                return ("resolved", m) if m else ("unresolved", None)
            if len(chain) == 3:
                tq = fi.cls.attr_types.get(chain[1])
                ci = table.class_by_qual(tq) if tq else None
                if ci is not None:
                    m = table.lookup_method(ci, chain[2])
                    if m:
                        return "resolved", m
            return "unresolved", None
        if head in fi.var_types and len(chain) == 2:
            ci = table.class_by_qual(fi.var_types[head])
            if ci is not None:
                m = table.lookup_method(ci, chain[1])
                if m:
                    return "resolved", m
            return "unresolved", None
        mod = fi.module
        if head in mod.functions and len(chain) == 1:
            return "resolved", mod.functions[head]
        if head in mod.classes:
            return self._resolve_via_class(mod.classes[head], chain[1:])
        if head in mod.from_symbols:
            return self._resolve_from_symbol(fi, chain)
        if head in mod.imports:
            return self._resolve_import(fi, chain)
        if head in _BUILTIN_NAMES:
            return "external", None
        if head in EXTERNAL_MODULES:
            return "external", None
        return "unresolved", None

    def _resolve_via_class(self, ci, rest):
        if len(rest) == 0:                      # Ctor()
            init = self.table.lookup_method(ci, "__init__")
            return "resolved", init             # init may be None
        if len(rest) == 1:                      # ClassName.method()
            m = self.table.lookup_method(ci, rest[0])
            return ("resolved", m) if m else ("unresolved", None)
        return "unresolved", None

    def _resolve_from_symbol(self, fi: FunctionInfo, chain):
        mod = fi.module
        base, sym = mod.from_symbols[chain[0]]
        top = (base or sym).split(".")[0]
        target_mod = self.table.modules.get(base) if base else None
        if target_mod is not None:
            if sym in target_mod.functions and len(chain) == 1:
                return "resolved", target_mod.functions[sym]
            if sym in target_mod.classes:
                return self._resolve_via_class(
                    target_mod.classes[sym], chain[1:])
        sub = self.table.modules.get(f"{base}.{sym}" if base else sym)
        if sub is not None:                     # from pkg import module
            return self._resolve_in_module(sub, chain[1:])
        if top in EXTERNAL_MODULES:
            return "external", None
        return "unresolved", None

    def _resolve_import(self, fi: FunctionInfo, chain):
        dotted = fi.module.imports[chain[0]]
        if dotted.split(".")[0] in EXTERNAL_MODULES:
            return "external", None
        parts = dotted.split(".") + list(chain[1:])
        for i in range(len(parts) - 1, 0, -1):
            mod = self.table.modules.get(".".join(parts[:i]))
            if mod is not None:
                return self._resolve_in_module(mod, tuple(parts[i:]))
        return "unresolved", None

    def _resolve_in_module(self, mod, rest):
        if len(rest) == 1 and rest[0] in mod.functions:
            return "resolved", mod.functions[rest[0]]
        if rest and rest[0] in mod.classes:
            return self._resolve_via_class(mod.classes[rest[0]],
                                           rest[1:])
        return "unresolved", None

    # -- propagation ----------------------------------------------------
    def blocking_path(self, fi: FunctionInfo,
                      _stack: set | None = None) -> list | None:
        """For a SYNC function: the first chain of (qual, lineno,
        what) steps reaching a blocking primitive through resolved
        sync calls, or None. Async callees terminate the walk (their
        own async roots are analyzed separately); memoized; cycles
        terminate via the in-progress stack."""
        if fi.is_async or fi.qual in SANCTIONED_SINKS:
            return None
        memo = self._blocking_memo
        if fi.qual in memo:
            return memo[fi.qual]
        stack = _stack if _stack is not None else set()
        if fi.qual in stack:
            self._cycle_cut = True
            return None
        stack.add(fi.qual)
        outer_cut = self._cycle_cut
        self._cycle_cut = False
        result = None
        for site in self.calls.get(fi.qual, ()):
            if site.kind == "blocking":
                result = [(fi.qual, site.lineno, site.what)]
                break
            if site.kind == "resolved" and site.target is not None \
                    and not site.target.is_async \
                    and not site.target.is_generator:
                sub = self.blocking_path(site.target, stack)
                if sub is not None:
                    result = [(fi.qual, site.lineno,
                               site.target.qual)] + sub
                    break
        stack.discard(fi.qual)
        # A concrete path is valid no matter what the stack suppressed
        # (suppression only removes paths). A None computed after a
        # callee walk was cut at an in-stack node depends on THIS
        # query's stack — memoizing it would permanently hide a cycle
        # member's real path from later queries via other callers.
        if result is not None or not self._cycle_cut:
            memo[fi.qual] = result
        self._cycle_cut = self._cycle_cut or outer_cut
        return result

    def unresolved_rate(self) -> float:
        cand = self.stats["resolved"] + self.stats["unresolved"]
        return (self.stats["unresolved"] / cand) if cand else 0.0
