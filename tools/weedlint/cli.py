"""weedlint CLI: rule selection, text/JSON output, baseline
management, per-rule summary, exit-code policy.

Exit codes: 0 clean (or --report-only), 1 findings / stale baseline /
format errors, 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from dataclasses import dataclass, field

from . import baseline as baseline_mod
from .baseline import Baseline
from .core import REPO, Finding
from .rules import ALL_RULE_CLASSES, SELECT_PRESETS, make_rules

DEFAULT_PATHS = ["seaweedfs_tpu", "tools"]


@dataclass
class LintResult:
    findings: list = field(default_factory=list)
    stale: list = field(default_factory=list)
    baseline_errors: list = field(default_factory=list)

    @property
    def problems(self) -> list[Finding]:
        """Findings that actually gate: not suppressed, not
        grandfathered, not advisory (unresolved-call reports but
        never fails the run — its ceiling lives in
        tests/test_callgraph.py)."""
        return [f for f in self.findings
                if not f.suppressed and not f.baselined
                and not f.advisory]

    def summary(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for f in self.problems:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return dict(sorted(counts.items()))

    @property
    def ok(self) -> bool:
        return not self.problems and not self.stale \
            and not self.baseline_errors

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "ok": self.ok,
            "findings": [f.to_dict() for f in self.findings],
            "summary": self.summary(),
            "stale_baseline": [e.to_dict() for e in self.stale],
            "baseline_errors": list(self.baseline_errors),
        }


def apply_baseline(findings, baseline_path):
    """Load + apply the baseline (None = the checked-in default;
    '' / '-' = none). Returns (baseline, stale_entries, errors)."""
    if baseline_path in ("", "-"):
        return None, [], []
    path = baseline_path or baseline_mod.DEFAULT_PATH
    bl = Baseline.load(path)
    bl.apply(findings)
    return bl, bl.stale(), list(bl.format_errors)


def _print_rules() -> None:
    for c in ALL_RULE_CLASSES:
        print(f"{c.id}: {c.title}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.weedlint",
        description="whole-tree static analysis for asyncio "
                    "correctness, resource safety and invalidation "
                    "discipline (see STATIC_ANALYSIS.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help=f"files/dirs to lint (default: "
                        f"{' '.join(DEFAULT_PATHS)})")
    p.add_argument("--select", default="",
                   help="comma-separated rule ids to run (default "
                        "all); presets 'tests-enforced' and 'cancel' "
                        "expand to the id tuples in rules/__init__.py "
                        "so ci.sh and the tests share one source of "
                        "truth")
    p.add_argument("--ignore", default="",
                   help="comma-separated rule ids to skip")
    p.add_argument("--format", choices=("text", "json"),
                   default="text")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file (default "
                        "tools/weedlint/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline entirely")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "(carries existing justifications; new "
                        "entries need one written before the tree "
                        "passes)")
    p.add_argument("--report-only", action="store_true",
                   help="print findings but always exit 0 (tests/ "
                        "runs in this mode)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed/baselined findings")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="lint only files changed vs the git ref "
                        "(default HEAD: staged+unstaged+untracked) — "
                        "the sub-second pre-commit loop; phase 2 "
                        "still resolves over the WHOLE tree but "
                        "reports only into changed files")
    p.add_argument("--jobs", default="1", metavar="N",
                   help="phase-1 process-pool width; 'auto' = cpu "
                        "count (output stays path-sorted and "
                        "deterministic regardless)")
    p.add_argument("--stats", action="store_true",
                   help="print call-resolution stats (the "
                        "unresolved-call precision metric) after "
                        "linting")
    return p


def changed_files(ref: str, scope_paths: list[str],
                  repo: str = REPO) -> list[str]:
    """Files changed vs `ref` (plus untracked), filtered to .py under
    the scanned paths — plus changed .md anywhere, so docs-drift can
    report into an edited catalog. Renames are followed explicitly
    (`--name-status --find-renames`, immune to the host's
    diff.renames config): an R row lints under its NEW path — the
    stale old path must never stand in for it, nor silently drop the
    file from the changed set. Deleted files are skipped (nothing to
    parse). Raises RuntimeError when git fails — a typo'd ref or a
    shallow checkout must NOT silently lint nothing and pass."""
    import subprocess
    out: list[str] = []
    for cmd in (["git", "diff", "--name-status", "--find-renames",
                 ref, "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, cwd=repo, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(f"--changed: {' '.join(cmd)!r} "
                               f"failed: {e}") from e
        if proc.returncode != 0:
            raise RuntimeError(
                f"--changed: {' '.join(cmd)!r} exited "
                f"{proc.returncode}: {proc.stderr.strip()}")
        if "--name-status" not in cmd:
            out += proc.stdout.splitlines()
            continue
        for line in proc.stdout.splitlines():
            fields = line.split("\t")
            if len(fields) < 2:
                continue
            status = fields[0]
            if status.startswith("D"):
                continue                      # deleted: nothing to parse
            # R100/C75 rows are "status<TAB>old<TAB>new": the NEW path
            # is the file that exists and must be linted
            out.append(fields[-1])
    scopes = [os.path.relpath(os.path.abspath(p), repo)
              .replace(os.sep, "/") for p in scope_paths]
    picked: list[str] = []
    for rel in sorted(dict.fromkeys(out)):
        if not rel.endswith((".py", ".md")):
            continue
        if not any(s in (".", "") or rel == s or rel.startswith(s + "/")
                   for s in scopes) and not rel.endswith(".md"):
            continue
        path = os.path.join(repo, rel)
        if os.path.isfile(path):
            picked.append(path)
    return picked


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        _print_rules()
        return 0
    paths = args.paths or [os.path.join(REPO, p)
                           for p in DEFAULT_PATHS]
    select = [s for s in args.select.split(",") if s]
    select = [r for s in select
              for r in SELECT_PRESETS.get(s, (s,))]
    ignore = [s for s in args.ignore.split(",") if s]
    try:
        rules = make_rules(select or None, ignore or None)
    except ValueError as e:
        print(f"weedlint: {e}", file=sys.stderr)
        return 2
    try:
        jobs = (os.cpu_count() or 1) if args.jobs == "auto" \
            else int(args.jobs)
    except ValueError:
        print(f"weedlint: --jobs wants an integer or 'auto', got "
              f"{args.jobs!r}", file=sys.stderr)
        return 2

    restrict_rels = None
    if args.changed is not None:
        from .core import relpath
        try:
            changed = changed_files(args.changed, paths, repo=REPO)
        except RuntimeError as e:
            print(f"weedlint: {e}", file=sys.stderr)
            return 2
        restrict_rels = {relpath(p) for p in changed}
        paths = [p for p in changed if p.endswith(".py")]
        if not restrict_rels:
            print(f"weedlint: clean (nothing changed vs "
                  f"{args.changed})")
            return 0

    from .core import run_paths
    check_unused = not select and not ignore
    stats: dict = {}
    findings = run_paths(paths, rules, check_unused=check_unused,
                         jobs=jobs, restrict_rels=restrict_rels,
                         stats_out=stats)

    baseline_path = "-" if args.no_baseline else args.baseline
    if args.write_baseline:
        path = args.baseline or baseline_mod.DEFAULT_PATH
        old = Baseline.load(path) if os.path.exists(path) else None
        bl = Baseline.from_findings(findings, old=old, path=path)
        if old is not None:
            # a scoped run (subset of paths / --select) must not wipe
            # entries it never re-checked: carry over every old entry
            # outside this run's scope, justification intact
            from .core import relpath
            scanned = [relpath(p) for p in paths]
            run_rules = {r.id for r in rules}
            have = {e.key for e in bl.entries}
            for e in old.entries:
                in_paths = any(rp in ("", ".") or e.path == rp
                               or e.path.startswith(rp + "/")
                               for rp in scanned)
                if (e.rule not in run_rules or not in_paths) \
                        and e.key not in have:
                    bl.entries.append(e)
        bl.save()
        missing = sum(1 for e in bl.entries if not e.justification)
        print(f"wrote {len(bl.entries)} baseline entr"
              f"{'y' if len(bl.entries) == 1 else 'ies'} to {path}"
              + (f" ({missing} need a justification written before "
                 f"the tree passes)" if missing else ""))
        return 0

    _, stale, errors = apply_baseline(findings, baseline_path)
    result = LintResult(findings=findings, stale=stale,
                        baseline_errors=errors)

    if args.format == "json":
        json.dump(result.to_dict(), sys.stdout, indent=2)
        print()
    else:
        shown = result.problems if not args.show_suppressed \
            else result.findings
        for f in shown:
            tag = ""
            if f.suppressed:
                tag = f"  (suppressed: {f.suppress_reason})"
            elif f.baselined:
                tag = "  (baselined)"
            print(f.render() + tag)
        for e in result.stale:
            print(f"stale baseline entry: {e.render()} — the finding "
                  f"is gone, delete the entry")
        for msg in result.baseline_errors:
            print(msg)
        summary = result.summary()
        if summary or result.stale or result.baseline_errors:
            parts = [f"{rule}={n}" for rule, n in summary.items()]
            if result.stale:
                parts.append(f"stale-baseline={len(result.stale)}")
            if result.baseline_errors:
                parts.append(
                    f"baseline-format={len(result.baseline_errors)}")
            total = len(result.problems)
            print(f"weedlint: {total} finding(s): {' '.join(parts)}")
        else:
            print("weedlint: clean")
    if args.stats and stats:
        cand = stats.get("resolved", 0) + stats.get("unresolved", 0)
        print(f"call resolution: {stats.get('resolved', 0)} resolved, "
              f"{stats.get('unresolved', 0)} unresolved, "
              f"{stats.get('external', 0)} external, "
              f"{stats.get('blocking', 0)} blocking primitives "
              f"({stats.get('unresolved_rate', 0.0):.1%} of {cand} "
              f"candidates unresolved)")
    if args.report_only:
        return 0
    return 0 if result.ok else 1
