"""weedlint core: the shared single-walk visitor driver.

Every rule is a class with a stable ``id`` (the thing suppression
comments and the baseline key on), a set of AST node types it wants to
see, and a ``visit(ctx, node)`` callback. The driver parses each file
exactly once, builds the shared per-file context (parent links,
enclosing-function map, finally-block membership), then dispatches
each node of the walk to every interested rule — so adding a pass
costs one class, not another O(tree) traversal.

Findings flow through ``ctx.report(...)``; suppression comments
(tools/weedlint/suppress.py) and the checked-in baseline
(tools/weedlint/baseline.py) are applied after the walk, so a rule
never needs to know either mechanism exists.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from . import suppress

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclass
class Finding:
    """One problem at one site. ``rule`` is the stable id used by
    ``# weedlint: ignore[rule]`` comments and baseline entries."""

    path: str                   # path as given on the command line
    rel: str                    # repo-relative (baseline key), '/'-sep
    line: int
    rule: str
    message: str
    code: str = ""              # stripped source line (baseline key)
    suppressed: bool = False
    suppress_reason: str = ""
    baselined: bool = False

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {"path": self.rel, "line": self.line, "rule": self.rule,
                "message": self.message, "code": self.code,
                "suppressed": self.suppressed,
                "baselined": self.baselined}


class Rule:
    """Base class for one pass. Subclasses set ``id`` (kebab-case,
    stable forever — suppressions and baselines reference it),
    ``title``/``rationale``/``example``/``fix`` (the STATIC_ANALYSIS.md
    catalog is generated from these), and ``node_types``; they get
    ``visit`` calls for matching nodes plus optional ``begin``/
    ``finish`` hooks around the walk."""

    id: str = ""
    title: str = ""
    rationale: str = ""
    example: str = ""
    fix: str = ""
    node_types: tuple = ()

    def begin(self, ctx: "FileContext") -> None:  # pragma: no cover
        pass

    def visit(self, ctx: "FileContext", node: ast.AST) -> None:
        raise NotImplementedError

    def finish(self, ctx: "FileContext") -> None:  # pragma: no cover
        pass


class FileContext:
    """Shared per-file analysis state built once per parse."""

    def __init__(self, path: str, src: str, tree: ast.AST):
        self.path = path
        self.rel = relpath(path)
        self.src = src
        self.lines = src.splitlines()
        self.tree = tree
        self.findings: list[Finding] = []
        self._parent: dict[int, ast.AST] = {}
        self._func: dict[int, ast.AST | None] = {}
        self._finally: set[int] = set()
        self._index(tree)

    def _index(self, tree: ast.AST) -> None:
        stack: list[tuple[ast.AST, ast.AST | None]] = [(tree, None)]
        while stack:
            node, func = stack.pop()
            self._func[id(node)] = func
            child_func = node if isinstance(node, _FUNC_NODES) else func
            for child in ast.iter_child_nodes(node):
                self._parent[id(child)] = node
                stack.append((child, child_func))
        for node in ast.walk(tree):
            if isinstance(node, ast.Try) and node.finalbody:
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        self._finally.add(id(sub))

    # -- ancestry helpers ------------------------------------------------
    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parent.get(id(node))

    def parents(self, node: ast.AST):
        cur = self._parent.get(id(node))
        while cur is not None:
            yield cur
            cur = self._parent.get(id(cur))

    def enclosing_function(self, node: ast.AST) -> ast.AST | None:
        """Nearest function-like ancestor (def / async def / lambda),
        not counting `node` itself."""
        return self._func.get(id(node))

    def in_async_def(self, node: ast.AST) -> bool:
        """True when the *nearest* enclosing function is ``async def``
        — code inside a nested sync def/lambda (e.g. an executor thunk)
        runs off the loop and is exempt by construction."""
        return isinstance(self.enclosing_function(node),
                          ast.AsyncFunctionDef)

    def in_finally(self, node: ast.AST) -> bool:
        return id(node) in self._finally

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # -- reporting -------------------------------------------------------
    def report(self, rule: Rule | str, node: ast.AST | int,
               message: str) -> None:
        rule_id = rule if isinstance(rule, str) else rule.id
        line = node if isinstance(node, int) else node.lineno
        self.findings.append(Finding(
            path=self.path, rel=self.rel, line=line, rule=rule_id,
            message=message, code=self.source_line(line)))


def relpath(path: str) -> str:
    """Repo-relative '/'-separated path when under the repo (the
    stable baseline key), the input otherwise (fixture tmp files)."""
    ap = os.path.abspath(path)
    if ap == REPO or ap.startswith(REPO + os.sep):
        return os.path.relpath(ap, REPO).replace(os.sep, "/")
    return path.replace(os.sep, "/")


def run_file(path: str, rules: list[Rule], *,
             src: str | None = None,
             check_unused: bool = True) -> list[Finding]:
    """Lint one file with `rules`: parse once, one walk, dispatch by
    node type, then apply suppression comments. Returns every finding
    (suppressed ones included, flagged) so callers can choose between
    enforcement and report-only."""
    if src is None:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(path=path, rel=relpath(path), line=e.lineno or 1,
                        rule="syntax-error",
                        message=f"syntax error: {e.msg}")]
    ctx = FileContext(path, src, tree)
    dispatch: dict[type, list[Rule]] = {}
    for r in rules:
        r.begin(ctx)
        for t in r.node_types:
            dispatch.setdefault(t, []).append(r)
    for node in ast.walk(tree):
        for r in dispatch.get(type(node), ()):
            r.visit(ctx, node)
    for r in rules:
        r.finish(ctx)
    suppress.apply(ctx, check_unused=check_unused)
    ctx.findings.sort(key=lambda f: (f.line, f.rule))
    return ctx.findings


def iter_py_files(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__"
                             and not d.startswith("."))
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def run_paths(paths: list[str], rules: list[Rule], *,
              check_unused: bool = True) -> list[Finding]:
    findings: list[Finding] = []
    for p in iter_py_files(paths):
        findings += run_file(p, rules, check_unused=check_unused)
    return findings
