"""weedlint core: the shared single-walk visitor driver.

Every rule is a class with a stable ``id`` (the thing suppression
comments and the baseline key on), a set of AST node types it wants to
see, and a ``visit(ctx, node)`` callback. The driver parses each file
exactly once, builds the shared per-file context (parent links,
enclosing-function map, finally-block membership), then dispatches
each node of the walk to every interested rule — so adding a pass
costs one class, not another O(tree) traversal.

Findings flow through ``ctx.report(...)``; suppression comments
(tools/weedlint/suppress.py) and the checked-in baseline
(tools/weedlint/baseline.py) are applied after the walk, so a rule
never needs to know either mechanism exists.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from . import suppress

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclass
class Finding:
    """One problem at one site. ``rule`` is the stable id used by
    ``# weedlint: ignore[rule]`` comments and baseline entries."""

    path: str                   # path as given on the command line
    rel: str                    # repo-relative (baseline key), '/'-sep
    line: int
    rule: str
    message: str
    code: str = ""              # stripped source line (baseline key)
    suppressed: bool = False
    suppress_reason: str = ""
    baselined: bool = False
    advisory: bool = False      # reported but never gates exit code

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {"path": self.rel, "line": self.line, "rule": self.rule,
                "message": self.message, "code": self.code,
                "suppressed": self.suppressed,
                "baselined": self.baselined,
                "advisory": self.advisory}


class Rule:
    """Base class for one pass. Subclasses set ``id`` (kebab-case,
    stable forever — suppressions and baselines reference it),
    ``title``/``rationale``/``example``/``fix`` (the STATIC_ANALYSIS.md
    catalog is generated from these), and ``node_types``; they get
    ``visit`` calls for matching nodes plus optional ``begin``/
    ``finish`` hooks around the walk."""

    id: str = ""
    title: str = ""
    rationale: str = ""
    example: str = ""
    fix: str = ""
    node_types: tuple = ()
    phase: int = 1              # 1 = per-file walk, 2 = whole-program
    advisory: bool = False      # advisory rules never gate (exit 0)

    def begin(self, ctx: "FileContext") -> None:  # pragma: no cover
        pass

    def visit(self, ctx: "FileContext", node: ast.AST) -> None:
        raise NotImplementedError

    def finish(self, ctx: "FileContext") -> None:  # pragma: no cover
        pass


class ProgramRule(Rule):
    """Base class for a phase-2 (whole-program) pass: runs once per
    invocation over the shared symbol table + call graph instead of
    once per file. ``run`` reports through a ProgramReporter (see
    program.py), which anchors findings, fills source lines and
    filters to the scanned file set (unless ``report_everywhere``,
    e.g. docs-drift findings landing in .md files)."""

    phase = 2
    report_everywhere = False

    def run(self, program, reporter) -> None:
        raise NotImplementedError


class FileContext:
    """Shared per-file analysis state built once per parse."""

    def __init__(self, path: str, src: str, tree: ast.AST):
        self.path = path
        self.rel = relpath(path)
        self.src = src
        self.lines = src.splitlines()
        self.tree = tree
        self.findings: list[Finding] = []
        self._parent: dict[int, ast.AST] = {}
        self._func: dict[int, ast.AST | None] = {}
        self._finally: set[int] = set()
        self._index(tree)

    def _index(self, tree: ast.AST) -> None:
        stack: list[tuple[ast.AST, ast.AST | None]] = [(tree, None)]
        while stack:
            node, func = stack.pop()
            self._func[id(node)] = func
            child_func = node if isinstance(node, _FUNC_NODES) else func
            for child in ast.iter_child_nodes(node):
                self._parent[id(child)] = node
                stack.append((child, child_func))
        for node in ast.walk(tree):
            if isinstance(node, ast.Try) and node.finalbody:
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        self._finally.add(id(sub))

    # -- ancestry helpers ------------------------------------------------
    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parent.get(id(node))

    def parents(self, node: ast.AST):
        cur = self._parent.get(id(node))
        while cur is not None:
            yield cur
            cur = self._parent.get(id(cur))

    def enclosing_function(self, node: ast.AST) -> ast.AST | None:
        """Nearest function-like ancestor (def / async def / lambda),
        not counting `node` itself."""
        return self._func.get(id(node))

    def in_async_def(self, node: ast.AST) -> bool:
        """True when the *nearest* enclosing function is ``async def``
        — code inside a nested sync def/lambda (e.g. an executor thunk)
        runs off the loop and is exempt by construction."""
        return isinstance(self.enclosing_function(node),
                          ast.AsyncFunctionDef)

    def in_finally(self, node: ast.AST) -> bool:
        return id(node) in self._finally

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # -- reporting -------------------------------------------------------
    def report(self, rule: Rule | str, node: ast.AST | int,
               message: str) -> None:
        rule_id = rule if isinstance(rule, str) else rule.id
        line = node if isinstance(node, int) else node.lineno
        self.findings.append(Finding(
            path=self.path, rel=self.rel, line=line, rule=rule_id,
            message=message, code=self.source_line(line)))


def relpath(path: str) -> str:
    """Repo-relative '/'-separated path when under the repo (the
    stable baseline key), the input otherwise (fixture tmp files)."""
    ap = os.path.abspath(path)
    if ap == REPO or ap.startswith(REPO + os.sep):
        return os.path.relpath(ap, REPO).replace(os.sep, "/")
    return path.replace(os.sep, "/")


def analyze_file(path: str, rules: list[Rule], *,
                 src: str | None = None):
    """Phase-1 walk of one file: parse once, dispatch by node type,
    mark (but never judge) suppressions. Returns ``(findings, sups)``
    — the unused-suppression verdict is deferred to the caller, which
    may still match sups against phase-2 findings."""
    if src is None:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(path=path, rel=relpath(path), line=e.lineno or 1,
                        rule="syntax-error",
                        message=f"syntax error: {e.msg}")], []
    ctx = FileContext(path, src, tree)
    dispatch: dict[type, list[Rule]] = {}
    for r in rules:
        if r.phase != 1:
            continue
        r.begin(ctx)
        for t in r.node_types:
            dispatch.setdefault(t, []).append(r)
    for node in ast.walk(tree):
        for r in dispatch.get(type(node), ()):
            r.visit(ctx, node)
    for r in rules:
        if r.phase == 1:
            r.finish(ctx)
    sups = suppress.apply(ctx, check_unused=False)
    ctx.findings.sort(key=lambda f: (f.line, f.rule))
    return ctx.findings, sups


def run_file(path: str, rules: list[Rule], *,
             src: str | None = None,
             check_unused: bool = True) -> list[Finding]:
    """Lint one file with the phase-1 `rules`. Returns every finding
    (suppressed ones included, flagged) so callers can choose between
    enforcement and report-only."""
    findings, sups = analyze_file(path, rules, src=src)
    if check_unused:
        findings += suppress.unused_findings(path, relpath(path), sups)
        findings.sort(key=lambda f: (f.line, f.rule))
    return findings


def iter_py_files(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__"
                             and not d.startswith("."))
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def _analyze_one(args):
    """Process-pool entry point for parallel phase 1 (must be a
    module-level function to pickle)."""
    path, rule_ids = args
    from .rules import make_rules
    return path, analyze_file(path, make_rules(rule_ids or None))


def run_paths(paths: list[str], rules: list[Rule], *,
              check_unused: bool = True, jobs: int = 1,
              restrict_rels: set[str] | None = None,
              stats_out: dict | None = None) -> list[Finding]:
    """Lint `paths`: phase 1 over every file (optionally across a
    process pool), then the phase-2 whole-program passes, then one
    suppression/unused verdict over the union. Output is
    deterministic: findings sorted by (path, line, rule) regardless
    of pool scheduling.

    ``restrict_rels`` is --changed mode: phase 2 still builds the
    whole-tree symbol table, but every finding (docs-drift's .md
    anchors included) must land in the restricted set. ``stats_out``
    receives the call-resolution counters when phase 2 runs."""
    files = sorted(dict.fromkeys(iter_py_files(paths)))
    file_rules = [r for r in rules if r.phase == 1]
    program_rules = [r for r in rules if r.phase == 2]

    per_file: dict[str, tuple[list[Finding], list]] = {}
    if jobs > 1 and len(files) > 1:
        from concurrent.futures import ProcessPoolExecutor
        from concurrent.futures.process import BrokenProcessPool
        try:
            ids = [r.id for r in rules]
            with ProcessPoolExecutor(max_workers=jobs) as pool:
                for path, result in pool.map(
                        _analyze_one, [(f, ids) for f in files],
                        chunksize=8):
                    per_file[path] = result
        except (OSError, ImportError, BrokenProcessPool):
            # no fork on this host / worker died mid-run: serial
            per_file = {}
    if not per_file:
        for f in files:
            per_file[f] = analyze_file(f, file_rules)

    findings: list[Finding] = []
    for f in files:
        findings += per_file[f][0]

    if program_rules:
        from .program import run_program
        rel_to_path = {relpath(f): f for f in files}
        prog_findings = run_program(program_rules, paths,
                                    scanned_rels=set(rel_to_path),
                                    restrict_rels=restrict_rels,
                                    stats_out=stats_out)
        # phase-2 findings ride the same per-line suppressions
        by_rel: dict[str, list[Finding]] = {}
        for pf in prog_findings:
            by_rel.setdefault(pf.rel, []).append(pf)
        for rel, group in by_rel.items():
            path = rel_to_path.get(rel)
            if path is not None:
                suppress.mark(group, per_file[path][1])
        findings += prog_findings

    if check_unused:
        for f in files:
            findings += suppress.unused_findings(
                f, relpath(f), per_file[f][1])
    findings.sort(key=lambda x: (x.rel, x.line, x.rule))
    return findings
