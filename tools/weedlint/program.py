"""Phase-2 driver: build the whole-program symbol table + call graph
once, run every ProgramRule over it, anchor the findings.

The symbol table always covers the WHOLE tree (plus any scanned paths
outside it): a `--changed`/subpath run still resolves calls across
every module — only the *reporting* is filtered to the scanned files.
A scan of a fixture tree outside the repo builds its table from the
fixture roots alone, so tests stay hermetic.
"""

from __future__ import annotations

import os

from .callgraph import Program
from .core import REPO, Finding, ProgramRule, relpath
from .symbols import SymbolTable

DEFAULT_ROOTS = [os.path.join(REPO, "seaweedfs_tpu"),
                 os.path.join(REPO, "tools")]


def program_roots(paths: list[str]) -> list[str]:
    """Symbol-table roots for a scan of `paths`: the enforced-tree
    roots whenever the scan touches the repo (so cross-module
    resolution always sees everything), plus any scanned directories
    outside them; a fully-out-of-repo scan (fixtures) uses only its
    own roots."""
    if not paths:               # --changed with only .md edits: the
        return list(DEFAULT_ROOTS)   # whole-tree table still resolves
    roots: list[str] = []
    in_repo = False
    for p in paths:
        ap = os.path.abspath(p)
        if not os.path.isdir(ap):
            ap = os.path.dirname(ap)
        if ap == REPO or ap.startswith(REPO + os.sep):
            in_repo = True
            if any(ap == d or ap.startswith(d + os.sep)
                   for d in DEFAULT_ROOTS):
                continue
            if ap == REPO:
                # the repo root itself must never BE a root: module
                # quals would gain the checkout dir's name as a prefix
                # ('repo.seaweedfs_tpu....'), silently defeating every
                # qual-keyed table (SANCTIONED_SINKS, ...). The
                # package roots are covered via in_repo; sibling
                # top-level dirs (tests/, ...) become their own roots.
                for entry in sorted(os.listdir(ap)):
                    sub = os.path.join(ap, entry)
                    if os.path.isdir(sub) and sub not in DEFAULT_ROOTS \
                            and not entry.startswith("."):
                        roots.append(sub)
                continue
        roots.append(ap)
    if in_repo:
        roots = DEFAULT_ROOTS + roots
    out: list[str] = []
    for r in roots:                       # drop nested/duplicate roots
        if not any(other != r and (r == other
                   or r.startswith(other + os.sep))
                   for other in roots) and r not in out:
            out.append(r)
    return out


class ProgramReporter:
    """Collects phase-2 findings: fills the source line from the
    symbol table (or the file itself for non-.py anchors like docs),
    and filters to the scanned file set unless the rule opts out."""

    def __init__(self, table: SymbolTable, scanned_rels: set[str],
                 restrict_rels: set[str] | None = None):
        self.table = table
        self.scanned_rels = scanned_rels
        self.restrict_rels = restrict_rels
        self.findings: list[Finding] = []
        self._doc_lines: dict[str, list[str]] = {}

    def _source_line(self, rel: str, line: int) -> str:
        mod = self.table.by_rel.get(rel)
        if mod is not None:
            lines = mod.src.splitlines()
        else:
            if rel not in self._doc_lines:
                path = os.path.join(REPO, rel)
                try:
                    with open(path, encoding="utf-8") as f:
                        self._doc_lines[rel] = f.read().splitlines()
                except OSError:
                    self._doc_lines[rel] = []
            lines = self._doc_lines[rel]
        if 1 <= line <= len(lines):
            return lines[line - 1].strip()
        return ""

    def report(self, rule: ProgramRule, rel: str, line: int,
               message: str, *, path: str | None = None) -> None:
        if not rule.report_everywhere \
                and self.scanned_rels \
                and rel not in self.scanned_rels:
            return
        if self.restrict_rels is not None \
                and rel not in self.restrict_rels:
            return              # --changed: report into changed files only
        mod = self.table.by_rel.get(rel)
        self.findings.append(Finding(
            path=path or (mod.path if mod else rel), rel=rel,
            line=line, rule=rule.id, message=message,
            advisory=rule.advisory,
            code=self._source_line(rel, line)))


def run_program(program_rules: list[ProgramRule], paths: list[str],
                *, scanned_rels: set[str],
                restrict_rels: set[str] | None = None,
                table: SymbolTable | None = None,
                stats_out: dict | None = None) -> list[Finding]:
    if table is None:
        table = SymbolTable.build(program_roots(paths))
    program = Program(table)
    if stats_out is not None:
        stats_out.update(program.stats)
        stats_out["unresolved_rate"] = program.unresolved_rate()
    reporter = ProgramReporter(table, scanned_rels, restrict_rels)
    for rule in program_rules:
        rule.run(program, reporter)
    reporter.findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    return reporter.findings
