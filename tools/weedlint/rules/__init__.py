"""Rule registry. Adding a pass = write the class, list it here,
document it in STATIC_ANALYSIS.md (the catalog test cross-checks)."""

from __future__ import annotations

from .asynchrony import (AwaitInLockRule, BlockingIoRule,
                         LockAcquireRule, OrphanTaskRule)
from .cache import CacheInvalidateRule, FailpointSiteRule
from .cancel import (AwaitAtomicityRule, CancelLeakRule,
                     DetachDisciplineRule)
from .drift import DocsDriftRule
from .exceptions import SilentExceptRule
from .executor import ExecutorCtxRule
from .interproc import (LockOrderRule, TimeoutDisciplineRule,
                        TransitiveBlockingRule,
                        TransitiveOrphanSpanRule, UnresolvedCallRule)
from .metrics import MetricHelpRule, MetricNameRule, SpanFinishRule
from .resources import ResourceWithRule

ALL_RULE_CLASSES = (
    # phase 1: one shared walk per file
    SilentExceptRule,
    MetricNameRule,
    MetricHelpRule,
    SpanFinishRule,
    BlockingIoRule,
    OrphanTaskRule,
    AwaitInLockRule,
    LockAcquireRule,
    ResourceWithRule,
    CacheInvalidateRule,
    FailpointSiteRule,
    ExecutorCtxRule,
    # phase 2: whole-program, over the shared symbol table + call graph
    TransitiveBlockingRule,
    LockOrderRule,
    TimeoutDisciplineRule,
    TransitiveOrphanSpanRule,
    UnresolvedCallRule,
    DocsDriftRule,
    # phase 3: cancellation/atomicity dataflow (same phase-2 driver,
    # riding the call graph one resolved call deep)
    CancelLeakRule,
    AwaitAtomicityRule,
    DetachDisciplineRule,
)

# findings the framework itself emits (no Rule class walks for these)
META_RULE_IDS = ("suppress-format", "unused-suppression",
                 "syntax-error")

ALL_RULE_IDS = tuple(c.id for c in ALL_RULE_CLASSES)

# rules whose findings report but never gate (exit code stays 0)
ADVISORY_RULE_IDS = tuple(c.id for c in ALL_RULE_CLASSES if c.advisory)

# the subset safe to ENFORCE over tests/ (fixtures legitimately write
# blocking I/O, unclosed sessions-on-purpose, and lock inversions to
# feed the rules; exception/task/fd hygiene applies to test code too)
TESTS_ENFORCED_RULE_IDS = ("silent-except", "orphan-task",
                           "resource-with")

# the three passes the original tools/lint_robustness.py shipped —
# its shim keeps exactly this behavior
LEGACY_RULE_IDS = ("silent-except", "metric-name", "metric-help",
                   "span-finish")

# the phase-3 cancellation/atomicity subset (the `--select cancel`
# preset: the focused pre-commit loop after touching an await-heavy
# protocol core)
CANCEL_RULE_IDS = ("cancel-leak", "await-atomicity",
                   "detach-discipline")

# --select presets: one name expanding to a maintained id tuple so
# ci.sh, tests and humans share a single source of truth
SELECT_PRESETS = {
    "tests-enforced": TESTS_ENFORCED_RULE_IDS,
    "cancel": CANCEL_RULE_IDS,
}


def make_rules(select=None, ignore=None):
    """Instantiate the ruleset. `select`/`ignore` are iterables of
    rule ids; unknown ids raise (a typoed --select silently checking
    nothing would 'pass' while testing nothing)."""
    known = set(ALL_RULE_IDS)
    for group in (select, ignore):
        unknown = set(group or ()) - known
        if unknown:
            raise ValueError(
                f"unknown rule id(s): {sorted(unknown)} "
                f"(known: {sorted(known)})")
    classes = ALL_RULE_CLASSES
    if select:
        classes = [c for c in classes if c.id in set(select)]
    if ignore:
        classes = [c for c in classes if c.id not in set(ignore)]
    return [c() for c in classes]
