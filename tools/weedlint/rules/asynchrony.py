"""Rules for asyncio correctness: blocking calls on the event loop,
fire-and-forget tasks, and lock/await interleavings.

These are the bug classes PRs 1-4 actually shipped and hand-fixed:
a blocking pread stalling every in-flight request, a dropped
create_task whose exception wedged a connection forever, an await
under a threading.Lock deadlocking the loop against its own executor
threads.
"""

from __future__ import annotations

import ast
import re

from ..core import FileContext, Rule

# Module-attribute calls that block the calling thread. Deliberately
# conservative: every entry here stalls the loop for a disk/DNS/sleep
# latency, not a few ns.
_BLOCKING_ATTRS: dict[str, set[str]] = {
    "time": {"sleep"},
    "os": {"open", "read", "write", "pread", "pwrite", "fsync",
           "fdatasync", "sendfile", "ftruncate", "truncate",
           "listdir", "scandir", "walk", "remove", "unlink",
           "rename", "replace", "rmdir", "makedirs", "mkdir",
           "stat", "fstat",
           # vectored/zero-copy forms (the unified-wire data plane):
           # group-commit pwritev and raw sendfile block exactly like
           # their scalar siblings — they belong on the executor or in
           # sanctioned zero-copy helpers (await loop.sendfile, which
           # never trips this rule because it is awaited, not os.*)
           "pwritev", "preadv", "writev", "readv", "sendmsg"},
    "shutil": {"copy", "copyfile", "copyfileobj", "copytree",
               "rmtree", "move"},
    "mmap": {"mmap"},
    "subprocess": {"run", "call", "check_call", "check_output",
                   "Popen"},
    "socket": {"create_connection", "getaddrinfo", "gethostbyname"},
}
_BLOCKING_NAMES = {"open", "input"}

# lock-ish terminal names: `lock`, `_lock`, `vol_lock`, `mu`, `mutex`,
# plus bare `rlock`/`wlock`. Deliberately NOT a bare `lock$` suffix —
# that would flag `block`/`clock`/`datablock` context managers.
LOCKISH_RE = re.compile(r"(?i)((^|_)(lock|mutex|mu)$)|(^[rw]?lock$)")


def tail_name(node: ast.AST) -> str:
    """`self._vol_lock` -> '_vol_lock', `lock` -> 'lock',
    `x.lock()` -> 'lock' (the called attribute)."""
    if isinstance(node, ast.Call):
        return tail_name(node.func)
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _awaits_in(stmts):
    """Await nodes in `stmts`, not descending into nested defs (their
    awaits run on their own schedule, not under this block)."""
    out = []
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Await):
            out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


class BlockingIoRule(Rule):
    id = "blocking-io"
    title = "blocking call in an async def body"
    rationale = ("a blocking disk/DNS/sleep call inside `async def` "
                 "stalls every request sharing the event loop for the "
                 "full latency — the whole-process stall class PR-3 "
                 "hand-fixed by moving disk-tier mmap I/O off the "
                 "loop. Thunks handed to run_in_executor are sync "
                 "functions and exempt by construction.")
    example = ("async def h(req):\n"
               "    time.sleep(0.1)          # stalls the whole loop\n"
               "    data = open(p).read()    # ditto")
    fix = ("await asyncio.sleep(...), or route the I/O through "
           "tracing.run_in_executor(fn, *args)")
    node_types = (ast.Call,)

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        if not ctx.in_async_def(node):
            return
        func = node.func
        what = ""
        if isinstance(func, ast.Name) and func.id in _BLOCKING_NAMES:
            what = func.id
        elif (isinstance(func, ast.Attribute)
              and isinstance(func.value, ast.Name)
              and func.attr in _BLOCKING_ATTRS.get(func.value.id, ())):
            what = f"{func.value.id}.{func.attr}"
        if not what:
            return
        ctx.report(self, node,
                   f"blocking call {what}() on the event loop — "
                   f"stalls every in-flight request; route through "
                   f"tracing.run_in_executor (or asyncio.sleep for "
                   f"sleeps)")


class OrphanTaskRule(Rule):
    id = "orphan-task"
    title = "create_task/ensure_future result dropped"
    rationale = ("a task whose handle is dropped can be GC-cancelled "
                 "mid-flight, and its exception is silently parked "
                 "until interpreter exit — the PR-1 class where a "
                 "fire-and-forget handler task wedged its connection "
                 "forever. Retain the handle and give it a "
                 "done-callback (or await it).")
    example = "asyncio.create_task(self._heartbeat_loop())"
    fix = ("keep the handle (self._tasks.append(...)) and attach "
           "add_done_callback, or await it")
    node_types = (ast.Call,)

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        func = node.func
        name = ""
        if isinstance(func, ast.Attribute) and func.attr in (
                "create_task", "ensure_future"):
            name = func.attr
        elif isinstance(func, ast.Name) and func.id in (
                "create_task", "ensure_future"):
            name = func.id
        if not name:
            return
        parent = ctx.parent(node)
        dropped = isinstance(parent, ast.Expr)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name) \
                and parent.targets[0].id == "_":
            dropped = True
        if dropped:
            ctx.report(self, node,
                       f"{name}() result dropped — the task can be "
                       f"GC-collected mid-flight and its exception is "
                       f"never observed; retain the handle and attach "
                       f"a done-callback")


class AwaitInLockRule(Rule):
    id = "await-in-lock"
    title = "await while holding a synchronous lock"
    rationale = ("`with threading.Lock(): await ...` parks the "
                 "coroutine while the OS lock stays held; any executor "
                 "thread (or another coroutine resumed on this loop) "
                 "that wants the lock deadlocks the process.")
    example = ("with self._lock:\n"
               "    await client.upload(...)")
    fix = ("shrink the critical section so no await happens under the "
           "lock, or switch to asyncio.Lock + async with")
    node_types = (ast.With,)

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.With)
        lockish = [item for item in node.items
                   if LOCKISH_RE.search(tail_name(item.context_expr))]
        if not lockish:
            return
        awaits = _awaits_in(node.body)
        if not awaits:
            return
        name = tail_name(lockish[0].context_expr)
        first = min(a.lineno for a in awaits)
        ctx.report(self, node,
                   f"await at line {first} while holding sync lock "
                   f"{name!r} — a coroutine parked under an OS lock "
                   f"deadlocks executor threads; shrink the critical "
                   f"section or use asyncio.Lock with `async with`")


class LockAcquireRule(Rule):
    id = "lock-acquire"
    title = "asyncio lock acquired without async-with discipline"
    rationale = ("`await lock.acquire()` not immediately followed by "
                 "try/finally release leaks the lock on any exception "
                 "between acquire and release — every later waiter "
                 "hangs forever. And a *sync* `with` on an "
                 "asyncio.Lock raises at runtime only when that path "
                 "finally executes.")
    example = ("await self._lock.acquire()\n"
               "do_work()   # an exception here orphans the lock")
    fix = "use `async with lock:`"
    node_types = (ast.Await, ast.With)

    def begin(self, ctx: FileContext) -> None:
        # names bound to asyncio.Lock()/Semaphore()/Condition() in this
        # file (x = asyncio.Lock() and self.x = asyncio.Lock())
        self._async_locks: set[str] = set()
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            f = node.value.func
            if not (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "asyncio"
                    and f.attr in ("Lock", "Semaphore",
                                   "BoundedSemaphore", "Condition")):
                continue
            for t in node.targets:
                n = tail_name(t)
                if n:
                    self._async_locks.add(n)

    @staticmethod
    def _releases(stmts, holder: str) -> bool:
        for stmt in stmts:
            for fin in ast.walk(stmt):
                if (isinstance(fin, ast.Call)
                        and isinstance(fin.func, ast.Attribute)
                        and fin.func.attr == "release"
                        and tail_name(fin.func.value) == holder):
                    return True
        return False

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        if isinstance(node, ast.With):
            for item in node.items:
                n = tail_name(item.context_expr)
                if n and n in self._async_locks:
                    ctx.report(self, node,
                               f"sync `with` on asyncio lock {n!r} — "
                               f"asyncio locks only support `async "
                               f"with` (this raises at runtime on the "
                               f"first contended path)")
            return
        assert isinstance(node, ast.Await)
        call = node.value
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == "acquire"):
            return
        holder = tail_name(call.func.value)
        stmt = ctx.parent(node)
        if not isinstance(stmt, ast.Expr):
            # e.g. `ok = await lock.acquire()` — still manual, flag it
            stmt = stmt if isinstance(stmt, ast.stmt) else None
        if stmt is None:
            return
        parent = ctx.parent(stmt)
        body = getattr(parent, "body", None)
        protected = False
        if isinstance(body, list) and stmt in body:
            i = body.index(stmt)
            # canonical: acquire, then try/finally release
            if i + 1 < len(body) and isinstance(body[i + 1], ast.Try):
                protected = self._releases(body[i + 1].finalbody,
                                           holder)
        if not protected and isinstance(parent, ast.Try) \
                and stmt in parent.body:
            # tolerated variant: acquire as the first statement of a
            # try whose finally releases
            protected = self._releases(parent.finalbody, holder)
        if not protected:
            ctx.report(self, node,
                       f"manual `await {holder}.acquire()` without an "
                       f"immediate try/finally {holder}.release() — an "
                       f"exception in between orphans the lock; use "
                       f"`async with {holder}:`")
