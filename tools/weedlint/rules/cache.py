"""Rules for this repo's two hand-rolled disciplines:

* cache-invalidate — PR-3 put read caches under the volume store and
  the client; every mutating entry point must visibly invalidate (or
  carry a suppression explaining why it cannot race a cached read).
* failpoint-site — PR-2's chaos harness only exercises faults at
  planted sites; a new outbound network / raw-disk call in the data
  plane that plants no failpoint is invisible to the soak.
"""

from __future__ import annotations

import ast
import re

from ..core import FileContext, Rule
from .asynchrony import tail_name

# class name -> (method regex that mutates, what must be mentioned)
MUTATOR_SPECS: dict[str, re.Pattern] = {
    "Store": re.compile(
        r"^_?(write|delete|vacuum|commit|mount|unmount|batch_delete"
        r"|truncate|apply_tail|receive_tail)"),
    "WeedClient": re.compile(r"^(upload|delete)"),
}
# identifier substrings that count as touching the cache layer
_EVIDENCE = ("cache", "invalid", "drop", "gen_fence", "bump_gen")

_HTTP_VERBS = {"get", "post", "put", "delete", "head", "patch",
               "request"}
_SESSIONISH = re.compile(r"(?i)(sess|session|http|client|chan|channel)$")
# repo-relative path fragments where the failpoint discipline applies
# (the data plane the chaos soak drives)
FAILPOINT_SCOPE = ("seaweedfs_tpu/server/", "seaweedfs_tpu/replication/",
                   "seaweedfs_tpu/util/client.py",
                   "seaweedfs_tpu/util/masterclient.py",
                   # the sharded filer metadata plane: every routed
                   # hop (redirect chase, merged-listing fan-out,
                   # split/move migration batch) must be chaos-
                   # reachable (filer.shard.route/split/move)
                   "seaweedfs_tpu/filer/shard.py",
                   "seaweedfs_tpu/storage/store.py",
                   # the EC recovery data plane: degraded-read shard
                   # preads + the scrubber's window reads must sit
                   # within chaos-site reach (ec.shard_read,
                   # ec.recover.read, scrub.read)
                   "seaweedfs_tpu/ec/ec_volume.py",
                   "seaweedfs_tpu/ec/scrub.py",
                   # the autopilot maintenance plane: chaos.py must be
                   # able to break the healer itself (observe probes,
                   # executor dispatch)
                   "seaweedfs_tpu/autopilot/",
                   # the HA control plane: every raft RPC (vote/append/
                   # snapshot), the follower->leader proxy hop, the
                   # grow/delete fan-outs and the etcd id reservation
                   # must sit within chaos-site reach — tools/chaos.py
                   # ha partitions the quorum through them
                   "seaweedfs_tpu/master/",
                   # the frame fabric itself: every multiplexed request
                   # send (worker.frame) and the sync frame pool the EC
                   # gather rides must stay chaos-reachable
                   "seaweedfs_tpu/util/frame.py",
                   "seaweedfs_tpu/util/connpool.py",
                   # cluster-scope introspection: the per-node debug
                   # pull behind /debug/cluster/* must degrade to a
                   # missing_node row under chaos (introspect.fanout) —
                   # a hang here wedges the operator's one cluster view
                   "seaweedfs_tpu/stats/introspect.py")


def _mentions_evidence(fn: ast.AST, spec: re.Pattern) -> bool:
    for node in ast.walk(fn):
        name = ""
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and any(s in name.lower() for s in _EVIDENCE):
            return True
        # delegation to a sibling mutator (self.upload(...) from
        # upload_data) counts: the invalidation is checked there
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and node.func.attr != getattr(fn, "name", "")
                and spec.match(node.func.attr)):
            return True
    return False


class CacheInvalidateRule(Rule):
    id = "cache-invalidate"
    title = "mutating entry point with no visible cache invalidation"
    rationale = ("PR-3's needle/chunk caches answer reads without "
                 "touching disk; a write/delete/vacuum/commit path "
                 "that forgets to invalidate serves stale bytes "
                 "forever after. Mechanically: every mutating method "
                 "on Store/WeedClient must reference the cache layer "
                 "(invalidate/drop/generation bump) somewhere in its "
                 "body.")
    example = ("class Store:\n"
               "    def write_needle(self, vid, n):\n"
               "        return self._volume(vid).write(n)  # no "
               "invalidation")
    fix = ("invalidate/drop the affected cache entries (or bump the "
           "generation fence) before acking the mutation; if the "
           "method genuinely cannot race a cached read, suppress with "
           "the reason")
    node_types = (ast.ClassDef,)

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.ClassDef)
        spec = MUTATOR_SPECS.get(node.name)
        if spec is None:
            return
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not spec.match(item.name):
                continue
            if _mentions_evidence(item, spec):
                continue
            ctx.report(self, item,
                       f"{node.name}.{item.name} mutates state but "
                       f"never references the cache layer "
                       f"(invalidate/drop/generation) — a cached read "
                       f"racing this returns stale bytes")


class FailpointSiteRule(Rule):
    id = "failpoint-site"
    title = "data-plane I/O call site without failpoint coverage"
    rationale = ("the chaos soak can only inject faults at planted "
                 "failpoint sites; an outbound HTTP call or raw "
                 "pread/pwrite added to the data plane without one is "
                 "a path the soak can never break, i.e. never proves. "
                 "Scope: server/, replication/, util/client.py, "
                 "util/masterclient.py, storage/store.py.")
    example = ("async def replicate(self, url, body):\n"
               "    await self._session.post(url, data=body)  # no "
               "failpoints.fail(...) in reach")
    fix = ("plant `await failpoints.fail('<tier>.<op>')` (or "
           "sync_fail/corrupt) in the function before the call, or "
           "suppress with a pointer to the site that already covers "
           "this path one level up")
    node_types = (ast.Call,)

    def _function_has_failpoint(self, ctx: FileContext,
                                fn: ast.AST | None) -> bool:
        scope = fn if fn is not None else ctx.tree
        for node in ast.walk(scope):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "failpoints":
                return True
            if isinstance(node, ast.Name) and node.id == "failpoints":
                return True
        return False

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        if not any(frag in ctx.rel for frag in FAILPOINT_SCOPE):
            return
        f = node.func
        site = ""
        if isinstance(f, ast.Attribute) and f.attr in _HTTP_VERBS \
                and _SESSIONISH.search(tail_name(f.value) or ""):
            site = f"{tail_name(f.value)}.{f.attr}"
        elif isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name) \
                and f.value.id == "os" \
                and f.attr in ("pread", "pwrite", "pwritev", "preadv",
                               "sendfile"):
            # the vectored/zero-copy forms are data-plane I/O exactly
            # like their scalar siblings: the group-commit batch append
            # and sendfile reads must sit within chaos-site reach
            site = f"os.{f.attr}"
        if not site:
            return
        fn = ctx.enclosing_function(node)
        while isinstance(fn, ast.Lambda):
            fn = ctx.enclosing_function(fn)
        if self._function_has_failpoint(ctx, fn):
            return
        ctx.report(self, node,
                   f"outbound {site}(...) in the data plane with no "
                   f"failpoint in the enclosing function — the chaos "
                   f"soak cannot exercise this path's failure "
                   f"handling")
